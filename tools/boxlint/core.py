"""boxlint core: source loading, suppressions, violations, baseline io.

No third-party deps — stdlib ``ast`` + ``tokenize`` only, so the checker
runs anywhere the repo's Python does (CI, the container, a laptop without
jax installed).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ``# boxlint: disable=BX101,BX401`` or ``# boxlint: disable`` (all codes)
_SUPPRESS_RE = re.compile(
    r"#\s*boxlint:\s*disable(?:\s*=\s*(?P<codes>[A-Z0-9,\s]+))?")
# ``boxlint: BXnnn ok (reason)`` comment — the device-contract waiver
# form: the reason string is MANDATORY (a reasonless waiver is itself a
# finding, BX932), so every tolerated host sync / contract exception
# carries its justification at the site
WAIVER_RE = re.compile(
    r"#\s*boxlint:\s*(?P<code>BX\d+)\s+ok\b"
    r"(?:\s*\((?P<reason>[^)]*)\))?")
# ``# guarded-by: <lock-attr>`` trailing annotation (pass 4)
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w]*)")


@dataclass(frozen=True)
class Violation:
    path: str          # repo-relative, forward slashes
    line: int
    code: str          # BXnnn
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift under unrelated edits, so
        matching ignores them (file, code, message)."""
        return (self.path, self.code, self.message)


class SourceFile:
    """One parsed module plus the comment-derived metadata ast drops."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line -> set of suppressed codes (empty set == all codes)
        self.suppress: Dict[int, Optional[Set[str]]] = {}
        # line -> lock attr name from a guarded-by annotation
        self.guarded_by: Dict[int, str] = {}
        # line -> raw comment text (every comment; BX503 reads these as
        # swallow-site rationales)
        self.comments: Dict[int, str] = {}
        # line -> (code, reason) for reasoned `# boxlint: BXnnn ok (...)`
        self.waivers: Dict[int, Tuple[str, str]] = {}
        # (line, code) for waivers WITHOUT a reason string — BX932 material
        self.bare_waivers: List[Tuple[int, str]] = []
        self._scan_comments()
        # lines covered by a def/class-level suppression
        self._block_suppress: List[Tuple[int, int, Optional[Set[str]]]] = []
        self._scan_block_suppressions()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                self.comments[tok.start[0]] = tok.string
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    codes = m.group("codes")
                    self.suppress[tok.start[0]] = (
                        {c.strip() for c in codes.split(",") if c.strip()}
                        if codes else None)
                w = WAIVER_RE.search(tok.string)
                if w:
                    reason = (w.group("reason") or "").strip()
                    if reason:
                        self.waivers[tok.start[0]] = (w.group("code"),
                                                      reason)
                        prev = self.suppress.get(tok.start[0], set())
                        if prev is not None:
                            self.suppress[tok.start[0]] = (
                                set(prev) | {w.group("code")})
                    else:
                        # reasonless waiver: does NOT suppress — it flags
                        self.bare_waivers.append(
                            (tok.start[0], w.group("code")))
                g = GUARDED_BY_RE.search(tok.string)
                if g:
                    self.guarded_by[tok.start[0]] = g.group("lock")
        except tokenize.TokenError:
            pass  # malformed tail; ast.parse already succeeded

    def _scan_block_suppressions(self) -> None:
        """A disable comment on a ``def``/``class`` line suppresses the
        whole body — the ergonomic form for deliberately lock-free
        boundary methods."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for ln in range(node.lineno, node.body[0].lineno):
                    if ln in self.suppress:
                        self._block_suppress.append(
                            (node.lineno, node.end_lineno or node.lineno,
                             self.suppress[ln]))
                        break

    def suppressed(self, line: int, code: str) -> bool:
        codes = self.suppress.get(line, False)
        if codes is not False:
            if codes is None or code in codes:
                return True
        for start, end, blk in self._block_suppress:
            if start <= line <= end and (blk is None or code in blk):
                return True
        return False


def load_tree(paths: Sequence[str], root: Optional[str] = None,
              sources: Optional[Sequence[Tuple[str, str, str]]] = None
              ) -> Tuple[List[SourceFile], List[Violation]]:
    """Collect and parse every .py under ``paths``. Unparseable files are
    reported as BX000 rather than crashing the run. ``sources`` (already
    read (abs, rel, text) triples from cache.collect_sources) skips the
    re-read on the cache-miss path."""
    root = root or os.getcwd()
    files: List[SourceFile] = []
    errors: List[Violation] = []
    if sources is None:
        # ONE walk implementation: the cache digest must be computed
        # over exactly the file set that gets linted, so the legacy
        # path reuses collect_sources rather than mirroring its
        # walk/prune rules (lazy import — cache.py imports Violation
        # from here)
        from tools.boxlint.cache import collect_sources
        sources = collect_sources(paths, root=root)
    for f, rel, text in sources:
        if text is None:   # collect_sources read failure marker
            errors.append(Violation(
                rel, 1, "BX000", "unparseable: unreadable file "
                "(I/O or encoding error)"))
            continue
        try:
            files.append(SourceFile(f, rel, text))
        except (SyntaxError, ValueError) as e:
            line = getattr(e, "lineno", 1) or 1
            errors.append(Violation(
                rel, line, "BX000",
                f"unparseable: {e.__class__.__name__}: {e}"))
    return files, errors


# --------------------------------------------------------------- baseline

def load_baseline(path: str) -> List[Tuple[str, str, str]]:
    """Baseline lines are rendered violations; identity ignores the line
    number (see Violation.key). Returns a multiset as a list."""
    entries: List[Tuple[str, str, str]] = []
    if not os.path.exists(path):
        return entries
    pat = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+): (?P<code>BX\d+) "
                     r"(?P<msg>.*)$")
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            raw = raw.rstrip("\n")
            if not raw or raw.startswith("#"):
                continue
            m = pat.match(raw)
            if m:
                entries.append((m.group("path"), m.group("code"),
                                m.group("msg")))
    return entries


def diff_against_baseline(violations: Sequence[Violation],
                          baseline: Sequence[Tuple[str, str, str]]
                          ) -> Tuple[List[Violation], List[Tuple[str, str, str]]]:
    """Multiset subtraction: returns (new_violations, stale_baseline)."""
    pool: Dict[Tuple[str, str, str], int] = {}
    for entry in baseline:
        pool[entry] = pool.get(entry, 0) + 1
    new: List[Violation] = []
    for v in violations:
        k = v.key()
        if pool.get(k, 0) > 0:
            pool[k] -= 1
        else:
            new.append(v)
    stale = [k for k, n in pool.items() for _ in range(n)]
    return new, stale


def format_baseline(violations: Sequence[Violation]) -> str:
    header = ("# boxlint baseline — pre-existing violations the gate "
              "tolerates.\n"
              "# Regenerate with: python -m tools.boxlint --fix-baseline "
              "paddlebox_tpu/ tools/\n"
              "# Matching ignores line numbers (file + code + message), "
              "so unrelated edits\n"
              "# above a baselined site do not break the gate.\n")
    body = "\n".join(v.render() for v in
                     sorted(violations, key=lambda v: (v.path, v.line, v.code)))
    return header + body + ("\n" if body else "")


# --------------------------------------------------------------- drivers

def run_passes(files: Sequence[SourceFile],
               passes: Optional[Iterable[str]] = None) -> List[Violation]:
    from tools.boxlint import (blocking, collectives, determinism, donation,
                               flagscheck, hostsync, jitreg, lockorder,
                               locks, prints, purity, recompile, reentrancy,
                               spans, swallow, tierbudget)
    registry = {
        "purity": purity.check,
        "collectives": collectives.check,
        "flags": flagscheck.check,
        "locks": locks.check,
        "prints": prints.check,
        "spans": spans.check,
        "swallow": swallow.check,
        "blocking": blocking.check,
        "lockorder": lockorder.check,
        "reentrancy": reentrancy.check,
        "jitreg": jitreg.check,
        "tierbudget": tierbudget.check,
        "recompile": recompile.check,
        "donation": donation.check,
        "hostsync": hostsync.check,
        "determinism": determinism.check,
    }
    names = list(passes) if passes else list(registry)
    out: List[Violation] = []
    for name in names:
        out.extend(registry[name](files))
    out = [v for v in out if not _is_suppressed(files, v)]
    return sorted(out, key=lambda v: (v.path, v.line, v.code))


ALL_PASSES = ("purity", "collectives", "flags", "locks", "prints",
              "spans", "swallow", "blocking", "lockorder", "reentrancy",
              "jitreg", "tierbudget", "recompile", "donation", "hostsync",
              "determinism")

# Per-pass rule versions, folded into the result-cache digest
# (cache.tree_digest): bump a pass's version whenever its RULES change
# meaning (new code, changed detection) so persistent caches keyed on an
# older ruleset — e.g. a cache file shared across checkouts via
# BOXLINT_CACHE — can never replay a stale verdict for the new rules.
# (The digest also hashes boxlint's own sources; the stamp covers the
# cases content-hashing cannot: caches that outlive the sources that
# wrote them.)
PASS_VERSIONS: Dict[str, int] = {name: 1 for name in ALL_PASSES}

# code -> (pass name, one-line summary): the --list-rules inventory and
# the documentation source of truth for what each family checks
RULES: List[Tuple[str, str, str]] = [
    ("BX000", "-", "unparseable file (I/O, encoding or syntax error)"),
    ("BX101", "purity", "host sync / side effect inside a traced body"),
    ("BX102", "purity", "python-scalar cast of a traced value"),
    ("BX103", "purity", "numpy op on a traced value (breaks tracing)"),
    ("BX104", "purity", "value-dependent output shape inside jit"),
    ("BX105", "purity", "boolean-mask indexing inside jit"),
    ("BX201", "collectives", "collective axis name outside the registry"),
    ("BX202", "collectives", "collective with no axis argument at all"),
    ("BX301", "flags", "flag read without a registry declaration"),
    ("BX302", "flags", "flag declared but never read"),
    ("BX303", "flags", "define_flag with an empty help string"),
    ("BX304", "flags", "duplicate flag name / env-name collision"),
    ("BX305", "flags", "define_flag/get_flag with a non-literal name"),
    ("BX401", "locks", "guarded-by attr touched without its lock"),
    ("BX402", "locks", "guarded-by names a lock the class never assigns"),
    ("BX403", "locks", "threaded class with mutable shared attrs and no "
                       "guarded-by map"),
    ("BX501", "prints", "bare print in library code (use obs logging)"),
    ("BX502", "spans", "span() result discarded (records nothing)"),
    ("BX503", "swallow", "silent exception swallow without rationale"),
    ("BX601", "blocking", "blocking sink reachable while holding a lock"),
    ("BX701", "lockorder", "cycle in the lock-acquisition graph"),
    ("BX801", "reentrancy", "non-reentrant lock on a handler path"),
    ("BX802", "reentrancy", "unbounded blocking sink on a handler path"),
    ("BX901", "jitreg", "bare jax.jit in library code (instrument_jit)"),
    ("BX911", "recompile", "recompile hazard at a jit entry call site "
                           "(runtime twin: recompile sentinel)"),
    ("BX921", "donation", "donation contract breach at a jit entry "
                          "(runtime twin: donation audit)"),
    ("BX931", "hostsync", "hidden D2H sync on a device value in a "
                          "loop/lock/handler (runtime twin: transfer "
                          "ledger)"),
    ("BX932", "hostsync", "boxlint waiver without a reason string"),
    ("BX941", "determinism", "replay-nondeterministic dataflow (runtime "
                             "twin: journal parity)"),
    ("BX951", "tierbudget", "10M-literal-scale test without "
                            "@pytest.mark.slow"),
]


def _is_suppressed(files: Sequence[SourceFile], v: Violation) -> bool:
    for f in files:
        if f.rel == v.path:
            return f.suppressed(v.line, v.code)
    return False
