"""The curated blocking-sink list shared by BX6xx (blocking-under-lock)
and BX8xx (handler reentrancy).

A *sink* is a call that can park the calling thread for an unbounded (or
operator-visible) time: socket primitives, framed RPC / TcpStore ops
(reached transitively — their bodies bottom out in socket sends/recvs),
channel blocking get/put, ``time.sleep``, thread/process ``join()``,
``subprocess``, ``fsync``, ``Future.result``, condition/event waits — plus
the one curated *heavy-compute* entry, the trapezoid-AUC math, because
"quality report computed UNDER the add-path lock" (PR 13 hand-review) is
this repo's recurring stall shape and no name-based heuristic can find
"slow numpy" in general.

Each match returns ``(line, label, bound_lock_identity, has_timeout)``:

  * ``bound_lock_identity`` is non-None only for ``Condition.wait`` — a
    wait *releases* the condition's lock, so holding exactly that lock is
    the legitimate pattern, not a bug (Channel.get's shape). BX601 drops
    the bound lock from the held set before flagging.
  * ``has_timeout`` records whether the call carries an explicit bound
    (timeout kwarg / wait(n) / sleep is its own bound). BX6xx flags
    either way (holding a lock for a full timeout window still stalls
    every peer); BX8xx only flags the unbounded form — a bounded wait in
    a dying process resolves, an unbounded one is the PR-9 seal deadlock.

False-positive control is by receiver typing where names are generic:
``.get``/``.put`` only flag on receivers the call graph types as
Channel/Queue, ``.wait`` only on Condition/Event attrs, ``.join()`` only
with zero positional args (``str.join`` always has one).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Tuple

from tools.boxlint.purity import dotted

# receiver class-name tails whose get/put family blocks
_CHANNEL_TYPES = {"Channel", "Queue", "SimpleQueue", "LifoQueue",
                  "PriorityQueue"}
_SOCKET_ATTRS = {"connect": "socket.connect", "recv": "socket.recv",
                 "recv_into": "socket.recv_into",
                 "sendall": "socket.sendall", "accept": "socket.accept"}
_AUC_NAMES = {"table_auc", "trapezoid_auc"}


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def match_sink(call: ast.Call, node, index, local_types: Dict[str, str]
               ) -> Optional[Tuple[int, str, Optional[str], bool]]:
    """See module docstring. ``node``/``index`` are the callgraph context
    (receiver typing + condition bound-lock resolution)."""
    d = dotted(call.func)
    line = call.lineno
    if d:
        parts = d.split(".")
        tail = parts[-1]
        if d in ("time.sleep",) or (tail == "sleep" and len(parts) == 1):
            return (line, "time.sleep", None, True)
        if parts[0] == "subprocess":
            return (line, f"subprocess.{tail}", None, _has_timeout_kw(call))
        if d in ("os.fsync", "fsync"):
            return (line, "os.fsync", None, False)
        if tail == "create_connection" and parts[0] in ("socket",):
            # the dial idiom (FramedClient.__init__): connect + DNS
            return (line, "socket.connect", None, _has_timeout_kw(call))
        if tail in _AUC_NAMES:
            return (line, f"heavy AUC math ({tail})", None, False)
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = call.func.value
    if attr in _SOCKET_ATTRS:
        return (line, _SOCKET_ATTRS[attr], None, False)
    if attr == "join":
        # str.join always takes one positional ITERABLE; thread/process
        # joins take nothing or a numeric/None timeout. Receivers typed
        # as Thread match with any argument shape; untyped receivers
        # match zero-arg and single-CONSTANT-arg forms (join(None) is
        # the unbounded wait BX802 exists for; join(60.0) is bounded).
        tname = _receiver_type(recv, node, index, local_types)
        if not call.args:
            return (line, "Thread.join", None, _has_timeout_kw(call))
        if len(call.args) == 1 and not call.keywords:
            a = call.args[0]
            if isinstance(a, ast.Constant) and a.value is None:
                return (line, "Thread.join", None, False)
            if isinstance(a, ast.Constant) and isinstance(
                    a.value, (int, float)):
                return (line, "Thread.join", None, True)
            if tname == "Thread":   # join(timeout_var): bounded intent
                return (line, "Thread.join", None, True)
        return None
    if attr == "result" and not call.args:
        return (line, "Future.result", None, _has_timeout_kw(call))
    if attr == "wait":
        kind = _receiver_lockish(recv, node, index)
        if kind == "condition":
            ident = index.lock_identity(recv, node)
            bound = ident[0] if ident else None
            has_to = bool(call.args) or _has_timeout_kw(call)
            return (line, "Condition.wait", bound, has_to)
        if kind == "event":
            has_to = bool(call.args) or _has_timeout_kw(call)
            return (line, "Event.wait", None, has_to)
        return None
    if attr in ("get", "put", "get_many", "put_many"):
        tname = _receiver_type(recv, node, index, local_types)
        if tname in _CHANNEL_TYPES:
            has_to = _has_timeout_kw(call) or (
                attr in ("get",) and bool(call.args))
            return (line, f"Channel.{attr}", None, has_to)
        return None
    return None


def _receiver_lockish(recv: ast.AST, node, index) -> Optional[str]:
    """'condition'/'event' when the receiver is a known lock-ish attr."""
    if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
            and recv.value.id in ("self", "cls") and node.cls):
        own = index._class_in_module(node.cls, node.module)
        return index.lock_kind(own, recv.attr)
    return None


def _receiver_type(recv: ast.AST, node, index,
                   local_types: Dict[str, str]) -> Optional[str]:
    if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
            and recv.value.id in ("self", "cls") and node.cls):
        own = index._class_in_module(node.cls, node.module)
        return index._attr_type(own, recv.attr)
    if isinstance(recv, ast.Name):
        return local_types.get(recv.id)
    return None
