"""Pass 5 — library print() hygiene (BX5xx).

Library code must report through the rank-prefixed structured logging
layer (paddlebox_tpu/obs/log.py) or a MetricsSink, never bare print():
multi-process runs interleave unattributed lines on stdout, output
capture/redirection breaks, and there is no level/filter control. The
reference had the same discipline mechanically — VLOG/LOG(INFO) macros
everywhere, never printf (monitor.h, box_wrapper.cc).

Scope: files under ``paddlebox_tpu/`` except any path containing a
``tools``, ``tests`` or ``examples`` component (CLIs print their JSON
contract lines, tests print diagnostics — both are stdout-by-design).
Files OUTSIDE the repo package tree (lint fixtures, ad-hoc paths) are
checked too, so the pass is testable on inline snippets; the repo gate
only feeds it paddlebox_tpu/ + tools/ anyway.

Codes:
  BX501  bare print() call in library code (use obs.log / a MetricsSink)
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from tools.boxlint.core import SourceFile, Violation

_EXEMPT_PARTS = {"tools", "tests", "examples"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        if _exempt(f.rel):
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                out.append(Violation(
                    f.rel, node.lineno, "BX501",
                    "bare print() in library code — use paddlebox_tpu."
                    "obs.log (rank-prefixed structured lines) or a "
                    "MetricsSink"))
    return out
