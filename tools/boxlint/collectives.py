"""Pass 2 — collective axis contracts (BX2xx).

Every ``lax.psum / pmean / ppermute / all_gather / all_to_all /
psum_scatter / axis_index`` names a mesh axis, and that name must be an
axis some enclosing ``shard_map`` / ``Mesh`` actually declares — the
contract NCCL comm groups enforced by construction in the reference and
the exact one behind the seed's shard_map drift failures (a collective
over an axis the mesh no longer names fails at dispatch time, on pod
hardware only).

Static resolution strategy (documented over-approximation):

  1. Collect the declared-axis vocabulary over the whole tree: literal
     axis tuples passed to ``Mesh(...)``, literal ``axis_names=`` /
     ``axis_name=`` kwargs, ``PartitionSpec``/``P`` literals, module
     constants named ``*AXIS*`` bound to a string, and — for
     ``parallel/mesh.py`` only, the canonical declaration site — any
     literal tuple of identifier-like strings (the ("data", "model",
     "pipeline") table).
  2. For each collective call, resolve its axis argument: a string
     literal checks directly; a plain Name resolves through function
     params' literal defaults, simple local ``name = "lit"`` assignments,
     and module string constants; literal tuples check element-wise.
     Dynamic expressions (``self.axis``, ``mesh.axis_names[0]``) are
     trusted — they are derived from a live Mesh by construction.

Codes:
  BX201  collective names an axis not declared by any Mesh/shard_map
  BX202  collective with no axis argument at all
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.purity import dotted

# collective -> positional index of the axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0, "pbroadcast": 1,
}
# only axis_name: in lax collectives the ``axis=`` kwarg is the ARRAY
# axis (an int), not the mesh axis
_AXIS_KWARGS = ("axis_name",)
_SPEC_CTORS = {"P", "PartitionSpec", "jax.sharding.PartitionSpec"}
_IDENT = str.isidentifier


def _literal_strings(node: ast.AST) -> List[str]:
    """String literals in a (possibly nested) tuple/list literal."""
    out: List[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            out.extend(_literal_strings(elt))
    return out


def collect_axis_vocabulary(files: Sequence[SourceFile]) -> Set[str]:
    vocab: Set[str] = set()
    for f in files:
        canonical = f.rel.endswith("parallel/mesh.py")
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d and d.split(".")[-1] in ("Mesh", "make_mesh"):
                    # Mesh(devices, ("dp",)) — 2nd positional or axis_names=
                    if len(node.args) >= 2:
                        vocab.update(_literal_strings(node.args[1]))
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            vocab.update(_literal_strings(kw.value))
                elif d and (d in _SPEC_CTORS or d.split(".")[-1] == "PartitionSpec"):
                    for a in node.args:
                        vocab.update(_literal_strings(a))
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis_names"):
                        vocab.update(_literal_strings(kw.value))
            elif isinstance(node, ast.Assign):
                # module constants: BOX_AXIS = "dp"
                for t in node.targets:
                    if (isinstance(t, ast.Name) and "AXIS" in t.id.upper()
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        vocab.add(node.value.value)
            if canonical and isinstance(node, (ast.Tuple, ast.List)):
                lits = _literal_strings(node)
                if lits and len(lits) == len(node.elts) and all(
                        _IDENT(s) for s in lits):
                    vocab.update(lits)
    return {v for v in vocab if v and _IDENT(v)}


class _NameEnv:
    """Literal string bindings visible to a function: module constants,
    parameter defaults, and simple local assignments."""

    def __init__(self, tree: ast.Module):
        self.module: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Constant):
                v = node.value.value
                if isinstance(v, str):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module[t.id] = v

    def for_function(self, fn: Optional[ast.AST]) -> Dict[str, str]:
        env = dict(self.module)
        if fn is None:
            return env
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn.args
            pos = list(a.posonlyargs) + list(a.args)
            for arg, dflt in zip(pos[len(pos) - len(a.defaults):], a.defaults):
                self._bind(env, arg.arg, dflt)
            for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
                if dflt is not None:
                    self._bind(env, arg.arg, dflt)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._bind(env, t.id, node.value)
        return env

    def _bind(self, env: Dict[str, str], name: str, value: ast.AST) -> None:
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            env[name] = value.value
        elif isinstance(value, ast.Name) and value.id in self.module:
            env[name] = self.module[value.id]


def _axis_arg(call: ast.Call, pos: int) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _resolve_axis_names(node: ast.AST, env: Dict[str, str]
                        ) -> Optional[List[str]]:
    """Axis name(s) if statically resolvable, else None (dynamic)."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return [node.value]
        return None  # e.g. integer positional axis — not a named axis
    if isinstance(node, ast.Name):
        if node.id in env:
            return [env[node.id]]
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            got = _resolve_axis_names(elt, env)
            if got is None:
                return None
            out.extend(got)
        return out
    return None


def check(files: Sequence[SourceFile]) -> List[Violation]:
    vocab = collect_axis_vocabulary(files)
    out: List[Violation] = []
    for f in files:
        envs = _NameEnv(f.tree)
        # map every node to its enclosing function for env resolution
        owner: Dict[int, ast.AST] = {}
        for fn in ast.walk(f.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    owner[id(sub)] = fn  # innermost wins (walk order: outer
                    # first, inner overwrites)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d:
                continue
            parts = d.split(".")
            if parts[-1] not in _COLLECTIVES:
                continue
            if parts[0] not in ("jax", "lax") and "lax" not in parts:
                continue
            arg = _axis_arg(node, _COLLECTIVES[parts[-1]])
            if arg is None:
                out.append(Violation(
                    f.rel, node.lineno, "BX202",
                    f"collective {parts[-1]} without an axis name: it "
                    f"reduces over nothing (or crashes at dispatch)"))
                continue
            env = envs.for_function(owner.get(id(node)))
            names = _resolve_axis_names(arg, env)
            if names is None:
                continue  # dynamic (mesh.axis_names[...], self.axis): trusted
            for name in names:
                if name not in vocab:
                    out.append(Violation(
                        f.rel, node.lineno, "BX201",
                        f"collective {parts[-1]} over axis {name!r} which "
                        f"no Mesh/shard_map/PartitionSpec in the tree "
                        f"declares (declared: {sorted(vocab)})"))
    return out
