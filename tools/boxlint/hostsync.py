"""Pass — hidden host sync on device values (BX931/BX932).

The static twin of the PR-15 transfer ledger: ``account_d2h`` sees every
device->host copy only in AGGREGATE, after the step already stalled.
This pass pins the three contexts where a hidden sync is a bug, at the
line, before it ships:

  * **loop bodies** — ``float(loss)`` per training step serializes the
    host loop against the device stream and erases async-dispatch
    pipelining (the PaddleBox one-thread-per-GPU loop stays fast
    precisely because nothing inside it blocks on the device);
  * **under held locks** — composing with the BX601 held-lock walk: a
    D2H while holding a lock adds device latency to every contender;
  * **handler closures** — the BX8xx reentrancy roots: a device sync in
    a crash/GC/watchdog handler can block on a wedged device stream at
    the worst possible time.

Device-ness comes from the taint layer (tools/boxlint/taint.py): values
produced through any resolved jit binding or jnp/jax op are device;
taint crosses function and module boundaries through the call closure,
so a helper that ``.item()``s its parameter is charged to the loop that
feeds it a device value, with the witness chain (BX601 form).

A deliberate sync carries a REASONED waiver — ``# boxlint: BX931 ok
(metrics need host preds per step; device-collect is the zero-sync
path)`` — which also lists the site in device_contracts.txt. A waiver
without a reason is itself a finding (BX932): an unexplained exception
is invisible to review.

Codes:
  BX931  hidden D2H sync on a device value in a loop / under a lock /
         on a handler path
  BX932  boxlint waiver without a reason string
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import FuncNode, chain_str, get_index
from tools.boxlint.taint import DEVICE, Contracts, get_contracts
from tools.boxlint import reentrancy

_EXEMPT_PARTS = {"tools", "tests", "examples"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    c = get_contracts(files)
    out: List[Violation] = []
    for f in files:
        if _exempt(f.rel):
            continue
        for line, code in f.bare_waivers:
            out.append(Violation(
                f.rel, line, "BX932",
                f"waiver for {code} without a reason — write "
                f"`# boxlint: {code} ok (<why this exception is safe>)`; "
                f"a reasonless waiver hides a device-contract exception "
                f"from review"))
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        body = getattr(node.fn, "body", None)
        if not isinstance(body, list):
            continue
        st = _State(node, index, c)
        for stmt in body:
            _walk(st, stmt, frozenset(), 0)
        out.extend(st.out)
    # handler closures: any device sync reachable on a BX8xx handler path
    roots = reentrancy._collect_roots(index)
    if roots:
        reached = reentrancy._closure(roots)
        seen: Set[Tuple[str, int]] = set()
        for _nid, (node, desc, chain) in sorted(
                reached.items(), key=lambda kv: kv[1][0].file.rel):
            if _exempt(node.file.rel):
                continue
            st = _State(node, index, c)
            for line, label in _direct_syncs(st):
                key = (node.file.rel, line)
                if key in seen:
                    continue
                seen.add(key)
                out.append(Violation(
                    node.file.rel, line, "BX931",
                    f"hidden host sync on a handler path ({desc}"
                    f"{chain_str(chain)}): {label} on a device value in "
                    f"`{node.qual}` — a D2H inside a crash/GC/watchdog "
                    f"handler blocks on the device stream at the worst "
                    f"time; gate it or waive with a reason"))
    return out


class _State:
    __slots__ = ("node", "index", "c", "taint", "local", "out", "seen")

    def __init__(self, node: FuncNode, index, c: Contracts):
        self.node = node
        self.index = index
        self.c = c
        self.taint = c.fn_taint(node)
        self.local = c._local_jits(node, direct_only=False)
        self.out: List[Violation] = []
        self.seen: Set[Tuple[int, str]] = set()


def _direct_syncs(st: _State) -> List[Tuple[int, str]]:
    """(line, label) for every sync applied to a DEVICE-tainted value in
    this function, regardless of loop/lock context (the handler check)."""
    hits: List[Tuple[int, str]] = []
    own = st.index._own_statement_ids(st.node)
    for sub in ast.walk(st.node.fn):
        if id(sub) not in own or not isinstance(sub, ast.Call):
            continue
        got = st.c.sync_call(sub, st.node.module)
        if got is None:
            continue
        label, value = got
        if DEVICE in st.c.expr_origins(value, st.node, st.taint, st.local):
            hits.append((sub.lineno, label))
    return hits


def _walk(st: _State, stmt: ast.AST, held: frozenset, loop: int) -> None:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return  # deferred execution: not under this lock/loop
    if isinstance(stmt, ast.With):
        inner = held | {ident for _, ident, _ in
                        st.index.with_locks(stmt, st.node)}
        for item in stmt.items:
            _check_expr(st, item.context_expr, held, loop)
        for s in stmt.body:
            _walk(st, s, inner, loop)
        return
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        _check_expr(st, stmt.iter, held, loop)
        for s in stmt.body:
            _walk(st, s, held, loop + 1)
        for s in stmt.orelse:
            _walk(st, s, held, loop)
        return
    if isinstance(stmt, ast.While):
        _check_expr(st, stmt.test, held, loop + 1)
        for s in stmt.body:
            _walk(st, s, held, loop + 1)
        for s in stmt.orelse:
            _walk(st, s, held, loop)
        return
    _STMT_LIKE = (ast.stmt, ast.ExceptHandler, ast.match_case)
    for c in ast.iter_child_nodes(stmt):
        if isinstance(c, _STMT_LIKE):
            _walk(st, c, held, loop)
        else:
            _check_expr(st, c, held, loop)


def _check_expr(st: _State, expr: ast.AST, held: frozenset,
                loop: int) -> None:
    if expr is None or (not held and not loop):
        return
    for sub in ast.walk(expr):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        if not isinstance(sub, ast.Call):
            continue
        # direct sync on a device value at this site
        got = st.c.sync_call(sub, st.node.module)
        if got is not None:
            label, value = got
            if DEVICE in st.c.expr_origins(value, st.node, st.taint,
                                           st.local):
                _flag(st, sub.lineno, label, (), held, loop)
        # transitive: a callee that syncs the parameter we pass a
        # device value into
        for callee in st.node.call_map.get(id(sub), []):
            ps = st.c.param_syncs.get(id(callee.fn))
            if not ps:
                continue
            amap = st.c.arg_origin_map(sub, callee, st.node, st.taint,
                                       st.local)
            for q, origins in amap.items():
                if DEVICE not in origins or q not in ps:
                    continue
                label, _ln, chain = ps[q]
                _flag(st, sub.lineno, label,
                      (callee.qual,) + chain, held, loop)
                break


def _flag(st: _State, line: int, label: str, chain: Tuple[str, ...],
          held: frozenset, loop: int) -> None:
    key = (line, label)
    if key in st.seen:
        return
    st.seen.add(key)
    if loop and held:
        where = (f"in a loop body under "
                 f"{'+'.join(sorted(held))}")
        fix = ("hoist the sync past the loop AND outside the lock")
    elif loop:
        where = "in a loop body"
        fix = ("hoist it to the pass/step boundary so the device stream "
               "runs ahead")
    else:
        where = f"under {'+'.join(sorted(held))}"
        fix = ("sync outside the lock — D2H latency while holding it "
               "stalls every contender")
    st.out.append(Violation(
        st.node.file.rel, line, "BX931",
        f"hidden host sync {where} in `{st.node.qual}`: {label} on a "
        f"device value{chain_str(chain)} — the transfer ledger only sees "
        f"this in aggregate; {fix} (or waive: # boxlint: BX931 ok "
        f"(reason))"))
