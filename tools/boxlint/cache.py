"""Content-hash result cache + ``--changed`` incremental mode.

The tier-1 gate and the dev loop both pay full-tree lint cost on every
run; at 163 files that is ~6 s (≈1.4 s parse, ≈1.4 s call-graph build,
≈3 s passes) and it grows with the tree. Two layers keep that honest:

  * **Result cache** (exact): one digest over (a) every boxlint module's
    own source, (b) every linted file's (rel, sha256(text)), (c) the
    pass list. A hit replays the stored violation list without parsing a
    single AST — the dominant dev-loop case (re-running tier-1 / the
    gate with an unchanged tree) drops to content-hashing cost (~0.1 s).
    Any content change anywhere — including to boxlint itself — misses.
    The cache lives at ``tools/boxlint/.cache.json`` (gitignored), one
    entry, last-write-wins.

  * **``--changed``** (approximate, dev loop only): lints the files that
    differ from ``git merge-base HEAD <base>`` (default base: HEAD
    itself — the uncommitted-edits view; pass ``--changed-base REF``
    for branch workflows) plus untracked .py files. Cross-file passes
    (flags, collectives vocabulary, the BX6xx/7xx/8xx call graph) still
    load the full tree — their verdicts depend on it — but per-file
    passes run only on the changed files and ALL reporting is filtered
    to them. The approximation (an edit can create a violation in an
    UNCHANGED file, e.g. deleting a flag its reader still gets) is why
    the gate always runs full-tree; --changed is for the edit loop.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import PASS_VERSIONS, Violation

_SELF_DIR = os.path.dirname(os.path.abspath(__file__))
CACHE_PATH = os.path.join(_SELF_DIR, ".cache.json")


def cache_path() -> str:
    """The result-cache file: BOXLINT_CACHE env overrides the default
    (tests point it at a tmp dir so they never race a developer's warm
    cache in the working tree)."""
    return os.environ.get("BOXLINT_CACHE") or CACHE_PATH

# passes whose verdict for a file depends only on that file (+ the
# global suppression machinery); safe to restrict to changed files
PER_FILE_PASSES = ("purity", "locks", "prints", "spans", "swallow",
                   "jitreg", "tierbudget")


def collect_sources(paths: Sequence[str], root: Optional[str] = None
                    ) -> List[Tuple[str, str, str]]:
    """(abspath, rel, text) for every .py under ``paths`` — the read
    half of core.load_tree, split out so a cache hit can skip the parse
    half entirely."""
    root = root or os.getcwd()
    out: List[Tuple[str, str, str]] = []
    seen: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            candidates = [p]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        candidates.append(os.path.join(dirpath, fn))
        for f in sorted(candidates):
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    out.append((f, rel, fh.read()))
            except (OSError, UnicodeDecodeError):
                # text=None marks an unreadable file: load_tree reports it
                # as BX000 (an empty-string substitute would lint as
                # silently CLEAN and poison the cache with that verdict)
                out.append((f, rel, None))
    return out


def _self_digest(h) -> None:
    for fn in sorted(os.listdir(_SELF_DIR)):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(_SELF_DIR, fn), "rb") as fh:
            h.update(fn.encode())
            h.update(hashlib.sha256(fh.read()).digest())


def tree_digest(sources: Sequence[Tuple[str, str, str]],
                passes: Sequence[str]) -> str:
    h = hashlib.sha256()
    _self_digest(h)
    h.update(("|".join(passes)).encode())
    # per-pass rule-version stamps (core.PASS_VERSIONS): the self-digest
    # covers *this checkout's* sources, but a cache file that outlives
    # them — BOXLINT_CACHE shared across checkouts, or a verdict written
    # before a pass was upgraded — must miss when any selected pass's
    # ruleset version moved, or the new rule silently never runs
    h.update(("|".join(f"{p}={PASS_VERSIONS.get(p, 0)}"
                       for p in sorted(passes))).encode())
    for _abs, rel, text in sources:
        h.update(rel.encode())
        h.update(hashlib.sha256(
            b"\x00unreadable" if text is None else text.encode()).digest())
    return h.hexdigest()


def load_cached(digest: str, path: Optional[str] = None
                ) -> Optional[List[Violation]]:
    path = path or cache_path()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if data.get("digest") != digest:
        return None
    try:
        return [Violation(p, int(ln), c, m)
                for p, ln, c, m in data["violations"]]
    except (KeyError, TypeError, ValueError):
        return None


def store_cached(digest: str, violations: Sequence[Violation],
                 path: Optional[str] = None) -> None:
    path = path or cache_path()
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"digest": digest,
                       "violations": [[v.path, v.line, v.code, v.message]
                                      for v in violations]}, fh)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # cache is best-effort; the lint result already stands


# ------------------------------------------------------------- --changed

def _git(args: List[str], cwd: str) -> Optional[str]:
    try:
        r = subprocess.run(["git"] + args, cwd=cwd, capture_output=True,
                           text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout if r.returncode == 0 else None


def changed_files(root: Optional[str] = None,
                  base: Optional[str] = None) -> Optional[Set[str]]:
    """Repo-relative paths changed vs ``merge-base(HEAD, base)`` (plus
    untracked .py). Default base is HEAD itself — the edit loop's
    "what did I touch since the last commit" view; pass a base ref for
    branch workflows (e.g. --changed origin/main). Returns None when git
    is unavailable — the caller falls back to a full run."""
    root = root or os.getcwd()
    merge_base = "HEAD"
    if base:
        out = _git(["merge-base", "HEAD", base], root)
        if out:
            merge_base = out.strip()
    diff = _git(["diff", "--name-only", merge_base], root)
    if diff is None:
        return None
    changed = {ln.strip() for ln in diff.splitlines() if ln.strip()}
    # untracked files, expanded per-file: `git status --porcelain`
    # collapses a whole new DIRECTORY to one `?? dir/` entry, which
    # would hide every .py inside it from the changed set
    others = _git(["ls-files", "--others", "--exclude-standard"],
                  root) or ""
    for ln in others.splitlines():
        if ln.strip():
            changed.add(ln.strip())
    return {c for c in changed if c.endswith(".py")}
