"""Pass 12 — tier-1 time-budget discipline for tests (BX951).

The tier-1 suite runs under a hard wall-clock budget (``timeout 870``
in CI; ROADMAP "no worse than the seed"). The way that budget dies is
never one big commit — it's a scale test that LOOKS small: a
100-million-key loop pasted into a default-tier test function. The
conftest duration tracker warns after the fact; this pass refuses
before merge.

Flagged: a ``test_*`` function (or method) whose body contains an
integer literal >= 10_000_000 and which carries no
``@pytest.mark.slow`` decorator. Ten million of ANYTHING — keys, rows,
bytes-as-a-loop-bound — does not belong in the budgeted tier; mark it
``slow`` (the slow-inclusive suite and the TPU windows run it) or
shrink the constant. Exempt by construction: helpers outside test
functions (fixtures, module constants); shifted/multiplied forms
(``1 << 30``, ``100 * M`` — BinOps, not Constants); and exact
``2**k`` / ``2**k - 1`` values — those are sentinels and masks
(UINT64_MAX feasigns, impossible-pid markers), not work sizes. The
pass targets the pasted-scale-literal failure mode, nothing subtler.

Codes:
  BX951  unmarked test function with a >= 10_000_000 literal — mark
         @pytest.mark.slow or shrink the scale
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from tools.boxlint.core import SourceFile, Violation

_SCALE_FLOOR = 10_000_000


def _is_slow_mark(dec: ast.expr) -> bool:
    """True for pytest.mark.slow / mark.slow (bare or called), and for
    pytest.mark.parametrize over marks containing slow — any decorator
    whose attribute path ends in ``slow``."""
    node = dec
    if isinstance(node, ast.Call):
        node = node.func
    while isinstance(node, ast.Attribute):
        if node.attr == "slow":
            return True
        node = node.value
    return False


def _is_sentinel(v: int) -> bool:
    """2**k or 2**k - 1: masks and impossible-value markers, not scale."""
    return (v & (v - 1)) == 0 or (v & (v + 1)) == 0


def _big_literal(fn: ast.AST) -> int:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and not isinstance(node.value, bool)
                and node.value >= _SCALE_FLOOR
                and not _is_sentinel(node.value)):
            return node.lineno
    return 0


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        base = f.rel.replace("\\", "/").rsplit("/", 1)[-1]
        if not (base.startswith("test_") or "/tests/" in f.rel
                or f.rel.startswith("tests/")):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            if any(_is_slow_mark(d) for d in node.decorator_list):
                continue
            line = _big_literal(node)
            if line:
                out.append(Violation(
                    f.rel, node.lineno, "BX951",
                    f"{node.name} holds a >= {_SCALE_FLOOR:,} literal "
                    f"(line {line}) without @pytest.mark.slow — scale "
                    "tests run in the slow suite, the budgeted tier-1 "
                    "run has 870 s for EVERYTHING"))
    return out
