"""Pass 7 — interprocedural blocking-under-lock (BX6xx).

The recurring hand-review bug class this machine-checks (ISSUE 14): a
``with self._lock:`` body that reaches — possibly through several calls
and modules — a blocking sink. PR 7 r3 found ``FramedClient`` dials
happening INSIDE ``MeshComm._conn_lock`` (a blackholed peer froze every
thread's pulls for the whole connect timeout); PR 13 found the quality
report's AUC math computed UNDER the add-path lock (a scrape storm could
stall training adds). Both shapes flag here now, at the call site, with
the chain that reaches the sink.

Mechanics: for every function the package defines, walk its statements
tracking the set of held lock identities (``Class._attr`` /
``module._NAME`` — see callgraph.py). At each call made while locks are
held, flag when

  * the call IS a curated sink (tools/boxlint/sinks.py), or
  * the call graph shows the callee transitively reaches one.

``Condition.wait`` releases its bound lock, so that lock is dropped from
the held set before judging (Channel.get's wait under ``_mutex`` is the
pattern, not the bug) — the bound identity travels with the sink through
the transitive closure, so a ``*_locked`` helper that waits on its own
class's condition stays clean too.

A deliberate hold-across-sink (e.g. a drain that must serialize with the
close path) carries a per-line ``# boxlint: disable=BX601`` WITH a
rationale comment — the same reviewable-decision contract as BX401.

Scope: library code (``tools/``, ``tests/``, ``examples/`` path parts are
exempt, same rule as BX501 — their with-bodies are test scaffolding, and
fixtures outside the package stay checkable).

Codes:
  BX601  blocking sink reachable while holding a lock
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import (FuncNode, PackageIndex, chain_str,
                                     get_index)

_EXEMPT_PARTS = {"tools", "tests", "examples"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    sink_sum = index.sink_closure()
    out: List[Violation] = []
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        body = getattr(node.fn, "body", None)
        if not isinstance(body, list):
            continue
        seen: Set[Tuple[int, str]] = set()
        for stmt in body:
            _walk(node, stmt, frozenset(), index, sink_sum, out, seen)
    return out


def _walk(node: FuncNode, stmt: ast.AST, held: frozenset,
          index: PackageIndex, sink_sum: Dict[int, Dict[str, Tuple]],
          out: List[Violation], seen: Set[Tuple[int, str]]) -> None:
    """Statement-ordered walk mirroring locks._audit_fn: `with` grows the
    held set for its body; expression positions are checked against the
    CURRENT held set."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return  # nested defs execute later, not under this lock
    if isinstance(stmt, ast.With):
        inner = held | {ident for _, ident, _ in
                        index.with_locks(stmt, node)}
        for item in stmt.items:
            _check_expr(node, item.context_expr, held, index, sink_sum,
                        out, seen)
        for s in stmt.body:
            _walk(node, s, inner, index, sink_sum, out, seen)
        return
    _STMT_LIKE = (ast.stmt, ast.ExceptHandler, ast.match_case)
    children = list(ast.iter_child_nodes(stmt))
    for c in children:
        if isinstance(c, _STMT_LIKE):
            _walk(node, c, held, index, sink_sum, out, seen)
        elif held:
            _check_expr(node, c, held, index, sink_sum, out, seen)


def _check_expr(node: FuncNode, expr: ast.AST, held: frozenset,
                index: PackageIndex, sink_sum: Dict[int, Dict[str, Tuple]],
                out: List[Violation], seen: Set[Tuple[int, str]]) -> None:
    if not held or expr is None:
        return
    for sub in ast.walk(expr):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue  # deferred execution
        if not isinstance(sub, ast.Call):
            continue
        # direct sink at this call site
        direct = node.sink_map.get(id(sub))
        if direct is not None:
            line, label, bound, _to = direct
            eff = held - {bound} if bound else held
            if eff:
                _flag(node, sub.lineno, eff, label, (), out, seen)
        # transitive: a resolved callee that reaches a sink
        for callee in node.call_map.get(id(sub), []):
            sinks = sink_sum.get(id(callee))
            if not sinks:
                continue
            best: Optional[Tuple[str, Tuple, frozenset]] = None
            for label in sorted(sinks):
                _l, bound, _to, chain = sinks[label]
                eff = held - {bound} if bound else held
                if not eff:
                    continue
                if best is None:
                    best = (label, (callee.qual,) + chain, eff)
            if best is not None:
                label, chain, eff = best
                _flag(node, sub.lineno, eff, label, chain, out, seen)


def _flag(node: FuncNode, line: int, held: frozenset, label: str,
          chain: Tuple[str, ...], out: List[Violation],
          seen: Set[Tuple[int, str]]) -> None:
    key = (line, label)
    if key in seen:
        return
    seen.add(key)
    locks = "+".join(sorted(held))
    out.append(Violation(
        node.file.rel, line, "BX601",
        f"blocking call under {locks} in `{node.qual}`: {label}"
        f"{chain_str(chain)} — a held lock across a blocking sink stalls "
        f"every contender; move it outside the lock (or disable with "
        f"rationale)"))
