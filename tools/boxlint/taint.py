"""Traced-value dataflow layer — the substrate of the BX9xx device-contract
passes (recompile BX911, donation BX921, hostsync BX931, determinism BX941).

Two questions the runtime device plane (obs/device.py, PR 15) answers only
AFTER a bad pattern ships are answered here statically, on the
``callgraph.PackageIndex`` closure:

1. **Where are the jit entry points, and what contract did each declare?**
   ``collect_contracts`` enumerates every ``instrument_jit(...)`` /
   ``jax.jit(...)`` construction site (the BX901 registry already forces
   the former in library code) and — the part BX901 never needed —
   resolves what each wrapped callable is BOUND to, so call sites can be
   matched back to their contract:

     * module level:   ``_KERNEL = instrument_jit(fn, ...)``
     * instance attr:  ``self._step = instrument_jit(fn, ...)``
     * factory return: ``return instrument_jit(fn, ...)`` — any
       assignment from a call to the factory inherits the binding
       (``self._step = self._build_step()``, the sharded-trainer shape),
       including tuple returns position-by-position
     * dataclass field: ``TrainStepFns(step=step, ...)`` where ``step``
       is locally jit-bound — so ``self.fns.step(...)`` resolves through
       the receiver's class (typed via attr_types or a
       ``return ClassName(...)`` factory)

2. **Which host values are device values?** Results of calls through any
   jit binding are device-tainted; taint propagates through locals,
   tuple unpacks, jnp/jax ops, returns, and — via a package-wide
   fixpoint — through call arguments into callee parameters, so a helper
   in another module that ``.item()``s its argument is chargeable to the
   loop that calls it with a device value (the witness-chain form BX601
   established).

Everything here is pure stdlib ``ast``; the index is shared with the
BX6xx/7xx/8xx passes via ``callgraph.get_index`` and the contract build
is memoized per index, so the four consuming passes pay the fixpoint
once per run.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile
from tools.boxlint.callgraph import (FuncNode, PackageIndex, get_index,
                                     _module_name, _self_attr)
from tools.boxlint.purity import dotted

# the device-taint origin marker; other origins are parameter names
DEVICE = "<device>"

# wrapped-callable transformers we see through to find the underlying
# function: instrument_jit(jax.shard_map(sync, ...), ...) wraps `sync`
_SEE_THROUGH = {"shard_map", "pjit", "partial", "checkpoint", "remat"}

# attribute reads that yield HOST metadata of a device value, not the
# value itself — they must not propagate taint (int(x.shape[0]) is fine)
_HOST_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "devices",
               "nbytes", "itemsize"}

# host-sync call forms: label -> matcher handled in sync_call()
_CAST_NAMES = {"float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}


class JitEntry:
    """One jit construction site + its declared device contract."""

    __slots__ = ("rel", "line", "name", "wrapped", "donate", "static_nums",
                 "static_names", "kind")

    def __init__(self, rel: str, line: int, name: str,
                 wrapped: Optional[FuncNode], donate: Tuple[int, ...],
                 static_nums: Tuple[int, ...],
                 static_names: Tuple[str, ...], kind: str):
        self.rel = rel
        self.line = line
        self.name = name            # the instrument_jit name string
        self.wrapped = wrapped      # FuncNode of the wrapped fn, if resolved
        self.donate = donate
        self.static_nums = static_nums
        self.static_names = static_names
        self.kind = kind            # "instrument_jit" | "jax.jit"

    def describe(self) -> str:
        return (f"{self.name or '<unnamed>'} @ {self.rel}:{self.line}")


class Contracts:
    """The package's jit-entry inventory + binding maps + taint summaries."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.entries: List[JitEntry] = []
        # binding maps: each value is a JitEntry
        self.module_binds: Dict[Tuple[str, str], JitEntry] = {}
        self.attr_binds: Dict[Tuple[str, str], JitEntry] = {}
        self.field_binds: Dict[Tuple[str, str], JitEntry] = {}
        # factory fn -> positional returns (None holes for non-jit slots)
        self.factory_returns: Dict[int, List[Optional[JitEntry]]] = {}
        # fn -> ClassName for `return ClassName(...)` factories (type
        # inference for `self.fns = make_train_step(...)` receivers)
        self.class_factories: Dict[int, str] = {}
        # (ClassName, attr) -> ClassName typed through a class factory
        self.extra_attr_types: Dict[Tuple[str, str], str] = {}
        # per-function device/param taint: id(fn ast) -> name -> origins
        self._taint: Dict[int, Dict[str, FrozenSet[str]]] = {}
        # construction-site memo: the binding sweeps revisit the same
        # ast.Call several times; one JitEntry per site
        self._entry_sites: Dict[int, JitEntry] = {}
        # param -> (label, line, chain) sync summary per function
        self.param_syncs: Dict[int, Dict[str, Tuple[str, int,
                                                    Tuple[str, ...]]]] = {}
        # origins a function's return value can carry
        self.return_origins: Dict[int, FrozenSet[str]] = {}
        self._np_names: Dict[str, Set[str]] = {}
        self._device_mods: Dict[str, Set[str]] = {}
        self._build()

    # ------------------------------------------------------- construction

    def _build(self) -> None:
        for f in self.index.files:
            mod = _module_name(f.rel)
            np_names, dev_names = {"np", "numpy"}, {"jnp", "jax", "lax"}
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name == "numpy":
                            np_names.add(a.asname or "numpy")
                        if a.name in ("jax", "jax.numpy"):
                            dev_names.add(a.asname or a.name.split(".")[0])
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "jax" and node.level == 0:
                        for a in node.names:
                            if a.name in ("numpy", "lax"):
                                dev_names.add(a.asname or a.name)
            self._np_names[mod] = np_names
            self._device_mods[mod] = dev_names
        # sweep 1: direct jit-call bindings + factory returns
        for f in self.index.files:
            self._scan_bindings(f, direct_only=True)
        # sweep 2: factory-call bindings, dataclass fields, class factories
        for f in self.index.files:
            self._scan_bindings(f, direct_only=False)
        # inventory completeness: construction sites that never bind
        # (inline tuples, direct-use jits) still belong in the artifact
        for f in self.index.files:
            mod = _module_name(f.rel)
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call):
                    self._jit_call(node, mod)
        self._fixpoint()

    def _jit_call(self, call: ast.Call, mod: str) -> Optional[JitEntry]:
        """A JitEntry when ``call`` constructs a jit (instrument_jit or
        bare jax.jit), else None. Memoized per site."""
        if id(call) in self._entry_sites:
            return self._entry_sites[id(call)]
        d = dotted(call.func) or ""
        tail = d.split(".")[-1]
        kind = None
        if tail == "instrument_jit":
            kind = "instrument_jit"
        elif tail == "jit" and (d != "jit" or "jit" in
                                self.index.imports.get(mod, {})):
            imp = self.index.imports.get(mod, {}).get(d.split(".")[0], "")
            if d.split(".")[0] == "jax" or imp == "jax" or \
                    self.index.imports.get(mod, {}).get("jit", "") \
                    == "jax.jit":
                kind = "jax.jit"
        if kind is None:
            return None
        name = ""
        if kind == "instrument_jit" and len(call.args) >= 2 and \
                isinstance(call.args[1], ast.Constant) and \
                isinstance(call.args[1].value, str):
            name = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = str(kw.value.value)
        wrapped = self._resolve_wrapped(
            call.args[0] if call.args else None, mod)
        donate = self._int_tuple(call, "donate_argnums")
        static = self._int_tuple(call, "static_argnums")
        names = self._str_tuple(call, "static_argnames")
        f = self.index.modules.get(mod)
        rel = f.rel if f is not None else mod
        e = JitEntry(rel, call.lineno, name, wrapped, donate, static,
                     names, kind)
        self._entry_sites[id(call)] = e
        self.entries.append(e)
        return e

    def _resolve_wrapped(self, expr: Optional[ast.AST], mod: str,
                         _depth: int = 0) -> Optional[FuncNode]:
        if expr is None or _depth > 3:
            return None
        if isinstance(expr, ast.Call):
            # see through shard_map/partial/etc to the inner callable
            tail = (dotted(expr.func) or "").split(".")[-1]
            if tail in _SEE_THROUGH and expr.args:
                return self._resolve_wrapped(expr.args[0], mod, _depth + 1)
            return None
        d = dotted(expr)
        if not d:
            return None
        hit = self.index.functions.get((mod, d))
        if hit:
            return hit
        imp = self.index.imports.get(mod, {}).get(d)
        if imp:
            tmod, _, tname = imp.rpartition(".")
            return self.index.functions.get((tmod, tname))
        return None

    @staticmethod
    def _int_tuple(call: ast.Call, kwarg: str) -> Tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg != kwarg:
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out)
        return ()

    @staticmethod
    def _str_tuple(call: ast.Call, kwarg: str) -> Tuple[str, ...]:
        for kw in call.keywords:
            if kw.arg != kwarg:
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
        return ()

    # ---------------------------------------------------------- bindings

    def _scan_bindings(self, f: SourceFile, direct_only: bool) -> None:
        mod = _module_name(f.rel)
        # module-level assigns
        for stmt in f.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                e = self._value_entry(stmt.value, mod, None, {},
                                      direct_only)
                if e is not None:
                    self.module_binds.setdefault(
                        (mod, stmt.targets[0].id), e)
        # per-function assigns / returns
        for node in self.index.nodes:
            if node.file is not f:
                continue
            local = self._local_jits(node, direct_only)
            cls = node.cls
            for sub in ast.walk(node.fn):
                if isinstance(sub, ast.Assign):
                    self._bind_assign(sub, node, cls, local, direct_only)
                elif isinstance(sub, ast.Return) and sub.value is not None:
                    self._bind_return(sub.value, node, local, direct_only)

    def _local_jits(self, node: FuncNode, direct_only: bool
                    ) -> Dict[str, JitEntry]:
        out: Dict[str, JitEntry] = {}
        for _ in range(2):   # two sweeps: `a = jit(...)`, `b = a if c else a`
            for sub in ast.walk(node.fn):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)):
                    continue
                e = self._value_entry(sub.value, node.module, node, out,
                                      direct_only)
                if e is not None:
                    out.setdefault(sub.targets[0].id, e)
        return out

    def _value_entry(self, value: ast.AST, mod: str,
                     ctx: Optional[FuncNode], local: Dict[str, JitEntry],
                     direct_only: bool) -> Optional[JitEntry]:
        """The JitEntry an assigned VALUE denotes, if any: a direct jit
        construction, a jit-bound name, an either-branch-bound IfExp, or
        (sweep 2) a call to a jit factory."""
        if isinstance(value, ast.IfExp):
            return (self._value_entry(value.body, mod, ctx, local,
                                      direct_only)
                    or self._value_entry(value.orelse, mod, ctx, local,
                                         direct_only))
        if isinstance(value, ast.Name):
            return local.get(value.id) or self.module_binds.get(
                (mod, value.id))
        if not isinstance(value, ast.Call):
            return None
        e = self._jit_call(value, mod)
        if e is not None:
            return e
        if direct_only:
            return None
        # sweep 2: call to a factory that returns a jit
        for callee in self._callees(value, mod, ctx):
            rets = self.factory_returns.get(id(callee.fn))
            if rets and len(rets) == 1 and rets[0] is not None:
                return rets[0]
        return None

    def _callees(self, call: ast.Call, mod: str,
                 ctx: Optional[FuncNode]) -> List[FuncNode]:
        if ctx is not None:
            got = ctx.call_map.get(id(call))
            if got:
                return got
            return []
        # module-level binding (``step = make_step()``): no call_map —
        # resolve the factory by name through the module / its imports
        d = dotted(call.func)
        if not d:
            return []
        hit = self.index.functions.get((mod, d))
        if hit is None:
            imp = self.index.imports.get(mod, {}).get(d)
            if imp:
                tmod, _, tname = imp.rpartition(".")
                hit = self.index.functions.get((tmod, tname))
        return [hit] if hit is not None else []

    def _bind_assign(self, stmt: ast.Assign, node: FuncNode,
                     cls: Optional[str], local: Dict[str, JitEntry],
                     direct_only: bool) -> None:
        if len(stmt.targets) != 1:
            return
        t = stmt.targets[0]
        # tuple-unpack from a tuple-returning factory call (sweep 2)
        if isinstance(t, ast.Tuple) and isinstance(stmt.value, ast.Call) \
                and not direct_only:
            for callee in self._callees(stmt.value, node.module, node):
                rets = self.factory_returns.get(id(callee.fn))
                if not rets or len(rets) != len(t.elts):
                    continue
                for elt, e in zip(t.elts, rets):
                    if e is None:
                        continue
                    attr = _self_attr(elt)
                    if attr and cls:
                        self.attr_binds.setdefault((cls, attr), e)
            return
        e = self._value_entry(stmt.value, node.module, node, local,
                              direct_only)
        attr = _self_attr(t)
        if attr and cls:
            if e is not None:
                self.attr_binds.setdefault((cls, attr), e)
            elif not direct_only and isinstance(stmt.value, ast.Call):
                # `self.fns = make_train_step(...)`: type the attr
                # through the class factory so field binds resolve
                for callee in self._callees(stmt.value, node.module, node):
                    cname = self.class_factories.get(id(callee.fn))
                    if cname:
                        self.extra_attr_types.setdefault((cls, attr),
                                                         cname)

    def _bind_return(self, value: ast.AST, node: FuncNode,
                     local: Dict[str, JitEntry], direct_only: bool) -> None:
        elts = value.elts if isinstance(value, ast.Tuple) else [value]
        rets = [self._value_entry(e, node.module, node, local, direct_only)
                for e in elts]
        if any(r is not None for r in rets):
            cur = self.factory_returns.get(id(node.fn))
            if cur is None or sum(r is not None for r in rets) > \
                    sum(r is not None for r in cur):
                self.factory_returns[id(node.fn)] = rets
        if isinstance(value, ast.Call):
            tail = (dotted(value.func) or "").split(".")[-1]
            if tail and tail[0].isupper() and \
                    self.index.class_by_name(tail) is not None:
                self.class_factories.setdefault(id(node.fn), tail)
            if not direct_only:
                # dataclass fields bound at construction:
                # TrainStepFns(step=step, ...)
                for kw in value.keywords:
                    if kw.arg is None:
                        continue
                    e = self._value_entry(kw.value, node.module, node,
                                          local, direct_only)
                    if e is not None and tail:
                        self.field_binds.setdefault((tail, kw.arg), e)

    # ------------------------------------------------- call-site resolution

    def receiver_class(self, expr: ast.AST, ctx: FuncNode) -> Optional[str]:
        """Class name of `expr` when it denotes a typed receiver
        (self.attr via attr_types / class factories, module singleton)."""
        attr = _self_attr(expr)
        if attr and ctx.cls:
            own = self.index._class_in_module(ctx.cls, ctx.module)
            t = self.index._attr_type(own, attr) if own else None
            if t:
                return t
            # walk the name-keyed base chain for factory-typed attrs
            seen, names = set(), [ctx.cls]
            while names:
                c = names.pop()
                if c in seen:
                    continue
                seen.add(c)
                hit = self.extra_attr_types.get((c, attr))
                if hit:
                    return hit
                cn = self.index.class_by_name(c)
                if cn is not None:
                    names.extend(cn.bases)
            return None
        if isinstance(expr, ast.Name):
            return self.index.module_vars.get(ctx.module, {}).get(expr.id)
        return None

    def entry_for_call(self, call: ast.Call, ctx: FuncNode,
                       local: Optional[Dict[str, JitEntry]] = None
                       ) -> Optional[JitEntry]:
        """The JitEntry a call site invokes, resolved through every
        binding form, else None."""
        func = call.func
        mod = ctx.module
        if isinstance(func, ast.Name):
            if local and func.id in local:
                return local[func.id]
            hit = self.module_binds.get((mod, func.id))
            if hit:
                return hit
            imp = self.index.imports.get(mod, {}).get(func.id)
            if imp:
                tmod, _, tname = imp.rpartition(".")
                return self.module_binds.get((tmod, tname))
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            seen, names = set(), [ctx.cls] if ctx.cls else []
            while names:
                c = names.pop()
                if c in seen:
                    continue
                seen.add(c)
                hit = self.attr_binds.get((c, meth))
                if hit:
                    return hit
                cn = self.index.class_by_name(c)
                if cn is not None:
                    names.extend(cn.bases)
            return None
        # typed receiver: self.fns.step(...) / SINGLETON.step(...)
        cname = self.receiver_class(recv, ctx)
        if cname:
            return (self.field_binds.get((cname, meth))
                    or self.attr_binds.get((cname, meth)))
        # module receiver: mod.STEP(...)
        rd = dotted(recv)
        if rd:
            imp = self.index.imports.get(mod, {}).get(rd.split(".")[0])
            if imp:
                return self.module_binds.get((imp, meth))
        return None

    # ------------------------------------------------------ taint machinery

    def sync_call(self, call: ast.Call, mod: str
                  ) -> Optional[Tuple[str, ast.AST]]:
        """(label, value-expr) when ``call`` is a host-sync form: the
        float()/int()/bool() casts, .item()/.tolist(), np.asarray/np.array
        and jax.device_get — each a blocking D2H when applied to a device
        value."""
        func = call.func
        if isinstance(func, ast.Name) and func.id in _CAST_NAMES \
                and len(call.args) == 1:
            return (f"{func.id}()", call.args[0])
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS \
                and not call.args:
            return (f".{func.attr}()", func.value)
        d = dotted(func) or ""
        parts = d.split(".")
        if len(parts) == 2 and parts[1] in ("asarray", "array") \
                and parts[0] in self._np_names.get(mod, ()) and call.args:
            return (f"{parts[0]}.{parts[1]}()", call.args[0])
        if d in ("jax.device_get",) and call.args:
            return ("jax.device_get()", call.args[0])
        return None

    def expr_origins(self, expr: Optional[ast.AST], ctx: FuncNode,
                     taint: Dict[str, FrozenSet[str]],
                     local: Dict[str, JitEntry]) -> FrozenSet[str]:
        """Taint origins of an expression: DEVICE and/or parameter names."""
        if expr is None:
            return frozenset()
        if isinstance(expr, ast.Name):
            return taint.get(expr.id, frozenset())
        if isinstance(expr, ast.Starred):
            return self.expr_origins(expr.value, ctx, taint, local)
        if isinstance(expr, ast.Subscript):
            return self.expr_origins(expr.value, ctx, taint, local)
        if isinstance(expr, ast.Attribute):
            if expr.attr in _HOST_ATTRS:
                return frozenset()
            return self.expr_origins(expr.value, ctx, taint, local)
        if isinstance(expr, ast.Call):
            return self.call_result_origins(expr, ctx, taint, local)
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for e in expr.elts:
                out |= self.expr_origins(e, ctx, taint, local)
            return frozenset(out)
        if isinstance(expr, ast.BinOp):
            return (self.expr_origins(expr.left, ctx, taint, local)
                    | self.expr_origins(expr.right, ctx, taint, local))
        if isinstance(expr, ast.UnaryOp):
            return self.expr_origins(expr.operand, ctx, taint, local)
        if isinstance(expr, ast.IfExp):
            return (self.expr_origins(expr.body, ctx, taint, local)
                    | self.expr_origins(expr.orelse, ctx, taint, local))
        if isinstance(expr, ast.NamedExpr):
            return self.expr_origins(expr.value, ctx, taint, local)
        return frozenset()

    def call_result_origins(self, call: ast.Call, ctx: FuncNode,
                            taint: Dict[str, FrozenSet[str]],
                            local: Dict[str, JitEntry]) -> FrozenSet[str]:
        if self.entry_for_call(call, ctx, local) is not None:
            return frozenset({DEVICE})
        if self.sync_call(call, ctx.module) is not None:
            return frozenset()      # sync RESULT is a host value
        d = dotted(call.func) or ""
        head = d.split(".")[0]
        if head and head in self._device_mods.get(ctx.module, ()):
            if d in ("jax.device_get",):
                return frozenset()
            # a jnp/jax/lax op yields a device value (and an op over
            # tainted inputs certainly does)
            return frozenset({DEVICE})
        # resolved package call: map return origins through the args
        out: Set[str] = set()
        for callee in ctx.call_map.get(id(call), []):
            rets = self.return_origins.get(id(callee.fn))
            if not rets:
                continue
            if DEVICE in rets:
                out.add(DEVICE)
            amap = self.arg_origin_map(call, callee, ctx, taint, local)
            for p in rets:
                if p in amap:
                    out |= amap[p]
        return frozenset(out)

    def arg_origin_map(self, call: ast.Call, callee: FuncNode,
                       ctx: FuncNode, taint: Dict[str, FrozenSet[str]],
                       local: Dict[str, JitEntry]
                       ) -> Dict[str, FrozenSet[str]]:
        """callee param name -> origins of the arg the call passes it."""
        params = [a.arg for a in callee.fn.args.args] \
            if hasattr(callee.fn, "args") else []
        offset = 0
        if params and params[0] in ("self", "cls") and \
                isinstance(call.func, ast.Attribute):
            offset = 1
        out: Dict[str, FrozenSet[str]] = {}
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                continue
            pi = i + offset
            if pi < len(params):
                o = self.expr_origins(arg, ctx, taint, local)
                if o:
                    out[params[pi]] = o
        for kw in call.keywords:
            if kw.arg and kw.arg in params:
                o = self.expr_origins(kw.value, ctx, taint, local)
                if o:
                    out[kw.arg] = o
        return out

    def fn_taint(self, node: FuncNode) -> Dict[str, FrozenSet[str]]:
        """name -> origins for one function: parameters carry their own
        name as origin (resolved to device-ness at call sites), names
        assigned from jit-entry calls / jnp ops carry DEVICE."""
        cached = self._taint.get(id(node.fn))
        if cached is not None:
            return cached
        taint: Dict[str, FrozenSet[str]] = {}
        args = getattr(node.fn, "args", None)
        if args is not None:
            names = [a.arg for a in args.posonlyargs + args.args
                     + args.kwonlyargs]
            for n in names:
                if n in ("self", "cls"):
                    continue
                taint[n] = frozenset({n})
        local = self._local_jits(node, direct_only=False)
        own = self.index._own_statement_ids(node)
        for _ in range(2):      # forward fixpoint over re-assignments
            for sub in ast.walk(node.fn):
                if id(sub) not in own:
                    continue
                if isinstance(sub, ast.Assign):
                    o = self.expr_origins(sub.value, node, taint, local)
                    for t in sub.targets:
                        self._taint_target(t, sub.value, o, node, taint,
                                           local)
                elif isinstance(sub, ast.AnnAssign) and sub.value:
                    o = self.expr_origins(sub.value, node, taint, local)
                    self._taint_target(sub.target, sub.value, o, node,
                                       taint, local)
                elif isinstance(sub, ast.AugAssign):
                    o = self.expr_origins(sub.value, node, taint, local)
                    if o and isinstance(sub.target, ast.Name):
                        taint[sub.target.id] = taint.get(
                            sub.target.id, frozenset()) | o
                elif isinstance(sub, ast.For):
                    o = self.expr_origins(sub.iter, node, taint, local)
                    if o:
                        self._taint_target(sub.target, None, o, node,
                                           taint, local)
        self._taint[id(node.fn)] = taint
        return taint

    def _taint_target(self, target: ast.AST, value: Optional[ast.AST],
                      origins: FrozenSet[str], node: FuncNode,
                      taint: Dict[str, FrozenSet[str]],
                      local: Dict[str, JitEntry]) -> None:
        if isinstance(target, ast.Name):
            if origins:
                taint[target.id] = origins
            elif target.id in taint and not taint[target.id] == \
                    frozenset({target.id}):
                # rebound to an untainted value: clear derived taint
                # (parameter self-origin stays — the param name is the
                # summary key, and rebinding params is rare)
                taint.pop(target.id, None)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            velts = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                     and len(value.elts) == len(target.elts) else None)
            for i, t in enumerate(target.elts):
                o = origins
                if velts is not None:
                    o = self.expr_origins(velts[i], node, taint, local)
                self._taint_target(t, velts[i] if velts else None, o,
                                   node, taint, local)

    # ----------------------------------------------------- global fixpoint

    def _fixpoint(self) -> None:
        """Two package-wide summaries to fixpoint: which parameters reach
        a host sync inside their function (param_syncs, with witness
        chains), and which origins a function can return."""
        for node in self.index.nodes:
            self._scan_summaries(node)
        # propagate param syncs through call edges: caller's arg taint
        # names its own params -> those params inherit the callee's sync
        for _ in range(6):
            changed = False
            for node in self.index.nodes:
                taint = self.fn_taint(node)
                local = self._local_jits(node, direct_only=False)
                own = self.index._own_statement_ids(node)
                for sub in ast.walk(node.fn):
                    if id(sub) not in own or not isinstance(sub, ast.Call):
                        continue
                    for callee in node.call_map.get(id(sub), []):
                        ps = self.param_syncs.get(id(callee.fn))
                        if not ps:
                            continue
                        amap = self.arg_origin_map(sub, callee, node,
                                                   taint, local)
                        mine = self.param_syncs.setdefault(id(node.fn), {})
                        for q, (label, _ln, chain) in ps.items():
                            if q not in amap or len(chain) >= 5:
                                continue
                            for origin in amap[q]:
                                if origin == DEVICE:
                                    continue
                                if origin not in mine:
                                    mine[origin] = (
                                        label, sub.lineno,
                                        (callee.qual,) + chain)
                                    changed = True
            if not changed:
                break

    def _scan_summaries(self, node: FuncNode) -> None:
        taint = self.fn_taint(node)
        local = self._local_jits(node, direct_only=False)
        own = self.index._own_statement_ids(node)
        syncs = self.param_syncs.setdefault(id(node.fn), {})
        rets: Set[str] = set()
        for sub in ast.walk(node.fn):
            if id(sub) not in own:
                continue
            if isinstance(sub, ast.Call):
                hit = self.sync_call(sub, node.module)
                if hit is not None:
                    label, value = hit
                    for origin in self.expr_origins(value, node, taint,
                                                    local):
                        if origin != DEVICE and origin not in syncs:
                            syncs[origin] = (label, sub.lineno, ())
            elif isinstance(sub, ast.Return) and sub.value is not None:
                rets |= self.expr_origins(sub.value, node, taint, local)
        if rets:
            self.return_origins[id(node.fn)] = frozenset(rets)


# ---------------------------------------------------------------- memo

_CACHE: List[Tuple[PackageIndex, Contracts]] = []


def get_contracts(files: Sequence[SourceFile]) -> Contracts:
    index = get_index(files)
    for idx, c in _CACHE:
        if idx is index:
            return c
    c = Contracts(index)
    del _CACHE[:]
    _CACHE.append((index, c))
    return c


# ----------------------------------------------------------- the artifact

def render_inventory(files: Sequence[SourceFile]) -> str:
    """The committed device-contract inventory (device_contracts.txt, the
    lock_graph.txt pattern): every jit entry with its declared donation /
    static keying, every reasoned host-sync waiver, and the pinned counts
    line review diffs against."""
    c = get_contracts(files)
    lines = [
        "# Device-contract inventory (boxlint BX9xx taint layer).",
        "# entry : site [wraps fn] donate=(..) static=(..) — one line per",
        "# instrument_jit/jax.jit construction the taint layer resolved.",
        "# Regenerate with: python -m tools.boxlint --device-contracts "
        "paddlebox_tpu/",
        "# The waiver section lists every reasoned `# boxlint: BXnnn ok",
        "# (reason)` site — the reviewed exceptions to the BX911/921/931/",
        "# 941 contracts; reasonless waivers are BX932 findings, never",
        "# listed here.",
        "",
    ]
    entries = sorted(c.entries, key=lambda e: (e.rel, e.line))
    donating = sum(1 for e in entries if e.donate)
    static_keyed = sum(1 for e in entries
                       if e.static_nums or e.static_names)
    for e in entries:
        bits = [f"{e.name or '<unnamed>'} : {e.rel}:{e.line}"]
        if e.wrapped is not None:
            bits.append(f"wraps {e.wrapped.qual}")
        if e.donate:
            bits.append(f"donate={tuple(e.donate)}")
        if e.static_nums:
            bits.append(f"static={tuple(e.static_nums)}")
        if e.static_names:
            bits.append(f"static_names={tuple(e.static_names)}")
        if e.kind != "instrument_jit":
            bits.append(f"[{e.kind}]")
        lines.append(" ".join(bits))
    lines.append("")
    waivers = []
    for f in sorted(c.index.files, key=lambda f: f.rel):
        for line, (code, reason) in sorted(f.waivers.items()):
            waivers.append(f"waived {code} : {f.rel}:{line} ({reason})")
    lines.extend(waivers)
    lines.append("")
    lines.append(f"# {len(entries)} jit entries ({donating} donating, "
                 f"{static_keyed} static-keyed), {len(waivers)} reasoned "
                 f"waivers")
    return "\n".join(lines) + "\n"
