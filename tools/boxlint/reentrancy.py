"""Pass 9 — handler reentrancy (BX8xx).

The PR-9 r2 seal-deadlock shape, machine-checked: ``sys.excepthook`` /
``threading.excepthook`` / signal handlers / the stall watchdog's fire
path / ``__del__`` all run at ARBITRARY points — a fatal signal can
interrupt a thread midway through a critical section, and the handler
then runs ON THAT THREAD. If the handler's reach acquires a
non-reentrant lock the interrupted code may already hold, the dying
process deadlocks instead of sealing its flight recorder (the exact bug:
``tracer._reg_lock`` was a plain Lock until the hand review made it an
RLock). Same story for unbounded blocking: a handler parked forever on a
socket or an un-timed-out join turns "crash with artifact" into "hang
with nothing".

Roots (curated):
  * functions assigned to ``sys.excepthook`` / ``threading.excepthook``
  * handler arguments of ``signal.signal(...)``
  * ``fire`` / ``render_dump`` methods of classes whose name contains
    ``Watchdog`` (the stall watchdog dumps from its daemon thread while
    every other thread is wedged mid-whatever)
  * every ``__del__`` (GC runs it wherever an allocation happens)

Codes:
  BX801  non-reentrant lock acquired on a handler path while
         non-handler code also takes it (make it an RLock, or disable
         with a rationale explaining why the pair can't interleave)
  BX802  blocking sink without a timeout reachable from a handler
         (bounded waits resolve in a dying process; unbounded ones hang
         the crash path)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import (FuncNode, PackageIndex, chain_str,
                                     get_index)
from tools.boxlint.purity import dotted

_EXEMPT_PARTS = {"tools", "tests", "examples"}
_HOOK_TARGETS = {"sys.excepthook", "threading.excepthook"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def _collect_roots(index: PackageIndex) -> List[Tuple[FuncNode, str]]:
    """(node, root description) for every curated handler entry point."""
    roots: List[Tuple[FuncNode, str]] = []
    for f in index.files:
        mod = None
        for m, sf in index.modules.items():
            if sf is f:
                mod = m
                break
        if mod is None:
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    d = dotted(t)
                    if d in _HOOK_TARGETS:
                        fn = _resolve_name(node.value, mod, index)
                        if fn is not None:
                            roots.append((fn, d))
            elif isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in ("signal.signal",) and len(node.args) >= 2:
                    fn = _resolve_name(node.args[1], mod, index)
                    if fn is not None:
                        roots.append((fn, "signal handler"))
    for name, class_list in index.classes.items():
        for cn in class_list:
            if "Watchdog" in cn.name:
                for meth in ("fire", "render_dump"):
                    if meth in cn.methods:
                        roots.append((cn.methods[meth],
                                      f"{cn.name} fire path"))
            if "__del__" in cn.methods:
                roots.append((cn.methods["__del__"],
                              f"{cn.name}.__del__"))
    return roots


def _resolve_name(expr: ast.AST, mod: str,
                  index: PackageIndex) -> Optional[FuncNode]:
    d = dotted(expr)
    if not d:
        return None
    hit = index.functions.get((mod, d))
    if hit:
        return hit
    imp = index.imports.get(mod, {}).get(d)
    if imp:
        tmod, _, tname = imp.rpartition(".")
        return index.functions.get((tmod, tname))
    return None


def _closure(roots: Sequence[Tuple[FuncNode, str]]
             ) -> Dict[int, Tuple[FuncNode, str, Tuple[str, ...]]]:
    """BFS from the roots: id(node) -> (node, root description, chain
    from the root to this node). First (shortest) reach wins."""
    reached: Dict[int, Tuple[FuncNode, str, Tuple[str, ...]]] = {}
    work: List[Tuple[FuncNode, str, Tuple[str, ...]]] = [
        (n, desc, ()) for n, desc in roots]
    while work:
        node, desc, chain = work.pop(0)
        if id(node) in reached:
            continue
        reached[id(node)] = (node, desc, chain)
        if len(chain) >= 8:
            continue
        for _line, callee in node.calls:
            if id(callee) not in reached:
                work.append((callee, desc, chain + (callee.qual,)))
    return reached


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    roots = _collect_roots(index)
    if not roots:
        return []
    reached = _closure(roots)
    lock_sum = index.lock_closure()
    # identities the non-handler world acquires (directly or through its
    # calls) — the contention side of the BX801 pair
    outside: Set[str] = set()
    for node in index.nodes:
        if id(node) in reached:
            continue
        for ident in lock_sum.get(id(node), {}):
            outside.add(ident)
    out: List[Violation] = []
    seen: Set[Tuple[str, int, str]] = set()
    for nid, (node, desc, chain) in sorted(
            reached.items(), key=lambda kv: kv[1][0].file.rel):
        if _exempt(node.file.rel):
            continue
        for line, ident, reentrant in node.direct_locks:
            if reentrant or ident not in outside:
                continue
            key = (node.file.rel, line, ident)
            if key in seen:
                continue
            seen.add(key)
            out.append(Violation(
                node.file.rel, line, "BX801",
                f"non-reentrant {ident} acquired on a handler path "
                f"({desc}{chain_str(chain)}) while non-handler code also "
                f"takes it — a handler interrupting the holder deadlocks "
                f"the dying process; use an RLock (or disable with "
                f"rationale)"))
        for line, label, _bound, has_to in node.direct_sinks:
            if has_to:
                continue
            key = (node.file.rel, line, label)
            if key in seen:
                continue
            seen.add(key)
            out.append(Violation(
                node.file.rel, line, "BX802",
                f"blocking sink without timeout on a handler path "
                f"({desc}{chain_str(chain)}): {label} — an unbounded "
                f"wait hangs the crash/teardown path; add a timeout (or "
                f"disable with rationale)"))
    return out
