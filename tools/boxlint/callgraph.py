"""Package-wide call-graph closure — the shared substrate of the
interprocedural concurrency passes (BX6xx blocking-under-lock, BX7xx
lock-order graph, BX8xx handler reentrancy).

``purity.py`` closes over *same-module* calls, which is exactly right for
jit entry points (a traced function crossing a module boundary is rare and
deliberate). The concurrency bug classes this substrate serves are the
opposite: a ``with self._conn_lock:`` body in ``fleet/mesh_comm.py``
reaching ``socket.connect`` happens THROUGH ``utils/rpc.py`` (the PR-7 r3
hand-review finding), and the PR-9 seal deadlock threaded
``obs/flight.py -> obs/tracer.py``. So the index here resolves calls
across the whole linted tree:

  * bare names      -> same-module defs, then ``from m import f`` targets
  * ``mod.f(...)``  -> defs of the imported package module
  * ``self.m(...)`` -> methods of the enclosing class, then its bases
                       (resolved by name through the package class index)
  * ``self.attr.m(...)`` / ``var.m(...)`` -> methods of the class the
                       attr/var was assigned from (``self._chan =
                       Channel(...)`` types ``self._chan``; first
                       assignment wins for locals)
  * ``ClassName(...)`` -> the class's ``__init__`` (constructors that
                       dial sockets are the historical bug shape)

Everything unresolvable is simply absent from the graph — the passes
over-approximate only through the curated *direct* sink name matches.

Lock identities are ``ClassName._attr`` (or ``module._NAME`` for
module-level locks): instances are conflated, which is the standard
static-lock-analysis approximation and the same key the runtime twin
(``utils/lockwatch.py``) registers, so static edges and dynamic
acquisition orders share one vocabulary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile
from tools.boxlint.purity import dotted

# Constructor tails recognized as lock-like objects. make_lock/make_rlock/
# make_condition are the lockwatch factories (utils/lockwatch.py): the
# runtime twin must not blind the static plane.
_LOCK_CTORS = {"Lock": "lock", "make_lock": "lock",
               "RLock": "rlock", "make_rlock": "rlock"}
_COND_CTORS = {"Condition": "condition", "make_condition": "condition"}
_EVENT_CTORS = {"Event": "event"}


class FuncNode:
    """One function/method definition in the package."""

    __slots__ = ("fn", "file", "cls", "module", "name", "qual",
                 "calls", "direct_sinks", "direct_locks",
                 "call_map", "sink_map")

    def __init__(self, fn: ast.AST, file: SourceFile, cls: Optional[str],
                 module: str):
        self.fn = fn
        self.file = file
        self.cls = cls
        self.module = module
        self.name = getattr(fn, "name", "<lambda>")
        self.qual = (f"{cls}.{self.name}" if cls else self.name)
        # filled by PackageIndex._link():
        self.calls: List[Tuple[int, "FuncNode"]] = []   # (line, callee)
        # (line, sink label, bound-lock identity or None, has_timeout)
        self.direct_sinks: List[Tuple[int, str, Optional[str], bool]] = []
        # (line, lock identity, reentrant?) for `with <lock>` acquisitions
        self.direct_locks: List[Tuple[int, str, bool]] = []
        # id(ast.Call) -> resolved callees / sink tuple (the per-site view
        # the statement-ordered walks in blocking.py need)
        self.call_map: Dict[int, List["FuncNode"]] = {}
        self.sink_map: Dict[int, Tuple[int, str, Optional[str], bool]] = {}


class ClassNode:
    __slots__ = ("name", "file", "node", "module", "bases", "methods",
                 "lock_attrs", "cond_binds", "attr_types")

    def __init__(self, node: ast.ClassDef, file: SourceFile, module: str):
        self.name = node.name
        self.file = file
        self.node = node
        self.module = module
        self.bases: List[str] = [b for b in (dotted(x) for x in node.bases)
                                 if b]
        self.methods: Dict[str, FuncNode] = {}
        # attr -> kind in {"lock", "rlock", "condition", "event"}
        self.lock_attrs: Dict[str, str] = {}
        # condition attr -> the lock attr it wraps (None = its own lock)
        self.cond_binds: Dict[str, Optional[str]] = {}
        # attr -> class name (tail) it was constructed from
        self.attr_types: Dict[str, str] = {}


def _module_name(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _self_attr(node: ast.AST) -> str:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return node.attr
    return ""


class PackageIndex:
    """All defs/classes/imports of one linted tree, with resolved call,
    lock-acquisition, and sink edges (see module docstring)."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        self.modules: Dict[str, SourceFile] = {}
        self.classes: Dict[str, List[ClassNode]] = {}
        self.functions: Dict[Tuple[str, str], FuncNode] = {}
        self.nodes: List[FuncNode] = []
        self.imports: Dict[str, Dict[str, str]] = {}   # module -> local->dotted
        self.module_locks: Dict[str, Dict[str, str]] = {}  # module -> name->kind
        self.module_vars: Dict[str, Dict[str, str]] = {}   # module -> var->class
        self._by_fnid: Dict[int, FuncNode] = {}
        for f in self.files:
            self._index_file(f)
        for f in self.files:
            self._link_file(f)

    # ------------------------------------------------------------ indexing

    def _index_file(self, f: SourceFile) -> None:
        mod = _module_name(f.rel)
        self.modules[mod] = f
        imports: Dict[str, str] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else
                        alias.name.split(".")[0])
                    if alias.asname:
                        imports[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this module
                    parts = mod.split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imports[alias.asname or alias.name] = (
                        f"{base}.{alias.name}" if base else alias.name)
        self.imports[mod] = imports

        mlocks: Dict[str, str] = {}
        mvars: Dict[str, str] = {}
        for stmt in f.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call):
                tail = (dotted(stmt.value.func) or "").split(".")[-1]
                kind = _LOCK_CTORS.get(tail) or _COND_CTORS.get(tail)
                for t in stmt.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if kind:
                        mlocks[t.id] = kind
                    elif tail and tail[0].isupper():
                        # module singleton: TRACER = SpanTracer(); typed
                        # so handler closures resolve TRACER.m() calls
                        mvars.setdefault(t.id, tail)
        self.module_locks[mod] = mlocks
        self.module_vars[mod] = mvars

        for stmt in f.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(stmt, f, None, mod)
            elif isinstance(stmt, ast.ClassDef):
                cn = ClassNode(stmt, f, mod)
                self.classes.setdefault(cn.name, []).append(cn)
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fn = self._add_func(sub, f, cn.name, mod)
                        cn.methods[sub.name] = fn
                self._scan_class_attrs(cn)

    def _add_func(self, fn: ast.AST, f: SourceFile, cls: Optional[str],
                  mod: str) -> FuncNode:
        node = FuncNode(fn, f, cls, mod)
        self.nodes.append(node)
        self._by_fnid[id(fn)] = node
        self.functions.setdefault((mod, node.qual), node)
        # nested defs resolve by bare name within the module (closure
        # helpers), same convention as purity._Scope
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not fn and id(sub) not in self._by_fnid:
                nested = FuncNode(sub, f, cls, mod)
                self.nodes.append(nested)
                self._by_fnid[id(sub)] = nested
                self.functions.setdefault((mod, nested.qual), nested)
        return node

    def _scan_class_attrs(self, cn: ClassNode) -> None:
        for sub in ast.walk(cn.node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            value = sub.value
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            if value is None or not isinstance(value, ast.Call):
                continue
            tail = (dotted(value.func) or "").split(".")[-1]
            for t in targets:
                attr = _self_attr(t)
                if not attr:
                    continue
                if tail in _LOCK_CTORS:
                    cn.lock_attrs[attr] = _LOCK_CTORS[tail]
                elif tail in _COND_CTORS:
                    cn.lock_attrs[attr] = "condition"
                    bound = None
                    if value.args:
                        bound = _self_attr(value.args[0]) or None
                    cn.cond_binds[attr] = bound
                elif tail in _EVENT_CTORS:
                    cn.lock_attrs[attr] = "event"
                elif tail and tail[0].isupper():
                    cn.attr_types.setdefault(attr, tail)

    # ----------------------------------------------------------- resolution

    def class_by_name(self, name: str) -> Optional[ClassNode]:
        lst = self.classes.get(name.split(".")[-1])
        return lst[0] if lst else None

    def method_on(self, cls: Optional[ClassNode], meth: str,
                  _depth: int = 0) -> Optional[FuncNode]:
        """Resolve a method through the (name-keyed) MRO."""
        if cls is None or _depth > 8:
            return None
        if meth in cls.methods:
            return cls.methods[meth]
        for b in cls.bases:
            hit = self.method_on(self.class_by_name(b), meth, _depth + 1)
            if hit is not None:
                return hit
        return None

    def lock_kind(self, cls: Optional[ClassNode], attr: str,
                  _depth: int = 0) -> Optional[str]:
        """Lock kind of ``self.<attr>`` through the base chain."""
        if cls is None or _depth > 8:
            return None
        if attr in cls.lock_attrs:
            return cls.lock_attrs[attr]
        for b in cls.bases:
            k = self.lock_kind(self.class_by_name(b), attr, _depth + 1)
            if k:
                return k
        return None

    def lock_owner(self, cls: Optional[ClassNode], attr: str,
                   _depth: int = 0) -> Optional[ClassNode]:
        if cls is None or _depth > 8:
            return None
        if attr in cls.lock_attrs:
            return cls
        for b in cls.bases:
            o = self.lock_owner(self.class_by_name(b), attr, _depth + 1)
            if o is not None:
                return o
        return None

    def cond_bind(self, cls: Optional[ClassNode], attr: str,
                  _depth: int = 0) -> Optional[str]:
        """The lock attr a Condition wraps, through the base chain."""
        if cls is None or _depth > 8:
            return None
        if attr in cls.cond_binds:
            return cls.cond_binds[attr]
        for b in cls.bases:
            bound = self.cond_bind(self.class_by_name(b), attr, _depth + 1)
            if bound is not None:
                return bound
        return None

    def node_for(self, fn: ast.AST) -> Optional[FuncNode]:
        return self._by_fnid.get(id(fn))

    def _resolve_call(self, call: ast.Call, ctx: FuncNode,
                      local_types: Dict[str, str]) -> List[FuncNode]:
        func = call.func
        mod = ctx.module
        imports = self.imports.get(mod, {})
        # ClassName(...) -> __init__ (+ base __init__s are reached through
        # the ctor's own super() calls when present)
        d = dotted(func)
        if d:
            tail = d.split(".")[-1]
            target_cls = None
            if d in imports and self.class_by_name(imports[d]):
                target_cls = self.class_by_name(imports[d])
            elif self.class_by_name(tail) and (
                    tail in imports or (mod, tail) not in self.functions):
                cand = self.class_by_name(tail)
                # only trust a bare-name class hit when the name is
                # actually visible in this module (imported or defined)
                if cand is not None and (
                        tail in imports or cand.module == mod):
                    target_cls = cand
            if target_cls is not None and tail[:1].isupper():
                init = self.method_on(target_cls, "__init__")
                return [init] if init else []
        if isinstance(func, ast.Name):
            name = func.id
            hit = self.functions.get((mod, name))
            if hit:
                return [hit]
            imp = imports.get(name)
            if imp:
                # from pkg.m import f  ->  pkg.m.f
                tmod, _, tname = imp.rpartition(".")
                hit = self.functions.get((tmod, tname))
                if hit:
                    return [hit]
            return []
        if isinstance(func, ast.Attribute):
            meth = func.attr
            recv = func.value
            # self.m(...) / cls.m(...)
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
                own = None
                if ctx.cls:
                    own = self._class_in_module(ctx.cls, mod)
                hit = self.method_on(own, meth)
                return [hit] if hit else []
            # mod.f(...) through an imported module
            rd = dotted(recv)
            if rd:
                imp = imports.get(rd.split(".")[0])
                if imp:
                    full = imp + rd[len(rd.split(".")[0]):]
                    hit = self.functions.get((full, meth))
                    if hit:
                        return [hit]
                    cn = self.class_by_name(full.split(".")[-1])
                    if cn is not None:
                        m = self.method_on(cn, meth)
                        if m:
                            return [m]
                hit = self.functions.get((rd, meth))
                if hit:
                    return [hit]
            # typed receivers: self.attr.m(...) and local var.m(...)
            tname = None
            attr = _self_attr(recv)
            if attr and ctx.cls:
                own = self._class_in_module(ctx.cls, mod)
                if own is not None:
                    tname = self._attr_type(own, attr)
            elif isinstance(recv, ast.Name):
                tname = local_types.get(recv.id) or \
                    self.module_vars.get(mod, {}).get(recv.id)
            if tname:
                m = self.method_on(self.class_by_name(tname), meth)
                return [m] if m else []
        return []

    def _class_in_module(self, name: str, mod: str) -> Optional[ClassNode]:
        for cn in self.classes.get(name, []):
            if cn.module == mod:
                return cn
        return self.class_by_name(name)

    def _attr_type(self, cls: Optional[ClassNode], attr: str,
                   _depth: int = 0) -> Optional[str]:
        if cls is None or _depth > 8:
            return None
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        for b in cls.bases:
            t = self._attr_type(self.class_by_name(b), attr, _depth + 1)
            if t:
                return t
        return None

    # ------------------------------------------------------------- linking

    def _link_file(self, f: SourceFile) -> None:
        from tools.boxlint import sinks as sinkmod
        mod = _module_name(f.rel)
        for node in self.nodes:
            if node.file is not f:
                continue
            local_types = self._local_types(node)
            own_body_ids = self._own_statement_ids(node)
            for sub in ast.walk(node.fn):
                if id(sub) not in own_body_ids:
                    continue
                if isinstance(sub, ast.Call):
                    callees = self._resolve_call(sub, node, local_types)
                    if callees:
                        node.call_map[id(sub)] = callees
                        for callee in callees:
                            node.calls.append((sub.lineno, callee))
                    sink = sinkmod.match_sink(sub, node, self, local_types)
                    if sink is not None:
                        node.sink_map[id(sub)] = sink
                        node.direct_sinks.append(sink)
                elif isinstance(sub, ast.With):
                    for line, ident, reentrant in self.with_locks(sub, node):
                        node.direct_locks.append((line, ident, reentrant))

    def _own_statement_ids(self, node: FuncNode) -> Set[int]:
        """ids of AST nodes belonging to this def but NOT to a nested def
        (nested defs are their own FuncNodes)."""
        nested: Set[int] = set()
        for sub in ast.walk(node.fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node.fn:
                for inner in ast.walk(sub):
                    nested.add(id(inner))
        return {id(sub) for sub in ast.walk(node.fn)
                if id(sub) not in nested}

    def _local_types(self, node: FuncNode) -> Dict[str, str]:
        """var -> class-name for single `v = ClassName(...)` assignments
        (first assignment wins)."""
        out: Dict[str, str] = {}
        for sub in ast.walk(node.fn):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)):
                tail = (dotted(sub.value.func) or "").split(".")[-1]
                if tail and tail[0].isupper() and (
                        self.class_by_name(tail) is not None):
                    out.setdefault(sub.targets[0].id, tail)
        return out

    # --------------------------------------------------- lock identification

    def with_locks(self, stmt: ast.With, ctx: FuncNode
                   ) -> List[Tuple[int, str, bool]]:
        """(line, lock identity, reentrant?) for each lock this `with`
        acquires. Condition attrs resolve to their bound lock's identity
        (entering a Condition enters its lock)."""
        out: List[Tuple[int, str, bool]] = []
        for item in stmt.items:
            ctx_expr = item.context_expr
            ident = self.lock_identity(ctx_expr, ctx)
            if ident is not None:
                out.append((stmt.lineno, ident[0], ident[1]))
        return out

    def lock_identity(self, expr: ast.AST, ctx: FuncNode
                      ) -> Optional[Tuple[str, bool]]:
        """(identity, reentrant?) when ``expr`` denotes a known lock:
        ``self._x`` with a lock-ish ctor in the class, or a module-level
        lock name. Conditions map to their bound lock."""
        attr = _self_attr(expr)
        if attr and ctx.cls:
            own = self._class_in_module(ctx.cls, ctx.module)
            kind = self.lock_kind(own, attr)
            owner = self.lock_owner(own, attr)
            if kind in ("lock", "rlock"):
                return (f"{owner.name}.{attr}", kind == "rlock")
            if kind == "condition":
                bound = self.cond_bind(own, attr)
                if bound:
                    bkind = self.lock_kind(own, bound)
                    bowner = self.lock_owner(own, bound)
                    if bowner is not None:
                        return (f"{bowner.name}.{bound}", bkind == "rlock")
                return (f"{owner.name}.{attr}", False)
            return None
        if isinstance(expr, ast.Name):
            kind = self.module_locks.get(ctx.module, {}).get(expr.id)
            if kind in ("lock", "rlock", "condition"):
                return (f"{ctx.module.split('.')[-1]}.{expr.id}",
                        kind == "rlock")
        # typed receiver: with self._dog._lock / with SINGLETON._lock —
        # the lock lives on another object whose class we can type
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            tname = None
            a = _self_attr(recv)
            if a and ctx.cls:
                own = self._class_in_module(ctx.cls, ctx.module)
                tname = self._attr_type(own, a)
            elif isinstance(recv, ast.Name):
                tname = self.module_vars.get(ctx.module, {}).get(recv.id)
            if tname:
                cn = self.class_by_name(tname)
                kind = self.lock_kind(cn, expr.attr)
                owner = self.lock_owner(cn, expr.attr)
                if kind in ("lock", "rlock") and owner is not None:
                    return (f"{owner.name}.{expr.attr}", kind == "rlock")
        return None

    # -------------------------------------------------- transitive closures

    def sink_closure(self) -> Dict[int, Dict[str, Tuple]]:
        """For every FuncNode: {sink label -> (line-in-node, bound-lock or
        None, has_timeout, chain tuple)} reachable transitively. The chain
        names the call path from the node to the sink (shortest found)."""
        summary: Dict[int, Dict[str, Tuple]] = {
            id(n): {} for n in self.nodes}
        for n in self.nodes:
            for line, label, bound, has_to in n.direct_sinks:
                cur = summary[id(n)].get(label)
                if cur is None or line < cur[0]:
                    summary[id(n)][label] = (line, bound, has_to, ())
        # reverse propagation to fixpoint
        callers: Dict[int, List[Tuple[FuncNode, int]]] = {}
        for n in self.nodes:
            for line, callee in n.calls:
                callers.setdefault(id(callee), []).append((n, line))
        work = [n for n in self.nodes if summary[id(n)]]
        seen_rounds = 0
        while work and seen_rounds < 100000:
            cur = work.pop()
            for caller, line in callers.get(id(cur), []):
                changed = False
                for label, (sline, bound, has_to, chain) in \
                        summary[id(cur)].items():
                    if len(chain) >= 6:
                        continue
                    entry = summary[id(caller)].get(label)
                    new_chain = (cur.qual,) + chain
                    if entry is None:
                        summary[id(caller)][label] = (
                            line, bound, has_to, new_chain)
                        changed = True
                if changed:
                    work.append(caller)
            seen_rounds += 1
        return summary

    def lock_closure(self) -> Dict[int, Dict[str, Tuple]]:
        """For every FuncNode: {lock identity -> (line-in-node, reentrant,
        chain)} of locks acquired transitively by calling it."""
        summary: Dict[int, Dict[str, Tuple]] = {
            id(n): {} for n in self.nodes}
        for n in self.nodes:
            for line, ident, reent in n.direct_locks:
                cur = summary[id(n)].get(ident)
                if cur is None or line < cur[0]:
                    summary[id(n)][ident] = (line, reent, ())
        callers: Dict[int, List[Tuple[FuncNode, int]]] = {}
        for n in self.nodes:
            for line, callee in n.calls:
                callers.setdefault(id(callee), []).append((n, line))
        work = [n for n in self.nodes if summary[id(n)]]
        rounds = 0
        while work and rounds < 100000:
            cur = work.pop()
            for caller, line in callers.get(id(cur), []):
                changed = False
                for ident, (sline, reent, chain) in summary[id(cur)].items():
                    if len(chain) >= 6:
                        continue
                    if ident not in summary[id(caller)]:
                        summary[id(caller)][ident] = (
                            line, reent, (cur.qual,) + chain)
                        changed = True
                if changed:
                    work.append(caller)
            rounds += 1
        return summary


def reverse_dependents(files: Sequence[SourceFile],
                       changed_rels: Set[str]) -> Set[str]:
    """Repo-relative paths of every module that (transitively) imports
    one of ``changed_rels`` — the reverse import closure the --changed
    mode lints alongside the edits themselves, so an edit that breaks a
    CALLER's invariant (a deleted helper a jit factory still wraps, a
    lock a caller still nests) is reported in the sub-second loop, not
    first by the full-tree gate."""
    idx = get_index(files)
    rel_by_mod = {m: sf.rel for m, sf in idx.modules.items()}
    rev: Dict[str, Set[str]] = {}
    for mod, imports in idx.imports.items():
        for _local, target in imports.items():
            dep = None
            if target in idx.modules:
                dep = target
            else:
                head = target.rpartition(".")[0]
                if head in idx.modules:
                    dep = head
            if dep is not None and dep != mod:
                rev.setdefault(dep, set()).add(mod)
    changed_mods = [m for m, rel in rel_by_mod.items()
                    if rel in changed_rels]
    out: Set[str] = set(changed_mods)
    work = list(changed_mods)
    while work:
        cur = work.pop()
        for m in rev.get(cur, ()):
            if m not in out:
                out.add(m)
                work.append(m)
    return {rel_by_mod[m] for m in out}


# ------------------------------------------------------------------ memo

_CACHE: List[Tuple[List[SourceFile], PackageIndex]] = []


def get_index(files: Sequence[SourceFile]) -> PackageIndex:
    """One PackageIndex shared by the three interprocedural passes within
    a run_passes invocation (keyed on the exact SourceFile objects; the
    strong reference in the cache keeps ids stable)."""
    flist = list(files)
    for cached_files, idx in _CACHE:
        if len(cached_files) == len(flist) and all(
                a is b for a, b in zip(cached_files, flist)):
            return idx
    idx = PackageIndex(flist)
    del _CACHE[:]
    _CACHE.append((flist, idx))
    return idx


def chain_str(chain: Tuple[str, ...]) -> str:
    if not chain:
        return ""
    shown = list(chain[:3])
    if len(chain) > 3:
        shown.append("...")
    return " via " + " -> ".join(shown)
