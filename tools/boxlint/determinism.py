"""Pass — replay-determinism hazards (BX941).

The static twin of the journal bit-parity contract: PR 16's spill path
fought to keep ``replay_segments`` byte-identical to the live run, and
the device plane's journal parity checks only catch a divergence AFTER a
replay mismatches. This pass pins the two classic nondeterminism sources
at the line:

  * **numeric accumulation ordered by set iteration** — ``for k in
    set(...): total += ...`` (or ``sum(<set>)``): float addition is not
    associative and set order varies per process (hash randomization),
    so the accumulated value — and any journaled state derived from it —
    differs between the run and its replay; iterate ``sorted(...)``.
    Sets reaching the loop through a helper in another module resolve
    via the call closure (a function whose return value is set-ish
    marks its callers' loop iterables).
  * **wall-clock / global-RNG values** — a module-global
    ``np.random.*`` draw is unseedable per-run (the repo's convention is
    an explicitly seeded ``np.random.RandomState``/``Generator``
    threaded from config, which stays clean), and ``time.time()``-
    derived values flowing into the journaled embedding-state mutators
    (``append_rows``/``append_move``/``append_event``/``anchor_full``/
    ``rebase``) replay differently by construction.

Codes:
  BX941  replay-nondeterministic dataflow
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import FuncNode, get_index
from tools.boxlint.purity import dotted

_EXEMPT_PARTS = {"tools", "tests", "examples"}

# journaled embedding-state mutators (train/journal.py EmbJournal API):
# a time-derived argument here replays differently by construction
_JOURNAL_MUTATORS = {"append_rows", "append_move", "append_event",
                     "anchor_full", "rebase", "replay_record"}

# global-RNG draws on the np.random module itself (seeded RandomState /
# default_rng instances are the blessed, replayable form)
_RNG_DRAWS = {"rand", "randn", "randint", "random", "random_sample",
              "normal", "uniform", "choice", "shuffle", "permutation",
              "bytes", "standard_normal"}

_TIME_CALLS = {"time.time", "time.time_ns", "time.monotonic",
               "datetime.now", "datetime.utcnow"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    # functions whose return value is set-ish: callers' loop iterables
    # resolve through this (the closure-crossing form)
    setish_fns: Set[int] = set()
    for node in index.nodes:
        for sub in ast.walk(node.fn):
            if isinstance(sub, ast.Return) and sub.value is not None \
                    and _setish(sub.value, {}, None, index):
                setish_fns.add(id(node.fn))
                break
    out: List[Violation] = []
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        own = index._own_statement_ids(node)
        local_sets = _local_setish(node, own, setish_fns, index)
        time_names = _time_tainted(node, own)
        np_names = _np_aliases(node.file)
        for sub in ast.walk(node.fn):
            if id(sub) not in own:
                continue
            if isinstance(sub, ast.For) and _setish(
                    sub.iter, local_sets, node, index, setish_fns):
                acc = _accumulation_in(sub, own)
                if acc is not None:
                    out.append(Violation(
                        node.file.rel, sub.lineno, "BX941",
                        f"numeric accumulation at line {acc} ordered by "
                        f"set iteration in `{node.qual}` — float "
                        f"addition is not associative and set order "
                        f"varies per process, so a replay accumulates a "
                        f"different value; iterate sorted(...)"))
            elif isinstance(sub, ast.Call):
                d = dotted(sub.func) or ""
                tail = d.split(".")[-1]
                if tail == "sum" and len(sub.args) == 1 and _setish(
                        sub.args[0], local_sets, node, index, setish_fns):
                    out.append(Violation(
                        node.file.rel, sub.lineno, "BX941",
                        f"sum() over a set in `{node.qual}` — the "
                        f"accumulation order varies per process; "
                        f"sum(sorted(...)) makes the replay "
                        f"bit-identical"))
                parts = d.split(".")
                if len(parts) == 3 and parts[0] in np_names \
                        and parts[1] == "random" and parts[2] in _RNG_DRAWS:
                    out.append(Violation(
                        node.file.rel, sub.lineno, "BX941",
                        f"module-global {parts[0]}.random.{parts[2]} in "
                        f"`{node.qual}` — unseedable per-run, so any "
                        f"journaled state it feeds breaks replay "
                        f"bit-parity; use a seeded np.random.RandomState"
                        f"/Generator threaded from config"))
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _JOURNAL_MUTATORS:
                    for arg in list(sub.args) + [k.value for k in
                                                 sub.keywords]:
                        if _time_derived(arg, time_names):
                            out.append(Violation(
                                node.file.rel, sub.lineno, "BX941",
                                f"time-derived value flows into "
                                f"journaled state "
                                f"(.{sub.func.attr}) in `{node.qual}` — "
                                f"a replay re-executes with a different "
                                f"clock; derive the value from journaled "
                                f"inputs instead"))
                            break
    return out


def _np_aliases(f: SourceFile) -> Set[str]:
    names = {"np", "numpy"}
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    names.add(a.asname or "numpy")
    return names


def _setish(expr: Optional[ast.AST], local_sets: Dict[str, bool],
            node: Optional[FuncNode], index,
            setish_fns: Optional[Set[int]] = None) -> bool:
    if expr is None:
        return False
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Name):
        return bool(local_sets.get(expr.id))
    if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.BitAnd, ast.BitOr, ast.BitXor, ast.Sub)):
        return (_setish(expr.left, local_sets, node, index, setish_fns)
                or _setish(expr.right, local_sets, node, index,
                           setish_fns))
    if isinstance(expr, ast.Call):
        tail = (dotted(expr.func) or "").split(".")[-1]
        if tail in ("set", "frozenset"):
            return True
        if tail == "sorted":
            return False        # canonical order: the fix
        if tail in ("intersection", "union", "difference",
                    "symmetric_difference") and isinstance(
                expr.func, ast.Attribute):
            return _setish(expr.func.value, local_sets, node, index,
                           setish_fns)
        if setish_fns and node is not None:
            for callee in node.call_map.get(id(expr), []):
                if id(callee.fn) in setish_fns:
                    return True
    return False


def _local_setish(node: FuncNode, own: Set[int], setish_fns: Set[int],
                  index) -> Dict[str, bool]:
    out: Dict[str, bool] = {}
    for _ in range(2):
        for sub in ast.walk(node.fn):
            if id(sub) not in own or not isinstance(sub, ast.Assign):
                continue
            if len(sub.targets) == 1 and isinstance(sub.targets[0],
                                                    ast.Name):
                if _setish(sub.value, out, node, index, setish_fns):
                    out[sub.targets[0].id] = True
                elif sub.targets[0].id in out:
                    out.pop(sub.targets[0].id, None)  # rebound stably
    return out


def _time_tainted(node: FuncNode, own: Set[int]) -> Set[str]:
    """Local names assigned (possibly through arithmetic) from wall-clock
    calls, two-sweep."""
    names: Set[str] = set()
    for _ in range(2):
        for sub in ast.walk(node.fn):
            if id(sub) not in own or not isinstance(sub, ast.Assign):
                continue
            if _time_derived(sub.value, names):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _time_derived(expr: Optional[ast.AST], names: Set[str]) -> bool:
    if expr is None:
        return False
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and (dotted(sub.func) or "") \
                in _TIME_CALLS:
            return True
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


def _accumulation_in(loop: ast.For, own: Set[int]) -> Optional[int]:
    """Line of a numeric AugAssign accumulation in the loop body (set
    union ``|=`` and friends are order-insensitive and stay clean)."""
    for sub in ast.walk(loop):
        if id(sub) not in own:
            continue
        if isinstance(sub, ast.AugAssign) and isinstance(
                sub.op, (ast.Add, ast.Sub, ast.Mult)):
            if isinstance(sub.value, (ast.Set, ast.SetComp)):
                continue
            return sub.lineno
    return None
