"""boxlint CLI.

Usage:
    python -m tools.boxlint [options] PATH [PATH ...]

Exit codes (the CI contract):
    0  clean — no violations beyond the committed baseline
    1  NEW violations (or --fail-on-stale and the baseline has dead entries)
    2  internal error (checker crash, unreadable baseline, bad arguments)

Typical invocations:
    python -m tools.boxlint paddlebox_tpu/ tools/
    python -m tools.boxlint --no-baseline paddlebox_tpu/parallel/mesh.py
    python -m tools.boxlint --fix-baseline paddlebox_tpu/ tools/
    python -m tools.boxlint --changed paddlebox_tpu/ tools/   # edit loop
    python -m tools.boxlint --lock-graph paddlebox_tpu/      # artifact
    python -m tools.boxlint --suggest-guards paddlebox_tpu/  # artifact
    python -m tools.boxlint --device-contracts paddlebox_tpu/ tools/
    python -m tools.boxlint --check-baseline paddlebox_tpu/ tools/
    python -m tools.boxlint --list-rules
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from tools.boxlint.core import (
    ALL_PASSES, RULES, diff_against_baseline, format_baseline,
    load_baseline, load_tree, run_passes,
)
from tools.boxlint import cache as cachemod

_SELF_DIR = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_BASELINE = os.path.join(_SELF_DIR, "baseline.txt")
_DEFAULT_LOCK_GRAPH = os.path.join(_SELF_DIR, "lock_graph.txt")
_DEFAULT_GUARDS = os.path.join(_SELF_DIR, "guard_suggestions.txt")
_DEFAULT_CONTRACTS = os.path.join(_SELF_DIR, "device_contracts.txt")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.boxlint",
        description=(
            "AST-level invariant checker for this repo: jit purity / "
            "static shapes (BX1xx), collective axis contracts (BX2xx), "
            "flag registry hygiene (BX3xx), guarded-by lock discipline "
            "(BX4xx), library print hygiene (BX501), span "
            "context-manager discipline (BX502), silent exception "
            "swallows (BX503), and the interprocedural concurrency "
            "passes on the package-wide call graph: blocking-under-lock "
            "(BX601), lock-order deadlock cycles (BX701), handler "
            "reentrancy (BX801/BX802), and jit entry-point registration "
            "(BX901: bare jax.jit must go through "
            "obs.device.instrument_jit), tier-1 time-budget "
            "discipline (BX951: test functions at >= 10M-literal scale "
            "must carry @pytest.mark.slow), and the device-contract "
            "suite on the traced-value taint layer: recompile hazards "
            "(BX911), donation contract (BX921), hidden host syncs in "
            "loops/locks/handlers (BX931, reasoned waivers via "
            "'# boxlint: BX931 ok (reason)'; reasonless waivers are "
            "BX932), and replay determinism (BX941). Suppress a single "
            "site with '# boxlint: "
            "disable=BX101' on the line (or the def line for a whole "
            "method); long-lived exceptions belong in the baseline."),
        epilog=(
            "exit codes: 0 = clean vs baseline; 1 = new violations "
            "(each printed as file:line: CODE message); 2 = internal "
            "error. Regenerate the baseline after deliberate changes "
            "with --fix-baseline (review the diff — shrinking is "
            "progress, growth needs a reason)."))
    p.add_argument("paths", nargs="*", metavar="PATH",
                   help="files or directories to lint (e.g. "
                        "paddlebox_tpu/ tools/); optional with "
                        "--list-rules")
    p.add_argument("--baseline", default=_DEFAULT_BASELINE, metavar="FILE",
                   help="baseline file of tolerated pre-existing "
                        "violations (default: tools/boxlint/baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every violation, ignoring the baseline")
    p.add_argument("--fix-baseline", action="store_true",
                   help="rewrite the baseline file to exactly the current "
                        "violation set and exit 0")
    p.add_argument("--passes", default=",".join(ALL_PASSES), metavar="LIST",
                   help="comma-separated subset of passes to run "
                        f"(default: {','.join(ALL_PASSES)})")
    p.add_argument("--fail-on-stale", action="store_true",
                   help="also exit 1 when baseline entries no longer "
                        "match any violation (ratchet mode)")
    p.add_argument("--check-baseline", dest="fail_on_stale",
                   action="store_true",
                   help="synonym for --fail-on-stale: a baselined "
                        "finding that no longer fires is stale and "
                        "fails the run, so the suppression file cannot "
                        "fossilize")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule inventory (code, pass, "
                        "one-line summary) and exit 0")
    p.add_argument("--changed", action="store_true",
                   help="incremental edit-loop mode: lint the files "
                        "changed vs HEAD (or vs `git merge-base HEAD "
                        "--changed-base REF`) plus untracked .py, PLUS "
                        "their reverse import closure (modules that "
                        "transitively import a changed file — an edit "
                        "can break a caller's invariant); cross-file "
                        "passes still read the full tree, reporting is "
                        "filtered to that set. The tier-1 gate always "
                        "runs full-tree")
    p.add_argument("--changed-base", default=None, metavar="REF",
                   help="base ref for --changed (e.g. origin/main); "
                        "default: HEAD (uncommitted edits only)")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the content-hash result cache "
                        "(tools/boxlint/.cache.json); the cache is "
                        "exact — any file or checker change misses")
    p.add_argument("--lock-graph", action="store_true",
                   help="write the interprocedural lock-nesting "
                        "inventory artifact to --artifact-out (default: "
                        "tools/boxlint/lock_graph.txt) and exit 0")
    p.add_argument("--suggest-guards", action="store_true",
                   help="write candidate '# guarded-by:' annotations for "
                        "attrs touched >=90%% under one lock to "
                        "--artifact-out (default: "
                        "tools/boxlint/guard_suggestions.txt) and exit 0")
    p.add_argument("--device-contracts", action="store_true",
                   help="write the jit device-contract inventory (every "
                        "entry with donation/static keying + every "
                        "reasoned waiver, with pinned counts) to "
                        "--artifact-out (default: "
                        "tools/boxlint/device_contracts.txt) and exit 0")
    p.add_argument("--artifact-out", default=None, metavar="PATH",
                   help="override the output path for --lock-graph / "
                        "--suggest-guards / --device-contracts")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line; print violations only")
    return p


def main(argv: List[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    passes = [s.strip() for s in args.passes.split(",") if s.strip()]
    bad = [s for s in passes if s not in ALL_PASSES]
    if bad:
        print(f"boxlint: unknown pass(es): {', '.join(bad)} "
              f"(valid: {', '.join(ALL_PASSES)})", file=sys.stderr)
        return 2

    if args.list_rules:
        width = max(len(code) for code, _, _ in RULES)
        pwidth = max(len(p) for _, p, _ in RULES)
        for code, pass_name, summary in RULES:
            print(f"{code:<{width}}  {pass_name:<{pwidth}}  {summary}")
        return 0
    if not args.paths:
        print("boxlint: at least one PATH is required", file=sys.stderr)
        return 2

    # --------------------------------------------------- artifact modes
    if args.lock_graph or args.suggest_guards or args.device_contracts:
        try:
            files, parse_errors = load_tree(args.paths)
            if args.lock_graph:
                from tools.boxlint import lockorder
                out_path = args.artifact_out or _DEFAULT_LOCK_GRAPH
                with open(out_path, "w", encoding="utf-8") as fh:
                    fh.write(lockorder.render_inventory(files))
                if not args.quiet:
                    print(f"boxlint: lock-nesting inventory -> {out_path}")
            if args.suggest_guards:
                from tools.boxlint import guards
                out_path = args.artifact_out or _DEFAULT_GUARDS
                with open(out_path, "w", encoding="utf-8") as fh:
                    fh.write(guards.render_report(files))
                if not args.quiet:
                    print(f"boxlint: guard suggestions -> {out_path}")
            if args.device_contracts:
                from tools.boxlint import taint
                out_path = args.artifact_out or _DEFAULT_CONTRACTS
                with open(out_path, "w", encoding="utf-8") as fh:
                    fh.write(taint.render_inventory(files))
                if not args.quiet:
                    print(f"boxlint: device-contract inventory -> "
                          f"{out_path}")
        except Exception as e:
            print(f"boxlint: internal error: {e.__class__.__name__}: {e}",
                  file=sys.stderr)
            return 2
        return 0

    if args.fix_baseline and args.changed:
        # the baseline must describe the FULL tree: rewriting it from a
        # changed-files-only violation set would silently drop every
        # baselined entry in the unchanged files
        print("boxlint: --fix-baseline requires a full-tree run "
              "(drop --changed)", file=sys.stderr)
        return 2

    # ------------------------------------------------------ lint proper
    try:
        sources = cachemod.collect_sources(args.paths)
        changed = None
        if args.changed:
            changed = cachemod.changed_files(base=args.changed_base)
            if changed is None and not args.quiet:
                print("boxlint: --changed: git unavailable, running "
                      "full-tree", file=sys.stderr)
        violations = None
        digest = cachemod.tree_digest(sources, passes)
        if not args.no_cache and changed is None:
            violations = cachemod.load_cached(digest)
        n_files = len(sources)
        if violations is None:
            files, parse_errors = load_tree(args.paths, sources=sources)
            if changed is not None:
                # expand with the reverse import closure: an edit can
                # invalidate an invariant in a file that IMPORTS the
                # edited one (a deleted flag, a changed jit contract),
                # so dependents re-lint too
                from tools.boxlint import callgraph
                changed = changed | callgraph.reverse_dependents(
                    files, changed)
                per_file = [p for p in passes
                            if p in cachemod.PER_FILE_PASSES]
                cross = [p for p in passes
                         if p not in cachemod.PER_FILE_PASSES]
                subset = [f for f in files if f.rel in changed]
                violations = list(parse_errors)
                if per_file and subset:
                    violations += run_passes(subset, per_file)
                if cross:
                    violations += run_passes(files, cross)
                violations = sorted(
                    (v for v in violations if v.path in changed),
                    key=lambda v: (v.path, v.line, v.code))
                n_files = len(subset)
            else:
                violations = list(parse_errors) + run_passes(files, passes)
                if not args.no_cache and not args.fix_baseline:
                    cachemod.store_cached(digest, violations)
    except Exception as e:  # checker bug — never masquerade as "clean"
        print(f"boxlint: internal error: {e.__class__.__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.fix_baseline:
        try:
            with open(args.baseline, "w", encoding="utf-8") as fh:
                fh.write(format_baseline(violations))
        except OSError as e:
            print(f"boxlint: cannot write baseline: {e}", file=sys.stderr)
            return 2
        if not args.quiet:
            print(f"boxlint: baseline rewritten with {len(violations)} "
                  f"entr{'y' if len(violations) == 1 else 'ies'} "
                  f"-> {args.baseline}")
        return 0

    if args.no_baseline:
        new, stale = violations, []
    else:
        try:
            baseline = load_baseline(args.baseline)
        except OSError as e:
            print(f"boxlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        new, stale = diff_against_baseline(violations, baseline)
        if changed is not None:
            # a fixed violation elsewhere must not read as stale when we
            # only looked at the changed files
            stale = [s for s in stale if s[0] in changed]

    for v in new:
        print(v.render())
    if stale and not args.quiet:
        for path, code, msg in stale:
            print(f"boxlint: stale baseline entry (fixed? run "
                  f"--fix-baseline): {path}: {code} {msg}", file=sys.stderr)
    if not args.quiet:
        total = len(violations)
        mode = " (changed-only)" if changed is not None else ""
        print(f"boxlint: {n_files} files{mode}, {total} violation"
              f"{'' if total == 1 else 's'} ({len(new)} new, "
              f"{total - len(new)} baselined, {len(stale)} stale)",
              file=sys.stderr)
    if new:
        return 1
    if stale and args.fail_on_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
