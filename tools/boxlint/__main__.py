"""boxlint CLI.

Usage:
    python -m tools.boxlint [options] PATH [PATH ...]

Exit codes (the CI contract):
    0  clean — no violations beyond the committed baseline
    1  NEW violations (or --fail-on-stale and the baseline has dead entries)
    2  internal error (checker crash, unreadable baseline, bad arguments)

Typical invocations:
    python -m tools.boxlint paddlebox_tpu/ tools/
    python -m tools.boxlint --no-baseline paddlebox_tpu/parallel/mesh.py
    python -m tools.boxlint --fix-baseline paddlebox_tpu/ tools/
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from tools.boxlint.core import (
    ALL_PASSES, diff_against_baseline, format_baseline, load_baseline,
    load_tree, run_passes,
)

_DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.boxlint",
        description=(
            "AST-level invariant checker for this repo: jit purity / "
            "static shapes (BX1xx), collective axis contracts (BX2xx), "
            "flag registry hygiene (BX3xx), guarded-by lock discipline "
            "(BX4xx), library print hygiene (BX501), span "
            "context-manager discipline (BX502). Suppress a single "
            "site with '# boxlint: "
            "disable=BX101' on the line (or the def line for a whole "
            "method); long-lived exceptions belong in the baseline."),
        epilog=(
            "exit codes: 0 = clean vs baseline; 1 = new violations "
            "(each printed as file:line: CODE message); 2 = internal "
            "error. Regenerate the baseline after deliberate changes "
            "with --fix-baseline (review the diff — shrinking is "
            "progress, growth needs a reason)."))
    p.add_argument("paths", nargs="+", metavar="PATH",
                   help="files or directories to lint (e.g. "
                        "paddlebox_tpu/ tools/)")
    p.add_argument("--baseline", default=_DEFAULT_BASELINE, metavar="FILE",
                   help="baseline file of tolerated pre-existing "
                        "violations (default: tools/boxlint/baseline.txt)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every violation, ignoring the baseline")
    p.add_argument("--fix-baseline", action="store_true",
                   help="rewrite the baseline file to exactly the current "
                        "violation set and exit 0")
    p.add_argument("--passes", default=",".join(ALL_PASSES), metavar="LIST",
                   help="comma-separated subset of passes to run "
                        f"(default: {','.join(ALL_PASSES)})")
    p.add_argument("--fail-on-stale", action="store_true",
                   help="also exit 1 when baseline entries no longer "
                        "match any violation (ratchet mode)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line; print violations only")
    return p


def main(argv: List[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    passes = [s.strip() for s in args.passes.split(",") if s.strip()]
    bad = [s for s in passes if s not in ALL_PASSES]
    if bad:
        print(f"boxlint: unknown pass(es): {', '.join(bad)} "
              f"(valid: {', '.join(ALL_PASSES)})", file=sys.stderr)
        return 2
    try:
        files, parse_errors = load_tree(args.paths)
        violations = list(parse_errors) + run_passes(files, passes)
    except Exception as e:  # checker bug — never masquerade as "clean"
        print(f"boxlint: internal error: {e.__class__.__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.fix_baseline:
        try:
            with open(args.baseline, "w", encoding="utf-8") as fh:
                fh.write(format_baseline(violations))
        except OSError as e:
            print(f"boxlint: cannot write baseline: {e}", file=sys.stderr)
            return 2
        if not args.quiet:
            print(f"boxlint: baseline rewritten with {len(violations)} "
                  f"entr{'y' if len(violations) == 1 else 'ies'} "
                  f"-> {args.baseline}")
        return 0

    if args.no_baseline:
        new, stale = violations, []
    else:
        try:
            baseline = load_baseline(args.baseline)
        except OSError as e:
            print(f"boxlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        new, stale = diff_against_baseline(violations, baseline)

    for v in new:
        print(v.render())
    if stale and not args.quiet:
        for path, code, msg in stale:
            print(f"boxlint: stale baseline entry (fixed? run "
                  f"--fix-baseline): {path}: {code} {msg}", file=sys.stderr)
    if not args.quiet:
        total = len(violations)
        print(f"boxlint: {len(files)} files, {total} violation"
              f"{'' if total == 1 else 's'} ({len(new)} new, "
              f"{total - len(new)} baselined, {len(stale)} stale)",
              file=sys.stderr)
    if new:
        return 1
    if stale and args.fail_on_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
