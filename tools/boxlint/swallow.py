"""Pass 10 — silent exception swallowing (BX503).

``except Exception: pass`` erases evidence: the failure happened, nobody
will ever know, and the next symptom shows up three planes away (the
repo's review record keeps re-finding this by hand). The contract this
pass pins (ISSUE 14 satellite): every silent swallow in library code
either

  * becomes a counted loud path — log a warning through
    ``paddlebox_tpu.obs.log`` and/or bump a StatRegistry counter (a
    handler body that DOES anything is by definition not silent and
    never flags), or
  * carries a rationale comment on the ``except`` clause's lines
    explaining why silence is the correct behavior (``__del__``
    teardown-ordering guards are the canonical case: the interpreter may
    be half-dead, logging itself can fail).

"Silent" means the handler catches ``Exception`` / ``BaseException`` /
bare ``except:`` and its body contains only ``pass`` / constants /
``continue`` / a bare or constant ``return``. Any comment on the
handler's lines counts as the rationale — the reviewable-decision bar is
"someone wrote down why", the same bar as BX401's disable rationale.

Scope: library code (``tools``/``tests``/``examples`` path parts exempt,
as BX501 — probes print their own diagnostics and tests assert on
failures anyway).

Codes:
  BX503  silent except-Exception swallow without a rationale comment
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.purity import dotted

_EXEMPT_PARTS = {"tools", "tests", "examples"}
_BROAD = {"Exception", "BaseException"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    d = dotted(handler.type)
    return bool(d) and d.split(".")[-1] in _BROAD


def _is_silent(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or isinstance(stmt.value, ast.Constant)):
            continue
        return False
    return True


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        if _exempt(f.rel):
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or not _is_silent(node.body):
                continue
            end = node.end_lineno or node.lineno
            if any(ln in f.comments
                   for ln in range(node.lineno, end + 1)):
                continue  # rationale written down — a reviewed decision
            out.append(Violation(
                f.rel, node.lineno, "BX503",
                "silent except-Exception swallow: the failure leaves no "
                "trace — log a counted warning through obs/log, or leave "
                "a rationale comment on the handler"))
    return out
