"""Pass 1 — jit-purity / static-shape (BX1xx).

The fused step's contract (ARCHITECTURE.md): everything reachable from a
``jax.jit`` / ``jax.shard_map`` / ``lax.scan`` entry point traces to one
pure, static-shaped XLA program. The reference got this for free from the
static graph (ops declare shapes at build time, host code can't leak in);
here a stray ``.item()`` or ``np.*`` on a tracer silently inserts a
device->host sync per step, and ``jnp.unique`` without ``size=`` is a
trace-time error only on the paths tests happen to cover.

Detection is deliberately an over-approximation with a taint heuristic:
entry functions are found by decorator and call-site (``jax.jit(f)``,
``jax.shard_map(f, ...)``, ``lax.scan(f, ...)``), the traced set closes
over same-module calls (``g(...)`` and ``self.m(...)``), and a value is
"traced" when it flows from a parameter of the traced function or from a
``jnp.* / jax.* / lax.*`` call. Host-callback bodies
(``jax.pure_callback`` / ``io_callback`` / ``debug.callback``) are host
code by design and are excluded.

Codes:
  BX101  host sync call (.item(), jax.device_get, print) in traced code
  BX102  float()/int()/bool() cast of a traced value
  BX103  np.* call on a traced value
  BX104  data-dependent output shape (jnp.unique/nonzero/... without size=)
  BX105  boolean-mask indexing (data-dependent shape)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation

_JIT_NAMES = {"jax.jit", "jit", "functools.partial", "partial"}
_ENTRY_WRAPPERS = {"jax.jit", "jit", "jax.pmap", "pmap",
                   "jax.shard_map", "shard_map",
                   "jax.experimental.shard_map.shard_map"}
_SCAN_WRAPPERS = {"jax.lax.scan", "lax.scan",
                  "jax.lax.fori_loop", "lax.fori_loop",
                  "jax.lax.while_loop", "lax.while_loop",
                  "jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch"}
_CALLBACKS = {"jax.pure_callback", "jax.experimental.io_callback",
              "io_callback", "pure_callback", "jax.debug.callback",
              "debug.callback"}
_DATA_DEP = {"unique", "nonzero", "flatnonzero", "argwhere"}
_TRACED_MODULES = ("jnp", "jax", "lax")


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _decorator_is_jit(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d in _ENTRY_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        f = dotted(dec.func)
        if f in _ENTRY_WRAPPERS:
            return True
        if f in _JIT_NAMES and dec.args:  # partial(jax.jit, ...)
            return dotted(dec.args[0]) in _ENTRY_WRAPPERS
    return False


class _Scope:
    """Function registry for one module: module functions by name,
    methods by (class, name)."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.owner: Dict[int, Optional[str]] = {}  # id(def) -> class name
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                self.functions[node.name] = node
                self.owner[id(node)] = None
                self._register_nested(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.methods[(node.name, sub.name)] = sub
                        self.owner[id(sub)] = node.name
                        self._register_nested(sub, node.name)

    def _register_nested(self, fn: ast.FunctionDef, cls: Optional[str]) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                # nested defs resolve by bare name too (closure helpers)
                self.functions.setdefault(node.name, node)
                self.owner.setdefault(id(node), cls)


# wrapper tail -> (positional indices of function args, kwarg names).
# Positions matter: fori_loop's args[0] is the loop bound and cond's
# args[0] is the predicate — seeding those would mistrace (or miss the
# real body entirely).
_FUNC_ARG_SPEC = {
    "scan": ((0,), ("f",)),
    "fori_loop": ((2,), ("body_fun",)),
    "while_loop": ((0, 1), ("cond_fun", "body_fun")),
    "cond": ((1, 2), ("true_fun", "false_fun")),
    "jit": ((0,), ("fun", "f", "func")),
    "pmap": ((0,), ("fun", "f", "func")),
    "shard_map": ((0,), ("f", "fun", "func")),
}


def _func_args(call: ast.Call, tail: str) -> List[ast.AST]:
    """The argument nodes of ``call`` that are traced functions."""
    if tail == "switch":  # switch(index, branches_sequence, *operands)
        out: List[ast.AST] = []
        branches = (call.args[1] if len(call.args) > 1 else
                    next((kw.value for kw in call.keywords
                          if kw.arg == "branches"), None))
        if isinstance(branches, (ast.Tuple, ast.List)):
            out.extend(branches.elts)
        elif branches is not None:
            out.append(branches)
        return out
    pos, kws = _FUNC_ARG_SPEC.get(tail, ((0,), ("f", "fun", "func")))
    out = [call.args[i] for i in pos if len(call.args) > i]
    out.extend(kw.value for kw in call.keywords if kw.arg in kws)
    return out


def _collect_entries(f: SourceFile, scope: _Scope
                     ) -> Tuple[Set[int], Set[str]]:
    """Returns (ids of entry FunctionDefs, names excluded as host callbacks)."""
    entries: Set[int] = set()
    callbacks: Set[str] = set()
    all_defs = list(scope.functions.values()) + list(scope.methods.values())
    for fn in all_defs:
        if any(_decorator_is_jit(d) for d in fn.decorator_list):
            entries.add(id(fn))
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d in _ENTRY_WRAPPERS or d in _SCAN_WRAPPERS:
            for target in _func_args(node, d.split(".")[-1]):
                fn = _resolve_target(target, scope)
                if fn is not None:
                    entries.add(id(fn))
                elif isinstance(target, ast.Lambda):
                    entries.add(id(target))
        elif d in _CALLBACKS:
            for target in _func_args(node, "callback"):
                name = dotted(target)
                if name:
                    callbacks.add(name.split(".")[-1])
    return entries, callbacks


def _resolve_target(target: Optional[ast.AST], scope: _Scope
                    ) -> Optional[ast.FunctionDef]:
    if target is None:
        return None
    d = dotted(target)
    if d is None:
        return None
    if d in scope.functions:
        return scope.functions[d]
    parts = d.split(".")
    if len(parts) == 2 and parts[0] == "self":
        for (cls, name), fn in scope.methods.items():
            if name == parts[1]:
                return fn
    return None


def _close_over_calls(f: SourceFile, scope: _Scope, entries: Set[int]
                      ) -> List[ast.AST]:
    """Worklist: traced set closes over same-module calls."""
    by_id = {}
    for fn in list(scope.functions.values()) + list(scope.methods.values()):
        by_id[id(fn)] = fn
    lambdas = {id(n): n for n in ast.walk(f.tree)
               if isinstance(n, ast.Lambda)}
    by_id.update(lambdas)
    work = [by_id[i] for i in entries if i in by_id]
    traced: Set[int] = set()
    out: List[ast.AST] = []
    while work:
        fn = work.pop()
        if id(fn) in traced:
            continue
        traced.add(id(fn))
        out.append(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolve_target(node.func, scope)
            if callee is not None and id(callee) not in traced:
                work.append(callee)
    return out


# ----------------------------------------------------------------- taint

def _taint_names(fn: ast.AST) -> Set[str]:
    """Names holding (likely) traced values: parameters, plus anything
    assigned from an expression referencing a tainted name or a
    jnp./jax./lax. call. Two sweeps approximate the fixpoint."""
    tainted: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            tainted.add(arg.arg)
        if a.vararg:
            tainted.add(a.vararg.arg)
        if a.kwarg:
            tainted.add(a.kwarg.arg)

    def expr_tainted(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d.split(".")[0] in _TRACED_MODULES:
                    return True
        return False

    body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
    for _ in range(2):
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets, value = [node.target], node.iter
            else:
                continue
            if expr_tainted(value):
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    return tainted


# ----------------------------------------------------------------- checks

_SAFE_NP = {"float32", "float64", "int32", "int64", "uint32", "uint64",
            "int8", "uint8", "int16", "uint16", "bool_", "dtype", "finfo",
            "iinfo", "ndim", "shape", "prod", "dtype"}


def _check_traced_fn(f: SourceFile, fn: ast.AST, callbacks: Set[str],
                     out: List[Violation]) -> None:
    tainted = _taint_names(fn)
    name = getattr(fn, "name", "<lambda>")
    skip_ids: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.FunctionDef) and node.name in callbacks:
            for sub in ast.walk(node):
                skip_ids.add(id(sub))

    def is_tainted_expr(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
        return False

    for node in ast.walk(fn):
        if id(node) in skip_ids:
            continue
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            # BX101: unconditional host syncs
            if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
                out.append(Violation(
                    f.rel, node.lineno, "BX101",
                    f"host sync in traced `{name}`: .item() forces a "
                    f"device->host transfer per call"))
            elif d in ("jax.device_get", "device_get"):
                out.append(Violation(
                    f.rel, node.lineno, "BX101",
                    f"host sync in traced `{name}`: jax.device_get blocks "
                    f"on the device inside the traced step"))
            elif d == "print":
                out.append(Violation(
                    f.rel, node.lineno, "BX101",
                    f"print() in traced `{name}` runs at trace time only "
                    f"(or syncs under callbacks); use jax.debug.print"))
            # BX102: host casts of traced values
            elif d in ("float", "int", "bool") and node.args:
                if is_tainted_expr(node.args[0]) and not _static_arg(node.args[0]):
                    out.append(Violation(
                        f.rel, node.lineno, "BX102",
                        f"{d}() cast of traced value in `{name}` forces a "
                        f"host sync (ConcretizationError off the happy path)"))
            # BX103: numpy on traced values
            elif d and d.split(".")[0] in ("np", "numpy"):
                attr = d.split(".")[-1]
                if attr not in _SAFE_NP and any(
                        is_tainted_expr(a) for a in node.args):
                    out.append(Violation(
                        f.rel, node.lineno, "BX103",
                        f"np.{attr}() on traced value in `{name}`: numpy "
                        f"concretizes tracers (host sync / trace error); "
                        f"use jnp.{attr}"))
            # BX104: data-dependent output shapes
            if d:
                parts = d.split(".")
                if (parts[-1] in _DATA_DEP
                        and parts[0] in ("jnp", "jax", "lax")):
                    has_size = any(kw.arg == "size" for kw in node.keywords)
                    if not has_size:
                        out.append(Violation(
                            f.rel, node.lineno, "BX104",
                            f"jnp.{parts[-1]} without size= in traced "
                            f"`{name}`: output shape depends on data "
                            f"(untraceable); pass size= + fill_value"))
                if (parts[-1] == "where" and parts[0] in ("jnp",)
                        and len(node.args) == 1 and not node.keywords):
                    out.append(Violation(
                        f.rel, node.lineno, "BX104",
                        f"one-arg jnp.where in traced `{name}` is "
                        f"data-dependent-shaped; use the 3-arg form or "
                        f"nonzero with size="))
        elif isinstance(node, ast.Subscript):
            # BX105: x[mask] with mask a comparison => data-dependent shape
            sl = node.slice
            if isinstance(sl, ast.Compare) and is_tainted_expr(sl):
                out.append(Violation(
                    f.rel, node.lineno, "BX105",
                    f"boolean-mask indexing in traced `{name}`: result "
                    f"shape depends on data; use jnp.where or a fixed-size "
                    f"gather"))


def _static_arg(e: ast.AST) -> bool:
    """Expressions that are static at trace time even when they mention a
    traced name: shapes, ndim, len()."""
    if isinstance(e, ast.Constant):
        return True
    if isinstance(e, ast.Call) and dotted(e.func) == "len":
        return True
    for n in ast.walk(e):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "size", "dtype"):
            return True
    return False


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        scope = _Scope(f.tree)
        entries, callbacks = _collect_entries(f, scope)
        if not entries:
            continue
        for fn in _close_over_calls(f, scope, entries):
            _check_traced_fn(f, fn, callbacks, out)
    return out
