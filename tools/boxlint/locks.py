"""Pass 4 — lock discipline via ``# guarded-by:`` annotations (BX4xx).

The reference documented lock ownership in C++ types (``std::mutex`` next
to the member it guards, lock_guard at every touch point); our growing
thread population (PromotePrefetcher, the chunk stager, AsyncDenseTable's
update loop, the Channel pipeline, checkpoint writers) shares state under
ad-hoc ``threading.Lock``s with the guard relationship living in
docstrings. The annotation convention makes it mechanical:

    self._deque = collections.deque()   # guarded-by: _mutex

Every later ``self._deque`` read or write in that class must then sit
inside a ``with self._mutex:`` block (``__init__``/``__del__`` are
exempt — no concurrent observer can exist yet/anymore). A deliberately
lock-free access (single-threaded boundary method, GIL-atomic probe)
carries ``# boxlint: disable=BX401`` — on the access line or on the
``def`` line for a whole boundary method — which turns each lock-free
access into an explicit, reviewable decision instead of an accident.

Audited classes are those with at least one annotation: annotating is
the opt-in that declares "instances of this are shared across threads".
(Thread creation itself is a hint, not the trigger — ShardedPassTable
never starts a thread, yet its store_lock serializes a PromotePrefetcher
started two modules away.)

Codes:
  BX401  annotated attribute touched outside ``with self.<lock>``
  BX402  guarded-by names a lock attribute the class never assigns
  BX403  class starts a threading.Thread and takes a threading.Lock but
         annotates nothing (unauditable shared state)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.purity import dotted

_EXEMPT_METHODS = {"__init__", "__del__", "__repr__"}


def _self_attr(node: ast.AST) -> str:
    """'x' for ``self.x`` / ``cls.x``, else ''."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return node.attr
    return ""


def _with_locks(stmt: ast.With) -> Set[str]:
    held: Set[str] = set()
    for item in stmt.items:
        ctx = item.context_expr
        attr = _self_attr(ctx)
        if attr:
            held.add(attr)
        elif isinstance(ctx, ast.Call):
            # with self._lock.acquire_timeout(...), with self._cv: etc.
            attr = _self_attr(ctx.func)
            if attr:
                held.add(attr)
            else:
                base = _self_attr(getattr(ctx.func, "value", None))
                if base:
                    held.add(base)
    return held


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, f: SourceFile):
        self.node = node
        self.f = f
        self.guards: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.assigned_attrs: Set[str] = set()
        self.starts_thread = False
        self.has_lock = False
        self._scan()

    def _scan(self) -> None:
        for sub in ast.walk(self.node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    attr = _self_attr(t)
                    if not attr:
                        continue
                    self.assigned_attrs.add(attr)
                    lock = self.f.guarded_by.get(t.lineno)
                    if lock is None and sub.end_lineno:
                        # annotation may trail the statement's last line
                        # (multi-line assignments)
                        lock = self.f.guarded_by.get(sub.end_lineno)
                    if lock is not None:
                        self.guards.setdefault(attr, (lock, t.lineno))
            elif isinstance(sub, ast.Call):
                d = dotted(sub.func)
                if d and d.split(".")[-1] == "Thread":
                    self.starts_thread = True
                if d and d.split(".")[-1] in ("Lock", "RLock", "Condition"):
                    self.has_lock = True


def _audit_class(info: _ClassInfo, out: List[Violation]) -> None:
    f = info.f
    for attr, (lock, line) in sorted(info.guards.items()):
        if lock not in info.assigned_attrs and not _lock_is_param(info, lock):
            out.append(Violation(
                f.rel, line, "BX402",
                f"guarded-by names {lock!r} but the class never assigns "
                f"self.{lock} (stale annotation?)"))
    for item in info.node.body:
        if not isinstance(item, ast.FunctionDef):
            continue
        if item.name in _EXEMPT_METHODS:
            continue
        _audit_fn(info, item, frozenset(), out)


def _lock_is_param(info: _ClassInfo, lock: str) -> bool:
    for item in info.node.body:
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            return any(a.arg == lock for a in item.args.args)
    return False


def _audit_fn(info: _ClassInfo, node: ast.AST, held: frozenset,
              out: List[Violation]) -> None:
    """Statement-ordered walk tracking the set of held ``self.*`` locks."""
    if isinstance(node, ast.With):
        inner = held | _with_locks(node)
        _check_expr_group(info, [i.context_expr for i in node.items],
                          held, node.lineno, out)
        for stmt in node.body:
            _audit_fn(info, stmt, inner, out)
        return
    # expression positions checked with the CURRENT lock set. Containers
    # that hold statement bodies without BEING statements (except
    # handlers, match cases) must recurse like statements, or a `with
    # self.<lock>` inside them is invisible and its accesses spuriously
    # flag
    _STMT_LIKE = (ast.stmt, ast.ExceptHandler, ast.match_case)
    children = list(ast.iter_child_nodes(node))
    stmt_children = [c for c in children if isinstance(c, _STMT_LIKE)]
    expr_children = [c for c in children if not isinstance(c, _STMT_LIKE)]
    _check_expr_group(info, expr_children, held, getattr(
        node, "lineno", info.node.lineno), out)
    for stmt in stmt_children:
        _audit_fn(info, stmt, held, out)


def _check_expr_group(info: _ClassInfo, exprs: Sequence[ast.AST],
                      held: frozenset, line: int,
                      out: List[Violation]) -> None:
    f = info.f
    for e in exprs:
        if e is None:
            continue
        for sub in ast.walk(e):
            attr = _self_attr(sub)
            if not attr or attr not in info.guards:
                continue
            lock, _ = info.guards[attr]
            if lock in held or attr == lock:
                continue
            kind = ("write" if isinstance(getattr(sub, "ctx", None),
                                          (ast.Store, ast.Del)) else "read")
            out.append(Violation(
                f.rel, getattr(sub, "lineno", line), "BX401",
                f"{kind} of {info.node.name}.{attr} (guarded-by {lock}) "
                f"outside `with self.{lock}`"))
        # nested defs inside expressions (lambdas/comprehensions) are
        # covered by ast.walk above; nested statements are not expected
        # in expression position


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassInfo(node, f)
            if info.guards:
                _audit_class(info, out)
            elif info.starts_thread and info.has_lock:
                out.append(Violation(
                    f.rel, node.lineno, "BX403",
                    f"class {node.name} starts a Thread and takes a Lock "
                    f"but has no `# guarded-by:` annotations — its shared "
                    f"state is unauditable (annotate the attributes the "
                    f"lock protects)"))
    return out
