"""Pass — recompile hazards at jit entry call sites (BX911).

The static twin of the PR-15 recompile sentinel: ``InstrumentedJit``
counts executable cache misses at runtime and alarms after the warmup
budget; this pass pins the three hazard shapes that CAUSE those misses,
at the call site, before a tunnel window ever burns compile time on
them:

  * **python scalars / set displays at traced positions** — a weak-typed
    python scalar keys a different executable than the array the other
    call sites pass (and a set is not even a pytree); wrap the value in
    ``jnp.asarray`` at the boundary or declare the position static;
  * **unstable static values** — ``tuple(<set>)`` / ``list(<set>)`` at a
    ``static_argnums``/``static_argnames`` position hashes differently
    per process (set iteration order), so every run retraces; iterate
    ``sorted(...)`` to make the static key canonical;
  * **mutable module state closed over by a jitted body** — a wrapped
    function reading a module-level ``list``/``dict``/``set`` bakes the
    value at trace time; later mutation is silently invisible (or forces
    a retrace when the shape leaks into the key).

Entry resolution comes from the taint layer's binding maps (module vars,
``self._step`` attrs, factory returns, dataclass fields), so the check
crosses modules: a scalar passed to ``self._step(...)`` is judged
against the ``instrument_jit`` contract declared in the factory that
built it.

Codes:
  BX911  recompile hazard at a jit entry call site / inside a wrapped
         body
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import get_index
from tools.boxlint.purity import dotted
from tools.boxlint.taint import JitEntry, get_contracts

_EXEMPT_PARTS = {"tools", "tests", "examples"}


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    c = get_contracts(files)
    out: List[Violation] = []
    seen: Set[Tuple[str, int, str]] = set()

    def flag(rel: str, line: int, msg: str) -> None:
        key = (rel, line, msg[:40])
        if key not in seen:
            seen.add(key)
            out.append(Violation(rel, line, "BX911", msg))

    # ---- call-site hazards -------------------------------------------
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        local = c._local_jits(node, direct_only=False)
        own = index._own_statement_ids(node)
        for sub in ast.walk(node.fn):
            if id(sub) not in own or not isinstance(sub, ast.Call):
                continue
            entry = c.entry_for_call(sub, node, local)
            if entry is None:
                continue
            _check_site(node.file.rel, sub, entry, flag)

    # ---- closure capture of mutable module state ----------------------
    for entry in c.entries:
        w = entry.wrapped
        if w is None or _exempt(w.file.rel):
            continue
        mutables = _module_mutables(w.file.tree)
        if not mutables:
            continue
        assigned = _assigned_names(w.fn)
        for sub in ast.walk(w.fn):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in mutables and sub.id not in assigned:
                flag(w.file.rel, sub.lineno,
                     f"jitted body `{w.qual}` (entry "
                     f"{entry.describe()}) closes over mutable module "
                     f"state `{sub.id}` — the value is baked at trace "
                     f"time and later mutation is invisible until an "
                     f"unrelated retrace; pass it as an argument or make "
                     f"it an immutable constant")
    return out


def _check_site(rel: str, call: ast.Call, entry: JitEntry, flag) -> None:
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            continue
        is_static = i in entry.static_nums
        if is_static:
            unstable = _set_ordered(arg)
            if unstable:
                flag(rel, call.lineno,
                     f"static_argnums value at position {i} of jit entry "
                     f"{entry.describe()} is derived from set iteration "
                     f"order ({unstable}) — the static key differs per "
                     f"process, so every run retraces; canonicalize with "
                     f"sorted(...)")
            continue
        hazard = _traced_hazard(arg)
        if hazard:
            flag(rel, call.lineno,
                 f"{hazard} at traced position {i} of jit entry "
                 f"{entry.describe()} — it keys a different executable "
                 f"than the array the other call sites pass (the "
                 f"recompile sentinel fires one miss per variant); wrap "
                 f"in jnp.asarray at the boundary or declare the "
                 f"position static")
    for kw in call.keywords:
        if kw.arg is None:
            continue
        if kw.arg in entry.static_names:
            unstable = _set_ordered(kw.value)
            if unstable:
                flag(rel, call.lineno,
                     f"static_argnames value `{kw.arg}` of jit entry "
                     f"{entry.describe()} is derived from set iteration "
                     f"order ({unstable}) — canonicalize with "
                     f"sorted(...)")
            continue
        hazard = _traced_hazard(kw.value)
        if hazard:
            flag(rel, call.lineno,
                 f"{hazard} at traced keyword `{kw.arg}` of jit entry "
                 f"{entry.describe()} — wrap in jnp.asarray or declare "
                 f"it static")


def _traced_hazard(arg: ast.AST) -> Optional[str]:
    """Why this argument destabilizes the signature at a traced position,
    or None. Scalar literals only — a variable may well hold an array."""
    if isinstance(arg, ast.Constant) and type(arg.value) in (int, float,
                                                             bool):
        return f"python scalar literal {arg.value!r}"
    if isinstance(arg, (ast.Set, ast.SetComp)):
        return "set display"
    if isinstance(arg, ast.Call):
        tail = (dotted(arg.func) or "").split(".")[-1]
        if tail == "set":
            return "set(...) value"
    return None


def _set_ordered(expr: ast.AST) -> Optional[str]:
    """An expression whose VALUE depends on set iteration order:
    tuple(<set>)/list(<set>) or a bare set-ish. sorted(...) is stable."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return "a set display"
    if isinstance(expr, ast.Call):
        tail = (dotted(expr.func) or "").split(".")[-1]
        if tail == "set":
            return "set(...)"
        if tail in ("tuple", "list") and expr.args:
            inner = expr.args[0]
            if isinstance(inner, (ast.Set, ast.SetComp)):
                return f"{tail}(<set display>)"
            if isinstance(inner, ast.Call):
                itail = (dotted(inner.func) or "").split(".")[-1]
                if itail == "set":
                    return f"{tail}(set(...))"
    return None


def _module_mutables(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable containers."""
    out: Set[str] = set()
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        v = stmt.value
        mutable = isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                 ast.DictComp, ast.SetComp))
        if isinstance(v, ast.Call):
            tail = (dotted(v.func) or "").split(".")[-1]
            mutable = tail in ("list", "dict", "set", "defaultdict",
                               "OrderedDict", "deque")
        if not mutable:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _assigned_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        out |= {a.arg for a in args.posonlyargs + args.args
                + args.kwonlyargs}
        if args.vararg:
            out.add(args.vararg.arg)
        if args.kwarg:
            out.add(args.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
    return out
