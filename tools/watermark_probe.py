"""Feed-to-serve watermark ladder: the freshness number, measured end
to end through REAL processes.

Round-20 acceptance probe for the watermark plane (obs/watermark.py).
Two modes:

  ladder    (default) one feed->train->serve chain: an in-process
            trainer runs the streaming micro-pass cadence (file drops
            -> admission -> train -> per-boundary journal publish, now
            carrying the window's born-ts watermark record), while a
            SPAWNED serving fleet (MultiBoxFleet, 1 box x 2 replica
            processes) tails the same journal dir, swaps overlays and
            stamps every pull response with its applied watermark. A
            sampler thread pulls through the FleetClient at ~20 ms
            cadence for the whole drain; each stamped response yields
            one TRUE end-to-end freshness sample (born -> served),
            which is exactly what /metrics publishes as
            ``freshness_e2e_ms`` + the ``_p50``/``_p99`` gauges. The
            JSON line carries the client-side p50/p99, the fleet-merged
            server-side percentiles (elementwise-summed replica
            histograms, min-reduced watermark), and the trainer-side
            tier hit ladder.

  --overhead
            pairwise on/off cost of the plane: alternating streaming
            runs with ``obs_watermark`` true/false on one trainer
            (same files, same windows), median pair ratio. The ISSUE
            bar: the whole watermark plane costs <= 2% of streaming
            examples/s. Pairwise because this container's CPU rate
            drifts more between minutes than the effect size.

Usage:  timeout 300 python -u tools/watermark_probe.py
        timeout 300 python -u tools/watermark_probe.py --overhead
Prints one JSON line {"probe": "watermark", ...}; exits 1 on failure
(ladder: no stamped samples; overhead: median cost > 2%).
Heavy imports stay inside functions: spawn re-imports this file in
every fleet child, which must come up jax-free in milliseconds.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

N_FILES, LINES, SLOTS, WIN_FILES = 6, 1500, 16, 2


def build_trainer(root: str):
    from paddlebox_tpu.config.configs import (CheckpointConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import write_synthetic_ctr_files
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.train import CheckpointManager
    from paddlebox_tpu.train.trainer import BoxTrainer

    files, feed = write_synthetic_ctr_files(
        os.path.join(root, "staging"), num_files=N_FILES,
        lines_per_file=LINES, num_slots=SLOTS, vocab_per_slot=5000,
        max_len=4, seed=17)
    feed = type(feed)(slots=feed.slots, batch_size=512)
    trainer = BoxTrainer(
        DeepFM(ModelSpec(num_slots=SLOTS, slot_dim=3 + 8),
               hidden=(256, 128)),
        TableConfig(embedx_dim=8, pass_capacity=1 << 18,
                    optimizer=SparseOptimizerConfig(
                        mf_create_thresholds=0.0, mf_initial_range=1e-3)),
        feed, TrainerConfig(dense_lr=1e-3), seed=0)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=os.path.join(root, "batch"),
                         xbox_model_dir=os.path.join(root, "xbox"),
                         async_save=False),
        trainer.table)
    return files, feed, trainer, cm


def drop(source: str, names) -> None:
    os.makedirs(source, exist_ok=True)
    for i, f in enumerate(names):
        dst = os.path.join(source, "drop-%04d.txt" % i)
        shutil.copyfile(f, dst + ".tmp")
        os.replace(dst + ".tmp", dst)


def run_windows(trainer, cm, feed, source, max_passes, base_every=0):
    from paddlebox_tpu.data import StreamingDataset
    from paddlebox_tpu.train import StreamingRunner
    stream = StreamingDataset(feed, source,
                              micro_pass_instances=WIN_FILES * LINES)
    runner = StreamingRunner(trainer, stream, cm=cm,
                             base_every=base_every,
                             admission_max_drift=10.0)
    return runner.run(max_micro_passes=max_passes, idle_timeout=10.0)


def ladder() -> int:
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.obs import watermark as wm
    from paddlebox_tpu.serving.fleet import MultiBoxFleet

    root = tempfile.mkdtemp(prefix="pbtpu_wmprobe_")
    old_poll = flags.get_flag("streaming_poll_secs")
    flags.set_flag("streaming_poll_secs", 0.02)
    trainer = None
    try:
        files, feed, trainer, cm = build_trainer(root)
        # window 1 with base_every=1 lands the base day the fleet
        # composes its views from (watermark record rides the same
        # boundary publish)
        src = os.path.join(root, "src")
        drop(src, files[:WIN_FILES])
        run_windows(trainer, cm, feed, src, 1, base_every=1)

        samples = []
        with MultiBoxFleet(
                os.path.join(root, "xbox"), boxes=1, replicas=2,
                journal_dirs=[cm.journal.dir],
                flag_overrides={"serving_refresh_secs": 0.05},
                start_timeout=120.0) as fleet:
            fc = fleet.client(timeout=10.0)
            try:
                probe_keys = np.arange(1, 129, dtype=np.uint64)
                stop = threading.Event()

                def sampler():
                    while not stop.is_set():
                        try:
                            fc.pull(probe_keys)
                        except (ConnectionError, RuntimeError):
                            pass
                        # the shard client's last stamped watermark ->
                        # one true born->served freshness sample
                        w = fc.clients[0].last_watermark
                        if w > 0:
                            samples.append(time.time() - w)
                        stop.wait(0.02)

                st = threading.Thread(target=sampler, daemon=True)
                st.start()
                # the remaining windows drain born->trained->published
                # while the fleet tails and the sampler pulls
                drop(src, files[WIN_FILES:])
                run_windows(trainer, cm, feed, src,
                            N_FILES // WIN_FILES - 1)
                time.sleep(0.4)      # final tail poll + overlay swap
                stop.set()
                st.join(timeout=5.0)
                merged = fleet.health()
            finally:
                fc.close()

        arr = np.sort(np.asarray(samples, np.float64))
        out = {
            "probe": "watermark",
            "windows": N_FILES // WIN_FILES,
            "window_instances": WIN_FILES * LINES,
            "e2e": {
                "samples": int(arr.size),
                "p50_secs": (round(float(np.percentile(arr, 50)), 3)
                             if arr.size else None),
                "p99_secs": (round(float(np.percentile(arr, 99)), 3)
                             if arr.size else None),
            },
            "fleet": {k: merged.get(k) for k in (
                "watermark_ts", "freshness_age_secs",
                "freshness_p50_secs", "freshness_p99_secs", "qps")},
            "tier_ladder": wm.tier_ladder(),
            "freshness_snapshot": wm.freshness_snapshot(),
        }
        ok = arr.size > 0
        out["ok"] = ok
        print(json.dumps(out), flush=True)
        return 0 if ok else 1
    finally:
        flags.set_flag("streaming_poll_secs", old_poll)
        if trainer is not None:
            trainer.close()
        shutil.rmtree(root, ignore_errors=True)


def overhead(pairs: int) -> int:
    from paddlebox_tpu.config import flags

    root = tempfile.mkdtemp(prefix="pbtpu_wmover_")
    old_poll = flags.get_flag("streaming_poll_secs")
    old_wm = flags.get_flag("obs_watermark")
    flags.set_flag("streaming_poll_secs", 0.02)
    trainer = None
    seq = [0]
    try:
        files, feed, trainer, cm = build_trainer(root)

        def one_run():
            seq[0] += 1
            src = os.path.join(root, "src-%d" % seq[0])
            drop(src, files[:4])
            return run_windows(trainer, cm, feed, src,
                               2)["examples_per_sec"]

        one_run()                            # compile + warm
        ratios = []
        rows = []
        for _ in range(pairs):
            flags.set_flag("obs_watermark", True)
            on = one_run()
            flags.set_flag("obs_watermark", False)
            off = one_run()
            ratios.append(off / on)
            rows.append({"on_eps": round(on, 1), "off_eps": round(off, 1)})
        med = float(np.median(ratios))
        cost_pct = round((med - 1.0) * 100.0, 2)
        ok = cost_pct <= 2.0
        print(json.dumps({"probe": "watermark_overhead", "pairs": rows,
                          "median_off_over_on": round(med, 4),
                          "watermark_cost_pct": cost_pct, "ok": ok}),
              flush=True)
        return 0 if ok else 1
    finally:
        flags.set_flag("streaming_poll_secs", old_poll)
        flags.set_flag("obs_watermark", old_wm)
        if trainer is not None:
            trainer.close()
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="feed-to-serve watermark freshness ladder over a "
                    "real multi-process train->journal->serve chain")
    ap.add_argument("--overhead", action="store_true",
                    help="pairwise obs_watermark on/off streaming cost "
                         "instead of the ladder")
    ap.add_argument("--pairs", type=int, default=3,
                    help="on/off pairs in --overhead mode (default 3)")
    args = ap.parse_args()
    return overhead(args.pairs) if args.overhead else ladder()


if __name__ == "__main__":
    sys.exit(main())
