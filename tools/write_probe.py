"""Round-5 slab-write strategy probe.

The round-4 characterization (BASELINE.md) left the push WRITE as the one
slab-size-dependent cost: rebuild ~ slab bytes (6-9 ms @1M rows, ~20 @4M),
scatter ~ 75 ns/index (14 ms @131k keys). This probe measures the round-5
candidates for a slab-size-INDEPENDENT write on the live runtime:

  rebuild    where(pos>=0, new_rows[pos], slab)      -- r4 baseline
  scatter    slab.at[uids].set(rows)                 -- r4 fallback
  dus        dynamic_update_slice(log, new, (off,0)) -- log-structured write
  shift      concat(log[K:], new)                    -- log write as pure copy
  pull2      where(m, slab[i1], log[i2])             -- slab+log combined read
  selonly    where(mask, c, slab)                    -- select w/o gather term
  opchain    16 dependent elementwise ops on [K,W]   -- per-op dispatch recal

Every timed region is a fori_loop chain ending in np.asarray of dependent
data (axon's block_until_ready returns early, BASELINE.md). Micro numbers
are only comparable within one run (2-4x cross-session drift, r4 finding).

Usage: timeout 1200 python -u tools/write_probe.py [platform] [caps...]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

W = 17                 # slab value width (bench layout)
K = 131072             # keys/batch at bench shapes (1024 x 32 x 4)
ITERS = 16
REPS = 3


def timed(name, fn, *args, extra=None):
    try:
        out = fn(*args)                      # compile
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fn(*args)
            np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    except Exception as e:  # one failed variant must not kill the battery
        print(json.dumps({"op": name, "error": str(e)[:200]}), flush=True)
        return None
    rec = {"op": name, "ms_per_call": round(ms, 4)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    return ms


def chain(body):
    def run(carry, *args):
        def step(i, c):
            return body(i, c, *args)
        return lax.fori_loop(0, ITERS, step, carry)
    return jax.jit(run)


def probe_cap(cap: int, rng):
    tag = {"cap": cap}
    slab = jnp.asarray(rng.rand(cap, W).astype(np.float32))
    n_uniq = int(K * 0.85)
    uids_np = np.sort(rng.choice(cap - 1, n_uniq, replace=False)).astype(
        np.int32)
    uids_np = np.concatenate(
        [uids_np, np.arange(K - n_uniq, dtype=np.int32) + cap])
    uids = jnp.asarray(uids_np)
    new_rows = jnp.asarray(rng.rand(K, W).astype(np.float32))
    pos_np = np.full(cap, -1, np.int32)
    pos_np[uids_np[:n_uniq]] = np.arange(n_uniq, dtype=np.int32)
    pos = jnp.asarray(pos_np)

    # 1. rebuild (r4 baseline): gather over [cap] + select over [cap, W]
    def rebuild(i, s, p, nr):
        sel = jnp.take(nr + 1.0, jnp.clip(p, 0, nr.shape[0] - 1), axis=0)
        return jnp.where((p >= 0)[:, None], sel, s)
    timed("rebuild", chain(rebuild), slab, pos, new_rows, extra=tag)

    # 2. scatter (r4 fallback)
    def scat(i, s, u, nr):
        return s.at[u].set(nr + 1.0, mode="drop", unique_indices=True)
    timed("scatter", chain(scat), slab, uids, new_rows, extra=tag)

    # 3. DUS of [K, W] at an iteration-varying offset into a [cap, W] log
    n_off = max(1, cap // K)

    def dus(i, lg, nr):
        off = (i % n_off) * K
        return lax.dynamic_update_slice(lg, nr + 1.0, (off, 0))
    timed("dus", chain(dus), slab + 0.0, new_rows, extra=tag)

    # 4. shift-log: pure copy, no gather/scatter; positions roll by K
    def shift(i, lg, nr):
        return jnp.concatenate([lg[K:], nr + lg[:1, :1]], axis=0)
    timed("shift", chain(shift), slab + 0.0, new_rows, extra=tag)

    # 5. combined slab+log pull: 2 gathers + select, K indices
    lg = jnp.asarray(rng.rand(min(cap, 8 * K), W).astype(np.float32))
    i1 = jnp.asarray(rng.randint(0, cap, K).astype(np.int32))
    i2 = jnp.asarray(rng.randint(0, lg.shape[0], K).astype(np.int32))
    msk = jnp.asarray((rng.rand(K) < 0.5))

    def pull2(i, c, s, l2, a, b, m):
        r = jnp.where(m[:, None], jnp.take(s, a, axis=0),
                      jnp.take(l2, b, axis=0))
        return c + r[:1, :1]
    timed("pull2", chain(pull2), jnp.zeros((1, 1)), slab, lg, i1, i2, msk,
          extra=tag)

    def pull1(i, c, s, a):
        return c + jnp.take(s, a, axis=0)[:1, :1]
    timed("pull1", chain(pull1), jnp.zeros((1, 1)), slab, i1, extra=tag)

    # 6. select-only over [cap, W] (no gather term)
    mask_cap = jnp.asarray((rng.rand(cap) < 0.1))

    def selonly(i, s, m):
        return jnp.where(m[:, None], s + 1.0, s)
    timed("selonly", chain(selonly), slab, mask_cap, extra=tag)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform,
                      "K": K, "W": W, "iters": ITERS}), flush=True)
    rng = np.random.RandomState(0)

    # per-op dispatch recalibration: 16 dependent elementwise ops on [K, W]
    x = jnp.asarray(rng.rand(K, W).astype(np.float32))

    def ops16(i, c):
        for j in range(16):
            c = jnp.sin(c) + np.float32(j)   # sin blocks fusion collapse
        return c
    timed("opchain16_sin_KxW", chain(ops16), x)

    caps = [int(a) for a in sys.argv[2:]] or [1 << 20, 1 << 22]
    for cap in caps:
        probe_cap(cap, rng)


if __name__ == "__main__":
    main()
