"""Regime-step probe: step time vs slab size at CONSTANT batch work
(ROADMAP open item 1's reproducing probe, round 11).

The round-5 VERDICT left one mechanism unnamed: every write mode steps
~16 ms on a 1M-row slab but ~24-26 ms at ≥4M rows (flat to 134M) on the
axon runtime — table size leaking into step time that the reference's
`heter_ps/hashtable.h` design keeps flat. This probe bisects it with the
PR-5 telemetry plane:

  1. row-count ladder — fine sweep across the 1M→4M threshold, same
     batch/key work at every size; per-step spans feed a StepReport-
     style histogram (utils/stats HIST_BOUNDS) so p50/p90/p99 survive,
     and every timed step is a span in a Perfetto-loadable chrome trace
     (--trace PATH).
  2. constant-bytes — row-width vs row-count at equal slab bytes
     (embedx 8 vs 40): a threshold that tracks BYTES indicts
     allocator/pagewalk mechanics; one that tracks ROWS indicts the
     scatter/gather index path.
  3. donated vs fresh — the production step donates the slab
     (buffer reuse in place); the fresh tier deep-copies the slab
     on device every step so the update can never reuse the pages.
     A regime step that vanishes with donation indicts allocation;
     one that survives it indicts access mechanics.

On this container (no axon plugin) the probe runs the CPU tier: it
measures the CPU-regime analog and records whatever threshold exists
HERE; the axon numbers fill in at a tunnel window. Findings →
BASELINE.md round 11.

Usage:
  timeout 3000 python -u tools/regime_step_probe.py [platform] \
      [--trace /tmp/regime_trace.json] [--caps 1048576,2097152,...]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

_args = [a for a in sys.argv[1:] if not a.startswith("--")]
jax.config.update("jax_platforms", _args[0] if _args else "cpu")

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.obs.tracer import get_tracer
from paddlebox_tpu.utils.stats import StatRegistry, hist_percentile
from tools.bench_util import make_bench_trainer, make_ctr_batches

D, NUM_SLOTS, BATCH, MAX_LEN = 8, 32, 512, 4
CHUNK, REPS = 4, 6


def _opt(name, default=None):
    for a in sys.argv[1:]:
        if a.startswith("--%s=" % name):
            return a.split("=", 1)[1]
        if a == "--%s" % name:
            i = sys.argv.index(a)
            if i + 1 < len(sys.argv):
                return sys.argv[i + 1]
    return default


def build(cap, d=D):
    """Bench trainer at `cap` rows with a device-resident slab (no
    multi-GB promote H2D — same dodge as capacity_probe)."""
    tr, feed = make_bench_trainer(cap, batch=BATCH, num_slots=NUM_SLOTS,
                                  max_len=MAX_LEN, d=d)
    batches = make_ctr_batches(feed, CHUNK, NUM_SLOTS, MAX_LEN, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    W = tr.table.layout.width
    tr.table._slab = jnp.zeros((cap, W), jnp.float32)
    tr.table._in_pass = True
    stacked = tr._stack_batches(batches)
    return tr, stacked, W


def timed_steps(tr, stacked, label, fresh=False, reps=REPS):
    """Per-rep spans + histogram samples; returns dict of ms stats.
    fresh=True deep-copies the slab on device before every rep so the
    donated-in buffer is a new allocation each call (donation still
    happens — the COPY is what defeats in-place reuse)."""
    tracer = get_tracer()
    reg = StatRegistry.instance()
    hist = "regime_%s_ms" % label
    state = (tr.table.slab, tr.params, tr.opt_state, tr.table.next_prng())
    for _ in range(2):  # compile + warm
        slab, params, opt, losses, _p, key = tr.fns.scan_steps(
            state[0], state[1], state[2], stacked, state[3])
        state = (slab, params, opt, key)
    np.asarray(losses)
    samples = []
    for _ in range(reps):
        slab_in = state[0]
        if fresh:
            slab_in = jax.block_until_ready(
                jax.jit(lambda x: x + 0.0)(slab_in))
        t0 = time.perf_counter()
        slab, params, opt, losses, _p, key = tr.fns.scan_steps(
            slab_in, state[1], state[2], stacked, state[3])
        np.asarray(losses)          # chain-dependent sync point
        t1 = time.perf_counter()
        tracer.record_span("regime_step:%s" % label, t0, t1)
        step_ms = (t1 - t0) / CHUNK * 1e3
        reg.observe(hist, step_ms)
        samples.append(step_ms)
        state = (slab, params, opt, key)
    counts = reg.hist_counts(hist) or []
    return {
        "ms_per_step_min": round(min(samples), 3),
        "ms_per_step_med": round(float(np.median(samples)), 3),
        "hist_p50": round(hist_percentile(counts, 0.50), 3),
        "hist_p90": round(hist_percentile(counts, 0.90), 3),
        "hist_p99": round(hist_percentile(counts, 0.99), 3),
    }


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform,
                      "batch": BATCH, "chunk": CHUNK, "reps": REPS}),
          flush=True)
    caps_arg = _opt("caps")
    caps = ([int(c) for c in caps_arg.split(",")] if caps_arg else
            [1 << 20, 3 << 19, 1 << 21, 3 << 20, 1 << 22, 3 << 21])

    # ---- tier 1: row-count ladder (constant work, growing slab) ----
    base_ms = None
    for cap in caps:
        try:
            tr, stacked, W = build(cap)
            rec = {"tier": "row_ladder", "cap_rows": cap,
                   "slab_mb": round(cap * W * 4 / 2**20, 1),
                   "push_write": tr._push_write}
            rec.update(timed_steps(tr, stacked, "rows_%d" % cap))
            if base_ms is None:
                base_ms = rec["ms_per_step_min"]
            rec["vs_first"] = round(rec["ms_per_step_min"] / base_ms, 3)
            tr.close()
        except Exception as e:  # OOM/compile fail is a finding, not a crash
            rec = {"tier": "row_ladder", "cap_rows": cap,
                   "error": repr(e)[:300]}
        print(json.dumps(rec), flush=True)

    # ---- tier 2: constant bytes, rows vs width ----
    # same slab BYTES by trading embedx width against row count: a
    # threshold that follows bytes (both shapes step alike) indicts
    # memory mechanics; one that follows rows indicts the index path
    bytes_target = caps[-1] * 17 * 4          # widest ladder slab, d=8
    for d in (8, 40):
        tmp, feed = make_bench_trainer(1024, batch=8, num_slots=NUM_SLOTS,
                                       max_len=MAX_LEN, d=d)
        W = tmp.table.layout.width
        tmp.close()
        cap = max(1 << 16, int(bytes_target // (4 * W)))
        try:
            tr, stacked, W = build(cap, d=d)
            rec = {"tier": "const_bytes", "embedx": d, "cap_rows": cap,
                   "width": W,
                   "slab_mb": round(cap * W * 4 / 2**20, 1)}
            rec.update(timed_steps(tr, stacked, "w%d_r%d" % (W, cap)))
            tr.close()
        except Exception as e:
            rec = {"tier": "const_bytes", "embedx": d, "cap_rows": cap,
                   "error": repr(e)[:300]}
        print(json.dumps(rec), flush=True)

    # ---- tier 3: donated vs fresh buffers at the threshold ----
    for cap in (caps[0], caps[-1]):
        try:
            rec = {"tier": "donated_vs_fresh", "cap_rows": cap}
            # fresh trainer per tier: the warmup of a timed run DONATES
            # the table's slab buffer — a second run on the same trainer
            # would start from a deleted buffer
            tr, stacked, W = build(cap)
            don = timed_steps(tr, stacked, "don_%d" % cap, fresh=False)
            tr.close()
            tr, stacked, W = build(cap)
            fre = timed_steps(tr, stacked, "fresh_%d" % cap, fresh=True)
            tr.close()
            rec["donated_ms"] = don["ms_per_step_min"]
            rec["fresh_ms"] = fre["ms_per_step_min"]
            rec["fresh_over_donated"] = round(
                fre["ms_per_step_min"] / max(don["ms_per_step_min"], 1e-9),
                3)
        except Exception as e:
            rec = {"tier": "donated_vs_fresh", "cap_rows": cap,
                   "error": repr(e)[:300]}
        print(json.dumps(rec), flush=True)

    trace_path = _opt("trace")
    if trace_path:
        get_tracer().export_chrome(trace_path,
                                   meta={"probe": "regime_step"})
        print(json.dumps({"trace": trace_path}), flush=True)


if __name__ == "__main__":
    main()
