"""Measure the serving-scale xbox mmap store (round-5 verdict item 8).

Builds a synthetic sorted columnar base of N keys DIRECTLY ON DISK (the
file is written in chunks — the probe box never holds the row matrix in
RAM, matching the store's no-full-ingest contract), then measures:
  * store open (mmap + native key-index build) seconds
  * lookup keys/s, hot (resident working set) and uniform-random over
    the whole base, at serving batch sizes
  * the searchsorted fallback tier for comparison

Usage: timeout 1800 python -u tools/xbox_store_probe.py [n_keys] [dim]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddlebox_tpu.serving.store import MmapXboxStore, _XBOX_MAGIC

N = int(sys.argv[1]) if len(sys.argv) > 1 else 30_000_000
DIM = int(sys.argv[2]) if len(sys.argv) > 2 else 9
PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "_xbox_probe.store")
CHUNK = 4_000_000
BATCH = 131072          # serving batch = the trainer's per-batch key budget


def build_file():
    """Sorted keys = 16*i + small jitter (strictly increasing, sparse in
    key space so misses are probeable); rows = f32 pattern."""
    t0 = time.perf_counter()
    key_off = (8 + 8 + 8 + 63) // 64 * 64
    row_off = (key_off + N * 8 + 63) // 64 * 64
    with open(PATH, "wb") as f:
        f.write(_XBOX_MAGIC)
        f.write(np.int64(N).tobytes())
        f.write(np.int64(DIM).tobytes())
        for lo in range(0, N, CHUNK):
            n = min(CHUNK, N - lo)
            ks = (np.arange(lo, lo + n, dtype=np.uint64) * 16
                  + np.uint64(3))
            f.seek(key_off + lo * 8)
            ks.tofile(f)
        for lo in range(0, N, CHUNK):
            n = min(CHUNK, N - lo)
            rows = np.ones((n, DIM), np.float32)
            rows[:, 0] = ((np.arange(lo, lo + n, dtype=np.int64)
               & 0xFFFF).astype(np.float32))  # f32-exact check value
            f.seek(row_off + lo * DIM * 4)
            rows.tofile(f)
    print(json.dumps({"stage": "build_file", "n": N, "dim": DIM,
                      "bytes": os.path.getsize(PATH),
                      "secs": round(time.perf_counter() - t0, 1)}),
          flush=True)


def run_lookups(store, tag):
    rng = np.random.RandomState(0)
    # hot set: 1M distinct keys probed repeatedly (the serving cache case)
    hot_ids = rng.randint(0, min(N, 1 << 20), 4 * BATCH).astype(np.uint64)
    hot = hot_ids * np.uint64(16) + np.uint64(3)
    # uniform: spans the whole base (page-cache-hostile case) + 10% misses
    uni_ids = rng.randint(0, N, 4 * BATCH).astype(np.uint64)
    uni = uni_ids * np.uint64(16) + np.uint64(3)
    uni[::10] += np.uint64(1)  # misses
    for name, probe in (("hot", hot), ("uniform", uni)):
        batches = probe.reshape(4, BATCH)
        store.lookup(batches[0])      # warm
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 3.0:
            out = store.lookup(batches[reps % 4])
            reps += 1
        dt = time.perf_counter() - t0
        kps = reps * BATCH / dt
        # correctness spot check on the last batch
        got = out[:, 0]
        ids = ((batches[(reps - 1) % 4] // np.uint64(16))
               .astype(np.int64) & 0xFFFF)
        hitmask = (batches[(reps - 1) % 4] % np.uint64(16)
                   ) == np.uint64(3)
        assert np.array_equal(got[hitmask], ids[hitmask].astype(np.float32))
        assert (out[~hitmask] == 0).all()
        print(json.dumps({"stage": f"lookup_{name}_{tag}",
                          "keys_per_sec": round(kps, 0),
                          "batch": BATCH, "reps": reps}), flush=True)


def main():
    if not (os.path.exists(PATH)
            and os.path.getsize(PATH) > N * (8 + DIM * 4)):
        build_file()
    t0 = time.perf_counter()
    store = MmapXboxStore(PATH)
    print(json.dumps({"stage": "open_with_index", "n": len(store),
                      "secs": round(time.perf_counter() - t0, 1),
                      "native_index": store._index is not None}),
          flush=True)
    run_lookups(store, "native")
    store.close()   # drops to the searchsorted fallback tier
    run_lookups(store, "searchsorted")


if __name__ == "__main__":
    main()
