"""Terminal ops console: poll every rank's live HTTP endpoints.

The operator-side consumer of obs/exporter.py (flag ``obs_http_port``):
polls ``/report``, ``/health`` and ``/quality`` across a set of ranks
and renders ONE refreshing table — rank, step, examples/s, health score
(+ flags), quality auc/copc, drift score — plus the rank-0 cluster
health summary. Works against trainers and serving replicas alike
(both bind port + rank off the same flag).

Usage:
    python tools/ops_console.py --base-port 9100 --ranks 2
    python tools/ops_console.py 127.0.0.1:9100 127.0.0.1:9101
    python tools/ops_console.py --base-port 9100 --ranks 2 --once --json

``--once`` prints a single snapshot (scripts, tests); the default loop
redraws every ``--interval`` seconds until interrupted. ``--json``
emits the raw merged snapshot as one JSON line instead of the table.
Exits 0; unreachable ranks render as ``down`` (an ops console must not
die because a rank did).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, List, Optional

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))


def fetch_json(endpoint: str, path: str,
               timeout: float = 2.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen("http://%s%s" % (endpoint, path),
                                    timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except Exception:  # noqa: BLE001 — a dead rank renders as down
        return None


def snapshot(endpoints: List[str]) -> dict:
    """One poll across every rank: {rank_endpoint: {report, health,
    quality}} + the first merged cluster_health found (rank 0's)."""
    ranks: Dict[str, dict] = {}
    cluster = None
    for ep in endpoints:
        rep = fetch_json(ep, "/report")
        health = fetch_json(ep, "/health")
        qual = fetch_json(ep, "/quality")
        ranks[ep] = {"report": rep, "health": health, "quality": qual}
        if (cluster is None and health
                and health.get("type") == "cluster_health"):
            cluster = health
    return {"ts": time.time(), "ranks": ranks, "cluster_health": cluster}


def _fmt(v, spec="%s", dash="-"):
    return spec % v if v is not None else dash


def render(snap: dict) -> str:
    lines = []
    lines.append("pbtpu ops console  %s"
                 % time.strftime("%H:%M:%S", time.localtime(snap["ts"])))
    hdr = ("%-22s %8s %10s %7s %-14s %7s %7s %7s"
           % ("endpoint", "step", "ex/s", "score", "flags", "auc",
              "copc", "drift"))
    lines.append(hdr)
    lines.append("-" * len(hdr))
    cluster = snap.get("cluster_health") or {}
    cranks = cluster.get("ranks") or {}
    for ep, d in snap["ranks"].items():
        rep = (d.get("report") or {}).get("report") or {}
        if not d.get("report"):
            lines.append("%-22s %8s" % (ep, "down"))
            continue
        rank = str((d.get("report") or {}).get("rank", ""))
        hent = cranks.get(rank) or {}
        health = d.get("health") or {}
        if not hent and health.get("type") == "rank_liveness":
            hent = {}
        q = (d.get("quality") or {}).get("quality") or {}
        allq = (q.get("tags") or {}).get("all") or {}
        drift = ((d.get("quality") or {}).get("drift") or {})
        last = (drift.get("last") or {}).get("drift") or {}
        lines.append("%-22s %8s %10s %7s %-14s %7s %7s %7s" % (
            ep,
            _fmt(rep.get("step")),
            _fmt(rep.get("examples_per_sec"), "%.1f"),
            _fmt(hent.get("score"), "%.2f"),
            ",".join(hent.get("flags") or ())[:14] or "-",
            _fmt(allq.get("auc"), "%.4f"),
            _fmt(allq.get("copc"), "%.3f"),
            _fmt(last.get("score"), "%.3f")))
    if cluster:
        unhealthy = cluster.get("unhealthy_ranks") or []
        lines.append("cluster: world=%s step=%s unhealthy=%s"
                     % (cluster.get("world"), cluster.get("step"),
                        unhealthy if unhealthy else "none"))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="poll per-rank obs HTTP endpoints into one "
                    "terminal dashboard")
    ap.add_argument("endpoints", nargs="*", metavar="HOST:PORT",
                    help="explicit endpoints (alternative to "
                         "--base-port/--ranks)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--base-port", type=int, default=0,
                    help="obs_http_port of the job; rank r polls "
                         "base+r")
    ap.add_argument("--ranks", type=int, default=1,
                    help="number of ranks to poll with --base-port")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw snapshot as one JSON line")
    args = ap.parse_args(argv)
    endpoints = list(args.endpoints)
    if args.base_port:
        endpoints += ["%s:%d" % (args.host, args.base_port + r)
                      for r in range(args.ranks)]
    if not endpoints:
        ap.error("no endpoints: pass HOST:PORT args or --base-port")
    while True:
        snap = snapshot(endpoints)
        if args.json:
            print(json.dumps(snap), flush=True)
        else:
            out = render(snap)
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")     # clear + home
            print(out, flush=True)
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
