"""Where does the per-step cap-proportional copy come from? (round 5)

capacity_probe shows the log-mode step growing ~2.6 ms per M slab rows
(~ one full-buffer copy/step at ~27 GB/s) while an isolated
gather+DUS scan over the same buffer is FLAT (scan_vs_fori). This grows
the scan body stepwise from the flat probe toward the real step and
measures the cap slope of each variant at two capacities:

  A  gather(buf, xs_src) -> rows; DUS(buf, rows*0.999)
  B  A with new_rows = apply_push-style column rewrite of rows
  C  B with the real _merged_new_rows (perm gather + segment-sum +
     in-table adagrad + threefry lazy-init)
  D  C plus a dense fwd/bwd-sized matmul chain on pooled rows

Usage: timeout 2400 python -u tools/slope_probe.py [platform]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

from paddlebox_tpu.config.configs import SparseOptimizerConfig
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding.optimizers import _merged_new_rows

W = 17
K = 131072
ITERS = 8
REPS = 3
L = 16 * K
CAPS = [1 << 22, 1 << 24]


def timed(name, fn, state, extra=None):
    try:
        st = fn(*state)
        np.asarray(jax.tree_util.tree_leaves(st)[-1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            st = fn(*st)
            np.asarray(jax.tree_util.tree_leaves(st)[-1])
        ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    except Exception as e:
        print(json.dumps({"op": name, "error": str(e)[:200]}), flush=True)
        return
    rec = {"op": name, "ms_per_iter": round(ms, 3)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def probe(cap, rng):
    tag = {"cap": cap}
    layout = ValueLayout(8, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    buf0 = jnp.asarray(np.zeros((cap + L, W), np.float32))
    src = jnp.asarray(
        rng.randint(0, cap, (ITERS, K)).astype(np.int32))
    n_u = int(K * 0.85)
    uids = jnp.asarray(np.broadcast_to(np.concatenate(
        [np.sort(rng.choice(cap - 1, n_u, replace=False)).astype(np.int32),
         np.arange(K - n_u, dtype=np.int32) + cap]), (ITERS, K)).copy())
    perm = jnp.asarray(np.broadcast_to(
        rng.permutation(K).astype(np.int32), (ITERS, K)).copy())
    inv = jnp.asarray(np.broadcast_to(
        np.sort(rng.randint(0, n_u, K)).astype(np.int32),
        (ITERS, K)).copy())
    first = jnp.asarray(np.broadcast_to(
        rng.randint(0, K, K).astype(np.int32), (ITERS, K)).copy())
    grads = jnp.asarray(rng.rand(ITERS, K, 12).astype(np.float32))
    prng0 = jax.random.PRNGKey(0)

    def scan_run(body):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run(carry):
            def step(c, xs):
                return body(c, xs), 0.0
            c2, _ = lax.scan(step, carry,
                             (src, uids, perm, inv, first, grads))
            return c2
        return lambda *c: (run(c[0]),)

    def mk():
        # fresh leaves per variant: donation consumes the whole carry
        return ((buf0 + 0.0, jnp.zeros((), jnp.int32),
                 jax.random.PRNGKey(0), jnp.zeros(())),)

    def vA(c, xs):
        buf, cur, prng, acc = c
        s, u, p, iv, f, g = xs
        rows = jnp.take(buf, s, axis=0)
        nr = rows * 0.999
        buf = lax.dynamic_update_slice(buf, nr, (jnp.int32(cap) + cur, 0))
        return (buf, (cur + K) % (L - K), prng, acc + nr[0, 0])

    def colwork(rows, g):
        # apply_push-shaped column rewrite (~30 masked col ops)
        out = rows
        show = g[:, 1:2]
        for col in range(W):
            out = out.at[:, col:col + 1].set(
                jnp.where(show > 0, out[:, col:col + 1] * 0.999 + 0.001,
                          out[:, col:col + 1]))
        return out

    def vB(c, xs):
        buf, cur, prng, acc = c
        s, u, p, iv, f, g = xs
        rows = jnp.take(buf, s, axis=0)
        nr = colwork(rows, g)
        buf = lax.dynamic_update_slice(buf, nr, (jnp.int32(cap) + cur, 0))
        return (buf, (cur + K) % (L - K), prng, acc + nr[0, 0])

    def vC(c, xs):
        buf, cur, prng, acc = c
        s, u, p, iv, f, g = xs
        prng, sub = jax.random.split(prng)
        rows = jnp.take(buf, s, axis=0)
        nr = _merged_new_rows(buf, u, p, iv, g, sub, layout, conf,
                              pulled_rows=rows, first_idx=f)
        buf = lax.dynamic_update_slice(buf, nr, (jnp.int32(cap) + cur, 0))
        return (buf, (cur + K) % (L - K), prng, acc + nr[0, 0])

    Wd = 352

    def vD(c, xs):
        buf, cur, prng, acc = c
        s, u, p, iv, f, g = xs
        prng, sub = jax.random.split(prng)
        rows = jnp.take(buf, s, axis=0)
        pooled = rows[:1024 * 11, :].reshape(1024, -1)[:, :Wd // 2]
        h = jnp.concatenate([pooled, pooled], axis=1).astype(jnp.bfloat16)
        for wm in (jnp.ones((Wd, 512), jnp.bfloat16),
                   jnp.ones((512, 256), jnp.bfloat16),
                   jnp.ones((256, 128), jnp.bfloat16)):
            h = jnp.tanh(h @ wm)
        loss = h.astype(jnp.float32).sum() * 1e-6
        nr = _merged_new_rows(buf, u, p, iv, g, sub, layout, conf,
                              pulled_rows=rows, first_idx=f)
        buf = lax.dynamic_update_slice(buf, nr, (jnp.int32(cap) + cur, 0))
        return (buf, (cur + K) % (L - K), prng, acc + nr[0, 0] + loss)

    for name, body in (("A_gather_dus", vA), ("B_colwork", vB),
                       ("C_real_push", vC), ("D_plus_dense", vD)):
        timed(name, scan_run(body), mk(), tag)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    for cap in CAPS:
        probe(cap, rng)


if __name__ == "__main__":
    main()
