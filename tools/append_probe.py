"""How to append rows cheaply on this backend + H2D bandwidth.

overlay_probe.py: dus of 131k rows into a 71MB buffer costs 4.1 ms (the
runtime copies the output buffer; no in-place aliasing). Folding scatters
amortize only with LARGE windows, which need a cheap append. Candidates:
(a) dus into buffers of growing size (does cost scale with buffer?),
(b) lax.scan's native ys stacking (loop machinery writes slices itself),
(c) donated-arg dus at top jit level (explicit donation may alias).
Plus: H2D throughput for the uids-from-host decision.

Usage: timeout 900 python -u tools/append_probe.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

K = 131072
W = 17
REPS = 5


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    rows = jnp.asarray(rng.rand(K, W).astype(np.float32))

    # (a) dus chained inside fori, buffer sizes 8K..64K rows worth
    for mult in (8, 16, 32, 64):
        buf = jnp.zeros((mult * K, W), jnp.float32)
        iters = 16

        def run(b, r):
            def step(i, c):
                return lax.dynamic_update_slice(
                    c, r + c[:1, :1] * 0, ((i * K) % ((mult - 1) * K), 0))
            return lax.fori_loop(0, iters, step, b)
        f = jax.jit(run)
        out = f(buf, rows); np.asarray(out.ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = f(out, rows)
            np.asarray(out.ravel()[:1])
        ms = (time.perf_counter() - t0) / REPS / iters * 1e3
        mb = mult * K * W * 4 // (1 << 20)
        print(json.dumps({"op": f"dus_into_{mb}MB_buffer",
                          "ms_per_call": round(ms, 4)}), flush=True)

    # (b) scan ys stacking: 16 iterations each emitting [K, W]
    def scan_ys(x):
        def step(c, _):
            c = c * 1.000001
            return c, c
        return lax.scan(step, x, None, length=16)
    f = jax.jit(scan_ys)
    c, ys = f(rows); np.asarray(ys.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        c, ys = f(c)
        np.asarray(ys.ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / 16 * 1e3
    print(json.dumps({"op": "scan_ys_append_131k_rows_x16",
                      "ms_per_call": round(ms, 4)}), flush=True)

    # (c) donated top-level dus, 142MB buffer
    buf = jnp.zeros((32 * K, W), jnp.float32)

    @jax.jit
    def dono(b, r, off):
        return lax.dynamic_update_slice(b, r, (off, 0))
    dono2 = jax.jit(lambda b, r, off: lax.dynamic_update_slice(b, r, (off, 0)),
                    donate_argnums=(0,))
    out = dono2(buf, rows, jnp.int32(0)); np.asarray(out.ravel()[:1])
    t0 = time.perf_counter()
    for i in range(REPS * 4):
        out = dono2(out, rows, jnp.int32((i * K) % (31 * K)))
    np.asarray(out.ravel()[:1])
    ms = (time.perf_counter() - t0) / (REPS * 4) * 1e3
    print(json.dumps({"op": "dus_donated_toplevel_142MB",
                      "ms_per_call": round(ms, 4)}), flush=True)

    # H2D: 512KB and 8MB device_put
    for nbytes, label in ((K * 4, "512KB"), (K * 4 * 16, "8MB")):
        arr = np.random.rand(nbytes // 4).astype(np.float32)
        jax.device_put(arr).block_until_ready()
        t0 = time.perf_counter()
        outs = [jax.device_put(arr) for _ in range(8)]
        np.asarray(outs[-1].ravel()[:1])
        ms = (time.perf_counter() - t0) / 8 * 1e3
        print(json.dumps({"op": f"h2d_{label}", "ms_per_call": round(ms, 4),
                          "gb_per_s": round(nbytes / (ms * 1e-3) / 1e9, 2)}),
              flush=True)


if __name__ == "__main__":
    main()
