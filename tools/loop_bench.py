"""Is lax control flow itself slow on this backend?

calib_bench.py measured 0.28 ms PER fori_loop ITERATION on a scalar body
(~100x a normal TPU). Hypothesis: the axon tunnel dispatches per loop
iteration. Compare: unrolled multiply chains vs fori_loop vs scan, and a
single fat op — at equal logical work.

Usage: timeout 900 python -u tools/loop_bench.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 5
N = 256


def timed(name, fn, *args):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS * 1e3
    print(json.dumps({"op": name, "ms_per_call": round(ms, 4),
                      "ms_per_unit": round(ms / N, 4)}), flush=True)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    x = jnp.float32(1.0)
    v = jnp.zeros((8, 128), jnp.float32) + 1.0

    @jax.jit
    def unrolled_scalar(y):
        for _ in range(N):
            y = y * 1.000001
        return y
    timed("unrolled_256_scalar_mults", unrolled_scalar, x)

    @jax.jit
    def loop_scalar(y):
        return lax.fori_loop(0, N, lambda i, c: c * 1.000001, y)
    timed("fori_256_scalar_mults", loop_scalar, x)

    @jax.jit
    def scan_scalar(y):
        def step(c, _):
            return c * 1.000001, ()
        out, _ = lax.scan(step, y, None, length=N)
        return out
    timed("scan_256_scalar_mults", scan_scalar, x)

    @jax.jit
    def unrolled_vec(y):
        for _ in range(N):
            y = y * 1.000001 + 1e-9
        return y
    timed("unrolled_256_vec_ops", unrolled_vec, v)

    @jax.jit
    def scan_vec(y):
        def step(c, _):
            return c * 1.000001 + 1e-9, ()
        out, _ = lax.scan(step, y, None, length=N)
        return out
    timed("scan_256_vec_ops", scan_vec, v)

    # dispatch cost: N separate tiny jit calls, python-chained
    f = jax.jit(lambda y: y * 1.000001)
    y = f(x); np.asarray(y.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(N):
        y = f(y)
    np.asarray(y.ravel()[:1])
    ms = (time.perf_counter() - t0) * 1e3
    print(json.dumps({"op": "python_256_dispatches",
                      "ms_per_call": round(ms, 4),
                      "ms_per_unit": round(ms / N, 4)}), flush=True)


if __name__ == "__main__":
    main()
