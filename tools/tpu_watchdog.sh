#!/bin/bash
# TPU-outage watchdog (VERDICT r2 next-round #1): the axon tunnel can be
# down for hours AND flap mid-measurement; retry a cheap probe forever and
# fire the full one-window measurement battery + bench the moment the chip
# answers. Only stops once BOTH artifacts contain real TPU results — a
# tunnel flap right after a good probe must not end the loop empty-handed.
#
# Run detached (nohup). Artifacts:
#   tools/tpu_watch.log        — probe attempts
#   tools/tpu_probe_out.jsonl  — stage battery (tools/tpu_probe.py)
#   tools/bench_out.json       — bench.py line captured on the chip
cd "$(dirname "$0")/.." || exit 1
SLEEP="${TPU_WATCH_SLEEP:-540}"
log() { echo "$(date -u +%FT%TZ) $*" >>tools/tpu_watch.log; }
while true; do
  if timeout 180 python bench.py --probe axon >/tmp/axon_probe.json 2>/dev/null \
      && grep -q '"ok": true' /tmp/axon_probe.json; then
    log "axon UP — running battery"
    # stderr goes to the log, NOT the artifacts — a stray warning line
    # would make the captured .json/.jsonl unparseable
    timeout 1800 python -u tools/tpu_probe.py >tools/tpu_probe_out.jsonl \
      2>>tools/tpu_watch.log
    rc_probe=$?
    timeout 900 python bench.py >tools/bench_out.json 2>>tools/tpu_watch.log
    rc_bench=$?
    if grep -q '"stage"' tools/tpu_probe_out.jsonl 2>/dev/null \
        && grep -Eq '"platform": "(axon|tpu)"' tools/bench_out.json 2>/dev/null; then
      log "battery done (probe rc=$rc_probe bench rc=$rc_bench) — TPU evidence captured"
      break
    fi
    log "battery incomplete (probe rc=$rc_probe bench rc=$rc_bench) — retrying"
  else
    log "axon down"
  fi
  sleep "$SLEEP"
done
