"""bf16 mixed-precision dense compute (TrainerConfig.compute_dtype):
matmuls run in the compute dtype, master params/opt state stay f32, and
learning survives the precision drop."""

import jax
import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.metrics.auc import BasicAucCalculator
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("bf16")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=300, num_slots=NUM_SLOTS,
        vocab_per_slot=80, max_len=3, seed=21)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def table_cfg():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.2,
                                        mf_learning_rate=0.2))


def test_bf16_box_trainer_learns(data):
    files, feed = data
    trainer = BoxTrainer(
        CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D), hidden=(16,)),
        table_cfg(), feed,
        TrainerConfig(dense_lr=0.01, compute_dtype="bfloat16"), seed=0)
    for _ in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()
    # master params stayed f32
    for leaf in jax.tree.leaves(trainer.params):
        assert leaf.dtype == np.float32, leaf.dtype
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    trainer.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=trainer.table.add_keys)
    trainer.table.end_feed_pass()
    preds, labels = trainer.predict_batches(ds)
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    assert calc.auc() > 0.68, calc.auc()


def test_bf16_sharded_trainer_step(data):
    files, feed = data
    trainer = ShardedBoxTrainer(
        CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D), hidden=(16,)),
        table_cfg(), feed,
        TrainerConfig(dense_lr=0.01, compute_dtype="bfloat16", scan_chunk=1),
        mesh=device_mesh_1d(8), seed=0)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    stats = trainer.train_pass(ds)
    assert np.isfinite(stats["loss"])
    for leaf in jax.tree.leaves(trainer.params):
        assert leaf.dtype == np.float32, leaf.dtype


def test_bf16_a2a_payload_close_to_f32(data):
    """a2a_dtype='bfloat16' halves the value-a2a wire bytes (the
    walk_to_src/walk_to_dest traffic); the in-table state stays f32, so
    training tracks the f32-wire run closely and still learns."""
    files, feed = data

    def run(a2a_dtype):
        trainer = ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,)),
            table_cfg(), feed,
            TrainerConfig(dense_lr=0.01, scan_chunk=1,
                          a2a_dtype=a2a_dtype),
            mesh=device_mesh_1d(8), seed=0)
        losses = []
        for _ in range(4):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(trainer.train_pass(ds)["loss"])
            ds.release_memory()
        return losses

    l32 = run("float32")
    l16 = run("bfloat16")
    assert l16[-1] < l16[0], l16               # still learns
    np.testing.assert_allclose(l16, l32, rtol=3e-2)
