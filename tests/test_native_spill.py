"""Native-store SSD spill tier (VERDICT r1 missing #6): spill, fault-in,
pass-cadence limiter, checkpoint-through-spill."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding.native_store import (NativeHostEmbeddingStore,
                                                  make_host_store)

D = 4


def table_cfg(ssd_dir=None, threshold_mb=0):
    return TableConfig(embedx_dim=D, ssd_dir=ssd_dir,
                       ssd_threshold_mb=threshold_mb,
                       optimizer=SparseOptimizerConfig(
                           mf_create_thresholds=0.0, mf_initial_range=1e-3))


def make_native(tmp_path):
    cfg = table_cfg(ssd_dir=str(tmp_path / "ssd"))
    layout = ValueLayout(D)
    try:
        return NativeHostEmbeddingStore(layout, cfg, seed=0), cfg
    except RuntimeError:
        pytest.skip("native library unavailable")


def test_native_spill_and_fault_in(tmp_path):
    st, cfg = make_native(tmp_path)
    keys = np.arange(1, 201, dtype=np.uint64)
    rows = st.lookup_or_create(keys)
    # make the first 50 keys cold (high unseen_days), stamp recognizable
    # values so fault-in can be verified bit-exact
    rows[:, acc.SHOW] = keys.astype(np.float32)
    rows[:50, acc.UNSEEN_DAYS] = 40.0
    st.write_back(keys, rows)

    spilled = st.spill(max_resident=150)
    assert spilled == 50
    assert len(st) == 150

    # test-mode peek reads through the spill without resurrecting
    cold = st.lookup(keys[:50])
    np.testing.assert_allclose(cold[:, acc.SHOW], keys[:50])
    assert len(st) == 150

    # create-mode fault-in restores the exact rows to DRAM
    back = st.lookup_or_create(keys[:50])
    np.testing.assert_allclose(back[:, acc.SHOW], keys[:50])
    np.testing.assert_allclose(back[:, acc.UNSEEN_DAYS], 40.0)
    assert len(st) == 200


def test_native_spill_beyond_dram_budget(tmp_path):
    """>budget scale: 200k rows against a 60k-row budget, spilled in
    waves, then bulk-promoted back (LoadSSD2Mem)."""
    st, cfg = make_native(tmp_path)
    rng = np.random.RandomState(0)
    budget = 60_000
    total = 200_000
    for wave in range(4):
        keys = (np.arange(wave * 50_000, (wave + 1) * 50_000, dtype=np.uint64)
                + np.uint64(1))
        rows = st.lookup_or_create(keys)
        rows[:, acc.SHOW] = keys.astype(np.float32)
        # older waves are colder
        rows[:, acc.UNSEEN_DAYS] = float(10 - wave)
        st.write_back(keys, rows)
        st.spill(max_resident=budget)
        assert len(st) <= budget
    assert len(st) + st.spilled_count() == total
    # every row—resident or spilled—still reads back correctly
    probe = rng.randint(1, total + 1, 1000).astype(np.uint64)
    got = st.lookup(probe)
    np.testing.assert_allclose(got[:, acc.SHOW], probe.astype(np.float32))
    # LoadSSD2Mem promotes everything
    n = st.load_spilled()
    assert n == total - budget
    assert len(st) == total and st.spilled_count() == 0


def test_native_spill_checkpoint_roundtrip(tmp_path):
    st, cfg = make_native(tmp_path)
    keys = np.arange(1, 101, dtype=np.uint64)
    rows = st.lookup_or_create(keys)
    rows[:, acc.SHOW] = keys.astype(np.float32)
    rows[:30, acc.UNSEEN_DAYS] = 9.0
    st.write_back(keys, rows)
    st.spill(max_resident=70)
    ckpt = str(tmp_path / "ck.pkl")
    st.save(ckpt)  # must include the 30 spilled rows

    st2, _ = make_native(tmp_path)
    st2.load(ckpt)
    assert len(st2) == 100
    got = st2.lookup(keys)
    np.testing.assert_allclose(got[:, acc.SHOW], keys.astype(np.float32))


def test_pass_cadence_limiter(tmp_path):
    """end_pass triggers CheckNeedLimitMem when the store exceeds the
    ssd_threshold_mb budget."""
    from paddlebox_tpu.embedding.pass_table import PassTable

    layout = ValueLayout(D)
    row_bytes = layout.width * 4
    # budget of 1 MB ≈ 21k rows at width 13
    cfg = TableConfig(embedx_dim=D, pass_capacity=1 << 16,
                      ssd_dir=str(tmp_path / "ssd"), ssd_threshold_mb=1,
                      optimizer=SparseOptimizerConfig(
                          mf_create_thresholds=0.0, mf_initial_range=1e-3))
    pt = PassTable(cfg, seed=0)
    if not hasattr(pt.store, "spill"):
        pytest.skip("store lacks spill support")
    keys = np.arange(1, 40_001, dtype=np.uint64)
    pt.begin_feed_pass()
    pt.add_keys(keys)
    pt.end_feed_pass()
    pt.begin_pass()
    pt.end_pass()
    budget_rows = (1 << 20) // row_bytes
    assert len(pt.store) <= budget_rows
    assert len(pt.store) + pt.store.spilled_count() == 40_000


def test_spill_file_gc(tmp_path):
    """Fault-in of every row in a spill block deletes the block file."""
    import os
    st, cfg = make_native(tmp_path)
    keys = np.arange(1, 101, dtype=np.uint64)
    rows = st.lookup_or_create(keys)
    rows[:40, acc.UNSEEN_DAYS] = 9.0
    st.write_back(keys, rows)
    st.spill(max_resident=60)
    ssd = tmp_path / "ssd"
    assert len(list(ssd.glob("spill_*.part"))) == 1
    st.lookup_or_create(keys[:40])  # fault all 40 back in
    assert len(list(ssd.glob("spill_*.part"))) == 0
    assert st.spilled_count() == 0
