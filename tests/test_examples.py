"""Smoke tests: every runnable example must work end to end against the
CURRENT public API (examples are documentation — API drift there is a bug,
and constructor/method renames have broken them before)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, *args, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    return r.stdout


def test_train_ctr_example():
    out = run_example("train_ctr.py", "--passes", "2")
    assert "loss" in out


def test_train_sharded_example():
    out = run_example("train_sharded.py", "--passes", "2")
    assert "streaming AUC" in out


# tier-1 budget (round-10 headroom audit, 15.0s): the downpour
# capability has its OWN dedicated suite (test_downpour.py: local
# client learns + over-TCP); this example smoke re-runs the same
# local-client path end to end. Runs in the slow-inclusive suite
# and on TPU windows
@pytest.mark.slow
def test_train_downpour_example():
    out = run_example("train_downpour.py", "--passes", "2")
    assert "eval AUC" in out


def test_train_pipeline_example():
    out = run_example("train_pipeline.py", "--passes", "2", "--stages", "4")
    assert "features trained" in out


# tier-1 budget: flag/mesh/expand variant of a base example that
# still runs above; the variant runs in the slow-inclusive suite
# and on TPU windows
@pytest.mark.slow
def test_train_sharded_example_2d_mesh_flags():
    out = run_example("train_sharded.py", "--passes", "1", "--mesh-2d", "2",
                      "--a2a-dtype", "bfloat16", "--device-auc")
    assert "streaming AUC" in out


# tier-1 budget: flag/mesh/expand variant of a base example that
# still runs above; the variant runs in the slow-inclusive suite
# and on TPU windows
@pytest.mark.slow
def test_train_ctr_example_expand():
    out = run_example("train_ctr.py", "--passes", "1", "--expand-dim", "4")
    assert "streaming AUC" in out


# tier-1 budget: flag/mesh/expand variant of a base example that
# still runs above; the variant runs in the slow-inclusive suite
# and on TPU windows
@pytest.mark.slow
def test_train_ctr_example_perf_knobs():
    # the round-4 throughput knobs must stay wired to the public example
    out = run_example("train_ctr.py", "--passes", "1", "--push-write",
                      "rebuild", "--sparse-chunk-sync")
    assert "streaming AUC" in out


def test_serve_xbox_example():
    out = run_example("serve_xbox.py", "--passes", "1")
    assert "serving view:" in out and "feasign" in out


def test_stream_train_serve_example():
    out = run_example("stream_train_serve.py")
    assert "micro-pass" in out
    assert "ingest-to-serve freshness" in out


# tier-1 budget (round-10 headroom audit, 8.6s): sharded-slab
# pipeline parity/learning is covered by test_pipeline.py's dedicated
# sharded suite; the base pipeline example above stays in tier-1.
# Runs in the slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_train_pipeline_example_sharded_slab():
    out = run_example("train_pipeline.py", "--passes", "2", "--stages", "4",
                      "--sharded-slab")
    assert "features trained" in out and "shards" in out


def test_train_mesh_tower_example():
    out = run_example("train_mesh_tower.py", "--kind", "tp", "--passes",
                      "2", "--wide", "256")
    assert "features trained" in out


def test_train_aux_input_example():
    out = run_example("train_aux_input.py", "--passes", "2")
    assert "aux rows served" in out
