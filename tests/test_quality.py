"""Quality plane (round 18): tagged AUC/COPC/CTR + slot drift monitor.

Pins the acceptance surface: numeric parity of the tagged metrics vs
plain-numpy oracles AND vs BasicAucCalculator on identical adds (incl.
empty-tag and one-class masks), the sum-mergeable state (2-virtual-rank
merged report == single-rank oracle, composed through the rank-0
cluster merge), per-slot actual/predicted CTR, the drift monitor
flagging an injected slot drop within ONE report window, and the
HealthMonitor quality penalties (data_drift weighted past the healthy
bar, copc band violation flagged).
"""

import json

import numpy as np
import pytest

from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.metrics import drift as drift_mod
from paddlebox_tpu.metrics import quality as quality_mod
from paddlebox_tpu.metrics.auc import BasicAucCalculator
from paddlebox_tpu.metrics.drift import SlotDriftMonitor
from paddlebox_tpu.metrics.quality import (TaggedQuality, merged_report,
                                           table_auc)
from paddlebox_tpu.obs.aggregate import merge_cluster_reports
from paddlebox_tpu.obs.health import HealthMonitor
from paddlebox_tpu.utils.stats import StatRegistry

T = 4096


def _data(n=20000, seed=0):
    rng = np.random.RandomState(seed)
    pred = rng.rand(n)
    label = (rng.rand(n) < pred * 0.4).astype(np.int64)
    return pred, label


def _numpy_auc_oracle(pred, label, table_size):
    """Independent plain-numpy AUC over the same bucketing: trapezoid
    from the top bucket down (the reference metrics.cc math)."""
    pos = np.minimum((np.asarray(pred, np.float64)
                      * table_size).astype(np.int64), table_size - 1)
    neg_t = np.bincount(pos[label == 0], minlength=table_size
                        ).astype(np.float64)
    pos_t = np.bincount(pos[label == 1], minlength=table_size
                        ).astype(np.float64)
    area = fp = tp = 0.0
    for i in range(table_size - 1, -1, -1):
        newfp, newtp = fp + neg_t[i], tp + pos_t[i]
        area += neg_t[i] * (tp + newtp) / 2.0
        fp, tp = newfp, newtp
    if fp < 1e-3 or tp < 1e-3:
        return -0.5
    return area / (fp * tp)


# -------------------------------------------------------------- parity

def test_tagged_auc_matches_basic_calculator_bitwise():
    pred, label = _data()
    q = TaggedQuality(table_size=T)
    q.add(pred, label)
    b = BasicAucCalculator(table_size=T)
    b.add_data(pred, label)
    b.compute()
    m = q.compute()
    assert m["auc"] == round(b.auc(), 6)
    assert m["actual_ctr"] == round(b.actual_ctr(), 6)
    assert m["predicted_ctr"] == round(b.predicted_ctr(), 6)
    assert m["mae"] == round(b.mae(), 6)
    assert m["rmse"] == round(b.rmse(), 6)


def test_tagged_auc_and_copc_vs_numpy_oracle():
    pred, label = _data(n=4000, seed=3)
    q = TaggedQuality(table_size=256)
    q.add(pred, label)
    m = q.compute()
    assert abs(table_auc(q._tables["all"])
               - _numpy_auc_oracle(pred, label, 256)) < 1e-12
    assert m["copc"] == round(float(label.sum() / pred.sum()), 6)
    assert m["actual_ctr"] == round(float(label.mean()), 6)
    assert m["predicted_ctr"] == round(float(pred.mean()), 6)


def test_masked_add_matches_prefiltered():
    pred, label = _data(n=5000, seed=5)
    mask = np.arange(5000) % 3 == 0
    q1 = TaggedQuality(table_size=T)
    q1.add(pred, label, mask=mask)
    q2 = TaggedQuality(table_size=T)
    q2.add(pred[mask], label[mask])
    assert q1.compute() == q2.compute()
    assert np.array_equal(q1._tables["all"], q2._tables["all"])


def test_empty_tag_and_one_class_masks():
    q = TaggedQuality(table_size=64)
    # never-fed tag: empty stream semantics
    m = q.compute("never_fed")
    assert m["size"] == 0.0 and m["auc"] == -0.5 and m["copc"] == 0.0
    # all-one-class: the reference's -0.5 degenerate convention
    pred, _ = _data(n=100, seed=7)
    q.add(pred, np.ones(100, np.int64), tag="ones")
    q.add(pred, np.zeros(100, np.int64), tag="zeros")
    assert q.compute("ones")["auc"] == -0.5
    assert q.compute("zeros")["auc"] == -0.5
    # empty mask add is a no-op, not an error
    q.add(pred, np.zeros(100), tag="masked", mask=np.zeros(100, bool))
    assert q.compute("masked")["size"] == 0.0


def test_add_tagged_groups_and_skips_zero_with_prefix():
    pred, label = _data(n=6000, seed=9)
    tags = np.arange(6000) % 3          # 0, 1, 2
    q = TaggedQuality(table_size=T)
    q.add_tagged(pred, label, tags, prefix="cmatch:")
    names = set(q.report()["tags"])
    assert names == {"cmatch:1", "cmatch:2"}    # tag 0 skipped
    oracle = TaggedQuality(table_size=T)
    oracle.add(pred[tags == 1], label[tags == 1])
    assert q.compute("cmatch:1") == oracle.compute("all")


def test_add_batch_feeds_all_cmatch_and_tasks():
    pred, label = _data(n=2000, seed=11)
    cmatch = (np.arange(2000, dtype=np.uint64) % 2) << np.uint64(32)
    q = TaggedQuality(table_size=T)
    q.add_batch({"pred": pred, "label": label,
                 "mask": np.ones(2000, bool), "cmatch_rank": cmatch,
                 "pred_ctcvr": pred, "label_ctcvr": label})
    names = set(q.report()["tags"])
    assert {"all", "cmatch:1", "task:ctcvr"} <= names


# ------------------------------------------------------- state / merge

def test_two_virtual_rank_merge_equals_single():
    pred, label = _data(n=10000, seed=13)
    whole = TaggedQuality(table_size=T)
    whole.add(pred, label)
    whole.add_slot_batch(pred[:6], label[:6],
                         np.zeros(6, np.int32),
                         np.array([0, 1, 2, 5, 7, 8]),
                         np.ones(6, bool), 3)
    r0 = TaggedQuality(table_size=T)
    r1 = TaggedQuality(table_size=T)
    r0.add(pred[:4000], label[:4000])
    r1.add(pred[4000:], label[4000:])
    r0.add_slot_batch(pred[:6], label[:6], np.zeros(6, np.int32),
                      np.array([0, 1, 2, 5, 7, 8]), np.ones(6, bool), 3)
    # states round-trip through JSON (they ride StepReports on the wire)
    states = [json.loads(json.dumps(r.state())) for r in (r0, r1)]
    merged = merged_report(states)
    assert merged == whole.report()
    # mismatched table size degrades to the mergeable subset, not a crash
    bad = TaggedQuality(table_size=128)
    bad.add(pred[:100], label[:100])
    still = merged_report(states + [bad.state()])
    assert still["tags"]["all"] == whole.report()["tags"]["all"]


def test_cluster_merge_carries_quality():
    pred, label = _data(n=8000, seed=17)
    reports = []
    whole = TaggedQuality(table_size=T)
    whole.add(pred, label)
    for r, sl in ((0, slice(0, 4000)), (1, slice(4000, 8000))):
        q = TaggedQuality(table_size=T)
        q.add(pred[sl], label[sl])
        reports.append({"rank": r, "step": 10, "examples_per_sec": 1.0,
                        "quality_state": q.state()})
    merged = merge_cluster_reports(reports)
    assert merged["quality"]["tags"]["all"] == \
        whole.report()["tags"]["all"]
    # reports without states don't grow a quality key
    assert "quality" not in merge_cluster_reports(
        [{"rank": 0, "step": 1}])


def test_per_slot_ctr_oracle():
    q = TaggedQuality(table_size=64)
    # 2 records, 3 slots: rec0 carries slots {0,1} (key in slot 1
    # twice), rec1 carries slot 2
    pred = np.array([0.25, 0.75])
    label = np.array([1, 0])
    slots = np.array([0, 1, 1, 2], np.int32)
    segments = np.array([0, 1, 1, 5], np.int32)   # rec*3 + slot
    valid = np.ones(4, bool)
    q.add_slot_batch(pred, label, slots, segments, valid, 3)
    slots_rep = q.report()["slots"]
    assert slots_rep["0"] == {"n": 1.0, "actual_ctr": 1.0,
                              "predicted_ctr": 0.25, "copc": 4.0}
    assert slots_rep["1"]["n"] == 1.0          # distinct (rec, slot) once
    assert slots_rep["2"] == {"n": 1.0, "actual_ctr": 0.0,
                              "predicted_ctr": 0.75, "copc": 0.0}


def test_add_bucket_table_folds_device_table():
    pred, label = _data(n=3000, seed=19)
    fine = TaggedQuality(table_size=4 * T)
    fine.add(pred, label)
    q = TaggedQuality(table_size=T)
    sc = fine._scalars["all"]
    q.add_bucket_table(fine._tables["all"], *sc)
    direct = TaggedQuality(table_size=T)
    direct.add(pred, label)
    assert np.array_equal(q._tables["all"], direct._tables["all"])
    assert q.compute() == direct.compute()
    with pytest.raises(ValueError):
        q.add_bucket_table(np.zeros((2, T - 1)), 0, 0, 0, 0, 0)


# ---------------------------------------------------------------- drift

def _block(n_recs=300, n_slots=4, drop_slot=None, seed=1, keys_per=2):
    rng = np.random.RandomState(seed)
    keys, slots, recs = [], [], []
    for i in range(n_recs):
        for s in range(n_slots):
            if s == drop_slot:
                continue
            k = rng.randint(1, 2000, size=keys_per)
            keys.extend(k.tolist())
            slots.extend([s] * keys_per)
            recs.extend([i] * keys_per)
    return ColumnarBlock.from_key_rec(
        np.array(keys, np.uint64), np.array(slots, np.int32),
        np.array(recs, np.int64),
        (rng.rand(n_recs) < 0.2).astype(np.int32))


def test_slot_stats_vs_loop_oracle():
    blk = _block(n_recs=50, seed=23)
    m = SlotDriftMonitor(drift_warn=0.5)
    m.observe_block(blk)
    cur = m._cur.summary()
    # loop oracle over the block
    n_slots = int(blk.key_slot.max()) + 1
    per_rec = [set() for _ in range(blk.n_recs)]
    key_count = np.zeros(n_slots)
    uniq = [set() for _ in range(n_slots)]
    for r in range(blk.n_recs):
        lo, hi = blk.rec_offsets[r], blk.rec_offsets[r + 1]
        for k, s in zip(blk.keys[lo:hi], blk.key_slot[lo:hi]):
            per_rec[r].add(int(s))
            key_count[s] += 1
            uniq[s].add(int(k))
    cov = np.array([sum(s in pr for pr in per_rec)
                    for s in range(n_slots)]) / blk.n_recs
    assert np.allclose(cur["coverage"], cov)
    kpr = key_count / np.maximum(
        [sum(s in pr for pr in per_rec) for s in range(n_slots)], 1)
    assert np.allclose(cur["keys_per_rec"], kpr)
    # linear-count sketch within 15% of the true distinct counts here
    for s in range(n_slots):
        assert abs(cur["cardinality"][s] - len(uniq[s])) \
            < 0.15 * len(uniq[s])


def test_drift_flags_injected_slot_drop_within_one_window(registry):
    m = SlotDriftMonitor(drift_warn=0.5)
    m.observe_block(_block(seed=1))
    r1 = m.roll()
    assert r1["drift"]["score"] == 0.0          # first window = reference
    m.observe_block(_block(seed=2))
    r2 = m.roll()
    assert r2["drift"]["score"] < 0.5           # steady state stays calm
    m.observe_block(_block(seed=3, drop_slot=2))
    r3 = m.roll()                               # the injection window
    assert r3["drift"]["score"] >= 0.5
    assert r3["drift"]["dropped_slots"] == [2]
    reg = StatRegistry.instance()
    assert reg.get_gauge("data_drift_score") >= 0.5
    assert reg.get_gauge("data_dropped_slots") == 1.0


def test_drift_empty_roll_returns_none_and_keeps_reference():
    m = SlotDriftMonitor(drift_warn=0.5)
    assert m.roll() is None
    m.observe_block(_block(seed=1))
    assert m.roll() is not None
    assert m.roll() is None                     # eval-only window: no-op
    assert len(m._ref) == 1


def test_drift_pred_distribution_shift_scores(registry):
    m = SlotDriftMonitor(drift_warn=0.5)
    rng = np.random.RandomState(0)
    m.observe_preds(rng.rand(5000) * 0.2)       # low-pred regime
    m.observe_block(_block(seed=1))
    m.roll()
    m.observe_preds(rng.rand(5000) * 0.2 + 0.8)  # calibration blow-up
    m.observe_block(_block(seed=2))
    r = m.roll()
    assert r["drift"]["pred_drift"] > 0.9
    assert r["drift"]["score"] >= 0.5


# --------------------------------------------------------------- health

def _merged_with_gauges(g0: dict, g1: dict) -> dict:
    reports = []
    for r, g in ((0, g0), (1, g1)):
        reports.append({"rank": r, "step": 5, "examples_per_sec": 1.0,
                        "gauges": g})
    m = merge_cluster_reports(reports)
    m["stale_ranks"] = []
    return m


def test_health_drift_penalty_unhealthy_on_its_own():
    h = HealthMonitor(world=2, drift_warn=0.5)
    rec = h.update(_merged_with_gauges({"data_drift_score": 0.0},
                                       {"data_drift_score": 0.9}))
    assert rec["ranks"]["0"]["healthy"]
    assert not rec["ranks"]["1"]["healthy"]
    assert "data_drift" in rec["ranks"]["1"]["flags"]
    assert rec["unhealthy_ranks"] == [1]


def test_health_copc_band_violation_flagged():
    h = HealthMonitor(world=2)
    rec = h.update(_merged_with_gauges({"quality_copc": 1.02},
                                       {"quality_copc": 2.4}))
    assert rec["ranks"]["0"]["healthy"]
    assert "miscalibrated" in rec["ranks"]["1"]["flags"]
    assert rec["ranks"]["1"]["score"] == 0.7
    # zero copc (no data yet) never flags
    rec = h.update(_merged_with_gauges({}, {"quality_copc": 0.0}))
    assert "flags" not in rec["ranks"]["1"]


# ------------------------------------------------------------- trainer

def test_trainer_pass_end_carries_quality(registry, tmp_path):
    import tempfile

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.data.generator import write_synthetic_ctr_files
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.ctr_dnn import CtrDnn
    from paddlebox_tpu.obs import ListSink
    from paddlebox_tpu.train.trainer import BoxTrainer

    flags.set_flag("obs_report_every", 1000)    # pass_end force only
    out = tempfile.mkdtemp(dir=str(tmp_path))
    files, feed = write_synthetic_ctr_files(
        out, num_files=1, lines_per_file=256, num_slots=4,
        vocab_per_slot=500, max_len=3, seed=5)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    trainer = BoxTrainer(
        CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + 4), hidden=(16,)),
        TableConfig(embedx_dim=4, pass_capacity=1 << 13,
                    optimizer=SparseOptimizerConfig()),
        feed, TrainerConfig(dense_lr=1e-3), seed=0)
    assert trainer.quality is not None
    assert quality_mod.active() is trainer.quality
    trainer.reporter.sink = ListSink()
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    trainer.train_pass(ds)
    recs = [r for r in trainer.reporter.sink.records
            if r.get("event") == "pass_end"]
    assert recs, "no pass_end report"
    qual = recs[-1].get("quality")
    assert qual and "all" in qual["tags"]
    assert qual["tags"]["all"]["size"] > 0
    assert "copc" in qual["tags"]["all"]
    assert qual.get("slots"), "per-slot ctr missing"
    # the ingest hook observed the pass block and pass_end rolled it
    assert recs[-1].get("data_quality"), "drift window did not roll"
    assert drift_mod.active() is not None
    assert StatRegistry.instance().get_gauge("quality_copc") > 0
    # the whole record (incl. quality extras) is json-serializable —
    # the sink contract every consumer relies on
    json.dumps(recs[-1])
    trainer.close()


@pytest.fixture
def registry():
    reg = StatRegistry.instance()
    saved = reg.snapshot_all()
    reg.reset()
    yield reg
    reg.reset()
    for k, v in saved["counters"].items():
        reg.set(k, v)
    for k, v in saved["gauges"].items():
        reg.set_gauge(k, v)
