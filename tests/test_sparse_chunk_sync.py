"""Chunk-synchronous sparse mode (TrainerConfig.sparse_chunk_sync): one
pull + one merged push per scan chunk, exact per-batch dense adam.

Correctness contracts:
  * scan_chunk=1 is BIT-IDENTICAL to the exact per-batch trainer (the
    merged push over one batch IS the exact push).
  * chunks whose batches share NO keys are bit-identical at any chunk
    size (no within-chunk staleness exists to observe).
  * overlapping keys: the model still learns (AUC lifts), losses finite.
"""
import dataclasses

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer

D = 8
NUM_SLOTS = 4


def make_data(tmp_path, lines=512, mb=64, vocab=150, seed=7):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=lines,
        num_slots=NUM_SLOTS, vocab_per_slot=vocab, max_len=3, seed=seed)
    return files, dataclasses.replace(feed, batch_size=mb)


def make_trainer(feed, chunk_sync, scan_chunk, seed=0, init_range=1e-3):
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=init_range,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(32, 16))
    return BoxTrainer(model, table_cfg, feed,
                      TrainerConfig(dense_lr=3e-3, scan_chunk=scan_chunk,
                                    sparse_chunk_sync=chunk_sync),
                      seed=seed)


def trained_state(trainer, files, feed, passes=1):
    for _ in range(passes):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()
    keys = np.sort(trainer.table._pass_keys)
    return keys, trainer.table.store.lookup(keys).copy(), trainer.params


def assert_same_state(a, b):
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    for k in a[2]:
        np.testing.assert_array_equal(np.asarray(a[2][k]),
                                      np.asarray(b[2][k]))


def test_chunk1_bitexact_vs_exact(tmp_path):
    files, feed = make_data(tmp_path, lines=256, mb=64)
    exact = trained_state(make_trainer(feed, False, 1, seed=3), files, feed)
    chunk = trained_state(make_trainer(feed, True, 1, seed=3), files, feed)
    assert_same_state(exact, chunk)


def test_disjoint_key_chunks_bitexact(tmp_path):
    """Batches within a chunk share no keys → merged push == sequential
    pushes and chunk-start pulls == pre-batch pulls, bit for bit.

    mf_initial_range=0 so lazy creation is deterministic: the two modes
    draw creation randoms from different PRNG streams (per-batch vs
    per-chunk sub keys) — an allowed difference in random INIT values,
    not in update semantics."""
    from paddlebox_tpu.data.packer import BatchPacker
    from paddlebox_tpu.data.slot_record import SlotRecord
    files, feed = make_data(tmp_path, lines=256, mb=64)
    # craft 4 batches with disjoint key ranges via per-batch offsets
    rng = np.random.RandomState(0)
    packer = BatchPacker(feed)
    batches = []
    for b in range(4):
        recs = []
        for _ in range(feed.batch_size):
            slots = {si: (rng.randint(0, 40, rng.randint(1, 4))
                          .astype(np.uint64) + np.uint64(1000 * b + 1))
                     for si in range(NUM_SLOTS)}
            recs.append(SlotRecord(label=int(rng.rand() < 0.3),
                                   uint64_slots=slots))
        batches.append(packer.pack(recs))

    def run(chunk_sync, scan_chunk):
        tr = make_trainer(feed, chunk_sync, scan_chunk, seed=5,
                          init_range=0.0)
        tr.table.begin_feed_pass()
        for b in batches:
            tr.table.add_keys(b.keys[b.valid])
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        import jax
        prng = jax.random.PRNGKey(9)
        staged = tr._stack_batches(batches)
        if chunk_sync:
            stacked, cpush = staged
            (slab, params, opt, losses, preds, prng) = tr.fns.scan_chunk(
                tr.table.slab, tr.params, tr.opt_state, stacked, cpush,
                prng)
        else:
            (slab, params, opt, losses, preds, prng) = tr.fns.scan_steps(
                tr.table.slab, tr.params, tr.opt_state, staged, prng)
        return (np.asarray(slab), {k: np.asarray(v) for k, v in
                                   params.items()}, np.asarray(losses))

    slab_e, params_e, losses_e = run(False, 4)
    slab_c, params_c, losses_c = run(True, 4)
    np.testing.assert_array_equal(losses_e, losses_c)
    np.testing.assert_array_equal(slab_e, slab_c)
    for k in params_e:
        np.testing.assert_array_equal(params_e[k], params_c[k])


def test_chunk_sync_learns(tmp_path):
    files, feed = make_data(tmp_path, lines=768, mb=64)
    tr = make_trainer(feed, True, 4)
    tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                           mask_var="mask")
    losses = []
    for _ in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
        ds.release_memory()
    assert losses[-1] < losses[0] - 0.02, losses
    msg = tr.metrics.get_metric_msg("auc")
    assert msg["auc"] > 0.55, msg


def test_chunk_sync_rejects_expand_and_summary(tmp_path):
    _, feed = make_data(tmp_path)
    table_cfg = TableConfig(embedx_dim=D, pass_capacity=1 << 12,
                            expand_embed_dim=4,
                            optimizer=SparseOptimizerConfig())
    from paddlebox_tpu.models.nn_cross import CtrDnnExpand
    model = CtrDnnExpand(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                         expand_dim=4, hidden=(16,))
    with pytest.raises(ValueError, match="sparse_chunk_sync"):
        BoxTrainer(model, table_cfg, feed,
                   TrainerConfig(sparse_chunk_sync=True, scan_chunk=2))
    # data_norm summary models and async dense hit the same gate
    plain_cfg = TableConfig(embedx_dim=D, pass_capacity=1 << 12,
                            optimizer=SparseOptimizerConfig())
    dn = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                hidden=(16,), use_data_norm=True)
    with pytest.raises(ValueError, match="sparse_chunk_sync"):
        BoxTrainer(dn, plain_cfg, feed,
                   TrainerConfig(sparse_chunk_sync=True, scan_chunk=2))
    plain = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,))
    with pytest.raises(ValueError, match="sparse_chunk_sync"):
        BoxTrainer(plain, plain_cfg, feed,
                   TrainerConfig(sparse_chunk_sync=True, scan_chunk=2,
                                 async_mode=True))
    from paddlebox_tpu.parallel.mesh_tower import MeshTowerTrainer
    from paddlebox_tpu.models.wide_tower import TpDeepFM
    tp = TpDeepFM(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                  n_shards=8, d_wide=32, d_mid=8)
    with pytest.raises(ValueError, match="sparse_chunk_sync"):
        MeshTowerTrainer(tp, plain_cfg, feed,
                         TrainerConfig(sparse_chunk_sync=True))


def test_parallel_trainers_reject_chunk_sync(tmp_path):
    from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
    _, feed = make_data(tmp_path, lines=64)
    table_cfg = TableConfig(embedx_dim=D, pass_capacity=1 << 10,
                            optimizer=SparseOptimizerConfig())
    model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,))
    with pytest.raises(ValueError, match="sparse_chunk_sync"):
        ShardedBoxTrainer(model, table_cfg, feed,
                          TrainerConfig(sparse_chunk_sync=True))


def test_chunk_sync_dump_and_metrics(tmp_path):
    """DumpField writers and streaming metrics compose with the chunk
    megastep: every batch's preds/labels stream once, dump lines appear."""
    import os
    files, feed = make_data(tmp_path / "d", lines=256, mb=64)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0))
    model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,))
    tr = BoxTrainer(model, table_cfg, feed,
                    TrainerConfig(dense_lr=1e-3, scan_chunk=2,
                                  sparse_chunk_sync=True,
                                  dump_fields=("pred", "label"),
                                  dump_fields_path=str(tmp_path / "dump")))
    tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                           mask_var="mask")
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    stats = tr.train_pass(ds)
    tr.close()
    assert stats["instances"] == 256
    msg = tr.metrics.get_metric_msg("auc")
    assert msg["size"] == 256            # every instance streamed once
    dumped = os.listdir(tmp_path / "dump")
    assert dumped
    text = open(os.path.join(tmp_path / "dump", dumped[0])).read()
    assert "pred:" in text and "label:" in text
