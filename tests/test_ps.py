"""CPU PS layer (mirrors distributed/test/: memory_sparse_table_test.cc,
ctr_accessor_test.cc, sparse_sgd_rule_test.cc, barrier_table_test.cc, and
brpc_service_sparse_sgd_test.cc's bring-up-a-real-server-in-process
pattern)."""

import threading

import numpy as np
import jax
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.ps import (DenseTable, PSCore, PSServer, PsLocalClient,
                              SparseTable, TcpPSClient, numpy_apply_push)

D = 4


def conf():
    return SparseOptimizerConfig(mf_create_thresholds=0.5,
                                 mf_initial_range=1e-3,
                                 feature_learning_rate=0.1,
                                 mf_learning_rate=0.1)


def table_cfg():
    return TableConfig(embedx_dim=D, pass_capacity=1 << 12, optimizer=conf())


def _random_rows(layout, n, rng, with_mf=True):
    rows = layout.new_rows(n, rng, conf())
    rows[:, acc.SLOT] = rng.randint(0, 5, n)
    rows[:, acc.SHOW] = rng.randint(1, 20, n)
    rows[:, acc.CLICK] = rng.randint(0, 5, n)
    if with_mf:
        rows[:, acc.MF_SIZE] = D
        rows[:, layout.embedx_w:layout.embedx_w + D] = rng.randn(n, D) * 0.01
    return rows.astype(np.float32)


def test_numpy_rule_matches_device_apply_push():
    """The CPU PS rule must be numerically identical to the device push
    (same accessor semantics on both tiers) — modulo the fresh-embedx
    random draw, so use rows already past mf creation."""
    from paddlebox_tpu.embedding.optimizers import apply_push
    layout = ValueLayout(embedx_dim=D, optimizer="adagrad")
    push = PushLayout(D)
    rng = np.random.RandomState(0)
    n = 64
    rows = _random_rows(layout, n, rng, with_mf=True)
    grads = np.zeros((n, push.width), np.float32)
    grads[:, push.SLOT] = rows[:, acc.SLOT]
    grads[:, push.SHOW] = rng.randint(0, 4, n)  # some zero-show rows
    grads[:, push.CLICK] = np.minimum(grads[:, push.SHOW],
                                      rng.randint(0, 2, n))
    grads[:, push.EMBED_G] = rng.randn(n) * 0.1
    grads[:, push.embedx_g:push.embedx_g + D] = rng.randn(n, D) * 0.1

    import jax.numpy as jnp
    want = np.asarray(apply_push(jnp.asarray(rows), jnp.asarray(grads),
                                 jax.random.PRNGKey(0), layout, conf()))
    got = numpy_apply_push(rows, grads, np.random.RandomState(1),
                           layout, conf())
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sparse_table_pull_creates_and_push_updates():
    t = SparseTable(table_cfg(), shard_num=4)
    keys = np.array([3, 11, 19, 3], np.uint64)  # dup key 3
    vals = t.pull(keys)
    assert vals.shape == (4, t.layout.width)
    np.testing.assert_array_equal(vals[0], vals[3])  # dup sees same row
    assert len(t) == 3

    push = t.push_layout
    grads = np.zeros((4, push.width), np.float32)
    grads[:, push.SHOW] = 1.0
    grads[:, push.CLICK] = [1, 0, 0, 1]
    grads[:, push.EMBED_G] = [0.5, -0.5, 0.1, 0.5]
    t.push(keys, grads)
    after = t.pull(keys)
    # dup key merged: show += 2
    assert after[0, acc.SHOW] == 2.0
    assert after[1, acc.SHOW] == 1.0
    # adagrad moved embed_w against the grad direction
    assert after[0, acc.EMBED_W] != vals[0, acc.EMBED_W]


def test_sparse_table_save_load_roundtrip(tmp_path):
    t = SparseTable(table_cfg(), shard_num=2)
    keys = np.arange(1, 33, dtype=np.uint64)
    t.pull(keys)
    push = t.push_layout
    g = np.zeros((32, push.width), np.float32)
    g[:, push.SHOW] = 1
    g[:, push.EMBED_G] = 0.3
    t.push(keys, g)
    before = t.pull(keys)
    t.save(str(tmp_path / "ck"))

    t2 = SparseTable(table_cfg(), shard_num=2)
    t2.load(str(tmp_path / "ck"))
    assert len(t2) == 32
    np.testing.assert_allclose(t2.pull(keys), before, rtol=1e-6)


def test_dense_table_rules():
    g = np.ones(8, np.float32)
    sgd = DenseTable(8, rule="sgd", lr=0.1)
    sgd.push(g)
    np.testing.assert_allclose(sgd.pull(), -0.1 * g, rtol=1e-6)
    summ = DenseTable(8, rule="summary")
    summ.push(g)
    summ.push(2 * g)
    np.testing.assert_allclose(summ.pull(), 3 * g, rtol=1e-6)
    adam = DenseTable(8, rule="adam", lr=0.1)
    adam.push(g)
    m, v = 0.1 * g, 0.001 * g
    expect = -0.1 * (m / 0.1) / (np.sqrt(v / 0.001) + 1e-8)
    np.testing.assert_allclose(adam.pull(), expect, rtol=1e-5)


def test_local_client_dispatch():
    cl = PsLocalClient()
    cl.create_sparse_table(0, table_cfg(), shard_num=2)
    cl.create_dense_table("fc", size=16, rule="sgd", lr=0.5)
    keys = np.array([7, 9], np.uint64)
    v = cl.pull_sparse(0, keys)
    assert v.shape[0] == 2
    cl.push_dense("fc", np.ones(16, np.float32))
    np.testing.assert_allclose(cl.pull_dense("fc"), -0.5)
    assert cl.sparse_size(0) == 2


def test_tcp_server_roundtrip(tmp_path):
    server = PSServer()
    cl = TcpPSClient("127.0.0.1", server.port)
    cl.create_sparse_table(5, table_cfg(), shard_num=2)
    cl.create_dense_table("w", size=4, rule="adam", lr=0.01)
    keys = np.array([1, 2, 3], np.uint64)
    vals = cl.pull_sparse(5, keys)
    assert vals.shape == (3, vals.shape[1])
    push = PushLayout(D)
    g = np.zeros((3, push.width), np.float32)
    g[:, push.SHOW] = 1
    g[:, push.EMBED_G] = 1.0
    cl.push_sparse(5, keys, g)
    after = cl.pull_sparse(5, keys)
    assert (after[:, acc.EMBED_W] != vals[:, acc.EMBED_W]).all()
    cl.push_dense("w", np.ones(4, np.float32))
    assert (cl.pull_dense("w") != 0).all()
    # save on server, reload into a fresh core
    cl.save(str(tmp_path / "ps_ck"))
    assert cl.sparse_size(5) == 3

    # error path surfaces server-side exceptions
    with pytest.raises(RuntimeError, match="pull_dense"):
        cl.pull_dense("missing")
    cl.stop_server()
    cl.close()


def test_tcp_barrier_two_clients():
    server = PSServer()
    results = []

    def worker(i):
        cl = TcpPSClient("127.0.0.1", server.port)
        cl.barrier(world=2, timeout=30.0)
        results.append(i)
        cl.close()

    ths = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert sorted(results) == [0, 1]
    server.stop()
