"""Worker for the 2-process sharded-pipeline cluster test: each process
owns one dp row of a (dp=world, stage=devs_per_proc) mesh — its pipeline
row's stages live on its own devices (a row never straddles processes) —
while the pass table key-mod-shards over ALL 2×4 devices, so every pull
and push crosses the real process boundary through the a2a.

Run via tests/test_multihost.py run_cluster, never directly by pytest.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    _devs = os.environ.get("PBTPU_DEVS_PER_PROC", "4")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=" + _devs).strip()
os.environ["PBTPU_DATASET_DISABLE_SHUFFLE"] = "1"  # strict parity

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    from jax.sharding import Mesh
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.fleet.fleet import fleet
    from paddlebox_tpu.parallel.pipeline import (STAGE_AXIS,
                                                 ShardedCtrPipelineRunner)

    cfg = json.loads(sys.argv[1])
    fleet.init()
    fleet.init_distributed()
    rank, world = fleet.worker_index(), fleet.worker_num()
    n_devs = len(jax.devices())
    S = n_devs // world

    # GPUPS variant: shard stores front ONE central CPU PS over TCP
    # (sections over the distributed PS at real process boundaries)
    ps_client = None
    store_factory = None
    if cfg.get("ps_endpoint"):
        from paddlebox_tpu.embedding.ps_store import ps_store_factory
        from paddlebox_tpu.ps import TcpPSClient
        host, port = cfg["ps_endpoint"].rsplit(":", 1)
        ps_client = TcpPSClient(host, int(port))
        store_factory = ps_store_factory(ps_client, cfg["ps_table_id"],
                                         process_primary=(rank == 0))

    nf = len(cfg["files"]) // world
    files = cfg["files"][rank * nf:(rank + 1) * nf]
    D = cfg["embedx_dim"]
    feed = default_feed_config(num_slots=cfg["num_slots"],
                               batch_size=cfg["batch_size"],
                               max_len=cfg["max_len"])
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=n_devs * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    # dp axis spans the processes (jax.devices() orders by process), the
    # stage axis stays within each
    mesh = Mesh(np.array(jax.devices()).reshape(world, S),
                ("dp", STAGE_AXIS))
    runner = ShardedCtrPipelineRunner(
        table_cfg, feed, n_stages=S, d_model=24, layers_per_stage=1,
        lr=1e-2, n_micro=cfg["n_micro"], mesh=mesh, seed=0, fleet=fleet,
        store_factory=store_factory)
    assert runner.multiprocess and runner.local_rows == [rank]

    losses, steps = [], 0
    for _ in range(cfg["passes"]):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = runner.train_pass(ds)
        losses.append(stats["loss"])
        steps += stats["steps"]
        ds.release_memory()

    rows = {}
    if ps_client is None:
        for s in runner.local_positions:
            st = runner.table.stores[s]
            keys, vals = st.state_items()
            order = np.argsort(keys)
            for k, v in zip(keys[order[:3]], vals[order[:3]]):
                rows[str(int(k))] = [round(float(x), 6) for x in v]
    ps_rows = (int(ps_client.sparse_size(cfg["ps_table_id"]))
               if ps_client is not None else None)
    # first stage block of this process's dp replica (replicated over dp
    # — every rank must report identical values; the global array is not
    # fully addressable, so read the lowest addressable stage shard)
    def _start(s):
        pos = s.index[0]
        return (pos.start or 0) if isinstance(pos, slice) else int(pos)

    sh0 = min(runner.params["blk_w"].addressable_shards, key=_start)
    blk = np.asarray(sh0.data).reshape(-1)[:8]
    print("RESULT " + json.dumps({
        "rank": rank, "losses": losses, "steps": steps, "rows": rows,
        "blk_head": [round(float(x), 6) for x in blk],
        "ps_rows": ps_rows,
    }), flush=True)
    if ps_client is not None:
        ps_client.close()
    fleet.stop()


if __name__ == "__main__":
    main()
