"""H2D wire modes — the round-8 lean-wire push reunification.

Contract under test: every wire the trainer can stage a train batch on
must train BIT-IDENTICALLY to the full host-staged oracle (the
perm/inv/uids/first_idx wire), because the content-addressed lazy-init
randoms and the ascending-occurrence merge order make the push a pure
function of (slab, batch, prng) regardless of WHERE the dedup ran:

  * uid wire (h2d_lean + h2d_uid_wire, the default lean config): the
    sorted [K] uid vector ships; inv/first (and the rebuild pos) derive
    on device by searchsorted — push_sparse_uidwire
  * ids-only wire (h2d_uid_wire off): the round-5 tier — nothing ships,
    jnp.unique dedups in the step
  * delta wire (wire_delta_ids): uids ship as (int32 base, int16 deltas)
  * chunk-amortized: sparse_chunk_sync stages ONE uid vector per scan
    chunk ([C*K]) that serves every batch of the chunk
  * sharded: only per-destination uids stage (stage_push_dedup
    uid_only); the step derives the maps from the a2a'd bucket ids —
    composes with the 2-process host-plane bucket exchange

The measured motivation (wire bytes vs device-sort trade) is bench.py's
e2e ladder / BASELINE.md round 8."""

import dataclasses

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("wire_modes_data")
    # small vocab → heavy key recurrence across batches: merge order,
    # first-occurrence reuse and the touched-row delta are exercised hard
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=480, num_slots=NUM_SLOTS,
        vocab_per_slot=120, max_len=3, seed=11)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    return files, feed


def run_mode(files, feed, mode, wire=None, scan_chunk=2, passes=2,
             chunk_sync=False):
    """wire: None = full host products | 'uid' | 'ids_only' | 'delta'."""
    flags.set_flag("push_write", mode)
    if wire is not None:
        flags.set_flag("h2d_lean", True)
        flags.set_flag("h2d_uid_wire", wire != "ids_only")
        flags.set_flag("wire_delta_ids", wire == "delta")
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(
                mf_create_thresholds=0.0, mf_initial_range=1e-3))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        tr = BoxTrainer(model, table, feed, TrainerConfig(
            scan_chunk=scan_chunk, sparse_chunk_sync=chunk_sync), seed=0)
        losses = []
        for p in range(passes):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(tr.train_pass(ds)["loss"])
            ds.release_memory()
        keys, vals = tr.table.store.state_items()
        order = np.argsort(keys)
        params = tr.params
        tr.close()
        return losses, keys[order], vals[order], params
    finally:
        flags.set_flag("push_write", "auto")
        flags.set_flag("h2d_lean", False)
        flags.set_flag("h2d_uid_wire", True)
        flags.set_flag("wire_delta_ids", False)


def assert_identical(a, b):
    la, ka, va, pa = a
    lb, kb, vb, pb = b
    assert la == lb
    assert np.array_equal(ka, kb)
    assert np.array_equal(va, vb)
    import jax
    for xa, xb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


# ------------------------------------------------------ single-host wires
def test_uid_wire_matches_host_dedup_chunked(data):
    """The reunified lean wire at scan_chunk>1 and multiple passes must be
    bit-identical to the full host-staged scatter oracle."""
    files, feed = data
    base = run_mode(files, feed, "scatter")
    uid = run_mode(files, feed, "scatter", wire="uid")
    assert_identical(base, uid)


def test_uid_wire_rebuild_matches_host_rebuild(data):
    """push_write=rebuild under the uid wire (pos derived ON DEVICE by an
    int32 scatter) vs the host-staged [capacity] pos map."""
    files, feed = data
    base = run_mode(files, feed, "rebuild", passes=1)
    uid = run_mode(files, feed, "rebuild", wire="uid", passes=1)
    assert_identical(base, uid)


def test_delta_wire_matches(data):
    """wire_delta_ids: (base, int16 delta)-coded uids decode on device to
    the same sorted vector — identical training, 2 bytes/key less wire."""
    files, feed = data
    base = run_mode(files, feed, "scatter", passes=1)
    delta = run_mode(files, feed, "scatter", wire="delta", passes=1)
    assert_identical(base, delta)


def test_ids_only_lean_matches_host_dedup(data):
    """The round-5 ids-only wire (h2d_uid_wire off): device-side
    jnp.unique dedup with the minimal wire — the content-addressed
    lazy-init randoms make created rows independent of WHERE the dedup
    ran."""
    files, feed = data
    base = run_mode(files, feed, "scatter", passes=1)
    lean = run_mode(files, feed, "auto", wire="ids_only", passes=1)
    assert_identical(base, lean)


def test_ids_only_lean_rejects_host_map_modes(data):
    files, feed = data
    with pytest.raises(ValueError, match="h2d_lean"):
        run_mode(files, feed, "rebuild", wire="ids_only", passes=1)


def test_push_write_log_deleted(data):
    """The round-5 'log' mode is gone (verdict item 8): the flag value
    fails loud with a pointer to the retained findings."""
    files, feed = data
    with pytest.raises(ValueError, match="round 8"):
        run_mode(files, feed, "log", passes=1)


def test_grouped_h2d_matches_per_chunk(data):
    """h2d_stack_chunks>1 (round-5 verdict item 4): G chunks sharing one
    transfer per leaf — with device-side slicing back to per-chunk views
    — must be bit-identical to per-chunk transfers, on the full AND the
    uid wire."""
    files, feed = data
    for wire in (None, "uid"):
        base = run_mode(files, feed, "scatter", wire=wire)
        flags.set_flag("h2d_stack_chunks", 4)
        try:
            grouped = run_mode(files, feed, "scatter", wire=wire)
        finally:
            flags.set_flag("h2d_stack_chunks", 1)
        assert_identical(base, grouped)


# ------------------------------------------------- chunk-amortized dedup
def test_chunk_sync_uid_wire_matches(data):
    """sparse_chunk_sync + uid wire: ONE sorted [C*K] uid vector per scan
    chunk serves every batch (the chunk-amortized dedup) — bit-identical
    to the chunk-sync path with full host-staged cpush products."""
    files, feed = data
    base = run_mode(files, feed, "scatter", chunk_sync=True)
    uid = run_mode(files, feed, "scatter", wire="uid", chunk_sync=True)
    assert_identical(base, uid)


def test_chunk_sync_delta_wire_matches(data):
    files, feed = data
    base = run_mode(files, feed, "scatter", chunk_sync=True, passes=1)
    delta = run_mode(files, feed, "scatter", wire="delta", chunk_sync=True,
                     passes=1)
    assert_identical(base, delta)


# ------------------------------------------------------------- test_mode
def test_uid_wire_test_mode(data):
    """SetTestMode under the uid wire: eval batches stage no push
    products on ANY wire (no creation, no write-back), and a uid-wire-
    trained table serves bit-identical predictions to the host-wire
    oracle."""
    files, feed = data

    def train_and_predict(wire):
        if wire is not None:
            flags.set_flag("h2d_lean", True)
        try:
            table = TableConfig(
                embedx_dim=D, pass_capacity=2048,
                optimizer=SparseOptimizerConfig(
                    mf_create_thresholds=0.0, mf_initial_range=1e-3))
            model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                           hidden=(16,))
            tr = BoxTrainer(model, table, feed,
                            TrainerConfig(scan_chunk=2), seed=0)
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            tr.train_pass(ds)
            ds.release_memory()
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            tr.table.begin_feed_pass()
            ds.load_into_memory(add_keys_fn=tr.table.add_keys)
            tr.table.end_feed_pass()
            preds, labels = tr.predict_batches(ds)
            tr.close()
            return preds, labels
        finally:
            flags.set_flag("h2d_lean", False)

    p_base, l_base = train_and_predict(None)
    p_uid, l_uid = train_and_predict("uid")
    assert np.array_equal(l_base, l_uid)
    assert np.array_equal(p_base, p_uid)


# ------------------------------------------------------------ unit tier
def test_push_sparse_uidwire_unit():
    """Direct kernel parity: device-derived maps (searchsorted inv,
    scatter-min first, scattered pos) against push_sparse_hostdedup /
    push_sparse_rebuild with host dedup products, scatter and rebuild
    writes, with and without pull-row reuse."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    push_sparse_rebuild,
                                                    push_sparse_uidwire)
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted,
                                                    first_occurrence_idx,
                                                    pos_for_rebuild)

    rng = np.random.RandomState(3)
    cap, K = 256, 64
    layout = ValueLayout(D, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    push = PushLayout(D)
    slab = rng.rand(cap, layout.width).astype(np.float32)
    ids = rng.randint(0, 40, K).astype(np.int32)
    ids[rng.rand(K) < 0.2] = cap - 1          # padding occurrences
    grads = rng.randn(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    grads[ids == cap - 1] = 0.0               # padding rows all-zero
    prng = jax.random.PRNGKey(7)

    uids, perm, inv = dedup_ids(ids, cap)
    first = first_occurrence_idx(perm, inv)
    pulled = jnp.asarray(slab[ids])
    host = push_sparse_hostdedup(jnp.asarray(slab), jnp.asarray(uids),
                                 jnp.asarray(perm), jnp.asarray(inv),
                                 jnp.asarray(grads), prng, layout, conf,
                                 pulled_rows=pulled,
                                 first_idx=jnp.asarray(first))
    suids = dedup_uids_sorted(ids, cap)
    for pr in (pulled, None):
        wire = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(suids),
                                   jnp.asarray(ids), jnp.asarray(grads),
                                   prng, layout, conf, pulled_rows=pr)
        np.testing.assert_array_equal(np.asarray(host), np.asarray(wire))

    pos = pos_for_rebuild(uids, cap)
    host_rb = push_sparse_rebuild(jnp.asarray(slab), jnp.asarray(uids),
                                  jnp.asarray(pos), jnp.asarray(perm),
                                  jnp.asarray(inv), jnp.asarray(grads),
                                  prng, layout, conf)
    wire_rb = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(suids),
                                  jnp.asarray(ids), jnp.asarray(grads),
                                  prng, layout, conf, write="rebuild")
    np.testing.assert_array_equal(np.asarray(host_rb), np.asarray(wire_rb))


def test_delta_encode_decode_unit():
    """Host coding invariants: exact round trip, padding recode to
    in-range ids stays unique/nondecreasing, oversize gaps fail loud."""
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.optimizers import decode_delta_uids
    from paddlebox_tpu.embedding.pass_table import (dedup_uids_sorted,
                                                    delta_encode_uids)

    cap = 1 << 14
    ids = np.array([5, 9, 5, 100, 2, cap - 1, cap - 1, 9], np.int32)
    uids = dedup_uids_sorted(ids, cap)
    assert np.all(np.diff(uids.astype(np.int64)) > 0)
    base, d16, cut = delta_encode_uids(uids, cap)
    assert d16.dtype == np.int16 and d16[0] == 0
    dec = np.asarray(decode_delta_uids(jnp.asarray(base),
                                       jnp.asarray(d16),
                                       jnp.asarray(cut), cap))
    # trash id (cap-1) present -> exact round trip incl. padding tail
    np.testing.assert_array_equal(dec, uids)
    # the data region is exempt from the trash jump: gaps beyond int16
    # only count BELOW the trash id, so this shape still encodes
    assert cut == 4

    # no trash id in the batch -> the tail decodes to [trash, padding...]
    # (trash maps no occurrence; only its own bits can be written back)
    ids2 = np.array([5, 9, 5, 2], np.int32)
    uids2 = dedup_uids_sorted(ids2, cap)
    base2, d2, cut2 = delta_encode_uids(uids2, cap)
    dec2 = np.asarray(decode_delta_uids(jnp.asarray(base2),
                                        jnp.asarray(d2),
                                        jnp.asarray(cut2), cap))
    np.testing.assert_array_equal(dec2[:3], [2, 5, 9])
    assert dec2[3] == cap - 1 and np.all(np.diff(dec2) > 0)

    with pytest.raises(ValueError, match="int16"):
        delta_encode_uids(np.array([0, 1 << 20], np.int32), 1 << 21)


# -------------------------------------------------------------- sharded
def make_sharded_trainer(feed, seed=0):
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * (1 << 9),
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,))
    return ShardedBoxTrainer(model, table_cfg, feed,
                             TrainerConfig(dense_lr=3e-3), seed=seed)


def test_sharded_uid_wire_matches_full_staging(data):
    """The 8-shard trainer on the uid wire (per-destination sorted uids
    only; maps derived in the shard_map step from the a2a'd bucket ids)
    must train bit-identically to the full push_perm/inv staging."""
    files, feed = data
    states = {}
    for uid_only in (True, False):
        flags.set_flag("h2d_uid_wire", uid_only)
        try:
            trainer = make_sharded_trainer(feed, seed=4)
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            trainer.train_pass(ds)
            states[uid_only] = [st.state_items()
                                for st in trainer.table.stores]
            trainer.close()
        finally:
            flags.set_flag("h2d_uid_wire", True)
    for (k_u, v_u), (k_f, v_f) in zip(states[True], states[False]):
        np.testing.assert_array_equal(k_u, k_f)
        np.testing.assert_array_equal(v_u, v_f)


def test_two_virtual_process_uid_staging():
    """The uid wire composed with the host-plane bucket exchange: two
    VIRTUAL processes (mesh positions 0-3 / 4-7) each stage their owned
    destinations' uids through exchange_outgoing_buckets and must
    reproduce the single-process staging exactly — and the staged uids
    must drive push_sparse_uidwire to the same rows as the full host
    dedup products over the same incoming ids."""
    import concurrent.futures

    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    push_sparse_uidwire)
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    first_occurrence_idx)
    from paddlebox_tpu.parallel.sharded_table import stage_push_dedup

    P, KB, shard_cap = 8, 16, 128
    rng = np.random.RandomState(5)
    # [P(src), P(dest), KB] local-id buckets, trash-padded like bucketize
    buckets = np.full((P, P, KB), shard_cap - 1, np.int32)
    for s in range(P):
        for d in range(P):
            n = rng.randint(2, KB)
            buckets[s, d, :n] = rng.randint(0, shard_cap - 1, n)
    pool = concurrent.futures.ThreadPoolExecutor(2)

    single = stage_push_dedup(list(buckets), list(range(P)), P, shard_cap,
                              multiprocess=False, all_gather=None,
                              rebuild=False, pool=pool, uid_only=True)
    assert set(single) == {"push_uids"}

    # two virtual processes: precompute both payloads, fake the gather
    def payload_of(bl, positions):
        bl = np.ascontiguousarray(bl, np.int32)
        header = np.array([len(positions), P, KB] + list(positions),
                          np.int32)
        return np.concatenate([header, bl.ravel()])

    parts = [payload_of(buckets[0:4], [0, 1, 2, 3]),
             payload_of(buckets[4:8], [4, 5, 6, 7])]
    fake_gather = lambda payload: parts  # noqa: E731
    touched = {}

    def note(d, uids):
        touched.setdefault(d, []).append(uids)

    out = {}
    for lo, positions in ((0, [0, 1, 2, 3]), (4, [4, 5, 6, 7])):
        staged = stage_push_dedup(
            list(buckets[lo:lo + 4]), positions, P, shard_cap,
            multiprocess=True, all_gather=fake_gather, rebuild=False,
            pool=pool, note_touched=note, uid_only=True)
        for i, d in enumerate(positions):
            out[d] = staged["push_uids"][i]
    for d in range(P):
        np.testing.assert_array_equal(out[d], single["push_uids"][d],
                                      err_msg=f"dest {d}")
        assert d in touched  # uids host-known -> touched-row accounting

    # numeric tier: staged uids == full host products, row for row
    layout = ValueLayout(D, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    push = PushLayout(D)
    d = 3
    incoming = np.concatenate([buckets[s][d] for s in range(P)])
    grads = rng.randn(incoming.size, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    grads[incoming == shard_cap - 1] = 0.0
    slab = rng.rand(shard_cap, layout.width).astype(np.float32)
    prng = jax.random.PRNGKey(1)
    uids, perm, inv = dedup_ids(incoming, shard_cap)
    host = push_sparse_hostdedup(
        jnp.asarray(slab), jnp.asarray(uids), jnp.asarray(perm),
        jnp.asarray(inv), jnp.asarray(grads), prng, layout, conf)
    wire = push_sparse_uidwire(
        jnp.asarray(slab), jnp.asarray(out[d]), jnp.asarray(incoming),
        jnp.asarray(grads), prng, layout, conf)
    np.testing.assert_array_equal(np.asarray(host), np.asarray(wire))
    pool.shutdown(wait=False)


# ---------------------------------------------- uid sortedness contract

def _assert_strictly_ascending(uids, where):
    """The uid-wire contract: the host-staged vector is STRICTLY
    ascending over its full length — data ids sorted unique, the padding
    tail (pad_base+i) continuing past them. The device searchsorted
    silently mis-maps every occurrence on unsorted input (no error, just
    corrupt rows), so sortedness must hold on every staging path."""
    uids = np.asarray(uids)
    assert uids.ndim == 1 and uids.size, where
    d = np.diff(uids.astype(np.int64))
    assert (d > 0).all(), "%s: uid vector not strictly ascending " \
        "(first break at %d)" % (where, int(np.argmin(d > 0)))


def test_dedup_uids_sorted_contract_all_paths(data):
    """Round-10 satellite: assert the sorted-uid contract on EVERY host
    staging path — the raw helper (whose native rt_dedup sibling returns
    hash-probe ORDER, so a refactor absorbing one into the other would
    corrupt silently), the single-host batch wire, the chunk-amortized
    chunk-sync wire, and the per-destination sharded staging."""
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted)

    rng = np.random.RandomState(7)
    # adversarial shapes: duplicates, full-range, single value, all-pad
    for ids in (rng.randint(0, 50, 256).astype(np.int32),
                np.arange(199, dtype=np.int32)[::-1].copy(),
                np.full(64, 3, np.int32),
                rng.randint(0, 2047, 512).astype(np.int32)):
        _assert_strictly_ascending(dedup_uids_sorted(ids, 2048),
                                   "dedup_uids_sorted")
    # the native rt_dedup fast path really is probe-ordered (the hazard
    # this contract guards): when its uids happen to differ from sorted
    # order, dedup_uids_sorted must still be sorted
    ids = rng.randint(0, 2000, 1024).astype(np.int32)
    _assert_strictly_ascending(dedup_uids_sorted(ids, 2048), "vs rt_dedup")
    uids_raw, _, _ = dedup_ids(ids, 2048)
    assert set(uids_raw.tolist()) == set(
        dedup_uids_sorted(ids, 2048).tolist())

    # single-host batch wire: host_batch stages out["uids"] under h2d_lean
    files, feed = data
    flags.set_flag("h2d_lean", True)
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                            mf_initial_range=1e-3))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        tr = BoxTrainer(model, table, feed, TrainerConfig(scan_chunk=2),
                        seed=0)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files[:1])
        tr.table.begin_feed_pass()
        ds.load_into_memory(add_keys_fn=tr.table.add_keys)
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        batches = ds.split_batches(num_workers=1)[0]
        for b in batches[:3]:
            staged = tr.host_batch(b, tr.table.lookup_ids(b.keys, b.valid))
            _assert_strictly_ascending(staged["uids"], "host_batch uid wire")
        # chunk-amortized wire: ONE [C*K] vector per scan chunk
        tr.sparse_chunk_sync = True
        _, cpush = tr._stack_batches_host(batches[:2])
        _assert_strictly_ascending(cpush["uids"], "chunk-sync cpush")
        tr.sparse_chunk_sync = False
        tr.table.end_pass()
        tr.close()
    finally:
        flags.set_flag("h2d_lean", False)

    # per-destination sharded staging (single-process + 2-virtual-rank
    # p2p pre-wire dedup): every destination's staged vector is sorted
    import concurrent.futures

    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    from paddlebox_tpu.parallel.sharded_table import (
        exchange_push_uids_p2p, stage_push_dedup)
    P, KB, shard_cap = 4, 32, 256
    buckets = np.full((P, P, KB), shard_cap - 1, np.int32)
    for s in range(P):
        for dd in range(P):
            n = rng.randint(2, KB)
            buckets[s, dd, :n] = rng.randint(0, shard_cap - 1, n)
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        single = stage_push_dedup(list(buckets), list(range(P)), P,
                                  shard_cap, multiprocess=False,
                                  all_gather=None, rebuild=False,
                                  pool=pool, uid_only=True)
        for dd, uids in enumerate(single["push_uids"]):
            _assert_strictly_ascending(uids, "sharded dest %d" % dd)

        meshes = [MeshComm(r, 2) for r in range(2)]
        eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
        pos = {0: [0, 1], 1: [2, 3]}
        try:
            for m in meshes:
                m.connect(eps)
                m.positions_of = dict(pos)
            f = pool.submit(exchange_push_uids_p2p, buckets[2:4], [2, 3],
                            P, shard_cap, meshes[1])
            out0 = exchange_push_uids_p2p(buckets[0:2], [0, 1], P,
                                          shard_cap, meshes[0])
            out1 = f.result()
            for dd, uids in {**out0, **out1}.items():
                _assert_strictly_ascending(uids, "p2p uid dest %d" % dd)
                # p2p pre-wire dedup == single-process product
                np.testing.assert_array_equal(uids, single["push_uids"][dd])
        finally:
            for m in meshes:
                m.close()

def test_rt_dedup_sorted_native_matches_numpy_oracle():
    """Round-11 satellite: the native rt_dedup_sorted fast path (presence
    mark + radix sort over uniques) must return EXACTLY the numpy tier's
    product — sorted uniques + pad_base+i tail — on every accepted shape,
    and must DECLINE (numpy fallback, still correct) low-duplication
    shapes where it measured slower. Skips when the native lib is absent
    (the wrapper is then the numpy tier by construction)."""
    import unittest.mock as mock

    from paddlebox_tpu.embedding.pass_table import dedup_uids_sorted
    from paddlebox_tpu.native.build import get_lib

    lib = get_lib()
    if lib is None or not hasattr(lib, "rt_dedup_sorted"):
        pytest.skip("native lib with rt_dedup_sorted not available")

    def numpy_tier(ids, pad_base):
        with mock.patch("paddlebox_tpu.native.build.get_lib",
                        return_value=None):
            return dedup_uids_sorted(ids, pad_base)

    rng = np.random.RandomState(17)
    shapes = [
        (1024, 64),     # heavy duplication — the accepted regime
        (1024, 512),    # boundary: span ~ K/2, still accepted
        (1024, 600),    # declined (live span > K/2) — numpy fallback
        (64, 1),        # single unique value
        (256, 8),
    ]
    for K, space in shapes:
        ids = rng.randint(0, space, K).astype(np.int32)
        got = dedup_uids_sorted(ids, space)
        ref = numpy_tier(ids, space)
        np.testing.assert_array_equal(got, ref, err_msg=f"K={K} {space}")
        _assert_strictly_ascending(got, f"rt_dedup_sorted K={K} {space}")
    # round-13 engagement re-key (the PR-6 named follow-up): the WIRED
    # shape — pad_base = capacity >> K, ids clustered in a small working
    # set PLUS the trash id (capacity-1) from bucket padding. The old
    # 2*pad_base<=K predicate always declined here; the span predicate
    # engages (the trash id rides out-of-band) and the product must
    # still be the numpy oracle's, bit for bit.
    for K, ws, cap in [(2048, 400, 1 << 16), (1024, 64, 1 << 20),
                       (4096, 2000, 1 << 13), (256, 255, 1 << 8)]:
        ids = rng.randint(0, ws, K).astype(np.int32)
        ids[::7] = cap - 1          # the bucket-padding trash id
        got = dedup_uids_sorted(ids, cap)
        np.testing.assert_array_equal(got, numpy_tier(ids, cap),
                                      err_msg=f"wired K={K} ws={ws}")
        _assert_strictly_ascending(got, f"wired K={K} ws={ws}")
    # all-trash batch (a fully-padded bucket column)
    ids = np.full(128, (1 << 12) - 1, np.int32)
    np.testing.assert_array_equal(dedup_uids_sorted(ids, 1 << 12),
                                  numpy_tier(ids, 1 << 12))
    # clustered low WITHOUT trash (single-host uid-wire shape)
    ids = rng.randint(0, 100, 1024).astype(np.int32)
    np.testing.assert_array_equal(dedup_uids_sorted(ids, 1 << 16),
                                  numpy_tier(ids, 1 << 16))
    # out-of-contract ids (>= pad_base) on an otherwise-accepted shape:
    # the native tier must DECLINE (its presence table is exactly
    # pad_base bytes — marking past it is a heap overwrite) and the
    # wrapper degrade to the numpy tier's well-defined product
    ids = rng.randint(0, 64, 1024).astype(np.int32)
    ids[7] = 100  # would index 36 bytes past the presence table
    np.testing.assert_array_equal(dedup_uids_sorted(ids, 64),
                                  numpy_tier(ids, 64))
    # empty batch: no native call, trivially sorted-empty
    assert dedup_uids_sorted(np.empty(0, np.int32), 16).size == 0
