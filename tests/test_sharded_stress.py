"""Seeded stress harness around the sharded staging path (round 12).

Chases the PR-6 flake (test_sharded_blocked_matches_scatter failed once
under native-recompile load: 6/780 show-like elements off by one —
never reproduced; see BASELINE.md round 12 for the accumulated
reproduction bound). The harness lives in tools/sharded_stress_probe.py
so campaigns can run long outside pytest; this suite keeps it honest:

  * the tier-flip hypothesis check runs for real (native vs numpy
    router must product-match absent bucket overflow)
  * one seeded stress rep under burner load runs the 4-config parity

Both slow-marked: multi-minute sharded e2e compositions (the flaky
composition itself is slow-marked too).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def stress_data(tmp_path_factory):
    from tools.sharded_stress_probe import make_data
    return make_data(13, str(tmp_path_factory.mktemp("stress")))


def test_router_tier_flip_product_match(stress_data):
    """Native router vs numpy fallback train bit-identically at the
    flaky test's shape (no bucket overflow): a mid-run recompile window
    flipping the tier cannot explain the PR-6 flake here. If THIS ever
    fails, the flake mechanism is pinned — record the diff and the
    bucketize-overflow state in BASELINE.md."""
    from tools.sharded_stress_probe import run_tier_flip
    files, feed = stress_data
    diff = run_tier_flip(files, feed, seed=13)
    assert diff is None, diff


def test_seeded_stress_rep_parity(stress_data):
    """One harness rep under burner load: blocked == scatter bit-exact
    on both wires. A failure here is the PR-6 flake reproducing —
    DON'T retry it away; capture the seed + diff into BASELINE.md."""
    from tools.sharded_stress_probe import LoadBurners, run_rep
    files, feed = stress_data
    burners = LoadBurners(2)
    try:
        bad = run_rep(files, feed, seed=17)
    finally:
        burners.stop()
    assert not bad, bad


def test_diff_states_detects_planted_mismatch():
    """The harness's comparator itself (fast): a planted off-by-one in
    one element must be reported with count/col diagnostics — guards
    against a silently-vacuous campaign."""
    from tools.sharded_stress_probe import diff_states
    k = np.arange(10, dtype=np.uint64)
    v = np.ones((10, 5), np.float32)
    v2 = v.copy()
    assert diff_states([(k, v)], [(k, v2)]) is None
    v2[3, 2] += 1.0
    d = diff_states([(k, v)], [(k, v2)])
    assert d == {"shard": 0, "kind": "values", "n_bad": 1, "of": 50,
                 "max_abs_diff": 1.0, "cols": [2]}
    # permuted key order is still the same state
    perm = np.random.RandomState(0).permutation(10)
    assert diff_states([(k, v)], [(k[perm], v[perm])]) is None
