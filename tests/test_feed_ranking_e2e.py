"""Feed-ranking composition (BASELINE.json final config, small-scale): the
pod-sharded trainer with an SSD spill tier under the host stores, driven
with load(N+1) ∥ train(N) preload overlap across multiple passes.

Ties together in ONE run what the per-subsystem suites test separately:
sharded a2a pull/push (heter_comm semantics), pass-cadence spill
(CheckNeedLimitMem/ShrinkResource, box_wrapper.h:627-629), the BoxHelper
PreLoad/Wait cadence (box_wrapper.h:1131-1172), and test-mode eval."""

import glob
import os

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset
from paddlebox_tpu.data.generator import write_synthetic_ctr_files
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
from paddlebox_tpu.train.preload import run_preloaded_passes

import jax

N_SLOTS = 8
D = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("feedrank")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=4, lines_per_file=200, num_slots=N_SLOTS,
        vocab_per_slot=600, max_len=3, seed=3)
    import dataclasses
    return files, dataclasses.replace(feed, batch_size=32)


def test_feed_ranking_composition(data, tmp_path):
    files, feed = data
    P = len(jax.devices())
    ssd_dir = str(tmp_path / "ssd")
    table = TableConfig(
        embedx_dim=D, pass_capacity=P * (1 << 11),
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3),
        # a budget small enough that the pass working set cannot stay
        # resident: every end_pass must spill cold rows to the SSD tier
        ssd_dir=ssd_dir, ssd_threshold_mb=0.02)
    trainer = ShardedBoxTrainer(
        DeepFM(ModelSpec(num_slots=N_SLOTS, slot_dim=3 + D), hidden=(32, 16)),
        table, feed, TrainerConfig(dense_lr=1e-2, scan_chunk=2),
        mesh=device_mesh_1d(P), seed=0)
    trainer.metrics.init_metric("auc", "label", "pred", mask_var="mask")

    datasets = []
    for _ in range(4):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        datasets.append(ds)
    stats = run_preloaded_passes(trainer, datasets, release=False)

    # training made progress across the spilling passes
    assert len(stats) == 4
    assert stats[-1]["loss"] < stats[0]["loss"]
    msg = trainer.metrics.get_metric_msg("auc")
    assert msg["auc"] > 0.55, msg

    # the spill tier is real: files exist and rows faulted back in pass 2+
    spill_files = glob.glob(os.path.join(ssd_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in spill_files), spill_files

    # eval over the last pass's data still sees every spilled feature
    preds, labels = trainer.predict_batches(datasets[-1])
    assert preds.size == len(datasets[-1])
    order = np.argsort(preds)
    ranks = np.empty(preds.size, float)
    ranks[order] = np.arange(preds.size)
    pos = labels == 1
    if pos.any() and (~pos).any():
        auc = (ranks[pos].mean() - (pos.sum() - 1) / 2) / (~pos).sum()
        assert auc > 0.6, auc
