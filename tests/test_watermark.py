"""Feed-to-serve watermark plane (round 20): lineage format, journal
publish, serving-side tracking, pull stamping, freshness SLO burn,
tiered-store telemetry, and the exact /metrics names dashboards pin.

The e2e acceptance test here is the stall one: a journal tail that
stops publishing must trip the HealthMonitor freshness burn within two
serving report windows — the plane exists so that failure mode is loud.
"""

import os
import time
import types
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.obs import watermark as wm
from paddlebox_tpu.obs.exporter import ObsExporter
from paddlebox_tpu.obs.health import HealthMonitor
from paddlebox_tpu.serving import codec
from paddlebox_tpu.serving.client import ServingClient
from paddlebox_tpu.serving.refresh import JournalDeltaSource
from paddlebox_tpu.serving.server import ServingServer
from paddlebox_tpu.serving.store import write_xbox_columnar
from paddlebox_tpu.train.journal import TouchedRowJournal, replay_segments
from paddlebox_tpu.utils import journal_format as jf
from paddlebox_tpu.utils.journal_format import iter_segment
from paddlebox_tpu.utils.stats import StatRegistry, gauge_set, stat_get

EMBEDX = 4
DIM = 1 + EMBEDX        # served xbox row width
WIDTH = 7 + 1 + EMBEDX  # header + adagrad state + embedx (store row)


@pytest.fixture
def registry():
    reg = StatRegistry.instance()
    saved = reg.snapshot_all()
    reg.reset()
    yield reg
    reg.reset()
    for k, v in saved["counters"].items():
        reg.set(k, v)
    for k, v in saved["gauges"].items():
        reg.set_gauge(k, v)


def journal_writer(tmp_path, name="_journal"):
    layout = types.SimpleNamespace(width=WIDTH, embedx_dim=EMBEDX,
                                   optimizer="adagrad")
    return TouchedRowJournal(os.path.join(str(tmp_path), name),
                             layout, None)


def make_day(tmp_path, n=200, seed=3):
    """A tiny xbox day dir a journal-fed server can compose views from."""
    rng = np.random.RandomState(seed)
    keys = np.unique(rng.randint(1, 1 << 40, n).astype(np.uint64))
    rows = rng.randn(keys.size, DIM).astype(np.float32)
    root = str(tmp_path / "xbox")
    day = os.path.join(root, "day0")
    os.makedirs(day)
    write_xbox_columnar(os.path.join(day, "view.xcol"), keys, rows)
    with open(os.path.join(day, "DONE"), "w") as f:
        f.write(str(time.time()))
    return root, keys


# ------------------------------------------------------------- format


def test_pack_unpack_watermark_roundtrip_and_forward_compat():
    payload = jf.pack_watermark(10.5, 20.25, 30.125, trace=0xDEAD)
    assert jf.unpack_watermark(payload) == (10.5, 20.25, 30.125, 0xDEAD)
    # unpack_from semantics: a FUTURE writer may append fields to the
    # payload — an old reader must still decode the prefix it knows
    assert jf.unpack_watermark(payload + b"future-fields") == (
        10.5, 20.25, 30.125, 0xDEAD)
    # trace ids are masked into u64, never a struct.error
    big = jf.pack_watermark(1.0, 2.0, 3.0, trace=1 << 80)
    assert jf.unpack_watermark(big)[3] == 0


def test_publish_writes_watermark_record_and_replay_ignores_it(tmp_path):
    # real store layout (width 13 for adagrad: header + embed_w/g2sum +
    # embedx) so the sealed segment replays onto a real store below
    from paddlebox_tpu.embedding.accessor import ValueLayout
    layout = ValueLayout(EMBEDX)
    j = TouchedRowJournal(os.path.join(str(tmp_path), "_jr"), layout, None)
    keys = np.arange(1, 9, dtype=np.uint64)
    vals = np.arange(8 * layout.width,
                     dtype=np.float32).reshape(8, layout.width)
    j.append_rows(keys, vals)
    t0 = time.time()
    sealed = j.publish(born_min=t0 - 3.0, born_max=t0 - 1.0, trace=42)
    j.close()
    kinds = [k for k, _ in iter_segment(sealed)]
    assert jf.KIND_WATERMARK in kinds
    # the watermark record rides the SAME segment as the window's rows
    assert jf.KIND_ROWS in kinds
    (wm_payload,) = [p for k, p in iter_segment(sealed)
                     if k == jf.KIND_WATERMARK]
    bmin, bmax, pub, trace = jf.unpack_watermark(wm_payload)
    assert (bmin, bmax, trace) == (t0 - 3.0, t0 - 1.0, 42)
    assert pub >= t0
    # replay applies the rows and ONLY the rows: pre-round-20 recovery
    # (and any store replay) treats the watermark as pure lineage
    from paddlebox_tpu.embedding.host_store import HostEmbeddingStore
    cfg = TableConfig(embedx_dim=EMBEDX,
                      optimizer=SparseOptimizerConfig(
                          mf_create_thresholds=0.0, mf_initial_range=1e-3))
    st = HostEmbeddingStore(layout, cfg)
    applied = replay_segments(st, cfg, [sealed])
    assert applied == 1 and len(st) == 8


def test_publish_without_born_writes_no_watermark(tmp_path):
    j = journal_writer(tmp_path)
    j.append_rows(np.array([5], np.uint64),
                  np.zeros((1, WIDTH), np.float32))
    sealed = j.publish()
    j.close()
    assert jf.KIND_WATERMARK not in [k for k, _ in iter_segment(sealed)]


# ----------------------------------------------------- serving tracking


def test_journal_source_applied_watermark_and_unapplied_age(
        tmp_path, registry):
    j = journal_writer(tmp_path)
    src = JournalDeltaSource([j.dir])
    try:
        assert src.applied_watermark() == 0.0
        t0 = time.time()
        j.append_rows(np.array([7], np.uint64),
                      np.zeros((1, WIDTH), np.float32))
        j.publish(born_min=t0 - 5.0, born_max=t0 - 2.0)
        assert src.poll()
        assert src.applied_watermark() == pytest.approx(t0 - 2.0)
        g = registry.snapshot_all()["gauges"]
        assert g["serving_watermark_age_secs"] >= 2.0
        # polled but not yet compiled into a served overlay: the
        # unapplied age runs from the publish instant...
        assert g["serving_unapplied_watermark_age_secs"] > 0.0
        src.compile_overlay()
        g = registry.snapshot_all()["gauges"]
        # ...and clears the moment the overlay materializes
        assert g["serving_unapplied_watermark_age_secs"] == 0.0
        # watermarks never regress: an older window's publish (replayed
        # segment, lagging dir) must not pull the low-water-mark back
        j.append_rows(np.array([8], np.uint64),
                      np.zeros((1, WIDTH), np.float32))
        j.publish(born_min=t0 - 50.0, born_max=t0 - 40.0)
        src.poll()
        assert src.applied_watermark() == pytest.approx(t0 - 2.0)
    finally:
        src.close()
        j.close()


def test_codec_watermark_stamp_roundtrip_and_garbage_safety():
    rows = np.zeros((2, DIM), np.float32)
    t0 = time.time()
    assert codec.decode_watermark(
        codec.encode_rows(rows, gen=1, watermark=t0)) == pytest.approx(t0)
    # cold journal → no stamp at all (forward compat with old clients)
    assert "wm" not in codec.encode_rows(rows, gen=1)
    assert "wm" not in codec.encode_rows(rows, gen=1, watermark=0.0)
    # garbage stamps decode to None, NEVER raise (telemetry contract)
    for resp in ({}, {"wm": "soon"}, {"wm": None}, {"wm": -4.0},
                 {"wm": b"\x00"}):
        assert codec.decode_watermark(resp) is None


# ------------------------------------------------------- e2e freshness


def _pull_until_stamped(client, keys, deadline=10.0):
    end = time.time() + deadline
    while time.time() < end:
        client.pull(keys)
        if client.last_watermark > 0.0:
            return
        time.sleep(0.02)
    raise AssertionError("pull responses never carried a watermark")


def test_server_stamps_pulls_and_freshness_is_observed(
        tmp_path, registry):
    """The tentpole path end to end in one process: journal publish
    with a born span → refresh poll applies it → every pull response
    carries the watermark → BOTH sides sample now-born into the
    freshness histogram → the report window republishes the p99."""
    root, keys = make_day(tmp_path)
    j = journal_writer(tmp_path)
    flags.set_flag("serving_journal_dir", j.dir)
    flags.set_flag("serving_refresh_secs", 0.1)
    flags.set_flag("serving_report_requests", 4)
    server = ServingServer(root, days=["day0"])
    client = ServingClient([("127.0.0.1", server.port)])
    try:
        t0 = time.time()
        j.append_rows(keys[:3],
                      np.ones((3, WIDTH), np.float32))
        j.publish(born_min=t0 - 2.0, born_max=t0 - 1.0)
        _pull_until_stamped(client, keys[:8])
        assert client.last_watermark == pytest.approx(t0 - 1.0)
        snap = wm.freshness_snapshot()
        # born 1s ago → every sample is >= 1s end-to-end age
        assert snap["freshness_e2e_secs"] >= 1.0
        assert snap["freshness_e2e_secs_p50"] >= 1.0
        assert snap["freshness_e2e_secs_p99"] >= \
            snap["freshness_e2e_secs_p50"]
        assert registry.hist_counts(wm.FRESHNESS_HIST)
        for _ in range(4):             # cross the report cadence
            client.pull(keys[:8])
        rep = server.reporter.peek()
        assert rep is not None
        assert rep["freshness_e2e_secs_p99"] >= 1.0
    finally:
        client.close()
        server.drain(timeout=2)
        j.close()


def test_journal_stall_trips_freshness_burn_within_two_windows(
        tmp_path, registry):
    """ISSUE acceptance: stall the journal tail and the freshness burn
    gauge must exceed 1.0 within TWO serving report windows, and the
    HealthMonitor must flag the rank. The SLO is shrunk to 0.2 s so
    'stale' is reachable in test time; the mechanism under test — per
    window histogram-delta p99 over the SLO — is the production one."""
    root, keys = make_day(tmp_path)
    j = journal_writer(tmp_path)
    flags.set_flag("serving_journal_dir", j.dir)
    flags.set_flag("serving_refresh_secs", 0.05)
    flags.set_flag("serving_report_requests", 4)
    flags.set_flag("freshness_slo_secs", 0.2)
    server = ServingServer(root, days=["day0"])
    client = ServingClient([("127.0.0.1", server.port)])
    try:
        t0 = time.time()
        j.append_rows(keys[:2], np.ones((2, WIDTH), np.float32))
        j.publish(born_min=t0, born_max=t0)
        _pull_until_stamped(client, keys[:8])
        # ... and then the tail goes silent: no more publishes. Served
        # watermark pins at t0 while wall time walks away from it.
        time.sleep(0.5)                # age the watermark past the SLO
        for _ in range(8):             # two full report windows
            client.pull(keys[:8])
        g = registry.snapshot_all()["gauges"]
        burn = g.get("serving_freshness_burn", 0.0)
        assert burn > 1.0, burn
        hm = HealthMonitor(world=1)
        health = hm.update({"step": 1, "stale_ranks": [], "metrics": {
            "gauges.serving_freshness_burn": {"per_rank": {"0": burn}}}})
        assert "freshness_burn" in health["ranks"]["0"]["flags"]
        assert health["ranks"]["0"]["score"] == pytest.approx(0.6)
    finally:
        client.close()
        server.drain(timeout=2)
        j.close()


def test_health_monitor_freshness_and_tier_penalties():
    """Pinned penalty weights: freshness burn −0.4, tier-hit burn −0.3;
    both together cross the 0.5 unhealthy bar."""
    hm = HealthMonitor(world=1)
    health = hm.update({"step": 3, "stale_ranks": [], "metrics": {
        "gauges.serving_freshness_burn": {"per_rank": {"0": 2.5}},
        "gauges.tier_hit_burn": {"per_rank": {"0": 4.0}}}})
    r0 = health["ranks"]["0"]
    assert "freshness_burn" in r0["flags"]
    assert "tier_hit_low" in r0["flags"]
    assert r0["score"] == pytest.approx(0.3)
    assert 0 in health["unhealthy_ranks"]
    assert r0["freshness_burn"] == pytest.approx(2.5)
    assert r0["tier_hit_burn"] == pytest.approx(4.0)
    # sub-1.0 burns are healthy quiet — no flag, no penalty
    health = hm.update({"step": 4, "stale_ranks": [], "metrics": {
        "gauges.serving_freshness_burn": {"per_rank": {"0": 0.4}},
        "gauges.tier_hit_burn": {"per_rank": {"0": 0.9}}}})
    assert "flags" not in health["ranks"]["0"]
    assert health["ranks"]["0"]["score"] == pytest.approx(1.0)


# -------------------------------------------------- tiered-store ladder


def _native_store(tmp_path):
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.embedding.native_store import NativeHostEmbeddingStore
    cfg = TableConfig(embedx_dim=EMBEDX, ssd_dir=str(tmp_path / "ssd"),
                      optimizer=SparseOptimizerConfig(
                          mf_create_thresholds=0.0, mf_initial_range=1e-3))
    try:
        return NativeHostEmbeddingStore(ValueLayout(EMBEDX), cfg, seed=0)
    except RuntimeError:
        pytest.skip("native library unavailable")


def test_tier_hit_rate_excludes_created_keys(tmp_path, registry):
    """Round-20 semantics fix: the hit rate is over keys the store
    already KNEW (resident + tier-faulted). Created keys are
    construction, not thrashing — an all-new batch must produce NO rate
    sample (not a false 0% that would trip tier_hit_burn on every cold
    start and on slab-resident working sets)."""
    st = _native_store(tmp_path)
    keys = np.arange(1, 101, dtype=np.uint64)
    st.lookup_or_create(keys)          # all created
    g = registry.snapshot_all()["gauges"]
    assert "tier_hit_rate" not in g
    assert "tier_hit_burn" not in g
    assert stat_get("sparse_keys_resident_hit") == 0
    # warm re-lookup: everything resident → rate 1.0, burn warn/1 << 1
    st.lookup_or_create(keys)
    g = registry.snapshot_all()["gauges"]
    assert g["tier_hit_rate"] == pytest.approx(1.0)
    assert g["tier_hit_burn"] < 1.0
    assert stat_get("sparse_keys_resident_hit") == 100
    # spill half, touch ONLY the spilled half: 0% resident over known
    # keys — this IS thrashing and must burn
    st.spill_exact(keys[:50])
    st.lookup_or_create(keys[:50])
    g = registry.snapshot_all()["gauges"]
    assert g["tier_hit_rate"] == pytest.approx(0.0)
    assert g["tier_hit_burn"] > 1.0
    assert stat_get("sparse_keys_faulted_in") == 50


def test_tier_ladder_snapshot_fractions(tmp_path, registry):
    st = _native_store(tmp_path)
    keys = np.arange(1, 41, dtype=np.uint64)
    st.lookup_or_create(keys)          # 40 created
    st.lookup_or_create(keys)          # 40 resident hits
    st.spill_exact(keys[:10])
    st.lookup_or_create(keys)          # 30 resident + 10 ssd promotes
    lad = wm.tier_ladder()
    assert lad["miss_created"] == 40
    assert lad["host_ram_hit"] == 70
    assert lad["ssd_promote"] == 10
    assert lad["total"] == 120
    assert lad["host_ram_hit_frac"] == pytest.approx(70 / 120, abs=1e-4)
    assert sum(lad[k + "_frac"] for k in (
        "miss_created", "host_ram_hit", "ssd_promote",
        "ssd_prefetch")) == pytest.approx(1.0, abs=1e-3)
    # a real dir-mode promote also lands the latency histogram
    assert lad["ssd_promote_p99_us"] > 0.0


# ------------------------------------------------------- /metrics names


def test_metrics_pins_watermark_tier_and_streaming_names(tmp_path,
                                                         registry):
    """The exact exposition names the round-20 dashboards scrape. A
    rename anywhere in the plane breaks here first. Every series is
    populated through the REAL code path that owns it (observe,
    journal poll, SSD promote) — only the two streaming-runner lag
    gauges are set directly (their producer needs a live trainer; the
    name contract is pinned via freshness_snapshot, which reads them)."""
    wm.observe_freshness(time.time() - 5.0)
    j = journal_writer(tmp_path)
    src = JournalDeltaSource([j.dir])
    j.append_rows(np.array([3], np.uint64),
                  np.zeros((1, WIDTH), np.float32))
    j.publish(born_min=time.time() - 1.0)
    src.poll()
    src.close()
    j.close()
    st = _native_store(tmp_path)
    keys = np.arange(1, 21, dtype=np.uint64)
    st.lookup_or_create(keys)
    st.spill_exact(keys)
    st.lookup_or_create(keys)          # dir-mode promote → ssd hists
    gauge_set("streaming_ingest_lag_secs", 0.5)
    gauge_set("streaming_publish_lag_secs", 0.7)
    gauge_set("serving_freshness_burn", 0.2)
    gauge_set("serving_tier_hit_rate", 0.9)
    snap = wm.freshness_snapshot()
    for k in ("freshness_e2e_secs", "freshness_e2e_secs_p50",
              "freshness_e2e_secs_p99", "streaming_ingest_lag_secs",
              "streaming_publish_lag_secs", "serving_watermark_age_secs"):
        assert k in snap
    exp = ObsExporter(port=0)
    try:
        r = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % exp.port, timeout=5.0)
        text = r.read().decode()
    finally:
        exp.close()
    for name in (
            'pbtpu_freshness_e2e_ms_bucket{le="+Inf"}',
            "pbtpu_freshness_e2e_secs ",
            "pbtpu_freshness_e2e_secs_p50 ",
            "pbtpu_freshness_e2e_secs_p99 ",
            "pbtpu_serving_watermark_ts ",
            "pbtpu_serving_watermark_age_secs ",
            "pbtpu_serving_unapplied_watermark_age_secs ",
            "pbtpu_serving_freshness_burn ",
            "pbtpu_serving_tier_hit_rate ",
            "pbtpu_tier_hit_rate ",
            "pbtpu_tier_hit_burn ",
            'pbtpu_ssd_promote_us_bucket{le="+Inf"}',
            "pbtpu_ssd_tier_live_keys ",
            "pbtpu_ssd_tier_blocks ",
            "pbtpu_ssd_tier_index_entries ",
            "pbtpu_sparse_keys_resident_hit ",
            "pbtpu_sparse_keys_faulted_in ",
            "pbtpu_streaming_ingest_lag_secs ",
            "pbtpu_streaming_publish_lag_secs ",
    ):
        assert name in text, name
