"""Runtime lock-order validator (utils/lockwatch.py, round 19).

The dynamic twin of boxlint's static BX7xx pass: these tests pin the
inversion-detection contract (the AB/BA precondition is caught on the
FIRST interleaving that could deadlock, from either thread count), the
zero-cost-off contract (plain threading primitives when the flag is
off), the hold-time histogram plumbing through the obs StatRegistry,
and the Condition(lock) interplay the Channel depends on. Pure host
tests — no jax, no devices.
"""

import threading

import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.utils import lockwatch
from paddlebox_tpu.utils.stats import StatRegistry, stat_get, stat_reset


@pytest.fixture
def watch_on():
    flags.set_flag("debug_lock_order", True)
    lockwatch.reset()
    yield
    lockwatch.reset()
    flags.set_flag("debug_lock_order", False)


def test_off_returns_plain_primitives():
    flags.set_flag("debug_lock_order", False)
    assert type(lockwatch.make_lock("X._l")) is type(threading.Lock())
    assert type(lockwatch.make_rlock("X._r")) is type(threading.RLock())


def test_seeded_ab_ba_inversion_detected(watch_on):
    """The acceptance-criteria toy: seed an AB nesting and then a BA
    nesting and assert lockwatch flags the pair — WITHOUT needing the
    unlucky interleaving that actually deadlocks."""
    la = lockwatch.make_lock("Toy._a")
    lb = lockwatch.make_lock("Toy._b")

    def ab():
        with la:
            with lb:
                pass

    def ba():
        with lb:
            with la:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    stat_reset("lockwatch_inversions")
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()
    inv = lockwatch.inversions()
    assert len(inv) == 1
    assert set(inv[0]["pair"]) == {"Toy._a", "Toy._b"}
    assert stat_get("lockwatch_inversions") == 1
    with pytest.raises(AssertionError, match="Toy._"):
        lockwatch.assert_consistent()


def test_consistent_global_order_stays_clean(watch_on):
    la = lockwatch.make_lock("C._a")
    lb = lockwatch.make_lock("C._b")
    for _ in range(3):
        with la:
            with lb:
                pass
    with la:  # repeat + partial orders never alarm
        pass
    lockwatch.assert_consistent()
    assert lockwatch.edges() == {("C._a", "C._b"): 3}
    assert "C._a -> C._b x3" in lockwatch.order_report()


def test_rlock_reentry_records_no_self_edge(watch_on):
    r = lockwatch.make_rlock("R._l")
    with r:
        with r:
            pass
    assert lockwatch.edges() == {}
    lockwatch.assert_consistent()


def test_hold_time_histogram_published(watch_on):
    lk = lockwatch.make_lock("H._l")
    with lk:
        pass
    counts = StatRegistry.instance().hist_counts("lock_hold_us_H__l")
    assert counts is not None and sum(counts) == 1


def test_condition_wait_rebalances_held_stack(watch_on):
    """Condition(watched_lock).wait releases and reacquires through the
    wrapper; the per-thread held stack must stay balanced (a leak here
    would fabricate edges for every later acquisition)."""
    mutex = lockwatch.make_lock("Cond._m")
    cv = threading.Condition(mutex)
    entered = threading.Event()
    hit = []

    def waiter():
        with cv:
            entered.set()   # set under the mutex: the notifier's `with
            cv.wait(timeout=5)  # cv` below can't run until wait releases
            hit.append(lockwatch.current_held())

    t = threading.Thread(target=waiter)
    t.start()
    assert entered.wait(timeout=5)
    with cv:
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hit and hit[0] == ["Cond._m"]
    assert lockwatch.current_held() == []
    lockwatch.assert_consistent()


def test_channel_under_watch_round_trip(watch_on):
    """The hot ingest queue works unchanged under the watch (its two
    Conditions share the watched mutex — bound-lock identity)."""
    from paddlebox_tpu.utils.channel import Channel
    c = Channel(capacity=2)
    c.put("a")
    c.put("b")
    assert c.get() == "a" and c.get() == "b"
    c.close()
    lockwatch.assert_consistent()


def test_foreign_release_counted_not_crashed(watch_on):
    """A lock acquired on one thread and released on another (handed
    across, e.g. an executor future) must not corrupt the stacks."""
    stat_reset("lockwatch_foreign_release")
    lk = lockwatch.make_lock("F._l")
    lk.acquire()
    t = threading.Thread(target=lk.release)
    t.start()
    t.join()
    assert stat_get("lockwatch_foreign_release") == 1
    assert not lk.locked()
    lockwatch.assert_consistent()
    # the acquiring thread's stack keeps a phantom entry (nothing popped
    # it here) — reset() must clear EVERY thread's stack, or the phantom
    # fabricates edges for every later acquisition (review find, pinned)
    assert lockwatch.current_held() == ["F._l"]
    lockwatch.reset()
    assert lockwatch.current_held() == []


def test_edge_identity_matches_static_vocabulary(watch_on):
    """Dynamic edges speak the same Class._attr identity language as the
    static inventory (tools/boxlint/lock_graph.txt), so the two planes
    can be diffed by eye."""
    outer = lockwatch.make_lock("MeshComm._conn_lock")
    inner = lockwatch.make_lock("FramedClient._lock")
    with outer:
        with inner:
            pass
    assert ("MeshComm._conn_lock", "FramedClient._lock") in lockwatch.edges()


def test_three_lock_cycle_detected_by_assert(watch_on):
    """A->B, B->C, C->A: every PAIR is individually consistent, so the
    eager inversion check never fires — assert_consistent must walk the
    nesting graph (the dynamic analog of BX701's Tarjan pass; review
    find, pinned)."""
    la = lockwatch.make_lock("Cy._a")
    lb = lockwatch.make_lock("Cy._b")
    lc = lockwatch.make_lock("Cy._c")
    for outer, inner in ((la, lb), (lb, lc), (lc, la)):
        t = threading.Thread(target=lambda o=outer, i=inner: (
            o.acquire(), i.acquire(), i.release(), o.release()))
        t.start()
        t.join()
    assert lockwatch.inversions() == []          # no 2-cycle fired
    assert lockwatch.order_cycles()              # but the 3-cycle exists
    with pytest.raises(AssertionError, match="cycle"):
        lockwatch.assert_consistent()


def test_condition_on_watched_rlock(watch_on):
    """Condition(make_rlock(...)) must behave exactly as on a plain
    RLock — the wrapper forwards _is_owned/_release_save/
    _acquire_restore with bookkeeping, including RECURSIVE holds
    (review find: hiding the RLock protocol made wait() raise, and a
    recursively-held lock would release only one level and deadlock)."""
    r = lockwatch.make_rlock("CR._l")
    cv = threading.Condition(r)
    entered = threading.Event()
    hit = []

    def waiter():
        with r:             # recursion level 1
            with cv:        # level 2 — wait must release BOTH
                entered.set()
                cv.wait(timeout=5)
                hit.append(list(lockwatch.current_held()))

    t = threading.Thread(target=waiter)
    t.start()
    assert entered.wait(timeout=5)
    with cv:                # acquirable only if wait released level 1 too
        cv.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hit and hit[0] == ["CR._l", "CR._l"]
    assert lockwatch.current_held() == []
    lockwatch.assert_consistent()
