"""Round 14: flight recorder, crash sealing, trace stitching, health.

Tier-1 covers the postmortem plane in-process: black-box segment
rotation + header self-containment, SEALED manifests (direct, via the
excepthook chain, via a watchdog fire), the flag lifecycle through
make_step_reporter, log-line counting into the health stats, the
aggregator's exponential-backoff re-probe under a flaky transport, the
health monitor's documented scoring, trace ids crossing the REAL p2p
mesh, and trace_stitch producing cross-rank flow events. The
real-2-process chaos leg (SIGABRT/SIGKILL a rank mid-pass) runs the
same assertions out-of-process in the slow tier via
tools/chaos_seal_probe.py.
"""

import concurrent.futures
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddlebox_tpu.obs as obs
from paddlebox_tpu.config import flags
from paddlebox_tpu.obs import flight
from paddlebox_tpu.obs.aggregate import ClusterAggregator
from paddlebox_tpu.obs.health import HealthMonitor
from paddlebox_tpu.obs.flight import FlightRecorder
from paddlebox_tpu.obs.tracer import (SpanTracer, get_tracer,
                                      next_trace_id, step_trace_id,
                                      trace_ctx)
from paddlebox_tpu.obs.watchdog import StallWatchdog
from tools.trace_stitch import stitch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def no_active_flight():
    """Restore the module-active recorder around tests that set it (the
    flag snapshot fixture can't see this module global)."""
    prev = flight.set_active(None)
    yield
    fr = flight.set_active(prev)
    if fr is not None and fr is not prev:
        fr.close()


def _read_jsonl(path):
    with open(path, encoding="utf-8") as fh:
        return [json.loads(ln) for ln in fh]


# ------------------------------------------------------------- black box

def test_flight_header_and_record_types(tmp_path):
    fr = FlightRecorder(str(tmp_path), rank=3)
    fr.record("custom", x=1)
    fr.on_log("WARNING", "w line")
    fr.on_beat("step")
    fr.close()
    recs = _read_jsonl(fr.segments()[0])
    assert recs[0]["type"] == "header"
    hdr = recs[0]
    assert hdr["rank"] == 3 and hdr["pid"] == os.getpid()
    assert "obs_flight_dir" in hdr["flags"]        # full flag snapshot
    assert isinstance(hdr["git_sha"], str)
    types = [r["type"] for r in recs[1:]]
    assert types == ["custom", "log", "beat"]


def test_flight_segment_rotation_bounded(tmp_path):
    fr = FlightRecorder(str(tmp_path), rank=0, segment_bytes=1500,
                        max_segments=3)
    for i in range(200):
        fr.record("noise", i=i, pad="x" * 40)
    fr.close()
    segs = sorted(p for p in os.listdir(tmp_path)
                  if p.startswith("flight_r0_"))
    assert 1 <= len(segs) <= 3                     # bounded on disk
    for s in segs:
        recs = _read_jsonl(os.path.join(str(tmp_path), s))
        # every segment is self-contained: header at its top
        assert recs[0]["type"] == "header"


def test_flight_beats_sampled(tmp_path):
    fr = FlightRecorder(str(tmp_path), rank=0, beat_secs=60.0)
    for _ in range(50):
        fr.on_beat("step")
    fr.close()
    beats = [r for r in _read_jsonl(fr.segments()[0])
             if r["type"] == "beat"]
    assert len(beats) == 1          # 50 beats inside one sample window


def test_seal_manifest_and_numbered_siblings(tmp_path):
    fr = FlightRecorder(str(tmp_path), rank=1)
    tr = get_tracer()
    with trace_ctx(step_trace_id(1, 5)):
        with tr.span("doomed_stage"):
            pass
    fr.on_report({"type": "step_report", "rank": 1, "step": 5})
    fr.on_log("ERROR", "it broke")
    p1 = fr.seal("unit:first")
    p2 = fr.seal("unit:second")
    assert p1.endswith("SEALED_r1.json") and p2.endswith("SEALED_r1.2.json")
    m = json.load(open(p1))
    assert m["reason"] == "unit:first" and m["rank"] == 1
    assert any("doomed_stage" == s[0] for s in m["spans"])
    assert any("0x" in str(s[5]) for s in m["spans"]
               if s[0] == "doomed_stage")          # trace id preserved
    assert m["threads"]                             # every thread's stack
    assert m["last_reports"][-1]["step"] == 5
    assert m["log_tail"][-1]["line"] == "it broke"
    assert m["segments"]
    fr.close()


def test_excepthook_chain_seals(lock_order_watch, tmp_path, no_active_flight):
    fr = FlightRecorder(str(tmp_path), rank=0)
    flight.set_active(fr)
    called = []
    prev = flight._PREV_EXCEPTHOOK
    flight._PREV_EXCEPTHOOK = lambda *a: called.append(a)
    try:
        try:
            raise ValueError("boom")
        except ValueError as e:
            flight._excepthook(ValueError, e, e.__traceback__)
    finally:
        flight._PREV_EXCEPTHOOK = prev
    assert called, "previous excepthook must stay chained"
    m = json.load(open(os.path.join(str(tmp_path), "SEALED_r0.json")))
    assert m["reason"] == "excepthook:ValueError"
    assert "boom" in m["exception"]
    fr.close()


def test_watchdog_fire_seals(lock_order_watch, tmp_path, no_active_flight):
    fr = FlightRecorder(str(tmp_path), rank=0)
    flight.set_active(fr)
    wd = StallWatchdog(threshold_s=0.05, tracer=get_tracer(),
                       stream=open(os.devnull, "w"))
    wd.fire("wedged_stage", 9.9)
    m = json.load(open(os.path.join(str(tmp_path), "SEALED_r0.json")))
    assert m["reason"] == "watchdog_stall:wedged_stage"
    assert "wedged_stage" in m["extra_text"]      # the rendered dump
    fr.close()


def test_flight_flag_lifecycle(tmp_path, no_active_flight):
    flags.set_flag("obs_flight_dir", str(tmp_path))
    rep = obs.make_step_reporter(rank=0, every=1, sink=obs.ListSink())
    assert flight.active() is not None
    with obs.span("lifecycle_stage"):
        pass
    rep.note_examples(10)
    rep.maybe_report(1)
    recs = []
    for s in flight.active().segments():
        recs.extend(_read_jsonl(s))
    types = {r["type"] for r in recs}
    assert {"header", "report"} <= types
    spans_rec = [r for r in recs if r["type"] == "spans"]
    assert spans_rec and any(
        s[0] == "lifecycle_stage" for r in spans_rec for s in r["spans"])
    # empty flag clears the active recorder (test self-healing contract)
    flags.set_flag("obs_flight_dir", "")
    flight.ensure_from_flags()
    assert flight.active() is None
    rep.close()


def test_log_lines_counted_and_recorded(tmp_path, no_active_flight):
    from paddlebox_tpu.obs import log as obs_log
    from paddlebox_tpu.utils.stats import stat_get
    fr = FlightRecorder(str(tmp_path), rank=0)
    flight.set_active(fr)
    w0 = stat_get("log_warning_lines")
    e0 = stat_get("log_error_lines")
    obs_log.warning("w one")
    obs_log.error("e one")
    obs_log.info("info is not counted")
    assert stat_get("log_warning_lines") == w0 + 1
    assert stat_get("log_error_lines") == e0 + 1
    logs = [r for r in _read_jsonl(fr.segments()[0])
            if r["type"] == "log"]
    assert [r["level"] for r in logs] == ["WARNING", "ERROR"]
    fr.close()


# ------------------------------------------------- aggregator backoff

class _FlakyTransport:
    """Fails the first `fail_n` publishes, then heals."""

    def __init__(self, fail_n):
        self.fail_n = fail_n
        self.calls = 0
        self.delivered = []

    def publish(self, payload):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise ConnectionError("NIC blip")
        self.delivered.append(payload)

    def drain(self):
        return []


def test_aggregator_backoff_reprobes_after_transport_heals():
    """The round-14 policy: after 3 consecutive failures skip 1 publish,
    then re-probe; another failure skips 2; a success resets. The
    backoff is denominated in skipped PUBLISHES (= stale windows at
    rank 0), so a transient blip costs a bounded number of windows."""
    tr = _FlakyTransport(fail_n=4)
    agg = ClusterAggregator(tr, rank=1, world=2)
    rep = {"type": "step_report", "rank": 1, "step": 1}
    for _ in range(3):              # failures 1..3 -> backoff starts
        agg.publish(rep)
    assert tr.calls == 3 and agg._skip_remaining == 1
    agg.publish(rep)                # skipped: no transport cost
    assert tr.calls == 3
    agg.publish(rep)                # re-probe: fails -> skips DOUBLE
    assert tr.calls == 4 and agg._skip_remaining == 2
    agg.publish(rep)
    agg.publish(rep)                # two skips burn down
    assert tr.calls == 4
    agg.publish(rep)                # re-probe: transport healed
    assert tr.delivered and agg._failures == 0
    agg.publish(rep)                # straight through, no residue
    assert len(tr.delivered) == 2


def test_aggregator_backoff_skip_cap_and_time_cap():
    tr = _FlakyTransport(fail_n=10**9)
    clock = [0.0]
    agg = ClusterAggregator(tr, rank=1, world=2, clock=lambda: clock[0])
    rep = {"type": "step_report", "rank": 1, "step": 1}
    for _ in range(200):
        agg.publish(rep)
    assert agg._skip_remaining <= ClusterAggregator.BACKOFF_SKIP_CAP
    # slow-cadence jobs: the WALL-CLOCK ceiling re-probes even with
    # skips remaining (a blip must not silence telemetry for minutes)
    calls = tr.calls
    agg._skip_remaining = ClusterAggregator.BACKOFF_SKIP_CAP
    clock[0] = agg._backoff_until + 0.01
    agg.publish(rep)
    assert tr.calls == calls + 1


# ----------------------------------------------------------- health plane

def _merged(stale_ranks=(), metrics=None, step=7):
    return {"type": "cluster_report", "step": step,
            "stale_ranks": list(stale_ranks),
            "metrics": metrics or {}}


def test_health_scoring_contract():
    hm = HealthMonitor(world=3)
    # window 1: rank 2 stale once -> degraded but healthy
    h = hm.update(_merged(stale_ranks=[2]))
    assert h["ranks"]["2"]["score"] == pytest.approx(0.6)
    assert h["ranks"]["2"]["healthy"] and h["unhealthy_ranks"] == []
    # window 2: still stale -> dead (score 0) within 2 windows
    h = hm.update(_merged(stale_ranks=[2]))
    assert h["ranks"]["2"]["score"] == 0.0
    assert h["unhealthy_ranks"] == [2]
    # recovery resets the streak
    h = hm.update(_merged())
    assert h["ranks"]["2"]["healthy"]


def test_health_beat_stall_scores_unhealthy():
    """A rank that still REPORTS but stopped beating (wedged step loop
    behind a live reporting path) must read unhealthy — freshness alone
    cannot see this, which is why beat_age_s is gauged at all."""
    hm = HealthMonitor(world=2, beat_age_warn=30.0)
    h = hm.update(_merged(metrics={
        "gauges.beat_age_s": {"per_rank": {"0": 0.4, "1": 120.0}}}))
    assert h["ranks"]["0"]["healthy"]
    r1 = h["ranks"]["1"]
    assert r1["flags"] == ["beat_stalled"] and not r1["healthy"]
    assert r1["beat_age_s"] == 120.0
    assert h["unhealthy_ranks"] == [1]


def test_flight_rotation_failure_degrades_closed(tmp_path):
    """Mid-run rotation hitting a dead dir must close the recorder, not
    raise into the training step (the record() 'never raises' contract
    covers the rotation path too)."""
    import shutil
    fr = FlightRecorder(str(tmp_path / "d"), rank=0, segment_bytes=400)
    fr.record("ok", pad="x" * 16)
    shutil.rmtree(str(tmp_path / "d"))      # tmpdir-cleanup scenario
    for i in range(50):                     # crosses the rotation bound
        fr.record("noise", i=i, pad="y" * 64)
    assert fr._closed                       # degraded, never raised
    fr.record("after", x=1)                 # still a no-op, still safe
    fr.close()


def test_health_error_rate_depth_and_slo_flags():
    hm = HealthMonitor(world=2)
    h = hm.update(_merged(metrics={
        "stats.log_error_lines": {"per_rank": {"1": 4.0}},
        "gauges.chan_route_depth": {"per_rank": {"1": 999.0}},
        "gauges.serving_slo_burn": {"per_rank": {"1": 1.8}},
    }))
    r1 = h["ranks"]["1"]
    assert set(r1["flags"]) == {"error_lines", "queue_depth", "slo_burn"}
    assert r1["score"] == pytest.approx(0.2) and not r1["healthy"]
    assert h["ranks"]["0"]["score"] == 1.0


def test_cluster_health_published_through_sink():
    class _Quiet:
        def publish(self, payload):
            raise AssertionError("rank 0 never publishes")

        def drain(self):
            return []

    sink = obs.ListSink()
    agg = ClusterAggregator(_Quiet(), rank=0, world=2, sink=sink,
                            health=HealthMonitor(2))
    agg.publish({"type": "step_report", "rank": 0, "step": 3,
                 "examples_per_sec": 1.0})
    types = [r["type"] for r in sink.records]
    assert types == ["cluster_report", "cluster_health"]
    json.loads(json.dumps(sink.records[-1]))       # sink-serializable


def test_in_process_chaos_twin(tmp_path, no_active_flight):
    """The tier-1 twin of the chaos leg: rank 1 publishes once, seals
    (its 'death'), and goes silent; rank 0's health plane flags it
    unhealthy within 2 windows; the SEALED bundle parses."""
    box = []

    class _To0:
        def publish(self, payload):
            box.append(payload)

        def drain(self):
            return []

    class _At0:
        def publish(self, payload):
            raise AssertionError("rank 0 never publishes")

        def drain(self):
            out, box[:] = list(box), []
            return out

    fr1 = FlightRecorder(str(tmp_path), rank=1)
    flight.set_active(fr1)
    sink = obs.ListSink()
    agg1 = ClusterAggregator(_To0(), rank=1, world=2)
    agg0 = ClusterAggregator(_At0(), rank=0, world=2, sink=sink,
                             health=HealthMonitor(2))

    def r(rank, step):
        return {"type": "step_report", "rank": rank, "step": step}

    agg1.publish(r(1, 1))                 # rank 1 alive, window 1
    agg0.publish(r(0, 1))
    assert agg0.last_cluster_health["unhealthy_ranks"] == []
    # rank 1 dies: seals, never publishes again
    sealed = flight.seal_active("signal:SIGABRT")
    windows = 0
    for step in (2, 3):
        agg0.publish(r(0, step))
        windows += 1
        if agg0.last_cluster_health["unhealthy_ranks"]:
            break
    assert windows <= 2
    assert agg0.last_cluster_health["unhealthy_ranks"] == [1]
    assert agg0.last_cluster_health["ranks"]["1"]["stale_windows"] >= 2
    m = json.load(open(sealed))
    assert m["reason"] == "signal:SIGABRT" and m["threads"]
    fr1.close()


# --------------------------------------------------- trace ids + stitch

@pytest.fixture
def mesh_pair():
    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    meshes = [MeshComm(r, 2) for r in range(2)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    for m in meshes:
        m.connect(eps)
    yield meshes
    for m in meshes:
        m.close()


def test_mesh_exchange_carries_trace_id(mesh_pair):
    """The wire contract: the receiver-side span records the SENDER's
    step trace id (both virtual ranks share this process's tracer, so
    the pairing is directly observable)."""
    m0, m1 = mesh_pair
    tr = get_tracer()
    tr.clear()
    t0_id = step_trace_id(0, 1)
    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        f = pool.submit(lambda: m1.exchange(
            {0: np.arange(4, dtype=np.int32),
             1: np.arange(4, dtype=np.int32)}))
        with trace_ctx(t0_id):
            m0.exchange({0: np.arange(4, dtype=np.int32),
                         1: np.arange(4, dtype=np.int32)})
        f.result()
    spans = tr.all_spans()
    sends = [s for s in spans if s[0] == "mesh_exchange"]
    recvs = [s for s in spans if s[0] == "mesh_recv_part"]
    assert any(s[5] == t0_id for s in sends)       # rank 0 inherited ctx
    assert any(s[5] == t0_id for s in recvs)       # receiver tagged it
    # rank 1 had no ctx: its exchange minted a rank+seq id in the
    # bit-62 namespace — the stager's seq counts ~1:1 with the step
    # counter, so an un-namespaced mint would collide with step ids
    assert any(s[5] == (1 << 62) | step_trace_id(1, 1) for s in sends)


def test_mesh_recv_garbage_trace_never_fails_exchange(mesh_pair):
    """A skewed peer shipping a non-int trace is a telemetry value —
    the lockstep part handler must accept the frame regardless."""
    m0, _ = mesh_pair
    assert m0._on_request({"op": "part", "seq": 999, "from": 1,
                           "data": b"\x00\x00\x00\x00",
                           "dtype": "int32", "shape": (1,),
                           "trace": "0xdeadbeef"}) is True
    with m0._cv:                    # the part parked despite the trace
        assert (999, 1) in m0._inbox


def test_trace_stitch_cross_rank_flow(tmp_path):
    """Acceptance pin: stitched output is loadable chrome JSON with >=1
    flow event whose source and destination spans live on DIFFERENT
    ranks."""
    tr0, tr1 = SpanTracer(32), SpanTracer(32)
    t = step_trace_id(0, 9)
    now = time.perf_counter()
    tr0.record_span("mesh_exchange", now, now + 0.002, trace=t)
    tr1.record_span("mesh_recv_part", now + 0.001, now + 0.0015, trace=t)
    tr1.record_span("untraced", now, now + 0.001)
    docs = [tr0.export_chrome(pid=0), tr1.export_chrome(pid=1)]
    stitched, summary = stitch(docs)
    assert summary["cross_rank_flows"] >= 1
    text = json.dumps(stitched)
    loaded = json.loads(text)
    flows = [e for e in loaded["traceEvents"] if e.get("ph") in "stf"]
    assert {e["ph"] for e in flows} >= {"s", "f"}
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], set()).add(e["pid"])
    assert any(len(pids) > 1 for pids in by_id.values())   # cross-rank
    # X events keep the Perfetto-required fields after stitching
    for e in loaded["traceEvents"]:
        if e.get("ph") == "X":
            for field in ("name", "ts", "dur", "pid", "tid"):
                assert field in e, field


def test_trace_stitch_aligns_clock_origins():
    tr0, tr1 = SpanTracer(8), SpanTracer(8)
    now = time.perf_counter()
    tr0.record_span("a", now, now + 0.001)
    tr1.record_span("b", now, now + 0.001)
    d0, d1 = tr0.export_chrome(pid=0), tr1.export_chrome(pid=1)
    # pretend rank 1 booted 2s later: its self-relative ts would be 2s
    # behind without the anchor shift
    d1["metadata"]["clock_origin_unix_s"] += 2.0
    for ev in d1["traceEvents"]:
        if "ts" in ev:
            ev["ts"] -= 2e6
    stitched, _ = stitch([d0, d1])
    xs = {e["pid"]: e["ts"] for e in stitched["traceEvents"]
          if e.get("ph") == "X"}
    assert abs(xs[0] - xs[1]) < 1e4    # realigned within 10ms


def test_trace_stitch_unanchored_doc_stays_unshifted():
    """A pre-round-14 export without clock_origin_unix_s must not drag
    the merged timeline to unix epoch 0 (a ~54-year shift for every
    anchored rank) — it stays unshifted and is named in the summary."""
    tr0 = SpanTracer(8)
    now = time.perf_counter()
    tr0.record_span("a", now, now + 0.001)
    d0 = tr0.export_chrome(pid=0)
    legacy = {"traceEvents": [{"ph": "X", "name": "old", "pid": 9,
                               "tid": 1, "ts": 5.0, "dur": 1.0}]}
    stitched, summary = stitch([d0, legacy])
    assert summary["unanchored_ranks"] == [1]
    xs = {e["pid"]: e["ts"] for e in stitched["traceEvents"]
          if e.get("ph") == "X"}
    assert xs[1] == 5.0                      # unshifted
    assert xs[0] < 1e13                      # no 54-year offset either


def test_flight_unwritable_dir_degrades_not_raises(tmp_path,
                                                   no_active_flight):
    blocker = tmp_path / "a_file"
    blocker.write_text("not a dir")
    flags.set_flag("obs_flight_dir", str(blocker / "sub"))
    assert flight.ensure_from_flags(rank=0) is None   # warned, not raised
    assert flight.active() is None


def test_next_trace_id_unique_and_disjoint():
    ids = {next_trace_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(i >> 63 for i in ids)               # request-id space
    assert step_trace_id(3, 12) >> 63 == 0         # step-id space


# ------------------------------------------------------------ bench trend

def test_bench_trend_deltas_and_regression_flag(tmp_path):
    from tools.bench_trend import load_rounds, trend

    def mk(n, value, platform="cpu", ms=10.0):
        with open(tmp_path / ("BENCH_r%02d.json" % n), "w") as fh:
            json.dump({"n": n, "parsed": {
                "value": value, "platform": platform,
                "steady_ms_per_step": ms}}, fh)

    mk(1, 100.0)
    mk(2, 85.0, ms=13.0)            # -15% rate, +30% ms: both regress
    mk(3, 90.0, platform="tpu")     # platform flip: never compared
    rounds = load_rounds(str(tmp_path))
    assert [r["round"] for r in rounds] == [1, 2, 3]
    out = trend(rounds, threshold=0.10)
    flagged = {(r["metric"], r["to_round"]) for r in out["regressions"]}
    assert flagged == {("value", 2), ("steady_ms_per_step", 2)}
    cross = [r for r in out["rows"] if r["to_round"] == 3]
    assert all(r["delta_pct"] is None for r in cross)


# ------------------------------------------------------------ chaos leg

@pytest.mark.slow
def test_chaos_seal_real_cluster():
    """Kill a rank mid-pass in a REAL 2-process cluster (SIGABRT and
    SIGKILL legs): parseable SEALED bundle / flight segments for the
    dead rank, rank 0 health flags it within 2 cadences, and the
    per-rank traces stitch with cross-rank flows."""
    r = subprocess.run(
        [sys.executable, "-u",
         os.path.join(REPO, "tools", "chaos_seal_probe.py")],
        capture_output=True, text=True, timeout=280,
        cwd=REPO)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    last = json.loads(r.stdout.strip().splitlines()[-1])
    assert last["all_ok"] is True
