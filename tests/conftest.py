"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
paths (mesh/pjit/shard_map/all_to_all) are exercised without TPU hardware —
the multi-host-sim test tier called for by SURVEY.md §4."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
