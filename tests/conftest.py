"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
paths (mesh/pjit/shard_map/all_to_all) are exercised without TPU hardware —
the multi-host-sim test tier called for by SURVEY.md §4.

Gotcha: the ambient axon sitecustomize calls
jax.config.update("jax_platforms", "axon,cpu") at interpreter start, which
overrides the JAX_PLATFORMS env var — so we must update the config again
here, before any backend is initialized."""

import json
import os
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flag_isolation():
    """Snapshot/restore the process-flag registry around EVERY test:
    round-4's full-suite-order flake (test_hierarchical_mesh_matches_flat
    passing alone, failing in suite order) was cross-test contamination of
    exactly this global state — a test that sets a flag and raises (or
    just forgets to reset) silently changes every later test's numerics.
    Restoring unconditionally makes test order irrelevant to flags."""
    from paddlebox_tpu.config import flags as _f

    snapshot = _f.all_flags()
    yield
    for name, value in snapshot.items():
        if _f.get_flag(name) != value:
            _f.set_flag(name, value)
    # round 18: the quality/drift planes keep module-global state (the
    # live-ops exporter reads them without a binding dance); a drift
    # reference window leaking across tests would score phantom drift
    # against the previous test's slot schema
    from paddlebox_tpu.metrics import drift as _drift
    from paddlebox_tpu.metrics import quality as _quality
    _quality.set_active(None)
    _drift.set_active(None)
    # with obs_http_port restored (default 0) this closes any exporter
    # a test left listening, releasing its port for later tests
    from paddlebox_tpu.obs import exporter as _exporter
    _exporter.ensure_from_flags()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the budgeted tier-1 run (-m 'not slow'); "
        "runs in the slow-inclusive suite and on TPU windows")
    config._pbtpu_t0 = time.monotonic()


# ---------------------------------------------------------------------------
# Tier-1 budget visibility (round 14): the suite runs against a hard
# 870s timeout with no per-test attribution — this hook writes one
# durations JSONL per run (who pays), prints the 15 slowest, and WARNS
# (never fails) when the run lands past 90% of the budget.

_DURATIONS = {}


def pytest_runtest_logreport(report):
    if report.when in ("setup", "call", "teardown"):
        _DURATIONS[report.nodeid] = (
            _DURATIONS.get(report.nodeid, 0.0) + report.duration)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _DURATIONS:
        return
    path = os.environ.get("PBTPU_TEST_DURATIONS",
                          "/tmp/pbtpu_test_durations.jsonl")
    wall = time.monotonic() - getattr(config, "_pbtpu_t0",
                                      time.monotonic())
    ranked = sorted(_DURATIONS.items(), key=lambda kv: -kv[1])
    try:
        with open(path, "w", encoding="utf-8") as fh:
            for nodeid, dur in ranked:
                fh.write(json.dumps({"nodeid": nodeid,
                                     "duration_s": round(dur, 3)}) + "\n")
            fh.write(json.dumps({"summary": True, "tests": len(ranked),
                                 "sum_s": round(sum(_DURATIONS.values()),
                                                1),
                                 "wall_s": round(wall, 1)}) + "\n")
    except OSError:
        path = "<unwritable>"
    tw = terminalreporter
    tw.write_line("")
    tw.write_line("slowest 15 tests (durations jsonl: %s)" % path)
    for nodeid, dur in ranked[:15]:
        tw.write_line("  %8.2fs  %s" % (dur, nodeid))
    budget = float(os.environ.get("PBTPU_TIER1_BUDGET_SECS", "870"))
    # wall is the honest projection (it includes collection + import);
    # the per-test sum attributes it
    if budget > 0 and wall > 0.9 * budget:
        tw.write_line(
            "WARNING: suite wall %.0fs exceeds 90%% of the %.0fs tier-1 "
            "budget (sum of test durations %.0fs) — new suites must "
            "earn their seconds or go slow" % (
                wall, budget, sum(_DURATIONS.values())),
            yellow=True)


@pytest.fixture
def lock_order_watch():
    """Run a concurrency test under the lockwatch runtime validator
    (utils/lockwatch.py, flag debug_lock_order): locks constructed while
    this fixture is live record per-thread acquisition order, and the
    teardown ASSERTS no AB/BA inversion was observed — the dynamic twin
    of boxlint's static BX7xx pass. Order matters: list this fixture
    BEFORE any fixture that constructs the objects under test, so their
    locks are built through the watch."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.utils import lockwatch

    flags.set_flag("debug_lock_order", True)
    lockwatch.reset()
    yield lockwatch
    try:
        lockwatch.assert_consistent()
    finally:
        lockwatch.reset()
        flags.set_flag("debug_lock_order", False)
