"""Test env: force CPU backend with 8 virtual devices so multi-chip sharding
paths (mesh/pjit/shard_map/all_to_all) are exercised without TPU hardware —
the multi-host-sim test tier called for by SURVEY.md §4.

Gotcha: the ambient axon sitecustomize calls
jax.config.update("jax_platforms", "axon,cpu") at interpreter start, which
overrides the JAX_PLATFORMS env var — so we must update the config again
here, before any backend is initialized."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flag_isolation():
    """Snapshot/restore the process-flag registry around EVERY test:
    round-4's full-suite-order flake (test_hierarchical_mesh_matches_flat
    passing alone, failing in suite order) was cross-test contamination of
    exactly this global state — a test that sets a flag and raises (or
    just forgets to reset) silently changes every later test's numerics.
    Restoring unconditionally makes test order irrelevant to flags."""
    from paddlebox_tpu.config import flags as _f

    snapshot = _f.all_flags()
    yield
    for name, value in snapshot.items():
        if _f.get_flag(name) != value:
            _f.set_flag(name, value)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the budgeted tier-1 run (-m 'not slow'); "
        "runs in the slow-inclusive suite and on TPU windows")
