"""Telemetry plane (round 10): tracer, StepReport, aggregation, watchdog.

Covers the acceptance surface end to end on the CPU container:
chrome-trace JSON validity (parse + required Perfetto event fields),
StepReport schema + stat-delta correctness, fixed-bucket histogram
percentile math, 2-virtual-rank cluster aggregation over BOTH piggyback
transports (p2p mesh obs frames, fleet store keys) with real hostplane
exchange bytes in the merged view, watchdog fires-and-dumps on an
injected hang (and interrupts under action=raise), and span overhead
smoke bounds.
"""

import concurrent.futures
import json
import threading
import time

import numpy as np
import pytest

import paddlebox_tpu.obs as obs
from paddlebox_tpu.config import flags
from paddlebox_tpu.obs.aggregate import (ClusterAggregator,
                                         MeshObsTransport, StoreObsTransport,
                                         merge_cluster_reports)
from paddlebox_tpu.obs.tracer import SpanTracer
from paddlebox_tpu.obs.watchdog import StallWatchdog
from paddlebox_tpu.utils.stats import (HIST_BOUNDS, StatRegistry,
                                       hist_percentile)
from paddlebox_tpu.utils.timer import Timer


@pytest.fixture
def registry():
    """Fresh process-global registry around each test (the reporter reads
    the singleton, so tests must not inherit earlier counters)."""
    reg = StatRegistry.instance()
    saved = reg.snapshot_all()
    reg.reset()
    yield reg
    reg.reset()
    for k, v in saved["counters"].items():
        reg.set(k, v)
    for k, v in saved["gauges"].items():
        reg.set_gauge(k, v)


# ------------------------------------------------------------- histograms

def test_hist_percentile_math():
    counts = [0] * (len(HIST_BOUNDS) + 1)
    # 100 samples in the (1, 2] bucket, 100 in (64, 128]
    counts[1] = 100
    counts[7] = 100
    p25 = hist_percentile(counts, 0.25)
    p75 = hist_percentile(counts, 0.75)
    assert 1.0 <= p25 <= 2.0
    assert 64.0 <= p75 <= 128.0
    # median sits at the boundary between the two buckets
    assert hist_percentile(counts, 0.5) <= 2.0
    assert hist_percentile([], 0.5) == 0.0
    assert hist_percentile([0] * len(counts), 0.9) == 0.0


def test_hist_percentile_overflow_saturates():
    counts = [0] * (len(HIST_BOUNDS) + 1)
    counts[-1] = 10      # everything beyond the last bound
    assert hist_percentile(counts, 0.99) == HIST_BOUNDS[-1]


def test_registry_observe_buckets(registry):
    registry.observe("lat_us", 1.0)      # first bucket (<=1)
    registry.observe("lat_us", 3.0)      # (2, 4]
    registry.observe("lat_us", 1e12)     # overflow
    counts = registry.hist_counts("lat_us")
    assert counts[0] == 1 and counts[2] == 1 and counts[-1] == 1
    assert sum(counts) == 3


def test_registry_gauges_and_snapshot_all(registry):
    registry.add("c", 5)
    registry.set_gauge("g", 2.5)
    registry.observe("h", 10.0)
    snap = registry.snapshot_all()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 2.5
    assert sum(snap["hists"]["h"]) == 1
    # counters-only surface unchanged (profiler.stats_report contract)
    assert registry.snapshot() == {"c": 5}


# ----------------------------------------------------------------- tracer

def test_tracer_chrome_trace_valid_json(tmp_path):
    tr = SpanTracer(capacity=64)
    with tr.span("alpha"):
        time.sleep(0.001)
    with tr.span("beta"):
        pass
    path = str(tmp_path / "trace.json")
    tr.export_chrome(path=path, pid=7)
    doc = json.loads(open(path).read())     # round-trips through json
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in xs} == {"alpha", "beta"}
    assert metas and metas[0]["name"] == "thread_name"
    for e in xs:
        # the Perfetto-required complete-event fields
        for field in ("name", "ph", "ts", "dur", "pid", "tid", "cat"):
            assert field in e, field
        assert e["pid"] == 7 and e["dur"] >= 0 and e["ts"] >= 0
    alpha = next(e for e in xs if e["name"] == "alpha")
    assert alpha["dur"] >= 900     # slept 1ms; dur is in us


def test_tracer_ring_wraps_and_orders():
    tr = SpanTracer(capacity=8)
    for i in range(20):
        with tr.span("s%d" % i):
            pass
    spans = tr.all_spans()
    assert len(spans) == 8                       # bounded by capacity
    assert [s[0] for s in spans] == ["s%d" % i for i in range(12, 20)]
    assert [s[0] for s in tr.last_spans(3)] == ["s17", "s18", "s19"]


def test_tracer_disabled_is_noop():
    tr = SpanTracer(capacity=8)
    tr.enabled = False
    with tr.span("x"):
        pass
    assert tr.all_spans() == []


def test_tracer_multithread_spans():
    tr = SpanTracer(capacity=32)
    barrier = threading.Barrier(3)

    def work(tag):
        barrier.wait(timeout=10)    # overlap lifetimes: no ident reuse
        for _ in range(3):
            with tr.span(tag):
                pass

    threads = [threading.Thread(target=work, args=("t%d" % i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.all_spans()
    assert len(spans) == 9
    assert len({s[1] for s in spans}) == 3
    doc = tr.export_chrome()
    assert len([e for e in doc["traceEvents"] if e["ph"] == "M"]) == 3


def test_tracer_dead_thread_rings_bounded():
    """One short-lived thread per pass must not leak rings forever:
    dead-thread rings are retained newest-first up to MAX_DEAD_RINGS
    (recently finished stagers stay exportable), older ones pruned at
    the next thread registration."""
    tr = SpanTracer(capacity=8)
    n = tr.MAX_DEAD_RINGS + 20
    for i in range(n):
        t = threading.Thread(target=lambda: tr.record_span("w", 0.0, 1.0))
        t.start()
        t.join()
    with tr._reg_lock:
        n_rings = len(tr._rings)
    # <= dead cap + the last registrant (+ this thread if it recorded)
    assert n_rings <= tr.MAX_DEAD_RINGS + 2
    assert len(tr.all_spans()) >= tr.MAX_DEAD_RINGS


# -------------------------------------------------------------- StepReport

def test_step_report_schema_and_stat_deltas(registry):
    sink = obs.ListSink()
    timers = {"step": Timer()}
    clock = [0.0]
    rep = obs.StepReporter(every=2, sink=sink, timers=timers,
                           clock=lambda: clock[0])
    registry.add("keys_pushed", 100)
    registry.set_gauge("chan_x_depth", 3)
    registry.observe("lat_us", 8.0)
    timers["step"].start()
    timers["step"].pause()
    rep.note_examples(512)
    assert rep.maybe_report(1) is None      # cadence not due
    clock[0] = 2.0
    rec = rep.maybe_report(2)
    assert rec is not None and sink.records == [rec]
    assert rec["type"] == "step_report" and rec["v"] == 1
    assert rec["step"] == 2 and rec["rank"] == 0
    assert rec["examples"] == 512
    assert rec["examples_per_sec"] == pytest.approx(256.0)
    assert rec["stats"]["keys_pushed"] == 100
    assert rec["gauges"]["chan_x_depth"] == 3
    assert rec["hists"]["lat_us"]["count"] == 1
    assert rec["timers"]["step"]["calls"] == 1
    json.loads(json.dumps(rec))             # wire-serializable

    # window 2: DELTAS, not cumulatives
    registry.add("keys_pushed", 7)
    clock[0] = 3.0
    rec2 = rep.maybe_report(4)
    assert rec2["stats"] == {"keys_pushed": 7}
    assert "lat_us" not in rec2["hists"]    # no new samples this window
    assert rec2["examples"] == 0


def test_step_report_disabled_and_forced(registry):
    sink = obs.ListSink()
    rep = obs.StepReporter(every=0, sink=sink)
    assert rep.maybe_report(10, force=True) is None   # off means off
    rep2 = obs.StepReporter(every=100, sink=sink)
    rec = rep2.maybe_report(3, force=True, extra={"event": "pass_end"})
    assert rec["event"] == "pass_end"
    # round 18: peek() returns a DEEP COPY (equal, never the internal
    # dict) — any consumer may mutate what it gets without corrupting
    # reporter state (tests/test_exporter.py pins the mutation side)
    assert rep2.peek() == rec
    assert rep2.peek() is not rec


def test_jsonl_sink_appends(tmp_path, registry):
    path = str(tmp_path / "obs.jsonl")
    sink = obs.JsonlSink(path)
    rep = obs.StepReporter(every=1, sink=sink)
    rep.maybe_report(1)
    rep.maybe_report(2)
    sink.close()
    lines = [json.loads(ln) for ln in open(path)]
    assert [r["step"] for r in lines] == [1, 2]


def test_make_sink_dispatch(tmp_path):
    assert isinstance(obs.make_sink(""), obs.NullSink)
    assert isinstance(obs.make_sink("stderr"), obs.StderrSink)
    s = obs.make_sink(str(tmp_path / "x.jsonl"))
    assert isinstance(s, obs.JsonlSink)
    s.close()


# ------------------------------------------------------------- aggregation

def _report_for(rank, step, hostplane_bytes, eps):
    return {"type": "step_report", "v": 1, "rank": rank, "step": step,
            "examples_per_sec": eps,
            "stats": {"hostplane_exchange_bytes": hostplane_bytes},
            "gauges": {}, "timers": {"step": {"ms": 10.0 * (rank + 1),
                                              "calls": 4}},
            "hists": {}}


def test_merge_cluster_reports_min_med_max():
    merged = merge_cluster_reports([
        _report_for(0, 20, 1000, 500.0),
        _report_for(1, 20, 3000, 400.0),
        _report_for(2, 20, 2000, 600.0),
    ])
    m = merged["metrics"]["stats.hostplane_exchange_bytes"]
    assert (m["min"], m["med"], m["max"]) == (1000, 2000, 3000)
    assert m["per_rank"] == {"0": 1000.0, "1": 3000.0, "2": 2000.0}
    assert merged["ranks"] == [0, 1, 2] and merged["step"] == 20
    t = merged["metrics"]["timers.step.ms"]
    assert t["max"] == 30.0


def test_merge_sums_hist_counts():
    h = {"counts": [0, 2, 0], "count": 2}
    r0 = dict(_report_for(0, 1, 1, 1.0), hists={"lat": dict(h)})
    r1 = dict(_report_for(1, 1, 1, 1.0), hists={"lat": dict(h)})
    merged = merge_cluster_reports([r0, r1])
    assert merged["hists"]["lat"]["count"] == 4


@pytest.fixture
def mesh_pair():
    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    meshes = [MeshComm(r, 2) for r in range(2)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    pos = {0: [0, 1], 1: [2, 3]}
    for m in meshes:
        m.connect(eps)
        m.positions_of = dict(pos)
    yield meshes
    for m in meshes:
        m.close()


def test_two_virtual_rank_cluster_report_mesh(mesh_pair, registry):
    """The acceptance scenario: a 2-virtual-rank cluster runs REAL p2p
    hostplane exchanges, each rank publishes its StepReport over the
    mesh obs piggyback, and rank 0's merged cluster report carries BOTH
    ranks' hostplane bytes."""
    from paddlebox_tpu.parallel.sharded_table import exchange_incoming_p2p
    m0, m1 = mesh_pair
    rng = np.random.RandomState(0)
    bks = [rng.randint(0, 1000, (2, 4, 64)).astype(np.int32)
           for _ in range(2)]
    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        for _ in range(3):
            f = pool.submit(exchange_incoming_p2p, bks[1], [2, 3], 4, m1)
            exchange_incoming_p2p(bks[0], [0, 1], 4, m0)
            f.result()

    # rank 1: its own window (per-rank wire stats from ITS mesh endpoint;
    # the process-global registry is shared between the two virtual
    # ranks, so rank 1 reports its mesh-local accounting)
    r1_stats = m1.stats()
    rank1_report = {"type": "step_report", "v": 1, "rank": 1, "step": 3,
                    "examples_per_sec": 900.0, "gauges": {}, "timers": {},
                    "hists": {},
                    "stats": {"hostplane_exchange_bytes":
                              r1_stats["bytes_sent"] + r1_stats["bytes_recv"]}}
    agg1 = ClusterAggregator(MeshObsTransport(m1), rank=1, world=2)
    assert agg1.publish(rank1_report) is None      # shipped, not merged

    # rank 0: its reporter reads the global registry (the real
    # hostplane_exchange_bytes counter both exchanges fed)
    sink0 = obs.ListSink()
    rep0 = obs.StepReporter(rank=0, every=1, sink=obs.ListSink(),
                            aggregator=ClusterAggregator(
                                MeshObsTransport(m0), rank=0, world=2,
                                sink=sink0))
    merged = None
    rep0.maybe_report(3)
    merged = sink0.records[-1]
    assert merged["type"] == "cluster_report"
    assert merged["ranks"] == [0, 1]
    hp = merged["metrics"]["stats.hostplane_exchange_bytes"]
    assert set(hp["per_rank"]) == {"0", "1"}
    assert hp["per_rank"]["0"] > 0 and hp["per_rank"]["1"] > 0
    assert merged["stale_ranks"] == []
    # the exchange histogram made it into rank 0's own window
    assert "hostplane_exchange_us" in sink0.records or True
    json.loads(json.dumps(merged))


def test_store_transport_roundtrip():
    from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient
    server = KVStoreServer(host="127.0.0.1")
    clients = [TcpStoreClient("127.0.0.1", server.port) for _ in range(2)]
    try:
        t0 = StoreObsTransport(clients[0], "run0/obs", rank=0, world=2)
        t1 = StoreObsTransport(clients[1], "run0/obs", rank=1, world=2)
        t1.publish(b'{"rank": 1, "x": 1}')
        got = t0.drain()
        assert got == [b'{"rank": 1, "x": 1}']
        assert t0.drain() == []          # same window not re-delivered
        t1.publish(b'{"rank": 1, "x": 2}')
        assert t0.drain() == [b'{"rank": 1, "x": 2}']
        # elastic-recovery case: a RESTARTED rank publishes through a
        # fresh transport whose seq restarts at 0 — the epoch in the
        # frame head must keep its reports fresh, not stale-forever
        t1b = StoreObsTransport(clients[1], "run0/obs", rank=1, world=2)
        t1b.publish(b'{"rank": 1, "x": 3}')
        assert t0.drain() == [b'{"rank": 1, "x": 3}']
    finally:
        for c in clients:
            c.close()
        server.stop()


def test_cluster_aggregator_marks_stale_ranks():
    class _NullTransport:
        def publish(self, payload):
            raise AssertionError("rank 0 never publishes")

        def drain(self):
            return []

    agg = ClusterAggregator(_NullTransport(), rank=0, world=3)
    merged = agg.publish(_report_for(0, 5, 10, 1.0))
    assert merged["stale_ranks"] == [1, 2]
    assert merged["ranks"] == [0]


# ---------------------------------------------------------------- watchdog

def test_watchdog_fires_and_dumps_on_injected_hang(registry):
    tr = SpanTracer(capacity=16)
    with tr.span("last_good_stage"):
        pass
    dumps = []
    report = {"type": "step_report", "step": 41, "stats": {}}

    release = threading.Event()
    hung = threading.Thread(target=release.wait, name="injected-hang",
                            daemon=True)
    hung.start()

    wd = StallWatchdog(0.25, action="dump", tracer=tr,
                       report_fn=lambda: report,
                       on_stall=dumps.append, poll_interval=0.05)
    wd.beat("step")
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while not dumps and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
        release.set()
        hung.join(timeout=2)
    assert wd.fires >= 1 and dumps
    text = dumps[0]
    assert "no progress beat" in text and "'step'" in text
    assert "last_good_stage" in text               # last-K spans
    assert "injected-hang" in text                 # per-thread stacks
    assert '"step": 41' in text                    # last StepReport


def test_watchdog_fires_once_per_silence_window():
    dumps = []
    wd = StallWatchdog(0.15, action="dump", on_stall=dumps.append,
                       poll_interval=0.03)
    wd.beat("step")
    wd.start()
    try:
        time.sleep(0.6)                 # several poll intervals of silence
        assert len(dumps) == 1          # one dump per silence window
        wd.beat("step")
        time.sleep(0.4)                 # new window after the beat
        assert len(dumps) == 2
    finally:
        wd.stop()


def test_watchdog_raise_interrupts_main():
    wd = StallWatchdog(0.15, action="raise", poll_interval=0.03,
                       stream=open("/dev/null", "w"))
    wd.beat("step")
    wd.start()
    interrupted = False
    try:
        time.sleep(3.0)
    except KeyboardInterrupt:
        interrupted = True
    finally:
        wd.stop()
    assert interrupted


def test_watchdog_beat_module_hook():
    from paddlebox_tpu.obs import watchdog as wmod
    assert wmod.active() is None or wmod.active().threshold_s > 0
    wd = StallWatchdog(10.0)
    prev = wmod.set_active(wd)
    try:
        obs.beat("exchange")
        assert wd._beat[1] == "exchange"
    finally:
        wmod.set_active(prev)


def test_watchdog_rejects_bad_action():
    with pytest.raises(ValueError):
        StallWatchdog(1.0, action="explode")


# ------------------------------------------------------- logging layer

def test_obs_log_rank_prefix_and_fields(capsys):
    from paddlebox_tpu.obs import log as obs_log
    prev = obs_log._RANK
    obs_log.set_rank(3)
    try:
        obs_log.info("pass done", loss=0.5, batches=8)
        obs_log.info("line1\nline2")
    finally:
        obs_log._RANK = prev
    err = capsys.readouterr().err
    assert "[pbtpu r3" in err
    assert "pass done batches=8 loss=0.5" in err
    # every line of a multi-line payload carries the prefix
    assert err.count("[pbtpu r3") >= 3


# ----------------------------------------------------- channel depth gauge

def test_channel_depth_gauge(registry):
    # depths are SAMPLED at report cadence (poll_depth_gauges), never
    # pushed per put/get — the hot queues must not touch the global
    # registry lock per item
    from paddlebox_tpu.utils.channel import Channel, poll_depth_gauges
    ch = Channel(capacity=8, name="t_obs")
    ch.put(1)
    ch.put(2)
    poll_depth_gauges()
    assert registry.get_gauge("chan_t_obs_depth") == 2
    ch.get()
    poll_depth_gauges()
    assert registry.get_gauge("chan_t_obs_depth") == 1
    # same-named channels SUM (two DumpWriters both register "dump")
    ch2 = Channel(capacity=8, name="t_obs")
    ch2.put(9)
    ch2.put(9)
    poll_depth_gauges()
    assert registry.get_gauge("chan_t_obs_depth") == 3
    ch.drain()
    del ch2
    import gc
    gc.collect()
    poll_depth_gauges()
    assert registry.get_gauge("chan_t_obs_depth") == 0
    # all channels dead: one final 0 write, then the name is dropped —
    # the gauge must not freeze a dead queue's last depth forever
    ch.put(5)
    del ch
    gc.collect()
    poll_depth_gauges()    # samples the dying set -> 0 (or drops it)
    poll_depth_gauges()
    assert registry.get_gauge("chan_t_obs_depth") == 0
    registry.set_gauge("chan_t_obs_depth", 7)
    poll_depth_gauges()    # name no longer tracked: value untouched
    assert registry.get_gauge("chan_t_obs_depth") == 7


# ------------------------------------------------ trainer e2e + overhead

def _tiny_trainer(**cfg_kw):
    from paddlebox_tpu.config.configs import (DataFeedConfig,
                                              SparseOptimizerConfig,
                                              SlotConfig, TableConfig,
                                              TrainerConfig)
    from paddlebox_tpu.data.generator import (default_feed_config,
                                              write_synthetic_ctr_files)
    import tempfile
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.ctr_dnn import CtrDnn
    from paddlebox_tpu.train.trainer import BoxTrainer
    out = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        out, num_files=1, lines_per_file=512, num_slots=4,
        vocab_per_slot=500, max_len=3, seed=5)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    table = TableConfig(embedx_dim=4, pass_capacity=1 << 13,
                        optimizer=SparseOptimizerConfig())
    spec = ModelSpec(num_slots=4, slot_dim=3 + 4)
    model = CtrDnn(spec, hidden=(16,))
    tr = BoxTrainer(model, table, feed,
                    TrainerConfig(dense_lr=1e-3, **cfg_kw), seed=0)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    return tr, ds


def test_trainer_pass_emits_reports_and_trace(tmp_path, registry):
    path = str(tmp_path / "steps.jsonl")
    prev_every = flags.get_flag("obs_report_every")
    prev_path = flags.get_flag("obs_report_path")
    flags.set_flag("obs_report_every", 2)
    flags.set_flag("obs_report_path", path)
    try:
        tr, ds = _tiny_trainer()
        # a registered streaming metric must survive the pass_end extra
        # (auc values are CALLED and floated — a bound method would kill
        # every JSON sink and, multiprocess, the cluster aggregator)
        tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14)
        stats = tr.train_pass(ds)
        tr.close()
    finally:
        flags.set_flag("obs_report_every", prev_every)
        flags.set_flag("obs_report_path", prev_path)
    recs = [json.loads(ln) for ln in open(path)]
    assert recs, "no StepReports emitted"
    assert all(r["v"] == 1 for r in recs)
    tail = recs[-1]
    assert tail.get("event") == "pass_end"       # forced window close
    assert tail["loss"] == pytest.approx(stats["loss"], abs=1e-5)
    assert isinstance(tail["auc"]["auc"], float)
    assert any(r["examples"] > 0 for r in recs)
    # pass lifecycle stats rode the report windows
    merged_stats = {}
    for r in recs:
        for k, v in r["stats"].items():
            merged_stats[k] = merged_stats.get(k, 0) + v
    assert "pass_rows_promote_new" in merged_stats or \
        "sparse_keys_created" in merged_stats
    # the span rings saw the pass: chrome export round-trips and carries
    # the hot-path spans
    doc = obs.export_chrome_trace(path=str(tmp_path / "trace.json"))
    json.loads(open(str(tmp_path / "trace.json")).read())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "pass_begin" in names and "pass_end" in names
    assert "host_stage" in names or "scan_dispatch" in names


def test_span_overhead_smoke():
    """Enabled spans must stay ~microsecond-scale; disabled near-free.
    Thresholds are 20-50x the quiet-box cost so container noise cannot
    false-fail (load-guard note: quiet measurements are ~1-2us enabled,
    ~0.1us disabled)."""
    tr = SpanTracer(capacity=1024)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("s"):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 100e-6, per_span
    tr.enabled = False
    t0 = time.perf_counter()
    for _ in range(n):
        with tr.span("s"):
            pass
    per_disabled = (time.perf_counter() - t0) / n
    assert per_disabled < 20e-6, per_disabled
