"""Heterogenous parallel (HeterWrapper/HeterXpuTrainer analog): CPU worker
does data + sparse PS traffic, the dense fwd/bwd runs in a separate
accelerator service over RPC."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.fleet.heter import (HeterDenseClient, HeterDenseService,
                                       HeterTrainer)
from paddlebox_tpu.metrics.auc import BasicAucCalculator
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.ps import PsLocalClient

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("heter")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=300, num_slots=NUM_SLOTS,
        vocab_per_slot=100, max_len=3, seed=31)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def test_heter_offload_learns(data):
    files, feed = data
    table_cfg = TableConfig(
        embedx_dim=D, optimizer=SparseOptimizerConfig(
            mf_create_thresholds=0.0, mf_initial_range=1e-3,
            feature_learning_rate=0.2, mf_learning_rate=0.2))
    model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,))
    service = HeterDenseService(model, feed, dense_lr=0.01, seed=0)
    heter = HeterDenseClient("127.0.0.1", service.port)
    trainer = HeterTrainer(PsLocalClient(), heter, table_cfg, feed, seed=0)
    trainer.metrics.init_metric("auc", "label", "pred",
                                table_size=1 << 14, mask_var="mask")
    for _ in range(8):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()

    # fresh test-mode eval over the service's eval_step; create=False pulls
    # must not insert rows server-side
    n_before = trainer.client.sparse_size(HeterTrainer.SPARSE_TABLE)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds, labels = trainer.predict_pass(ds)
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    assert calc.auc() > 0.7, calc.auc()
    assert trainer.client.sparse_size(HeterTrainer.SPARSE_TABLE) == n_before

    # sparse features were created on the CPU PS, not in the service
    assert n_before > 100
    trainer.close()
    heter.stop_server()
    heter.close()
