"""Aux subsystems: dump-fields writers, profiler reports, model merge,
slots-shuffle (AUC runner), parser plugins (SURVEY.md §5 coverage)."""

import os

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (DataFeedConfig, SlotConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.data.plugin import load_parser_plugin
from paddlebox_tpu.train.dump import DumpWriter
from paddlebox_tpu.utils.profiler import stats_report, timer_report
from paddlebox_tpu.utils.timer import Timer


def test_dump_writer_lines_and_rotation(tmp_path):
    w = DumpWriter(str(tmp_path / "dump"), thread_num=2, max_bytes=512)
    for step in range(20):
        w.dump_batch(
            {"pred": np.full(4, 0.25), "label": np.array([1, 0, 1, 0])},
            ins_ids=["i%d_%d" % (step, j) for j in range(4)],
            mask=np.array([True, True, True, False]))
    w.dump_param({"w0": np.arange(4.0)}, step=19)
    w.close()
    assert len(w.files) > 1  # rotated at 512 bytes
    text = "".join(open(f).read() for f in w.files)
    lines = [l for l in text.splitlines() if l and ":" in l]
    # masked instance never dumped
    assert not any(l.startswith("i0_3\t") for l in lines)
    ins_lines = [l for l in lines if "\t" in l]
    assert len(ins_lines) == 20 * 3
    one = next(l for l in ins_lines if l.startswith("i0_0\t"))
    assert "label:1" in one and "pred:0.25" in one
    assert "param_step:19" in text and "w0:0,1,2,3" in text


def test_trainer_dump_fields(tmp_path):
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train.trainer import BoxTrainer
    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "d"), num_files=1, lines_per_file=64, num_slots=3,
        vocab_per_slot=50, seed=5)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    tcfg = TableConfig(embedx_dim=4, optimizer=SparseOptimizerConfig(
        mf_create_thresholds=0.0))
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=3, slot_dim=7), hidden=(8,)),
                    tcfg, feed,
                    TrainerConfig(dump_fields=("pred", "label"),
                                  dump_fields_path=str(tmp_path / "dump"),
                                  scan_chunk=2))
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist(files)
    tr.train_pass(ds)
    tr.close()
    assert tr.dump_writer is None
    dumped = [f for f in os.listdir(tmp_path / "dump")]
    assert dumped
    text = open(os.path.join(tmp_path / "dump", dumped[0])).read()
    assert "pred:" in text and "label:" in text


def test_timer_and_stats_report():
    t = Timer()
    t.start(); t.pause()
    rep = timer_report({"step": t, "idle": Timer()})
    assert "step" in rep and "idle" not in rep
    from paddlebox_tpu.utils.stats import stat_add
    stat_add("aux_test_counter", 3)
    assert "aux_test_counter" in stats_report()


def test_merge_models(tmp_path):
    from paddlebox_tpu.embedding import accessor as acc
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.train.checkpoint import merge_models
    import pickle
    layout = ValueLayout(embedx_dim=2, optimizer="adagrad")

    def mk(d, keys, shows, ws):
        os.makedirs(d, exist_ok=True)
        vals = np.zeros((len(keys), layout.width), np.float32)
        vals[:, acc.SHOW] = shows
        vals[:, acc.CLICK] = 1.0
        vals[:, acc.EMBED_W] = ws
        with open(os.path.join(d, "sparse.pkl"), "wb") as f:
            pickle.dump({"keys": np.array(keys, np.uint64), "values": vals,
                         "embedx_dim": 2, "optimizer": "adagrad"}, f)

    mk(str(tmp_path / "m0"), [1, 2], [4.0, 1.0], [1.0, 5.0])
    mk(str(tmp_path / "m1"), [2, 3], [3.0, 2.0], [9.0, 7.0])
    out = merge_models([str(tmp_path / "m0"), str(tmp_path / "m1")],
                       str(tmp_path / "merged"))
    # merge output rides the round-15 format flag (columnar manifest by
    # default); read_batch_sparse dispatches on what the dir holds
    from paddlebox_tpu.train.checkpoint import read_batch_sparse
    blob = read_batch_sparse(out)
    got = dict(zip(blob["keys"].tolist(), blob["values"]))
    assert set(got) == {1, 2, 3}
    # key 2 in both: show sums, embed_w show-weighted avg
    assert got[2][acc.SHOW] == 4.0
    np.testing.assert_allclose(got[2][acc.EMBED_W],
                               (5.0 * 1 + 9.0 * 3) / 4, rtol=1e-6)
    # singletons pass through
    assert got[1][acc.EMBED_W] == 1.0 and got[3][acc.EMBED_W] == 7.0


def test_slots_shuffle(tmp_path):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=100, num_slots=3,
        vocab_per_slot=50, seed=9)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist(files)
    ds.load_into_memory()
    before_s0 = [r.uint64_slots.get(0, np.empty(0, np.uint64)).copy()
                 for r in ds.records]
    before_s1 = [r.uint64_slots.get(1, np.empty(0, np.uint64)).copy()
                 for r in ds.records]
    ds.slots_shuffle([0], seed=3)
    after_s0 = [r.uint64_slots.get(0, np.empty(0, np.uint64))
                for r in ds.records]
    after_s1 = [r.uint64_slots.get(1, np.empty(0, np.uint64))
                for r in ds.records]
    # slot 1 untouched
    for a, b in zip(before_s1, after_s1):
        np.testing.assert_array_equal(a, b)
    # slot 0 is a permutation: same multiset of value-lists, mostly moved
    key = lambda arrs: sorted(tuple(a.tolist()) for a in arrs)
    assert key(before_s0) == key(after_s0)
    moved = sum(1 for a, b in zip(before_s0, after_s0)
                if a.shape != b.shape or (a != b).any())
    assert moved > 50


def test_parser_plugin_python(tmp_path):
    plug = tmp_path / "myparser.py"
    plug.write_text(
        "import numpy as np\n"
        "from paddlebox_tpu.data.slot_record import SlotRecord\n"
        "class P:\n"
        "    def __init__(self, feed): self.feed = feed\n"
        "    def parse_file(self, path):\n"
        "        for line in open(path):\n"
        "            v = int(line)\n"
        "            yield SlotRecord(label=v % 2,\n"
        "                uint64_slots={0: np.array([v], np.uint64)})\n"
        "def make_parser(feed):\n"
        "    return P(feed)\n")
    data = tmp_path / "data.txt"
    data.write_text("\n".join(str(i) for i in range(10)))
    feed = DataFeedConfig(slots=(
        SlotConfig("click", type="float", dim=1, is_used=False),
        SlotConfig("s0", type="uint64", max_len=2)), batch_size=4)
    parser = load_parser_plugin(str(plug), feed)
    ds = BoxDataset(feed, read_threads=1, parser=parser, columnar=False)
    ds.set_filelist([str(data)])
    ds.load_into_memory()
    assert len(ds) == 10
    assert sum(r.label for r in ds.records) == 5

    with pytest.raises(ValueError):
        load_parser_plugin(str(tmp_path / "x.txt"), feed)


def test_sharded_trainer_dump_fields(tmp_path):
    """DumpField through the SHARDED trainer: per-worker rows, one line
    per real instance, works with the scan megastep path."""
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel.mesh import device_mesh_1d

    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "d"), num_files=2, lines_per_file=128, num_slots=3,
        vocab_per_slot=50, seed=5)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    tcfg = TableConfig(embedx_dim=4, pass_capacity=1 << 12,
                       optimizer=SparseOptimizerConfig(
                           mf_create_thresholds=0.0))
    tr = ShardedBoxTrainer(
        CtrDnn(ModelSpec(num_slots=3, slot_dim=7), hidden=(8,)),
        tcfg, feed,
        TrainerConfig(dump_fields=("pred", "label"),
                      dump_fields_path=str(tmp_path / "dump"),
                      scan_chunk=2),
        mesh=device_mesh_1d(8), seed=0)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    stats = tr.train_pass(ds)
    tr.close()
    assert tr.dump_writer is None
    dumped = os.listdir(tmp_path / "dump")
    assert dumped
    lines = []
    for f in dumped:
        lines += [l for l in open(os.path.join(tmp_path / "dump", f))
                  if l.strip()]
    assert len(lines) == stats["instances"] == 256
    assert all("pred:" in l and "label:" in l for l in lines)
