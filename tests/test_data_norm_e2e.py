"""data_norm model integration: the streaming "summary" params
(boxps_worker.cc:89-95) updated by the running-sums rule inside the fused
train step — never by the dense optimizer — in both trainers.

Also pins the ratio-invariance fact the multi-device design relies on:
data_norm output depends only on batch_sum/batch_size and
batch_size/batch_square_sum, so a pmean over workers (instead of the
reference's DenseDataNormal sum) changes nothing."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset
from paddlebox_tpu.data.generator import write_synthetic_ctr_files
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.ops.data_norm import DataNormState, data_norm
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
from paddlebox_tpu.train.trainer import BoxTrainer

N_SLOTS = 8
D = 4


def _data(tmp_path, batch_size=32):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=2, lines_per_file=256, num_slots=N_SLOTS,
        vocab_per_slot=500, max_len=3, seed=2)
    return files, dataclasses.replace(feed, batch_size=batch_size)


def _table():
    return TableConfig(embedx_dim=D, pass_capacity=1 << 13,
                       optimizer=SparseOptimizerConfig(
                           mf_create_thresholds=0.0, mf_initial_range=1e-3))


def test_ratio_invariance_under_worker_mean():
    """pmean of (batch_size, batch_sum, batch_square_sum) across P workers
    normalizes identically to the reference's P-worker sum."""
    rng = np.random.RandomState(0)
    P = 4
    states = [DataNormState(
        batch_size=jnp.asarray(rng.rand(6).astype(np.float32) + 1.0),
        batch_sum=jnp.asarray(rng.randn(6).astype(np.float32)),
        batch_square_sum=jnp.asarray(rng.rand(6).astype(np.float32) + 1.0))
        for _ in range(P)]
    mean_st = DataNormState(*[sum(getattr(s, f) for s in states) / P
                              for f in states[0]._fields])
    sum_st = DataNormState(*[sum(getattr(s, f) for s in states)
                             for f in states[0]._fields])
    x = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    np.testing.assert_allclose(np.asarray(data_norm(x, mean_st)),
                               np.asarray(data_norm(x, sum_st)),
                               rtol=1e-5)


def test_box_trainer_data_norm_learns_and_accumulates(tmp_path):
    files, feed = _data(tmp_path)
    model = CtrDnn(ModelSpec(num_slots=N_SLOTS, slot_dim=3 + D),
                   hidden=(32, 16), use_data_norm=True)
    tr = BoxTrainer(model, _table(), feed,
                    TrainerConfig(dense_lr=1e-2, scan_chunk=2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        bs0 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        losses = [tr.train_pass(ds)["loss"] for _ in range(3)]
        bs1 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        # summary accumulated every step (init 1e4, +batch rows per step)
        assert bs1 > bs0, (bs0, bs1)
        assert losses[-1] < losses[0], losses
        # the state stayed out of the optimizer: batch_sum finite and the
        # normalized model still separates classes in eval
        preds, labels = tr.predict_batches(ds)
        assert np.isfinite(preds).all()
    finally:
        tr.close()


def test_async_dense_data_norm_accumulates(tmp_path):
    """Async-dense mode: summary deltas ride the flat grad vector and the
    host table's summary mask applies them RAW (not through adam)."""
    files, feed = _data(tmp_path)
    model = CtrDnn(ModelSpec(num_slots=N_SLOTS, slot_dim=3 + D),
                   hidden=(32, 16), use_data_norm=True)
    tr = BoxTrainer(model, _table(), feed,
                    TrainerConfig(dense_lr=1e-2, async_mode=True,
                                  dense_optimizer="adam"))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        bs0 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        tr.train_pass(ds)
        tr.train_pass(ds)
        bs1 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        # init 1e4 decayed + per-step row counts added — strictly grows
        assert bs1 > bs0, (bs0, bs1)
        assert np.isfinite(
            np.asarray(tr.params["dn_summary"]["batch_sum"])).all()
    finally:
        tr.close()


def test_mixed_precision_preserves_summary_f32():
    """cast_for_compute must leave dn_summary in f32 (normalization at
    8-bit mantissa would defeat apply's explicit f32 cast)."""
    from paddlebox_tpu.train.trainer import cast_for_compute
    params = {"w": jnp.ones((4, 4), jnp.float32),
              "dn_summary": {"batch_size": jnp.full((4,), 1e4)}}
    cast = cast_for_compute(params, jnp.bfloat16)
    assert cast["w"].dtype == jnp.bfloat16
    assert cast["dn_summary"]["batch_size"].dtype == jnp.float32


def test_profile_per_op_mode(tmp_path):
    """profile_per_op routes a pass through staged, D2H-synced dispatches
    (TrainFilesWithProfiler analog) and keeps training state continuous
    with the fused path."""
    from paddlebox_tpu.config import flags

    files, feed = _data(tmp_path)
    # data_norm model: the profiled pass must run the SAME summary update
    # as the fused step (it reuses the fused closures)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=N_SLOTS, slot_dim=3 + D),
                           hidden=(16,), use_data_norm=True),
                    _table(), feed, TrainerConfig(dense_lr=1e-2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        bs0 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        flags.set_flag("profile_per_op", True)
        try:
            s1 = tr.train_pass(ds)
        finally:
            flags.set_flag("profile_per_op", False)
        bs1 = float(np.asarray(tr.params["dn_summary"]["batch_size"])[0])
        assert bs1 > bs0, (bs0, bs1)
        s2 = tr.train_pass(ds)   # fused pass continues from profiled state
        assert s2["loss"] < s1["loss"], (s1, s2)
    finally:
        tr.close()


def test_sharded_trainer_data_norm_replicated(tmp_path):
    files, feed = _data(tmp_path)
    P = len(jax.devices())
    model = CtrDnn(ModelSpec(num_slots=N_SLOTS, slot_dim=3 + D),
                   hidden=(32, 16), use_data_norm=True)
    tr = ShardedBoxTrainer(model, _table(), feed,
                           TrainerConfig(dense_lr=1e-2),
                           mesh=device_mesh_1d(P), seed=0)
    ds = BoxDataset(feed)
    ds.set_filelist(files)
    losses = [tr.train_pass(ds)["loss"] for _ in range(5)]
    assert losses[-1] < losses[0], losses
    # replicated params: every device holds the SAME pmean'd summary
    dn = tr.params["dn_summary"]["batch_size"]
    per_dev = [np.asarray(s.data) for s in dn.addressable_shards]
    for v in per_dev[1:]:
        np.testing.assert_allclose(v, per_dev[0], rtol=1e-6)
    assert float(per_dev[0][0]) > 1e4
