"""Bit-parity: incremental pass lifecycle vs the full rebuild path.

The incremental lifecycle (delta promote + touched-row writeback +
cross-pass HBM residency, flags.incremental_pass) must be byte-identical
to the full begin_pass/end_pass round trip: same slab contents after
every begin_pass, same host-store contents (values INCLUDING optimizer
state columns) after every end_pass, across consecutive overlapping
passes, at 0% overlap, and through a test_mode (no-create, no-writeback)
eval pass in the middle."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.embedding.pass_table import PassTable
from paddlebox_tpu.parallel.sharded_table import ShardedPassTable

D = 4
CAP = 1 << 10


def table_cfg():
    return TableConfig(
        embedx_dim=D, pass_capacity=CAP,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))


@pytest.fixture
def incremental_flag():
    """Restore the flag whatever a test sets it to."""
    saved = flags.get_flag("incremental_pass")
    yield
    flags.set_flag("incremental_pass", saved)


def make_passes(rng, n_passes=3, n_keys=500, overlap=0.9):
    """Consecutive sorted-unique key sets with ~`overlap` retention."""
    cur = np.unique(rng.randint(0, 1 << 30, n_keys).astype(np.uint64))
    out = [cur]
    for _ in range(n_passes - 1):
        keep = rng.rand(cur.size) < overlap
        fresh = np.unique(
            rng.randint(0, 1 << 30, max(8, int(n_keys * (1 - overlap))))
            .astype(np.uint64))
        cur = np.unique(np.concatenate([cur[keep], fresh]))
        out.append(cur)
    return out


def sorted_store_items(store):
    keys, vals = store.state_items()
    order = np.argsort(keys)
    return keys[order], vals[order]


def run_single(passes, incremental, test_pass=None, seed=11):
    """Drive a PassTable through the passes with real device pushes;
    returns per-pass (slab_after_pushes, store_keys, store_vals).
    test_pass, when given, is a key set run in test_mode between the
    train passes (after the first one)."""
    flags.set_flag("incremental_pass", incremental)
    t = PassTable(table_cfg(), seed=seed)
    pl = t.push_layout
    out = []
    for pi, ks in enumerate(passes):
        if test_pass is not None and pi == 1:
            # eval pass in the middle: no create, no writeback
            t.set_test_mode(True)
            t.begin_feed_pass()
            t.add_keys(test_pass)
            t.end_feed_pass()
            t.begin_pass()
            eval_ids = t.lookup_ids(test_pass)
            eval_rows = np.asarray(t.pull(jnp.asarray(eval_ids)))
            t.end_pass()
            t.set_test_mode(False)
            ek, ev = sorted_store_items(t.store)
            out.append(("eval", eval_rows, ek, ev))
        t.begin_feed_pass()
        t.add_keys(ks)
        t.end_feed_pass()
        t.begin_pass()
        # push gradients on a deterministic subset (with repeats, so the
        # dedup + merge path runs), leave the rest untouched
        sub = np.concatenate([ks[: max(1, ks.size // 2)], ks[:7]])
        ids = t.lookup_ids(sub)
        g = np.zeros((ids.size, pl.width), np.float32)
        g[:, pl.SHOW] = 1.0
        g[:, pl.CLICK] = (np.arange(ids.size) % 2).astype(np.float32)
        g[:, pl.EMBED_G] = 0.05
        g[:, pl.embedx_g:] = 0.01
        t.push(jnp.asarray(ids), jnp.asarray(g))
        slab = np.asarray(t.slab)
        t.end_pass()
        k, v = sorted_store_items(t.store)
        out.append(("train", slab, k, v))
    return out


def assert_runs_equal(full, inc):
    assert len(full) == len(inc)
    for (tag_f, slab_f, k_f, v_f), (tag_i, slab_i, k_i, v_i) in zip(full,
                                                                    inc):
        assert tag_f == tag_i
        np.testing.assert_array_equal(slab_f, slab_i)
        np.testing.assert_array_equal(k_f, k_i)
        np.testing.assert_array_equal(v_f, v_i)


def test_pass_table_parity_overlapping(incremental_flag):
    passes = make_passes(np.random.RandomState(0), n_passes=4, overlap=0.9)
    full = run_single(passes, incremental=False)
    inc = run_single(passes, incremental=True)
    assert_runs_equal(full, inc)


def test_pass_table_parity_zero_overlap(incremental_flag):
    rng = np.random.RandomState(1)
    # disjoint ranges: 0% overlap — the incremental worst case must still
    # be bit-exact (every row evicted + promoted each pass)
    passes = [np.unique((rng.randint(0, 1 << 20, 300)
                         + (p << 32)).astype(np.uint64))
              for p in range(3)]
    full = run_single(passes, incremental=False)
    inc = run_single(passes, incremental=True)
    assert_runs_equal(full, inc)


def test_pass_table_parity_through_test_mode(incremental_flag):
    rng = np.random.RandomState(2)
    passes = make_passes(rng, n_passes=3, overlap=0.85)
    # the eval set mixes resident keys with NEVER-SEEN keys: test mode
    # must not create them, and the incremental path must not leak the
    # eval slab (zero rows for unseen keys) into the next train promote
    unseen = np.unique((rng.randint(0, 1 << 20, 64)
                        + (7 << 40)).astype(np.uint64))
    test_keys = np.unique(np.concatenate([passes[0][:100], unseen]))
    full = run_single(passes, incremental=False, test_pass=test_keys)
    inc = run_single(passes, incremental=True, test_pass=test_keys)
    assert_runs_equal(full, inc)
    # the eval pass must not have created the unseen keys in either run
    for run in (full, inc):
        tag, _, keys, _ = run[1]
        assert tag == "eval"
        assert not np.isin(unseen, keys).any()


def test_pass_table_delta_path_actually_ran(incremental_flag):
    """Guard against the delta promote silently falling back to full
    builds: at high overlap the resident-hit stat must move."""
    from paddlebox_tpu.utils.stats import stat_get
    passes = make_passes(np.random.RandomState(3), n_passes=3, overlap=0.9)
    before = stat_get("pass_rows_promote_hit")
    run_single(passes, incremental=True)
    assert stat_get("pass_rows_promote_hit") > before


def test_pass_table_invalidation_forces_full_build(incremental_flag):
    """A store mutation outside the pass cadence (end_day aging) must
    drop residency — and the next pass must still be bit-exact vs a
    full-path table subjected to the same cadence."""
    passes = make_passes(np.random.RandomState(4), n_passes=2, overlap=0.9)

    def run(incremental):
        flags.set_flag("incremental_pass", incremental)
        t = PassTable(table_cfg(), seed=5)
        outs = []
        for ks in passes:
            t.begin_feed_pass()
            t.add_keys(ks)
            t.end_feed_pass()
            t.begin_pass()
            ids = t.lookup_ids(ks[: ks.size // 2])
            pl = t.push_layout
            g = np.zeros((ids.size, pl.width), np.float32)
            g[:, pl.SHOW] = 1.0
            g[:, pl.EMBED_G] = 0.1
            t.push(jnp.asarray(ids), jnp.asarray(g))
            outs.append(np.asarray(t.slab))
            t.end_pass()
            t.end_day()  # ages + shrinks between every pass
        return outs, sorted_store_items(t.store)

    slabs_f, store_f = run(False)
    slabs_i, store_i = run(True)
    for a, b in zip(slabs_f, slabs_i):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(store_f[0], store_i[0])
    np.testing.assert_array_equal(store_f[1], store_i[1])


# --------------------------------------------------------------- sharded
def run_sharded(passes, incremental, seed=9, num_shards=4):
    """Drive a ShardedPassTable through build → simulated push →
    write_back; the 'push' mutates a deterministic subset of each shard's
    rows on the host copy (the device step is exercised by the trainer
    tests; here the contract under test is the table's promote/writeback
    bookkeeping). Returns per-pass (built_slabs, store items per shard)."""
    flags.set_flag("incremental_pass", incremental)
    t = ShardedPassTable(table_cfg(), num_shards=num_shards,
                         bucket_cap=256, seed=seed)
    out = []
    for ks in passes:
        t.begin_feed_pass()
        t.add_keys(ks)
        t.end_feed_pass()
        slabs = t.build_slabs()
        built = slabs.copy()
        # simulate training: bump half of each shard's working set and
        # report those rows touched (the stage_push_dedup callback role)
        for s in range(num_shards):
            n = t._shard_keys[s].size
            if not n:
                continue
            rows = np.arange(0, n, 2, dtype=np.int32)
            slabs[s, rows] += 0.125
            t.note_touched(s, rows)
        t.write_back(slabs)
        items = [sorted_store_items(st) for st in t.stores]
        out.append((built, slabs.copy(), items))
    return out


def test_sharded_parity_overlapping(incremental_flag):
    passes = make_passes(np.random.RandomState(6), n_passes=4, overlap=0.9)
    full = run_sharded(passes, incremental=False)
    inc = run_sharded(passes, incremental=True)
    for (b_f, s_f, it_f), (b_i, s_i, it_i) in zip(full, inc):
        np.testing.assert_array_equal(b_f, b_i)
        np.testing.assert_array_equal(s_f, s_i)
        for (k_f, v_f), (k_i, v_i) in zip(it_f, it_i):
            np.testing.assert_array_equal(k_f, k_i)
            np.testing.assert_array_equal(v_f, v_i)


def test_sharded_parity_zero_overlap(incremental_flag):
    rng = np.random.RandomState(7)
    passes = [np.unique((rng.randint(0, 1 << 20, 300)
                         + (p << 32)).astype(np.uint64))
              for p in range(3)]
    full = run_sharded(passes, incremental=False)
    inc = run_sharded(passes, incremental=True)
    for (b_f, s_f, it_f), (b_i, s_i, it_i) in zip(full, inc):
        np.testing.assert_array_equal(b_f, b_i)
        for (k_f, v_f), (k_i, v_i) in zip(it_f, it_i):
            np.testing.assert_array_equal(k_f, k_i)
            np.testing.assert_array_equal(v_f, v_i)


def test_sharded_test_mode_no_create_no_writeback(incremental_flag):
    flags.set_flag("incremental_pass", True)
    rng = np.random.RandomState(8)
    passes = make_passes(rng, n_passes=2, overlap=0.9)
    t = ShardedPassTable(table_cfg(), num_shards=4, bucket_cap=256, seed=1)
    # train pass 0
    t.begin_feed_pass()
    t.add_keys(passes[0])
    t.end_feed_pass()
    slabs = t.build_slabs()
    t.write_back(slabs)
    sizes = [len(st) for st in t.stores]
    items = [sorted_store_items(st) for st in t.stores]
    # eval pass with unseen keys: stores must not change at all
    unseen = np.unique((rng.randint(0, 1 << 20, 50)
                        + (9 << 40)).astype(np.uint64))
    t.set_test_mode(True)
    t.begin_feed_pass()
    t.add_keys(np.concatenate([passes[0][:50], unseen]))
    t.end_feed_pass()
    eval_slabs = t.build_slabs()
    t.write_back(eval_slabs + 1.0)  # must be ignored in test mode
    t.set_test_mode(False)
    assert [len(st) for st in t.stores] == sizes
    for (k0, v0), st in zip(items, t.stores):
        k1, v1 = sorted_store_items(st)
        np.testing.assert_array_equal(k0, k1)
        np.testing.assert_array_equal(v0, v1)


def test_preloaded_incremental_matches_sequential_full(incremental_flag,
                                                       tmp_path):
    """End-to-end: run_preloaded_passes with the incremental lifecycle
    (+ promote prefetch thread) must produce the same losses as plain
    sequential passes with the lifecycle OFF — the whole stack (trainer
    staging, scan path, preloader, writeback) rides the same bits."""
    from paddlebox_tpu.config.configs import TrainerConfig
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train.preload import run_preloaded_passes
    from paddlebox_tpu.train.trainer import BoxTrainer

    num_slots = 4
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=2, lines_per_file=160, num_slots=num_slots,
        vocab_per_slot=60, max_len=3, seed=21)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    spec = ModelSpec(num_slots=num_slots, slot_dim=3 + D)
    flags.set_flag("dataset_disable_shuffle", True)
    try:
        def datasets(n):
            out = []
            for _ in range(n):
                ds = BoxDataset(feed, read_threads=1)
                ds.set_filelist(files)
                out.append(ds)
            return out

        flags.set_flag("incremental_pass", False)
        seq = BoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                         TrainerConfig(dense_lr=0.01), seed=0)
        seq_losses = [seq.train_pass(ds)["loss"] for ds in datasets(3)]
        sk, sv = sorted_store_items(seq.table.store)

        flags.set_flag("incremental_pass", True)
        pipe = BoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                          TrainerConfig(dense_lr=0.01), seed=0)
        stats = run_preloaded_passes(pipe, datasets(3))
        np.testing.assert_allclose([s["loss"] for s in stats], seq_losses,
                                   rtol=1e-6)
        pk, pv = sorted_store_items(pipe.table.store)
        np.testing.assert_array_equal(sk, pk)
        np.testing.assert_array_equal(sv, pv)
    finally:
        flags.set_flag("dataset_disable_shuffle", False)
