"""P2P host data plane (round 9): socket mesh vs the store allgather.

Fast tier: direct-endpoint mesh pairs + VIRTUAL 2-process staging (the
test_two_virtual_process_uid_staging pattern) asserting the p2p exchange
reproduces the store-path staging products BIT-IDENTICALLY in both wire
modes, plus the fleet-level rendezvous/caching/collective-fallback
contract, the store counter compaction, and the rpc transport fixes.

Slow tier: a REAL 3-process localhost cluster running the full exchange
ladder in parity mode (tools/hostplane_probe.py workers — pure host
plane, no jax collectives, so it runs on the jax-0.4.x CPU container
that skips test_multihost).
"""

import concurrent.futures
import logging
import os
import socket
import sys

import numpy as np
import pytest

from paddlebox_tpu.fleet.fleet import Fleet
from paddlebox_tpu.fleet.mesh_comm import MeshComm, MeshConnectError
from paddlebox_tpu.fleet.role_maker import RoleMaker
from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient


@pytest.fixture
def pool():
    with concurrent.futures.ThreadPoolExecutor(4) as p:
        yield p


@pytest.fixture
def mesh_pair():
    """Two direct-endpoint MeshComm instances (no store)."""
    meshes = [MeshComm(r, 2) for r in range(2)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    pos = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
    for m in meshes:
        m.connect(eps)
        m.positions_of = dict(pos)
    yield meshes
    for m in meshes:
        m.close()


def test_mesh_exchange_lockstep(lock_order_watch, mesh_pair, pool):
    """Per-rank parts land at the right peer, seqs pair send #n with
    recv #n across multiple rounds, and the wire accounting moves."""
    m0, m1 = mesh_pair
    for step in range(3):
        a = {0: np.array([step, 0]), 1: np.array([step, 1])}
        b = {0: np.array([step, 100]), 1: np.array([step, 101])}
        f = pool.submit(m1.exchange, b)
        r0 = m0.exchange(a)
        r1 = f.result()
        np.testing.assert_array_equal(r0[1], b[0])
        np.testing.assert_array_equal(r1[0], a[1])
        # self part passes through by reference, no wire bytes
        assert r0[0] is a[0] and r1[1] is b[1]
    s0 = m0.stats()
    assert s0["exchanges"] == 3
    assert s0["bytes_sent"] > 0 and s0["bytes_recv"] > 0
    assert m0.rank_of_position()[6] == 1


def test_mesh_exchange_timeout(mesh_pair):
    """A missing peer part surfaces as TimeoutError, not a hang."""
    m0, _m1 = mesh_pair
    m0._op_timeout = 0.3
    with pytest.raises(TimeoutError):
        m0.exchange({0: np.zeros(1), 1: np.zeros(1)})


def _virtual_buckets(P, KB, shard_cap, seed=5):
    rng = np.random.RandomState(seed)
    buckets = np.full((P, P, KB), shard_cap - 1, np.int32)
    for s in range(P):
        for d in range(P):
            n = rng.randint(2, KB)
            buckets[s, d, :n] = rng.randint(0, shard_cap - 1, n)
    return buckets


@pytest.mark.parametrize("uid_only", [False, True])
def test_p2p_vs_store_staging_parity(lock_order_watch, mesh_pair, pool, uid_only):
    """The acceptance bar: stage_push_dedup over the p2p mesh must
    reproduce the store-allgather path AND the single-process staging
    bit-identically — uids, perm/inv, and the rebuild pos maps."""
    from paddlebox_tpu.parallel.sharded_table import stage_push_dedup
    P, KB, shard_cap = 8, 16, 128
    buckets = _virtual_buckets(P, KB, shard_cap)

    single = stage_push_dedup(list(buckets), list(range(P)), P, shard_cap,
                              multiprocess=False, all_gather=None,
                              rebuild=True, pool=pool, uid_only=uid_only)

    def payload_of(bl, positions):
        header = np.array([len(positions), P, KB] + list(positions),
                          np.int32)
        return np.concatenate([header,
                               np.ascontiguousarray(bl, np.int32).ravel()])

    parts = [payload_of(buckets[0:4], [0, 1, 2, 3]),
             payload_of(buckets[4:8], [4, 5, 6, 7])]
    fake_gather = lambda payload: parts  # noqa: E731

    def run_rank(mesh, lo, positions, sink, touched):
        staged = stage_push_dedup(
            list(buckets[lo:lo + 4]), positions, P, shard_cap,
            multiprocess=True, all_gather=fake_gather, rebuild=True,
            pool=pool, uid_only=uid_only, mesh=mesh,
            note_touched=lambda d, u: touched.add(d))
        for i, d in enumerate(positions):
            sink[d] = {k: v[i] for k, v in staged.items()}

    out_store, out_p2p = {}, {}
    t_store, t_p2p = set(), set()
    run_rank(None, 0, [0, 1, 2, 3], out_store, t_store)
    run_rank(None, 4, [4, 5, 6, 7], out_store, t_store)
    f = pool.submit(run_rank, mesh_pair[1], 4, [4, 5, 6, 7], out_p2p,
                    t_p2p)
    run_rank(mesh_pair[0], 0, [0, 1, 2, 3], out_p2p, t_p2p)
    f.result()

    expect_keys = ({"push_uids"} if uid_only
                   else {"push_uids", "push_perm", "push_inv", "push_pos"})
    assert t_p2p == set(range(P))   # touched-row accounting still fires
    for d in range(P):
        assert set(out_p2p[d]) == expect_keys
        for k in out_store[d]:
            np.testing.assert_array_equal(
                out_store[d][k], out_p2p[d][k],
                err_msg=f"uid_only={uid_only} dest={d} key={k}")
        np.testing.assert_array_equal(out_p2p[d]["push_uids"],
                                      single["push_uids"][d])


def test_fleet_mesh_rendezvous_and_cache(pool):
    """Endpoints + positions rendezvous ONCE through the store; the mesh
    is cached per Fleet; exchanges ride the persistent connections."""
    server = KVStoreServer(host="127.0.0.1")
    ep = "127.0.0.1:%d" % server.port
    fls = [Fleet().init(RoleMaker(rank=r, world=2, store_endpoint=ep))
           for r in range(2)]
    try:
        f1 = pool.submit(fls[1].make_mesh_comm, [4, 5, 6, 7])
        m0 = fls[0].make_mesh_comm([0, 1, 2, 3])
        m1 = f1.result()
        assert m0 is not None and m1 is not None
        assert m0.positions_of == {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
        assert fls[0].make_mesh_comm([0, 1, 2, 3]) is m0  # cached
        f = pool.submit(m1.exchange, {0: np.array([7]), 1: np.array([8])})
        r0 = m0.exchange({0: np.array([1]), 1: np.array([2])})
        r1 = f.result()
        assert r0[1][0] == 7 and r1[0][0] == 2
    finally:
        for fl in fls:
            fl.stop()
        server.stop()


def test_fleet_p2p_fallback_collective_and_loud(pool, caplog):
    """If ANY rank fails mesh bring-up, EVERY rank falls back to the
    store plane together (a split decision would deadlock the lockstep
    exchange) — and it warns loudly on both the failing and the healthy
    rank."""
    from paddlebox_tpu.fleet import mesh_comm as mc
    server = KVStoreServer(host="127.0.0.1")
    ep = "127.0.0.1:%d" % server.port
    fls = [Fleet().init(RoleMaker(rank=r, world=2, store_endpoint=ep))
           for r in range(2)]
    orig = mc.MeshComm.connect

    def broken(self, endpoints, timeout=60.0):
        if self.rank == 1:
            raise MeshConnectError("simulated unreachable peer")
        return orig(self, endpoints, timeout)

    try:
        mc.MeshComm.connect = broken
        with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
            f1 = pool.submit(fls[1].make_mesh_comm, [4, 5, 6, 7])
            m0 = fls[0].make_mesh_comm([0, 1, 2, 3])
            m1 = f1.result()
        assert m0 is None and m1 is None
        assert any("bring-up FAILED" in m for m in caplog.messages)
        assert any("falling back to the store-allgather" in m
                   for m in caplog.messages)
    finally:
        mc.MeshComm.connect = orig
        for fl in fls:
            fl.stop()
        server.stop()


def test_store_counter_compaction(pool):
    """Collective counters older than 2 rounds are retired by rank 0 —
    a long multi-process run no longer grows the store unboundedly —
    while the last 2 rounds' (which a laggard may still wait on) stay."""
    server = KVStoreServer(host="127.0.0.1")
    ep = "127.0.0.1:%d" % server.port
    fls = [Fleet().init(RoleMaker(rank=r, world=2, store_endpoint=ep))
           for r in range(2)]
    admin = TcpStoreClient("127.0.0.1", server.port)
    try:
        for i in range(5):
            f = pool.submit(fls[1].all_gather, np.array([i + 10]))
            got = fls[0].all_gather(np.array([i]))
            f.result()
            assert int(got[1][0]) == i + 10   # collective still correct
        f = pool.submit(fls[1].barrier_worker)
        fls[0].barrier_worker()
        f.result()
        run, s = fls[0]._run_id, fls[0]._seq
        for q in range(1, s - 1):
            assert admin.counter("%s/coll/%d/ack" % (run, q)) == 0
            assert admin.counter("%s/barrier/%d" % (run, q)) == 0
        live = [admin.counter("%s/coll/%d/ack" % (run, q))
                + admin.counter("%s/barrier/%d" % (run, q))
                for q in (s - 1, s)]
        assert all(c == 2 for c in live), live
    finally:
        admin.close()
        for fl in fls:
            fl.stop()
        server.stop()


def test_hostplane_flag_validated():
    """A hostplane typo must fail loud, not silently select the slow
    store funnel; case/whitespace variants normalize."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.fleet.mesh_comm import resolve_hostplane
    assert resolve_hostplane() == "p2p"          # the default
    flags.set_flag("hostplane", "P2P ")
    assert resolve_hostplane() == "p2p"
    flags.set_flag("hostplane", "store")
    assert resolve_hostplane() == "store"
    flags.set_flag("hostplane", "p2pp")
    with pytest.raises(ValueError, match="hostplane"):
        resolve_hostplane()


def test_rpc_client_timeout_and_nodelay():
    """Satellite regression: FramedClient must HONOR its timeout arg at
    connect time (it used to hardcode 60s) and set TCP_NODELAY on the
    small-framed per-step connections."""
    from paddlebox_tpu.utils.rpc import FramedClient, FramedServer
    server = FramedServer(lambda req: req, host="127.0.0.1")
    try:
        c = FramedClient("127.0.0.1", server.port, timeout=7.5)
        assert c._sock.gettimeout() == 7.5
        assert c._sock.getsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY) != 0
        assert c.call({"op": "echo"}) == {"op": "echo"}
        c.close()
    finally:
        server.stop()


@pytest.mark.slow
def test_three_process_exchange_parity():
    """REAL 3-process localhost cluster (uneven shard ownership: 3|3|2
    of 8 mesh positions): every worker runs the full ladder in parity
    mode — store vs p2p vs p2p+uid products must be bit-identical."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.hostplane_probe import run_world
    r = run_world(world=3, kb=512, steps=1, runs=1, parity_only=True,
                  timeout=300.0)
    assert r["tiers"] == {"parity": "ok"}, r
