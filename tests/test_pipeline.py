"""GPipe pipeline over the stage axis: exactness vs the sequential oracle
(the property SectionWorker's scope-queue schedule guarantees by
construction) and end-to-end learning with stage-sharded adam."""

import numpy as np
import jax
import pytest

from paddlebox_tpu.parallel.pipeline import (GPipeRunner, PipelineConfig,
                                             mlp_stage_apply)


@pytest.fixture(scope="module")
def runner():
    return GPipeRunner(PipelineConfig(n_stages=4, n_micro=8, d_model=16,
                                      layers_per_stage=2, lr=1e-2), seed=3)


def test_pipeline_matches_sequential(runner):
    rng = np.random.RandomState(0)
    x = rng.randn(8 * 4, 16).astype(np.float32)
    got = np.asarray(runner.forward(x))
    want = np.asarray(runner.sequential_forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_bubble_does_not_corrupt(runner):
    """Micro-batch count not divisible into the pipe depth: every
    micro-batch must still come out exact (drain ticks are masked)."""
    r = GPipeRunner(PipelineConfig(n_stages=4, n_micro=5, d_model=16,
                                   layers_per_stage=1), seed=5)
    rng = np.random.RandomState(1)
    x = rng.randn(5 * 3, 16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(r.forward(x)),
                               np.asarray(r.sequential_forward(x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_trains(runner):
    rng = np.random.RandomState(2)
    x = rng.randn(8 * 4, 16).astype(np.float32)
    # target: a fixed random rotation of the input
    w = rng.randn(16, 16).astype(np.float32) * 0.5
    y = np.tanh(x @ w)
    losses = [runner.train_step(x, y) for _ in range(150)]
    # correctness is pinned by the exactness + grad-oracle tests; this just
    # checks the stage-sharded adam actually descends
    assert losses[-1] < 0.85 * losses[0], (losses[0], losses[-1])


def test_pipeline_grads_match_sequential():
    """Backward through scan+ppermute == backward through the plain
    composition (checked via loss after one identical step)."""
    cfg = PipelineConfig(n_stages=2, n_micro=4, d_model=8,
                         layers_per_stage=1, lr=1e-2)
    r = GPipeRunner(cfg, seed=7)
    rng = np.random.RandomState(3)
    x = rng.randn(4 * 2, 8).astype(np.float32)
    y = rng.randn(4 * 2, 8).astype(np.float32)

    # oracle grads on the same stacked params, sequential composition
    import jax.numpy as jnp
    params0 = jax.tree.map(np.asarray, r.params)

    def seq_loss(params):
        out = jnp.asarray(x)
        for s in range(cfg.n_stages):
            p = jax.tree.map(lambda a: a[s], params)
            out = mlp_stage_apply(p, out)
        return jnp.mean(jnp.square(out - y))

    want = jax.grad(seq_loss)(params0)

    # pipeline step then recover the applied update direction: compare
    # param delta signs/magnitudes via a fresh manual adam step on oracle
    # grads (same optimizer state = zeros)
    import optax
    opt = optax.adam(cfg.lr)
    upd, _ = opt.update(want, opt.init(params0), params0)
    want_params = optax.apply_updates(params0, upd)
    r.train_step(x, y)
    got_params = jax.tree.map(np.asarray, r.params)
    for wp, gp in zip(jax.tree.leaves(want_params),
                      jax.tree.leaves(got_params)):
        np.testing.assert_allclose(gp, wp, rtol=1e-4, atol=1e-5)
