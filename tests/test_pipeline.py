"""GPipe pipeline over the stage axis: exactness vs the sequential oracle
(the property SectionWorker's scope-queue schedule guarantees by
construction) and end-to-end learning with stage-sharded adam."""

import numpy as np
import jax
import pytest

from paddlebox_tpu.parallel.pipeline import (GPipeRunner, PipelineConfig,
                                             mlp_stage_apply)


@pytest.fixture(scope="module")
def runner():
    return GPipeRunner(PipelineConfig(n_stages=4, n_micro=8, d_model=16,
                                      layers_per_stage=2, lr=1e-2), seed=3)


def test_pipeline_matches_sequential(runner):
    rng = np.random.RandomState(0)
    x = rng.randn(8 * 4, 16).astype(np.float32)
    got = np.asarray(runner.forward(x))
    want = np.asarray(runner.sequential_forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_bubble_does_not_corrupt(runner):
    """Micro-batch count not divisible into the pipe depth: every
    micro-batch must still come out exact (drain ticks are masked)."""
    r = GPipeRunner(PipelineConfig(n_stages=4, n_micro=5, d_model=16,
                                   layers_per_stage=1), seed=5)
    rng = np.random.RandomState(1)
    x = rng.randn(5 * 3, 16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(r.forward(x)),
                               np.asarray(r.sequential_forward(x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_trains(runner):
    rng = np.random.RandomState(2)
    x = rng.randn(8 * 4, 16).astype(np.float32)
    # target: a fixed random rotation of the input
    w = rng.randn(16, 16).astype(np.float32) * 0.5
    y = np.tanh(x @ w)
    losses = [runner.train_step(x, y) for _ in range(150)]
    # correctness is pinned by the exactness + grad-oracle tests; this just
    # checks the stage-sharded adam actually descends
    assert losses[-1] < 0.85 * losses[0], (losses[0], losses[-1])


def test_pipeline_grads_match_sequential():
    """Backward through scan+ppermute == backward through the plain
    composition (checked via loss after one identical step)."""
    cfg = PipelineConfig(n_stages=2, n_micro=4, d_model=8,
                         layers_per_stage=1, lr=1e-2)
    r = GPipeRunner(cfg, seed=7)
    rng = np.random.RandomState(3)
    x = rng.randn(4 * 2, 8).astype(np.float32)
    y = rng.randn(4 * 2, 8).astype(np.float32)

    # oracle grads on the same stacked params, sequential composition
    import jax.numpy as jnp
    params0 = jax.tree.map(np.asarray, r.params)

    def seq_loss(params):
        out = jnp.asarray(x)
        for s in range(cfg.n_stages):
            p = jax.tree.map(lambda a: a[s], params)
            out = mlp_stage_apply(p, out)
        return jnp.mean(jnp.square(out - y))

    want = jax.grad(seq_loss)(params0)

    # pipeline step then recover the applied update direction: compare
    # param delta signs/magnitudes via a fresh manual adam step on oracle
    # grads (same optimizer state = zeros)
    import optax
    opt = optax.adam(cfg.lr)
    upd, _ = opt.update(want, opt.init(params0), params0)
    want_params = optax.apply_updates(params0, upd)
    r.train_step(x, y)
    got_params = jax.tree.map(np.asarray, r.params)
    for wp, gp in zip(jax.tree.leaves(want_params),
                      jax.tree.leaves(got_params)):
        np.testing.assert_allclose(gp, wp, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- CTR pipe


def _ctr_setup(tmp_path_factory_or_dir, n_files=2, lines=320, mb=16):
    import dataclasses
    from paddlebox_tpu.data import write_synthetic_ctr_files
    files, feed = write_synthetic_ctr_files(
        str(tmp_path_factory_or_dir), num_files=n_files,
        lines_per_file=lines, num_slots=4, vocab_per_slot=100, max_len=3,
        seed=7)
    return files, dataclasses.replace(feed, batch_size=mb)


def _ctr_table(cap=1 << 12, expand=0):
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    return TableConfig(
        embedx_dim=4, pass_capacity=cap, expand_embed_dim=expand,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=1e9,  # no rng
                                        mf_initial_range=0.0,
                                        feature_learning_rate=0.05,
                                        mf_learning_rate=0.05))


def test_ctr_pipeline_matches_sequential_oracle(tmp_path):
    """Gradient parity (VERDICT r2 #3): one pipelined step over a REAL
    sparse batch must produce the same params AND the same slab (push
    included) as the sequential single-chip composition of the same
    stages."""
    import jax.numpy as jnp
    import optax
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import build_push_grads, pull_sparse
    from paddlebox_tpu.parallel.pipeline import CtrPipelineRunner

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=64, mb=16)
    table_cfg = _ctr_table()
    S, L, M = 4, 1, 4
    r = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                          layers_per_stage=L, lr=1e-2, n_micro=M, seed=3)
    params0 = {k: np.asarray(v) for k, v in r.params.items()}
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    r.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=r.table.add_keys)
    r.table.end_feed_pass()
    r.table.begin_pass()
    slab0 = np.asarray(r.table.slab)
    batches = ds.split_batches(num_workers=1)[0][:M]
    batch = jax.tree.map(np.asarray, r.device_batch(batches))
    batch["key_valid"] = batch["ids"] != r.table.padding_id
    prng0 = np.asarray(r._prng)

    loss_pipe = r.train_step(batches)
    slab_pipe = np.asarray(r.table.slab)

    # ---- sequential oracle: same math, no pipeline, single device
    layout, conf = r.layout, table_cfg.optimizer
    num_slots, mb = r.num_slots, r.mb
    K = batch["ids"].shape[-1]

    def oracle_loss(p, emb_all):
        logits = []
        for t in range(M):
            pooled = fused_seqpool_cvm(
                emb_all[t], jnp.asarray(batch["segments"][t]),
                jnp.asarray(batch["key_valid"][t]), mb, num_slots, True,
                sorted_segments=True)
            x = jax.nn.relu(pooled.reshape(mb, -1) @ p["proj_w"][0]
                            + p["proj_b"][0])
            for s in range(S):
                for i in range(L):
                    x = jax.nn.relu(x @ p["blk_w"][s, i] + p["blk_b"][s, i])
            logits.append(x @ p["head_w"][S - 1] + p["head_b"][S - 1])
        logits = jnp.stack(logits)
        lab = jnp.asarray(batch["labels"]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    ids_flat = jnp.asarray(batch["ids"].reshape(-1))
    emb_all = pull_sparse(jnp.asarray(slab0), ids_flat,
                          layout).reshape(M, K, -1)
    loss_o, (dp, demb) = jax.value_and_grad(oracle_loss, argnums=(0, 1))(
        {k: jnp.asarray(v) for k, v in params0.items()}, emb_all)
    np.testing.assert_allclose(loss_pipe, float(loss_o), rtol=1e-5)

    # params: per-stage adam with local grads == runner's sharded update
    opt = optax.adam(1e-2)
    p0 = {k: jnp.asarray(v) for k, v in params0.items()}
    upd, _ = opt.update(dp, opt.init(p0), p0)
    want_params = optax.apply_updates(p0, upd)
    for k in want_params:
        np.testing.assert_allclose(np.asarray(r.params[k]),
                                   np.asarray(want_params[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    # slab: same push (same prng stream as the runner consumed)
    _, sub = jax.random.split(jnp.asarray(prng0))
    ins = batch["segments"] // num_slots
    m_off = (np.arange(M, dtype=ins.dtype) * mb)[:, None]
    clicks = batch["labels"].reshape(-1)[(ins + m_off).reshape(-1)]
    slots = (batch["segments"] % num_slots).reshape(-1)
    kv = batch["key_valid"].reshape(-1)
    pg = build_push_grads(demb.reshape(M * K, -1), jnp.asarray(slots),
                          jnp.asarray(clicks), jnp.asarray(kv))
    want_slab = push_sparse_dedup(jnp.asarray(slab0), ids_flat, pg, sub,
                                  layout, conf)
    np.testing.assert_allclose(slab_pipe, np.asarray(want_slab),
                               rtol=2e-4, atol=1e-6)


def test_ctr_pipeline_learns(tmp_path):
    """A CtrDnn-class tower split across 4 stages trains end to end:
    loss descends over passes and the pass cadence (feed → slab → steps →
    write-back) leaves trained rows in the store."""
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding import accessor as acc
    from paddlebox_tpu.parallel.pipeline import CtrPipelineRunner

    files, feed = _ctr_setup(tmp_path, n_files=2, lines=320, mb=16)
    r = CtrPipelineRunner(_ctr_table(), feed, n_stages=4, d_model=24,
                          layers_per_stage=1, lr=5e-3, n_micro=8, seed=0)
    losses = []
    for _ in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = r.train_pass(ds)
        losses.append(stats["loss"])
        ds.release_memory()
    assert stats["steps"] >= 4
    assert losses[-1] < losses[0] - 0.01, losses
    keys, vals = r.table.store.state_items()
    assert keys.size > 50
    assert vals[:, acc.SHOW].sum() > 0      # write-back happened


def test_factory_resolves_pipeline_trainers(tmp_path):
    """Reference trainer names resolve: PipelineTrainer → the GPipe
    runner; HeterPipelineTrainer/CtrPipelineTrainer → the CTR program
    split (trainer_factory.cc:68-89 name surface)."""
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 GPipeRunner,
                                                 PipelineConfig)
    from paddlebox_tpu.train.factory import create_trainer

    r = create_trainer("PipelineTrainer",
                       PipelineConfig(n_stages=2, n_micro=4, d_model=8,
                                      layers_per_stage=1), seed=0)
    assert isinstance(r, GPipeRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=64, mb=16)
    r2 = create_trainer("HeterPipelineTrainer", _ctr_table(), feed,
                        n_stages=2, d_model=16, n_micro=4, seed=0)
    assert isinstance(r2, CtrPipelineRunner)


# tier-1 budget (round-10 headroom audit, 9.9s): dp-composition
# parity is covered by test_sharded_ctr_pipeline_dp_composition and
# dp learning by test_ctr_pipeline_dp_learns; this oracle variant
# re-runs the same composition. Runs in the slow-inclusive suite
# and on TPU windows
@pytest.mark.slow
def test_ctr_pipeline_dp_composition_matches_oracle(tmp_path):
    """(dp, stage) mesh: each dp row pipelines its OWN micro-batch group,
    stage-block grads average over dp (per-step data-parallel sync), and
    ONE combined push applies every row's sparse grads. Exact parity with
    the sequential oracle."""
    import jax.numpy as jnp
    import optax
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import build_push_grads, pull_sparse
    from paddlebox_tpu.parallel.pipeline import STAGE_AXIS, CtrPipelineRunner
    from jax.sharding import Mesh

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=128, mb=16)
    table_cfg = _ctr_table()
    S, L, M, DP = 2, 1, 4, 2
    mesh = Mesh(np.array(jax.devices()[:DP * S]).reshape(DP, S),
                ("dp", STAGE_AXIS))
    r = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                          layers_per_stage=L, lr=1e-2, n_micro=M,
                          mesh=mesh, seed=3)
    assert r.dp == DP and r.batches_per_step == DP * M
    params0 = {k: np.asarray(v) for k, v in r.params.items()}
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    r.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=r.table.add_keys)
    r.table.end_feed_pass()
    r.table.begin_pass()
    slab0 = np.asarray(r.table.slab)
    batches = ds.split_batches(num_workers=1)[0][:DP * M]
    batch = jax.tree.map(np.asarray, r.device_batch(batches))  # [DP, M, ...]
    batch["key_valid"] = batch["ids"] != r.table.padding_id
    prng0 = np.asarray(r._prng)

    loss_pipe = r.train_step(batches)
    slab_pipe = np.asarray(r.table.slab)

    # ---- sequential oracle: per-row grads → mean → adam; combined push
    layout, conf = r.layout, table_cfg.optimizer
    num_slots, mb = r.num_slots, r.mb
    K = batch["ids"].shape[-1]

    def row_loss(p, emb_all, g):
        logits = []
        for t in range(M):
            pooled = fused_seqpool_cvm(
                emb_all[t], jnp.asarray(batch["segments"][g, t]),
                jnp.asarray(batch["key_valid"][g, t]), mb, num_slots, True,
                sorted_segments=True)
            x = jax.nn.relu(pooled.reshape(mb, -1) @ p["proj_w"][0]
                            + p["proj_b"][0])
            for s in range(S):
                for i in range(L):
                    x = jax.nn.relu(x @ p["blk_w"][s, i] + p["blk_b"][s, i])
            logits.append(x @ p["head_w"][S - 1] + p["head_b"][S - 1])
        logits = jnp.stack(logits)
        lab = jnp.asarray(batch["labels"][g]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"][g])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    p0 = {k: jnp.asarray(v) for k, v in params0.items()}
    losses, dps, pgs, ids_rows = [], [], [], []
    for g in range(DP):
        ids_g = jnp.asarray(batch["ids"][g].reshape(-1))
        emb_g = pull_sparse(jnp.asarray(slab0), ids_g, layout
                            ).reshape(M, K, -1)
        loss_g, (dp_g, demb_g) = jax.value_and_grad(
            row_loss, argnums=(0, 1))(p0, emb_g, g)
        losses.append(float(loss_g))
        dps.append(dp_g)
        ins = batch["segments"][g] // num_slots
        m_off = (np.arange(M, dtype=ins.dtype) * mb)[:, None]
        clicks = batch["labels"][g].reshape(-1)[(ins + m_off).reshape(-1)]
        slots = (batch["segments"][g] % num_slots).reshape(-1)
        kv = batch["key_valid"][g].reshape(-1)
        pgs.append(build_push_grads(demb_g.reshape(M * K, -1),
                                    jnp.asarray(slots), jnp.asarray(clicks),
                                    jnp.asarray(kv)))
        ids_rows.append(ids_g)

    np.testing.assert_allclose(loss_pipe, np.mean(losses), rtol=1e-5)
    dp_mean = jax.tree.map(lambda *xs: sum(xs) / DP, *dps)
    opt = optax.adam(1e-2)
    upd, _ = opt.update(dp_mean, opt.init(p0), p0)
    want_params = optax.apply_updates(p0, upd)
    for k in want_params:
        np.testing.assert_allclose(np.asarray(r.params[k]),
                                   np.asarray(want_params[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    _, sub = jax.random.split(jnp.asarray(prng0))
    want_slab = push_sparse_dedup(
        jnp.asarray(slab0), jnp.concatenate(ids_rows),
        jnp.concatenate(pgs), sub, layout, conf)
    np.testing.assert_allclose(slab_pipe, np.asarray(want_slab),
                               rtol=2e-4, atol=1e-6)


# tier-1 budget: the capability this composes is covered by its own
# dedicated suite (expand: test_expand_e2e, multi-task:
# test_multitask_labels, data_norm: test_data_norm_e2e, metrics:
# test_metrics); the through-the-pipe composition runs in the
# slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_ctr_pipeline_expand_oracle_and_sharded_parity(tmp_path):
    """Expand (NN-cross) through the pipeline (the round-3 'explicitly
    rejected' edge): one pipelined step with the dual-output extended
    pull must equal the sequential oracle — params AND slab including
    the expand-block gradients — and the sharded-slab runner must match
    the replicated one over full passes."""
    import jax.numpy as jnp
    import optax
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm, seqpool_sum
    from paddlebox_tpu.ops.sparse import (build_push_grads_extended,
                                          pull_sparse_extended)
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=128, mb=16)
    Ex = 3
    table_cfg = _ctr_table(expand=Ex)
    S, L, M = 4, 1, 4
    r = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                          layers_per_stage=L, lr=1e-2, n_micro=M, seed=3)
    params0 = {k: np.asarray(v) for k, v in r.params.items()}
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    r.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=r.table.add_keys)
    r.table.end_feed_pass()
    r.table.begin_pass()
    slab0 = np.asarray(r.table.slab)
    batches = ds.split_batches(num_workers=1)[0][:M]
    batch = jax.tree.map(np.asarray, r.device_batch(batches))
    batch["key_valid"] = batch["ids"] != r.table.padding_id
    prng0 = np.asarray(r._prng)

    loss_pipe = r.train_step(batches)
    slab_pipe = np.asarray(r.table.slab)

    # ---- sequential oracle with the extended pull + expand push
    layout, conf = r.layout, table_cfg.optimizer
    num_slots, mb = r.num_slots, r.mb
    K = batch["ids"].shape[-1]

    def oracle_loss(p, emb_all, exp_all):
        logits = []
        for t in range(M):
            pooled = fused_seqpool_cvm(
                emb_all[t], jnp.asarray(batch["segments"][t]),
                jnp.asarray(batch["key_valid"][t]), mb, num_slots, True,
                sorted_segments=True)
            pexp = seqpool_sum(exp_all[t],
                               jnp.asarray(batch["segments"][t]),
                               jnp.asarray(batch["key_valid"][t]), mb,
                               num_slots)
            x = jnp.concatenate([pooled.reshape(mb, -1),
                                 pexp.reshape(mb, -1)], axis=-1)
            x = jax.nn.relu(x @ p["proj_w"][0] + p["proj_b"][0])
            for s in range(S):
                for i in range(L):
                    x = jax.nn.relu(x @ p["blk_w"][s, i] + p["blk_b"][s, i])
            logits.append(x @ p["head_w"][S - 1] + p["head_b"][S - 1])
        logits = jnp.stack(logits)
        lab = jnp.asarray(batch["labels"]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    ids_flat = jnp.asarray(batch["ids"].reshape(-1))
    base, exp = pull_sparse_extended(jnp.asarray(slab0), ids_flat, layout)
    emb_all = base.reshape(M, K, -1)
    exp_all = exp.reshape(M, K, Ex)
    loss_o, (dp, demb, dexp) = jax.value_and_grad(
        oracle_loss, argnums=(0, 1, 2))(
        {k: jnp.asarray(v) for k, v in params0.items()}, emb_all, exp_all)
    np.testing.assert_allclose(loss_pipe, float(loss_o), rtol=1e-5)

    opt = optax.adam(1e-2)
    p0 = {k: jnp.asarray(v) for k, v in params0.items()}
    upd, _ = opt.update(dp, opt.init(p0), p0)
    want_params = optax.apply_updates(p0, upd)
    for k in want_params:
        np.testing.assert_allclose(np.asarray(r.params[k]),
                                   np.asarray(want_params[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    _, sub = jax.random.split(jnp.asarray(prng0))
    ins = batch["segments"] // num_slots
    m_off = (np.arange(M, dtype=ins.dtype) * mb)[:, None]
    clicks = batch["labels"].reshape(-1)[(ins + m_off).reshape(-1)]
    slots = (batch["segments"] % num_slots).reshape(-1)
    kv = batch["key_valid"].reshape(-1)
    pg = build_push_grads_extended(
        demb.reshape(M * K, -1), dexp.reshape(M * K, Ex),
        jnp.asarray(slots), jnp.asarray(clicks), jnp.asarray(kv))
    want_slab = push_sparse_dedup(jnp.asarray(slab0), ids_flat, pg, sub,
                                  layout, conf)
    np.testing.assert_allclose(slab_pipe, np.asarray(want_slab),
                               rtol=2e-4, atol=1e-6)
    ds.release_memory()

    # ---- sharded-slab runner parity over full passes (same seed)
    rep = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                            layers_per_stage=L, lr=1e-2, n_micro=M, seed=5)
    shd = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                                   layers_per_stage=L, lr=1e-2, n_micro=M,
                                   seed=5)
    stats = []
    for rr in (rep, shd):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats.append(rr.train_pass(ds))
        ds.release_memory()
    np.testing.assert_allclose(stats[1]["loss"], stats[0]["loss"],
                               rtol=1e-5)
    rk, rv = rep.table.store.state_items()
    sk, sv = shd.table.store_view().state_items()
    ro, so = np.argsort(rk), np.argsort(sk)
    np.testing.assert_array_equal(rk[ro], sk[so])
    np.testing.assert_allclose(sv[so], rv[ro], rtol=2e-4, atol=1e-6)


# tier-1 budget: the capability this composes is covered by its own
# dedicated suite (expand: test_expand_e2e, multi-task:
# test_multitask_labels, data_norm: test_data_norm_e2e, metrics:
# test_metrics); the through-the-pipe composition runs in the
# slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_ctr_pipeline_multi_task(tmp_path):
    """ESMM-style multi-task through the pipeline: the last stage's head
    emits T logits per instance trained on per-task labels. One
    pipelined step equals the sequential multi-task oracle; the sharded
    runner matches the replicated one; per-task metric columns stream."""
    import dataclasses
    import jax.numpy as jnp
    import optax
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import pull_sparse
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=128, num_slots=4,
        vocab_per_slot=100, max_len=3, seed=7, conversion=True)
    feed = dataclasses.replace(feed, batch_size=16)
    table_cfg = _ctr_table()
    S, L, M = 4, 1, 4
    TASKS = ("ctr", "cvr")
    r = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                          layers_per_stage=L, lr=1e-2, n_micro=M, seed=3,
                          task_names=TASKS)
    params0 = {k: np.asarray(v) for k, v in r.params.items()}
    assert params0["head_w"].shape == (S, 24, 2)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    r.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=r.table.add_keys)
    r.table.end_feed_pass()
    r.table.begin_pass()
    slab0 = np.asarray(r.table.slab)
    batches = ds.split_batches(num_workers=1)[0][:M]
    batch = jax.tree.map(np.asarray, r.device_batch(batches))
    batch["key_valid"] = batch["ids"] != r.table.padding_id

    loss_pipe = r.train_step(batches)

    # ---- sequential multi-task oracle (loss only — the params/slab
    # machinery is pinned by the single-task oracle tests; here the new
    # surface is the T-logit head + summed per-task loss)
    layout = r.layout
    num_slots, mb = r.num_slots, r.mb
    K = batch["ids"].shape[-1]

    def oracle_loss(p, emb_all):
        logits = []
        for t in range(M):
            pooled = fused_seqpool_cvm(
                emb_all[t], jnp.asarray(batch["segments"][t]),
                jnp.asarray(batch["key_valid"][t]), mb, num_slots, True,
                sorted_segments=True)
            x = jax.nn.relu(pooled.reshape(mb, -1) @ p["proj_w"][0]
                            + p["proj_b"][0])
            for s in range(S):
                for i in range(L):
                    x = jax.nn.relu(x @ p["blk_w"][s, i] + p["blk_b"][s, i])
            logits.append(x @ p["head_w"][S - 1] + p["head_b"][S - 1])
        logits = jnp.stack(logits)                       # [M, mb, 2]
        iv = jnp.asarray(batch["ins_valid"])
        denom = jnp.maximum(iv.sum(), 1.0)
        loss = 0.0
        for ti, t in enumerate(TASKS):
            lab = jnp.asarray(batch["labels_" + t]).astype(jnp.float32)
            bce = optax.sigmoid_binary_cross_entropy(logits[..., ti], lab)
            loss = loss + jnp.where(iv, bce, 0.0).sum() / denom
        return loss

    ids_flat = jnp.asarray(batch["ids"].reshape(-1))
    emb_all = pull_sparse(jnp.asarray(slab0), ids_flat,
                          layout).reshape(M, K, -1)
    loss_o = float(oracle_loss(
        {k: jnp.asarray(v) for k, v in params0.items()}, emb_all))
    np.testing.assert_allclose(loss_pipe, loss_o, rtol=1e-5)
    ds.release_memory()

    # ---- replicated vs sharded parity + per-task metric stream
    rep = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                            layers_per_stage=L, lr=1e-2, n_micro=M,
                            seed=5, task_names=TASKS)
    shd = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                                   layers_per_stage=L, lr=1e-2, n_micro=M,
                                   seed=5, task_names=TASKS)
    rep.metrics.init_metric("auc_cvr", "label_cvr", "pred_cvr",
                            table_size=1 << 14, mask_var="mask")
    stats = []
    for rr in (rep, shd):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats.append(rr.train_pass(ds))
        ds.release_memory()
    np.testing.assert_allclose(stats[1]["loss"], stats[0]["loss"],
                               rtol=1e-5)
    msg = rep.metrics.get_metric_msg("auc_cvr")
    assert msg["size"] > 0      # the cvr column streamed


# tier-1 budget: the capability this composes is covered by its own
# dedicated suite (expand: test_expand_e2e, multi-task:
# test_multitask_labels, data_norm: test_data_norm_e2e, metrics:
# test_metrics); the through-the-pipe composition runs in the
# slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_ctr_pipeline_data_norm(tmp_path):
    """data_norm through the pipeline: stage 0 normalizes its projection
    input by the running summaries, which update by the running-sums
    rule (never the optimizer). One step matches the hand-computed rule;
    the sharded runner matches the replicated one with dn on."""
    import jax.numpy as jnp
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.ops.data_norm import (DataNormState, data_norm,
                                             data_norm_summary_update)
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import pull_sparse
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=128, mb=16)
    table_cfg = _ctr_table()
    S, M = 4, 4
    r = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                          layers_per_stage=1, lr=1e-2, n_micro=M, seed=3,
                          use_data_norm=True)
    assert r.params["dn_size"].shape[0] == S
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    r.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=r.table.add_keys)
    r.table.end_feed_pass()
    r.table.begin_pass()
    slab0 = np.asarray(r.table.slab)
    batches = ds.split_batches(num_workers=1)[0][:M]
    batch = jax.tree.map(np.asarray, r.device_batch(batches))
    key_valid = batch["ids"] != r.table.padding_id
    dn0 = DataNormState(jnp.asarray(r.params["dn_size"][0]),
                        jnp.asarray(r.params["dn_sum"][0]),
                        jnp.asarray(r.params["dn_sqsum"][0]))

    loss_pipe = r.train_step(batches)

    # hand-computed oracle: assemble all M micros' proj inputs, apply
    # the running-sums rule to the INITIAL state (the step normalizes
    # with the pre-update summaries and updates after)
    layout = r.layout
    K = batch["ids"].shape[-1]
    emb_all = pull_sparse(jnp.asarray(slab0),
                          jnp.asarray(batch["ids"].reshape(-1)),
                          layout).reshape(M, K, -1)
    xs = []
    for t in range(M):
        pooled = fused_seqpool_cvm(
            emb_all[t], jnp.asarray(batch["segments"][t]),
            jnp.asarray(key_valid[t]), 16, r.num_slots, True,
            sorted_segments=True)
        xs.append(pooled.reshape(16, -1))
    x_all = jnp.concatenate(xs, axis=0)
    want = data_norm_summary_update(dn0, x_all, decay=r.dn_decay)
    np.testing.assert_allclose(np.asarray(r.params["dn_size"][0]),
                               np.asarray(want.batch_size), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(r.params["dn_sum"][0]),
                               np.asarray(want.batch_sum), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(r.params["dn_sqsum"][0]),
                               np.asarray(want.batch_square_sum),
                               rtol=1e-5, atol=1e-6)
    # the forward actually normalizes: shifting the running mean (a
    # poisoned dn_sum) must move the predictions — a deterministic probe
    # of the normalization being INSIDE the compiled program (loss-level
    # A/B at near-init weights sits below f32 resolution)
    dev_batch = r.device_batch(batches)
    ev_norm = np.asarray(r._eval(r.params, r.table.slab, dev_batch))
    poisoned = dict(r.params,
                    dn_sum=jnp.full_like(r.params["dn_sum"], 1e5))
    ev_poison = np.asarray(r._eval(poisoned, r.table.slab, dev_batch))
    assert np.abs(ev_norm - ev_poison).max() > 1e-5
    assert np.isfinite(loss_pipe)
    ds.release_memory()

    # replicated vs sharded parity with dn on, over a full pass
    rep = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                            layers_per_stage=1, lr=1e-2, n_micro=M,
                            seed=5, use_data_norm=True)
    shd = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                                   layers_per_stage=1, lr=1e-2, n_micro=M,
                                   seed=5, use_data_norm=True)
    stats = []
    for rr in (rep, shd):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats.append(rr.train_pass(ds))
        ds.release_memory()
    np.testing.assert_allclose(stats[1]["loss"], stats[0]["loss"],
                               rtol=1e-5)
    for k in ("dn_size", "dn_sum", "dn_sqsum"):
        np.testing.assert_allclose(np.asarray(shd.params[k]),
                                   np.asarray(rep.params[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_sharded_ctr_pipeline_matches_replicated(tmp_path):
    """Pipeline × sharded-table composition (the round-3 verdict's one
    remaining partial): the key-mod-sharded slab behind the SAME pipeline
    program must train identically to the replicated-slab runner — same
    per-pass losses, same stage params, same store rows — while each
    device holds only O(pass/P) table memory."""
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=192, mb=16)
    table_cfg = _ctr_table(cap=1 << 12)
    S, M = 4, 4
    rep = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                            layers_per_stage=1, lr=1e-2, n_micro=M, seed=3)
    shd = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                                   layers_per_stage=1, lr=1e-2, n_micro=M,
                                   seed=3)
    # same-seed init is bit-identical (shared ctr_stage_host_params)
    for k in rep.params:
        np.testing.assert_array_equal(np.asarray(rep.params[k]),
                                      np.asarray(shd.params[k]))
    # per-device slab is 1/P of the pass capacity
    assert shd.table.shard_cap == table_cfg.pass_capacity // S

    for _ in range(2):
        stats = []
        for r in (rep, shd):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            stats.append(r.train_pass(ds))
            ds.release_memory()
        assert stats[0]["steps"] == stats[1]["steps"] >= 2
        np.testing.assert_allclose(stats[1]["loss"], stats[0]["loss"],
                                   rtol=1e-5)

    for k in rep.params:
        np.testing.assert_allclose(np.asarray(shd.params[k]),
                                   np.asarray(rep.params[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)
    rk, rv = rep.table.store.state_items()
    sk, sv = shd.table.store_view().state_items()
    ro, so = np.argsort(rk), np.argsort(sk)
    np.testing.assert_array_equal(rk[ro], sk[so])
    np.testing.assert_allclose(sv[so], rv[ro], rtol=2e-4, atol=1e-6)


def test_sharded_ctr_pipeline_dp_composition(tmp_path):
    """(dp, stage) mesh with the table sharded over ALL devices: the
    shard-side dedup merges cross-row duplicate keys (no push all_gather)
    — parity with the replicated dp runner on the same batches."""
    from jax.sharding import Mesh
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.parallel.pipeline import (STAGE_AXIS,
                                                 CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=192, mb=16)
    table_cfg = _ctr_table(cap=1 << 12)
    S, M, DP = 2, 4, 2
    mesh = Mesh(np.array(jax.devices()[:DP * S]).reshape(DP, S),
                ("dp", STAGE_AXIS))
    rep = CtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                            layers_per_stage=1, lr=1e-2, n_micro=M,
                            mesh=mesh, seed=3)
    shd = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=S, d_model=24,
                                   layers_per_stage=1, lr=1e-2, n_micro=M,
                                   mesh=mesh, seed=3)
    assert shd.dp == DP and shd.batches_per_step == DP * M
    assert shd.P == DP * S          # table shards over every device
    stats = []
    for r in (rep, shd):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats.append(r.train_pass(ds))
        ds.release_memory()
    assert stats[0]["steps"] == stats[1]["steps"] >= 1
    np.testing.assert_allclose(stats[1]["loss"], stats[0]["loss"],
                               rtol=1e-5)
    for k in rep.params:
        np.testing.assert_allclose(np.asarray(shd.params[k]),
                                   np.asarray(rep.params[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)
    rk, rv = rep.table.store.state_items()
    sk, sv = shd.table.store_view().state_items()
    ro, so = np.argsort(rk), np.argsort(sk)
    np.testing.assert_array_equal(rk[ro], sk[so])
    np.testing.assert_allclose(sv[so], rv[ro], rtol=2e-4, atol=1e-6)


# tier-1 budget: the capability this composes is covered by its own
# dedicated suite (expand: test_expand_e2e, multi-task:
# test_multitask_labels, data_norm: test_data_norm_e2e, metrics:
# test_metrics); the through-the-pipe composition runs in the
# slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_pipeline_metrics_and_eval(tmp_path):
    """Both pipeline runners stream training predictions into the metric
    registry (Metric::add_data role) and serve test-mode inference
    (SetTestMode: no creation, no push): AUC lifts above chance after
    training, eval covers the dataset's grouped instances, and the store
    is untouched by eval."""
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.metrics.auc import BasicAucCalculator
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=2, lines=320, mb=16)
    for cls in (CtrPipelineRunner, ShardedCtrPipelineRunner):
        r = cls(_ctr_table(), feed, n_stages=4, d_model=24,
                layers_per_stage=1, lr=5e-3, n_micro=8, seed=0)
        r.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                              mask_var="mask")
        covered = 0
        for _ in range(4):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            stats = r.train_pass(ds)
            covered += stats["steps"] * r.batches_per_step * feed.batch_size
            ds.release_memory()
        msg = r.metrics.get_metric_msg("auc")
        # plumbing invariants (model quality is pinned by the loss-descent
        # tests): every trained instance streamed exactly once, and the
        # computed AUC is a real value, not the all-one-class sentinel
        assert msg["size"] == covered, (cls.__name__, msg["size"], covered)
        assert msg["auc"] > 0.5, (cls.__name__, msg)
        assert 0.0 < msg["actual_ctr"] < 1.0

        from paddlebox_tpu.embedding import accessor as acc
        store = (r.table.store if cls is CtrPipelineRunner
                 else r.table.store_view())
        keys_before, vals_before = store.state_items()
        show_before = vals_before[:, acc.SHOW].sum()
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        preds, labels = r.predict_batches(ds)
        assert preds.size == labels.size > 200
        assert (preds > 0).all() and (preds < 1).all()
        # eval AUC from the returned pairs beats chance too
        calc = BasicAucCalculator(table_size=1 << 14)
        calc.add_data(preds, labels, np.ones(labels.size, bool))
        calc.compute()
        assert calc.auc() > 0.5, (cls.__name__, calc.auc())
        _k, vals_after = store.state_items()
        assert vals_after[:, acc.SHOW].sum() == show_before, \
            "eval must not push"
        ds.release_memory()


def test_sharded_pipeline_over_gpups_store(tmp_path):
    """Section programs over the distributed CPU PS: the sharded pipeline
    with PS-backed shard stores (pass slabs built from / dumped to the
    server) must match the local-store run exactly — same seeds, same
    losses, rows land server-side."""
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding.ps_store import ps_store_factory
    from paddlebox_tpu.parallel.pipeline import ShardedCtrPipelineRunner
    from paddlebox_tpu.ps import PsLocalClient

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=192, mb=16)
    table_cfg = _ctr_table(cap=1 << 12)

    def run(store_factory=None):
        r = ShardedCtrPipelineRunner(
            table_cfg, feed, n_stages=4, d_model=24, layers_per_stage=1,
            lr=1e-2, n_micro=4, seed=3, store_factory=store_factory)
        losses = []
        for _ in range(2):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(r.train_pass(ds)["loss"])
            ds.release_memory()
        return r, losses

    _local, losses_local = run()
    cl = PsLocalClient()
    cl.create_sparse_table(5, table_cfg, shard_num=4, seed=3)
    _ps, losses_ps = run(ps_store_factory(cl, 5))
    np.testing.assert_allclose(losses_ps, losses_local, rtol=1e-5)
    assert cl.sparse_size(5) > 50    # features created server-side


def test_sharded_pipeline_day_cadence(tmp_path):
    """run_day composes over the sharded pipeline runner: cadenced delta
    saves, base save at day end, and the serving reader resolves trained
    rows from the xbox views."""
    from paddlebox_tpu.config.configs import CheckpointConfig
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding import accessor as acc
    from paddlebox_tpu.parallel.pipeline import ShardedCtrPipelineRunner
    from paddlebox_tpu.train.checkpoint import (CheckpointManager,
                                                XboxModelReader, run_day)

    files, feed = _ctr_setup(tmp_path, n_files=2, lines=192, mb=16)
    r = ShardedCtrPipelineRunner(_ctr_table(cap=1 << 12), feed, n_stages=4,
                                 d_model=24, layers_per_stage=1, lr=1e-2,
                                 n_micro=4, seed=0)
    cm = CheckpointManager(CheckpointConfig(
        batch_model_dir=str(tmp_path / "batch"),
        xbox_model_dir=str(tmp_path / "xbox"),
        save_delta_every_passes=1, async_save=False), r.table)
    datasets = []
    for _ in range(2):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        datasets.append(ds)
    stats, (batch_dir, xbox_dir) = run_day(r, datasets, cm, "d0",
                                           preload=False)
    assert len(stats) == 2 and all(s["steps"] >= 1 for s in stats)
    reader = XboxModelReader(str(tmp_path / "xbox"), "d0")
    assert reader.deltas_applied >= 1
    keys, vals = r.table.store_view().state_items()
    assert keys.size > 50 and vals[:, acc.SHOW].sum() > 0
    hot = keys[np.argsort(vals[:, acc.SHOW])[-5:]]
    rows = reader.lookup(hot)
    assert rows.shape == (5, 1 + 4)
    assert np.abs(rows).sum() > 0


def test_pipeline_dump_fields(tmp_path):
    """DumpField through the pipeline runners: one line per real instance
    covered by a full micro-batch group, rank-tagged files."""
    import os
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.parallel.pipeline import (CtrPipelineRunner,
                                                 ShardedCtrPipelineRunner)

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=192, mb=16)
    for cls in (CtrPipelineRunner, ShardedCtrPipelineRunner):
        dump_dir = str(tmp_path / f"dump_{cls.__name__}")
        r = cls(_ctr_table(), feed, n_stages=4, d_model=24,
                layers_per_stage=1, lr=1e-2, n_micro=4, seed=0,
                dump_fields=("pred", "label"), dump_fields_path=dump_dir)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = r.train_pass(ds)
        r.close()
        assert r.dump_writer is None
        lines = []
        for f in os.listdir(dump_dir):
            lines += [l for l in open(os.path.join(dump_dir, f))
                      if l.strip()]
        covered = stats["steps"] * r.batches_per_step * feed.batch_size
        assert len(lines) == covered > 0, (cls.__name__, len(lines))
        assert all("pred:" in l and "label:" in l for l in lines)
        ds.release_memory()


def test_ctr_pipeline_dp_learns(tmp_path):
    """dp × pipeline end to end: loss descends over passes with the
    combined push keeping the replicated slab consistent."""
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.parallel.pipeline import STAGE_AXIS, CtrPipelineRunner
    from jax.sharding import Mesh

    files, feed = _ctr_setup(tmp_path, n_files=2, lines=320, mb=16)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", STAGE_AXIS))
    r = CtrPipelineRunner(_ctr_table(), feed, n_stages=4, d_model=24,
                          layers_per_stage=1, lr=5e-3, n_micro=4,
                          mesh=mesh, seed=0)
    losses = []
    for _ in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = r.train_pass(ds)
        losses.append(stats["loss"])
        ds.release_memory()
    assert stats["steps"] >= 4
    assert losses[-1] < losses[0] - 0.01, losses


def test_sharded_pipeline_push_write_rebuild_matches_scatter(tmp_path):
    """push_write='rebuild' through the sharded pipeline runner (per-shard
    pos maps staged next to the a2a dedup) must train bit-identically to
    the scatter path."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.parallel.pipeline import ShardedCtrPipelineRunner

    files, feed = _ctr_setup(tmp_path, n_files=1, lines=128, mb=16)
    table_cfg = _ctr_table(cap=1 << 12)
    states = {}
    for mode in ("scatter", "rebuild"):
        flags.set_flag("push_write", mode)
        try:
            r = ShardedCtrPipelineRunner(table_cfg, feed, n_stages=4,
                                         d_model=24, layers_per_stage=1,
                                         lr=1e-2, n_micro=4, seed=6)
            assert r._push_write == mode
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            r.train_pass(ds)
            ks, vs = r.table.store_view().state_items()
            o = np.argsort(ks)
            states[mode] = (ks[o], vs[o])
        finally:
            flags.set_flag("push_write", "auto")
    np.testing.assert_array_equal(states["scatter"][0],
                                  states["rebuild"][0])
    np.testing.assert_array_equal(states["scatter"][1],
                                  states["rebuild"][1])
