"""Pod-sharded table + trainer on the 8-device virtual CPU mesh: routing
correctness vs the single-chip PassTable oracle, and e2e learning."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.metrics import BasicAucCalculator
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel import ShardedPassTable, ShardedBoxTrainer
from paddlebox_tpu.parallel.mesh import device_mesh_1d

D = 4


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def table_cfg(cap=1 << 9):
    return TableConfig(
        embedx_dim=D, pass_capacity=cap * 8,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))


def test_bucketize_routing():
    t = ShardedPassTable(table_cfg(), num_shards=8, bucket_cap=16)
    keys = np.array([8, 16, 17, 9, 8, 23], dtype=np.uint64)  # shards 0,0,1,1,0,7
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()
    valid = np.ones(6, bool)
    idx = t.bucketize(keys, valid)
    assert idx.overflow == 0
    # key 8 and dup: same slot; shard 0 holds {8,16} sorted → 8→0, 16→1
    assert idx.restore[0] == idx.restore[4]
    s0 = idx.buckets[0]
    assert set(s0[s0 != t.shard_cap - 1].tolist()) == {0, 1}
    # shard 1 holds {9,17} sorted → 9→0, 17→1
    s1 = idx.buckets[1]
    assert set(s1[s1 != t.shard_cap - 1].tolist()) == {0, 1}


def test_bucketize_overflow_drops():
    t = ShardedPassTable(table_cfg(), num_shards=8, bucket_cap=2)
    keys = (np.arange(5, dtype=np.uint64) * 8)  # all shard 0
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()
    valid = np.ones(5, bool)
    idx = t.bucketize(keys, valid)
    assert idx.overflow == 3
    assert valid.sum() == 2


def test_bucketize_overflow_is_loud(caplog):
    """Overflow = silently lost gradients — round-5 verdict item: one
    warning per pass, stat counter always, and a strict flag that raises
    (the PADDLE_ENFORCE discipline, box_wrapper_impl.h:139)."""
    import logging

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.utils.stats import stat_get

    t = ShardedPassTable(table_cfg(), num_shards=8, bucket_cap=2)
    keys = (np.arange(6, dtype=np.uint64) * 8)  # skewed: all shard 0
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()
    before = stat_get("sharded_bucket_overflow")
    with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
        t.bucketize(keys, np.ones(6, bool))
        t.bucketize(keys, np.ones(6, bool))
    assert stat_get("sharded_bucket_overflow") == before + 8
    warns = [r for r in caplog.records if "overflow" in r.message]
    assert len(warns) == 1          # once per pass, not per batch
    # next pass gets a fresh warning budget
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()
    with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
        t.bucketize(keys, np.ones(6, bool))
    warns = [r for r in caplog.records if "overflow" in r.message]
    assert len(warns) == 2
    # strict mode raises instead of dropping
    flags.set_flag("strict_bucket_overflow", True)
    try:
        with pytest.raises(RuntimeError, match="gradients"):
            t.bucketize(keys, np.ones(6, bool))
    finally:
        flags.set_flag("strict_bucket_overflow", False)


def test_unregistered_key_raises():
    t = ShardedPassTable(table_cfg(), num_shards=8, bucket_cap=4)
    t.begin_feed_pass()
    t.add_keys(np.array([1], np.uint64))
    t.end_feed_pass()
    with pytest.raises(KeyError):
        t.bucketize(np.array([2], np.uint64), np.ones(1, bool))


@pytest.fixture(scope="module")
def sharded_setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("sharded_data")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=4, lines_per_file=400, num_slots=4,
        vocab_per_slot=150, max_len=3, seed=11)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def make_sharded_trainer(feed, seed=0):
    spec = ModelSpec(num_slots=4, slot_dim=3 + D)
    model = CtrDnn(spec, hidden=(32, 16))
    return ShardedBoxTrainer(
        model, table_cfg(), feed,
        TrainerConfig(dense_lr=0.01), mesh=device_mesh_1d(8), seed=seed)


def test_sharded_e2e_learns(sharded_setup):
    files, feed = sharded_setup
    trainer = make_sharded_trainer(feed)
    trainer.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                                mask_var="mask")
    for ep in range(12):
        # read_threads=1 → deterministic record order → reproducible run
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = trainer.train_pass(ds)
        assert stats["instances"] == 1600
    msg = trainer.metrics.get_metric_msg("auc")
    assert msg["auc"] > 0.6, msg

    # show counters accumulated in the sharded stores across passes
    total_rows = sum(len(st) for st in trainer.table.stores)
    assert total_rows > 0
    keys0, vals0 = trainer.table.stores[0].state_items()
    from paddlebox_tpu.embedding import accessor as acc
    assert vals0[:, acc.SHOW].sum() > 0
    # every stored key belongs to shard 0 under the live sharding policy
    # (key % 8 == 0 under the default key-mod)
    assert (trainer.policy.shard_of(keys0) == 0).all()


def test_sharded_matches_single_chip_semantics(sharded_setup):
    """One batch through the 8-shard table must produce the same slab
    updates as the single-chip PassTable given identical grads."""
    from paddlebox_tpu.embedding.pass_table import PassTable
    from paddlebox_tpu.embedding.accessor import PushLayout
    from paddlebox_tpu.embedding import accessor as acc

    cfg_single = TableConfig(
        embedx_dim=D, pass_capacity=1 << 10,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=1e9,  # no mf rng
                                        mf_initial_range=0.0))
    cfg_shard = TableConfig(
        embedx_dim=D, pass_capacity=8 * (1 << 7),
        optimizer=cfg_single.optimizer)

    keys = np.array([3, 11, 19, 3, 27, 35], dtype=np.uint64)  # mixed shards
    push = PushLayout(D)
    grads = np.zeros((6, push.width), np.float32)
    grads[:, push.SHOW] = 1.0
    grads[:, push.CLICK] = [1, 0, 0, 1, 0, 1]
    grads[:, push.EMBED_G] = [0.5, -0.5, 1.0, 0.5, 0.2, -0.2]

    # single-chip oracle
    pt = PassTable(cfg_single, seed=0)
    pt.begin_feed_pass(); pt.add_keys(keys); pt.end_feed_pass()
    pt.begin_pass()
    ids = pt.lookup_ids(keys)
    pt.push(jnp.asarray(ids), jnp.asarray(grads))
    pt.end_pass()

    # sharded path: bucketize + scatter-merge + manual per-shard push
    st = ShardedPassTable(cfg_shard, num_shards=8, bucket_cap=8, seed=0)
    st.begin_feed_pass(); st.add_keys(keys); st.end_feed_pass()
    slabs = st.build_slabs()
    valid = np.ones(6, bool)
    idx = st.bucketize(keys, valid)
    assert idx.overflow == 0  # all 6 keys hash to shard 3; KB=8 holds them
    KB = 8
    bucket_g = np.zeros((8 * KB, push.width), np.float32)
    np.add.at(bucket_g, idx.restore[valid], grads[valid])
    from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
    for s in range(8):
        new = push_sparse_dedup(
            jnp.asarray(slabs[s]), jnp.asarray(idx.buckets[s]),
            jnp.asarray(bucket_g[s * KB:(s + 1) * KB]),
            jax.random.PRNGKey(0), st.layout, cfg_shard.optimizer)
        slabs[s] = np.asarray(new)
    st.write_back(slabs)

    for k in np.unique(keys):
        shard = int(st.policy.shard_of(np.array([k], np.uint64))[0])
        row_sharded = st.stores[shard].lookup(np.array([k], np.uint64))[0]
        row_single = pt.store.lookup(np.array([k], np.uint64))[0]
        np.testing.assert_allclose(row_sharded, row_single, rtol=1e-5,
                                   atol=1e-6, err_msg=f"key {k}")


def test_bucketize_native_numpy_parity():
    """The native router (route.cc) and the vectorized numpy fallback must
    produce equivalent routing: same per-occurrence restore targets (up to
    slot numbering), same bucket contents per shard, same overflow count."""
    from paddlebox_tpu.parallel import sharded_table as stmod

    if stmod._route_lib() is None:
        pytest.skip("native router unavailable (g++ build failed)")
    rng = np.random.RandomState(7)
    keys = rng.randint(0, 1 << 20, 4096).astype(np.uint64)
    t = ShardedPassTable(table_cfg(cap=1 << 12), num_shards=8, bucket_cap=1024)
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()

    valid_n = np.ones(keys.size, bool)
    idx_n = t.bucketize(keys, valid_n)

    orig = stmod._route_lib
    stmod._route_lib = lambda: None
    try:
        valid_p = np.ones(keys.size, bool)
        idx_p = t.bucketize(keys, valid_p)
    finally:
        stmod._route_lib = orig

    assert idx_n.overflow == idx_p.overflow == 0
    np.testing.assert_array_equal(valid_n, valid_p)
    # same local id reached for every occurrence (slot order may differ)
    flat_n = idx_n.buckets.reshape(-1)[idx_n.restore]
    flat_p = idx_p.buckets.reshape(-1)[idx_p.restore]
    np.testing.assert_array_equal(flat_n, flat_p)
    # same shard routing per occurrence
    np.testing.assert_array_equal(idx_n.restore // t.bucket_cap,
                                  idx_p.restore // t.bucket_cap)
    # same bucket membership per shard
    trash = t.shard_cap - 1
    for s in range(8):
        bn = idx_n.buckets[s][idx_n.buckets[s] != trash]
        bp = idx_p.buckets[s][idx_p.buckets[s] != trash]
        assert set(bn.tolist()) == set(bp.tolist())


def test_bucketize_max_key_sentinel():
    """UINT64_MAX is a legal feasign; the native router must not confuse it
    with its internal empty-slot sentinel. Exercises both router paths."""
    from paddlebox_tpu.parallel import sharded_table as stmod

    t = ShardedPassTable(table_cfg(), num_shards=8, bucket_cap=16)
    kmax = np.uint64(0xFFFFFFFFFFFFFFFF)
    keys = np.array([8, kmax, 9], dtype=np.uint64)
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()

    def check():
        valid = np.ones(3, bool)
        idx = t.bucketize(keys, valid)
        assert idx.overflow == 0 and valid.all()
        s = int(t.policy.shard_of(np.array([kmax], np.uint64))[0])
        local = idx.buckets.reshape(-1)[idx.restore[1]]
        assert t._shard_keys[s][local] == kmax

    check()  # native when built, else numpy
    orig = stmod._route_lib
    stmod._route_lib = lambda: None
    try:
        check()  # numpy fallback explicitly
    finally:
        stmod._route_lib = orig


def test_sharded_predict_batches(sharded_setup):
    """SetTestMode inference on the sharded trainer: forward-only a2a
    pulls, no feature creation, ranking beats chance after training."""
    files, feed = sharded_setup
    trainer = make_sharded_trainer(feed, seed=3)
    for _ in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()
    rows_before = sum(len(st) for st in trainer.table.stores
                      if st is not None)

    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds, labels = trainer.predict_batches(ds)
    assert preds.size == labels.size == 1600
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    assert calc.auc() > 0.62, calc.auc()
    # test-mode pulls created nothing
    rows_after = sum(len(st) for st in trainer.table.stores
                     if st is not None)
    assert rows_after == rows_before


def test_sharded_predict_excludes_wrap_duplicates(sharded_setup):
    """Equalization wraps short workers onto duplicate batches for lockstep
    collectives; predict_batches must not count those instances."""
    files, feed = sharded_setup
    trainer = make_sharded_trainer(feed, seed=5)
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist(files[:1])
    ds.load_into_memory()
    # shrink to 10 records: 8 workers → workers 5-7 run wrapped batches
    ds._records = ds.records[:10]
    preds, labels = trainer.predict_batches(ds)
    assert preds.size == labels.size == 10


def test_sharded_table_save_load_roundtrip(sharded_setup, tmp_path):
    """Per-shard checkpoint files: a fresh trainer loading them serves
    identical rows and keeps training (the sharded batch-model tier)."""
    files, feed = sharded_setup
    tr = make_sharded_trainer(feed, seed=3)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    tr.train_pass(ds)
    prefix = str(tmp_path / "sharded_ckpt")
    tr.table.save(prefix)

    tr2 = make_sharded_trainer(feed, seed=3)
    tr2.table.load(prefix)
    for s in range(8):
        k1, v1 = tr.table.stores[s].state_items()
        k2, v2 = tr2.table.stores[s].state_items()
        o1, o2 = np.argsort(k1), np.argsort(k2)
        np.testing.assert_array_equal(k1[o1], k2[o2])
        np.testing.assert_allclose(v1[o1], v2[o2], rtol=1e-6)
    # restored trainer keeps training from the loaded state
    tr2.params = tr.params
    tr2.opt_state = tr.opt_state
    ds2 = BoxDataset(feed, read_threads=1)
    ds2.set_filelist(files)
    stats = tr2.train_pass(ds2)
    assert np.isfinite(stats["loss"])


def test_stream_bounded_memory(sharded_setup):
    """shard_batches is a bounded STREAM (VERDICT r2 #2): training a long
    pass keeps at most stream_depth routed steps staged ahead — never the
    whole pass — while producing the same learning behavior (covered by
    the e2e/parity tests, which now also run through the stream)."""
    files, feed = sharded_setup
    feed_small = type(feed)(slots=feed.slots, batch_size=4)
    spec = ModelSpec(num_slots=4, slot_dim=3 + D)
    trainer = ShardedBoxTrainer(
        CtrDnn(spec, hidden=(16,)), table_cfg(), feed_small,
        TrainerConfig(dense_lr=0.01, scan_chunk=1),
        mesh=device_mesh_1d(8), seed=0)
    ds = BoxDataset(feed_small, read_threads=1)
    ds.set_filelist(files)
    stats = trainer.train_pass(ds)
    assert stats["batches"] >= 50, stats        # long pass, many steps
    # live staged steps = queue (<= stream_depth=2) + the one in hand
    assert 1 <= trainer.stream_high_water <= 3, trainer.stream_high_water


def test_stream_surfaces_producer_errors(sharded_setup):
    """A routing failure on the stager thread must surface in the training
    loop, not hang the queue."""
    files, feed = sharded_setup
    trainer = make_sharded_trainer(feed)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    per_worker = ds.split_batches(num_workers=8)
    # no feed pass registered → bucketize must raise through the stream
    with pytest.raises(RuntimeError, match="no active pass"):
        for _ in trainer.shard_batches(per_worker):
            pass


@pytest.mark.parametrize("mode", ["step", "sharding"])
def test_hierarchical_mesh_matches_flat(sharded_setup, mode):
    """2D ("node","chip") mesh (VERDICT r2 #4): hierarchical dense sync —
    reduce-scatter over chips (ICI), psum over nodes (DCN at 1/chips the
    bytes), allgather over chips (SyncParam, boxps_worker.cc:1169-1236) —
    must match the flat 1D mesh; key routing is identical (8 shards
    either way)."""
    from paddlebox_tpu.parallel.mesh import device_mesh_2d

    files, feed = sharded_setup

    def run(mesh):
        trainer = ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D), hidden=(16,)),
            table_cfg(), feed,
            TrainerConfig(dense_lr=0.01, scan_chunk=1, sync_mode=mode),
            mesh=mesh, seed=0)
        losses = []
        for _ in range(2):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(trainer.train_pass(ds)["loss"])
            ds.release_memory()
        leaves = [np.asarray(l) for l in jax.tree.leaves(trainer.params)]
        k0, v0 = trainer.table.stores[0].state_items()
        order = np.argsort(k0)
        return losses, leaves, v0[order]

    losses_flat, params_flat, rows_flat = run(device_mesh_1d(8))
    losses_2d, params_2d, rows_2d = run(device_mesh_2d(2, 4))
    # rtol matches the param/row asserts below: the two meshes reduce in
    # different (mathematically equivalent) collective orders —
    # reduce_scatter+psum+allgather vs one psum — so f32 losses compound
    # a legitimate reordering difference over the two passes (round-4
    # full-suite run measured 2.8e-5 rel; 1e-5 was overtight and made
    # the test order-sensitive through the XLA compile cache)
    np.testing.assert_allclose(losses_flat, losses_2d, rtol=1e-4)
    for a, b in zip(params_flat, params_2d):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(rows_flat, rows_2d, rtol=1e-4, atol=1e-6)


def test_device_collect_auc_parity(sharded_setup):
    """mode_collect_in_device (VERDICT r2 #5): the [2, T] AUC bucket table
    accumulated INSIDE the jitted step (scatter-add, merged once per pass)
    must reproduce the host calculator, with the per-step pred D2H
    eliminated (host-row fetches drop from one per step to two per pass —
    the table + stats merge)."""
    files, feed = sharded_setup

    def run(collect):
        trainer = make_sharded_trainer(feed)
        trainer.metrics.init_metric(
            "auc", "label", "pred", table_size=1 << 12, mask_var="mask",
            mode_collect_in_device=collect)
        fetches = {"n": 0}
        orig = trainer._local_rows

        def counting_local_rows(arr):
            fetches["n"] += 1
            return orig(arr)

        trainer._local_rows = counting_local_rows
        n_steps = 0
        for _ in range(3):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            n_steps += trainer.train_pass(ds)["batches"]
            ds.release_memory()
        msg = trainer.metrics.get_metric_msg("auc")
        return msg, fetches["n"], n_steps

    msg_host, fetches_host, n_steps = run(False)
    msg_dev, fetches_dev, _ = run(True)
    # host mode: >= 1 pred fetch per step (+1 per extra pred tensor);
    # collect mode: exactly 2 per pass (table + stats), preds untouched
    assert fetches_host >= n_steps, (fetches_host, n_steps)
    assert fetches_dev == 2 * 3, fetches_dev
    assert msg_dev["size"] == msg_host["size"]
    np.testing.assert_allclose(msg_dev["auc"], msg_host["auc"], rtol=2e-3)
    for k in ("mae", "rmse", "actual_ctr", "predicted_ctr"):
        np.testing.assert_allclose(msg_dev[k], msg_host[k], rtol=1e-4,
                                   err_msg=k)
    np.testing.assert_allclose(msg_dev["bucket_error"],
                               msg_host["bucket_error"], atol=5e-3)


def test_sync_one_ring_matches_hierarchical(sharded_setup):
    """sync_one_ring forces the flat allreduce ring on a 2D mesh — same
    result as the hierarchical split (they compute the same mean), just a
    different collective schedule (the reference's sync_one_ring_ knob)."""
    from paddlebox_tpu.parallel.mesh import device_mesh_2d

    files, feed = sharded_setup

    def run(one_ring):
        trainer = ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D), hidden=(16,)),
            table_cfg(), feed,
            TrainerConfig(dense_lr=0.01, scan_chunk=1,
                          sync_one_ring=one_ring),
            mesh=device_mesh_2d(2, 4), seed=0)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        loss = trainer.train_pass(ds)["loss"]
        return loss, [np.asarray(l) for l in jax.tree.leaves(trainer.params)]

    loss_h, params_h = run(False)
    loss_r, params_r = run(True)
    np.testing.assert_allclose(loss_h, loss_r, rtol=1e-6)
    for a, b in zip(params_h, params_r):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_sharded_push_write_rebuild_matches_scatter(sharded_setup):
    """push_write='rebuild' on the sharded mesh (per-shard pos maps staged
    next to the per-destination dedup) must train bit-identically to the
    scatter path."""
    from paddlebox_tpu.config import flags
    files, feed = sharded_setup
    states = {}
    for mode in ("scatter", "rebuild"):
        flags.set_flag("push_write", mode)
        try:
            trainer = make_sharded_trainer(feed, seed=4)
            assert trainer._push_write == mode
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            trainer.train_pass(ds)
            states[mode] = [st.state_items()
                            for st in trainer.table.stores]
        finally:
            flags.set_flag("push_write", "auto")
    for (k_s, v_s), (k_r, v_r) in zip(states["scatter"], states["rebuild"]):
        np.testing.assert_array_equal(k_s, k_r)
        np.testing.assert_array_equal(v_s, v_r)
