"""Join-phase / variant ops vs literal numpy oracles of the CUDA kernels:
rank_attention, batch_fc, fused_seqpool_cvm_with_conv, masked_data_norm,
extended (expand) sparse pull/push."""

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
from paddlebox_tpu.config.configs import SparseOptimizerConfig
from paddlebox_tpu.ops import (batch_fc, build_push_grads_extended,
                               fused_seqpool_cvm_with_conv, masked_data_norm,
                               masked_data_norm_stat_update,
                               pull_sparse_extended, rank_attention)
from paddlebox_tpu.ops.data_norm import DataNormState


# ------------------------------------------------------------ rank_attention
def _rank_attention_oracle(x, rank_offset, rank_param, max_rank):
    """Literal transcription of expand_input_by_rank_kernel +
    expand_rank_attention_param_kernel + GEMM (rank_attention.cu.h:28-111)."""
    N, F = x.shape
    out_dim = rank_param.shape[1]
    block_row = max_rank * F
    input_help = np.zeros((N, block_row), x.dtype)
    param_help = np.zeros((N * block_row, out_dim), x.dtype)
    ins_rank = np.zeros((N, 1), x.dtype)
    for row in range(N):
        ins_rank[row] = rank_offset[row, 0]
        for col in range(block_row):
            k = col // F
            faster = rank_offset[row, 2 * k + 1] - 1
            if rank_offset[row, 0] - 1 < 0 or faster < 0:
                continue
            index = rank_offset[row, 2 * k + 2]
            input_help[row, col] = x[index, col % F]
    for prow in range(N * block_row):
        ins_idx = prow // block_row
        start_offset = prow % block_row
        k = start_offset // F
        k_offset = start_offset % F
        lower = rank_offset[ins_idx, 0] - 1
        faster = rank_offset[ins_idx, 2 * k + 1] - 1
        if lower < 0 or faster < 0:
            continue
        start = lower * max_rank + faster
        for oc in range(out_dim):
            param_help[prow, oc] = rank_param[
                start * F + k_offset, oc]
    out = np.zeros((N, out_dim), x.dtype)
    for i in range(N):
        out[i] = input_help[i] @ param_help[i * block_row:(i + 1) * block_row]
    return out, ins_rank


def test_rank_attention_matches_cuda_oracle():
    rng = np.random.RandomState(0)
    N, F, R, out_dim = 5, 3, 2, 4
    x = rng.randn(N, F).astype(np.float32)
    # pv structure: ins 0,1 one pv (ranks 1,2); ins 2 alone; 3,4 one pv
    rank_offset = np.array([
        # rank, (peer_rank, peer_idx) * R
        [1, 1, 0, 2, 1],
        [2, 1, 0, 2, 1],
        [1, 1, 2, 0, -1],   # single-ad pv: only itself
        [2, 1, 4, 2, 3],
        [1, 1, 4, 2, 3],
    ], np.int32)
    rank_param = rng.randn(R * R * F, out_dim).astype(np.float32)
    out, ins_rank = rank_attention(
        jnp.asarray(x), jnp.asarray(rank_offset), jnp.asarray(rank_param),
        max_rank=R)
    ref_out, ref_rank = _rank_attention_oracle(x, rank_offset, rank_param, R)
    np.testing.assert_allclose(np.asarray(out), ref_out, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ins_rank), ref_rank)


def test_rank_attention_invalid_rows_zero():
    x = np.ones((2, 2), np.float32)
    rank_offset = np.array([[0, 0, -1, 0, -1],
                            [1, 1, 1, 0, -1]], np.int32)
    param = np.ones((2 * 2 * 2, 3), np.float32)
    out, _ = rank_attention(jnp.asarray(x), jnp.asarray(rank_offset),
                            jnp.asarray(param), max_rank=2)
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)
    assert np.asarray(out)[1].sum() != 0


def test_rank_attention_is_differentiable():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 3).astype(np.float32))
    ro = jnp.asarray(np.array([[1, 1, 0, 2, 1], [2, 1, 0, 2, 1],
                               [1, 1, 2, 0, -1], [1, 1, 3, 0, -1]], np.int32))
    param = jnp.asarray(rng.randn(2 * 2 * 3, 2).astype(np.float32))

    def loss(param, x):
        out, _ = rank_attention(x, ro, param, max_rank=2)
        return (out ** 2).sum()

    gp, gx = jax.grad(loss, argnums=(0, 1))(param, x)
    assert np.isfinite(np.asarray(gp)).all() and np.asarray(gp).any()
    assert np.isfinite(np.asarray(gx)).all() and np.asarray(gx).any()


# ------------------------------------------------------------------ batch_fc
def test_batch_fc_oracle():
    rng = np.random.RandomState(2)
    S, N, din, dout = 3, 4, 5, 2
    x = rng.randn(S, N, din).astype(np.float32)
    w = rng.randn(S, din, dout).astype(np.float32)
    b = rng.randn(S, dout).astype(np.float32)
    out = np.asarray(batch_fc(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
    for s in range(S):
        np.testing.assert_allclose(out[s], x[s] @ w[s] + b[s], rtol=1e-5)


# ------------------------------------------------- fused_seqpool_cvm_with_conv
def test_seqpool_with_conv_cvm_columns():
    B, S = 1, 1
    # two keys, cols [show, click, conv, e0]
    emb = jnp.asarray(np.array([[2.0, 1.0, 1.0, 0.5],
                                [1.0, 0.0, 1.0, 0.25]], np.float32))
    seg = jnp.asarray(np.array([0, 0], np.int32))
    valid = jnp.asarray(np.array([1, 1], bool))
    out = np.asarray(fused_seqpool_cvm_with_conv(emb, seg, valid, B, S))
    show, click, conv = 3.0, 1.0, 2.0
    np.testing.assert_allclose(out[0, 0, 0], np.log(show + 1), rtol=1e-6)
    np.testing.assert_allclose(out[0, 0, 1], np.log(click + 1), rtol=1e-6)
    np.testing.assert_allclose(out[0, 0, 2],
                               np.log(conv + 1) - np.log(click + 1), rtol=1e-6)
    np.testing.assert_allclose(out[0, 0, 3], 0.75)
    # show_filter drops the show column
    out2 = np.asarray(fused_seqpool_cvm_with_conv(emb, seg, valid, B, S,
                                                  show_filter=True))
    assert out2.shape[-1] == out.shape[-1] - 1
    np.testing.assert_allclose(out2[0, 0, 0], np.log(click + 1), rtol=1e-6)


def test_seqpool_with_conv_need_filter():
    B, S = 1, 1
    # key 1 fails the show/click score threshold and is dropped
    emb = jnp.asarray(np.array([[5.0, 1.0, 0.0, 1.0],
                                [1.0, 0.0, 0.0, 100.0]], np.float32))
    seg = jnp.asarray(np.array([0, 0], np.int32))
    valid = jnp.asarray(np.array([1, 1], bool))
    out = np.asarray(fused_seqpool_cvm_with_conv(
        emb, seg, valid, B, S, use_cvm=False, need_filter=True,
        show_coeff=0.2, clk_coeff=1.0, threshold=0.96))
    # key0 score = (5-1)*0.2 + 1 = 1.8 >= 0.96 kept; key1 = 0.2 < 0.96 dropped
    np.testing.assert_allclose(out[0, 0, 0], 1.0)


# ------------------------------------------------------------ masked_data_norm
def test_masked_data_norm_forward_and_stats():
    rng = np.random.RandomState(3)
    N, C = 6, 4
    x = rng.randn(N, C).astype(np.float32)
    mask = np.array([1, 0, 1, 1, 0, 1], bool)
    st = DataNormState(
        batch_size=jnp.asarray(rng.rand(C).astype(np.float32) + 1),
        batch_sum=jnp.asarray(rng.randn(C).astype(np.float32)),
        batch_square_sum=jnp.asarray(rng.rand(C).astype(np.float32) + 1))
    y = np.asarray(masked_data_norm(jnp.asarray(x), jnp.asarray(mask), st))
    mean = np.asarray(st.batch_sum) / np.asarray(st.batch_size)
    scale = np.sqrt(np.asarray(st.batch_size) /
                    np.asarray(st.batch_square_sum))
    np.testing.assert_allclose(y[mask], (x[mask] - mean) * scale, rtol=1e-5)
    np.testing.assert_allclose(y[~mask], 0.0)

    # stat update: per-column means over masked rows, batch_size decays + 1
    decay = 0.5
    eps = 1e-4
    st2 = masked_data_norm_stat_update(st, jnp.asarray(x), jnp.asarray(mask),
                                       decay=decay, squared_sum_epsilon=eps)
    m = mask.sum()
    np.testing.assert_allclose(np.asarray(st2.batch_size),
                               np.asarray(st.batch_size) * decay + 1.0,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st2.batch_sum),
                               np.asarray(st.batch_sum) * decay
                               + x[mask].sum(0) / m, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(st2.batch_square_sum),
        np.asarray(st.batch_square_sum) * decay
        + ((x[mask] - mean) ** 2).sum(0) / m + eps, rtol=1e-5)


def test_masked_data_norm_empty_mask_skips_decay():
    st = DataNormState.init(3)
    x = jnp.asarray(np.ones((2, 3), np.float32))
    mask = jnp.asarray(np.zeros(2, bool))
    st2 = masked_data_norm_stat_update(st, x, mask, decay=0.5)
    np.testing.assert_allclose(np.asarray(st2.batch_size),
                               np.asarray(st.batch_size))


# --------------------------------------------------------- extended pull/push
def test_extended_layout_columns():
    lay = ValueLayout(4, "adagrad", expand_dim=3)
    base = ValueLayout(4, "adagrad")
    assert lay.width == base.width + 3 + 1  # expand_w[3] + g2sum
    assert lay.expand_w == base.width
    push = PushLayout(4, 3)
    assert push.width == 4 + 4 + 3


def test_extended_pull_and_push_updates_expand_block():
    D, E = 2, 3
    lay = ValueLayout(D, "adagrad", expand_dim=E)
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0)
    cap = 8
    rng = np.random.RandomState(4)
    slab = np.zeros((cap, lay.width), np.float32)
    slab[:, acc.MF_SIZE] = D  # embedx exists → updates, not creation
    slab[:, lay.expand_w:lay.expand_w + E] = rng.rand(cap, E)
    slab_j = jnp.asarray(slab)
    ids = jnp.asarray(np.array([1, 2, 1], np.int32))

    base, expand = pull_sparse_extended(slab_j, ids, lay)
    assert base.shape == (3, 3 + D) and expand.shape == (3, E)
    np.testing.assert_allclose(np.asarray(expand)[0],
                               slab[1, lay.expand_w:lay.expand_w + E])

    d_emb = jnp.asarray(rng.randn(3, 3 + D).astype(np.float32))
    d_exp = jnp.asarray(rng.randn(3, E).astype(np.float32))
    slots = jnp.asarray(np.zeros(3, np.float32))
    clicks = jnp.asarray(np.array([1, 0, 1], np.float32))
    valid = jnp.asarray(np.ones(3, bool))
    pg = build_push_grads_extended(d_emb, d_exp, slots, clicks, valid)
    assert pg.shape == (3, 4 + D + E)

    new_slab = np.asarray(push_sparse_dedup(
        slab_j, ids, pg, jax.random.PRNGKey(0), lay, conf))
    # expand block of pushed rows changed; untouched rows unchanged
    assert not np.allclose(new_slab[1, lay.expand_w:lay.expand_w + E],
                           slab[1, lay.expand_w:lay.expand_w + E])
    np.testing.assert_allclose(new_slab[5], slab[5])
    # g2sum state advanced for pushed rows
    assert new_slab[1, lay.expand_state] > 0


def test_extended_requires_adagrad_or_naive():
    import pytest
    with pytest.raises(ValueError):
        ValueLayout(4, "adam", expand_dim=2)
