"""Native C++ components: parser parity vs the Python reference parser,
columnar packer parity vs the object packer, host store behavior."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import (BatchPacker, BoxDataset, MultiSlotParser,
                                write_synthetic_ctr_files)
from paddlebox_tpu.data.columnar import (ColumnarBlock, pack_columnar,
                                         _group_cumcount, _run_aranges)
from paddlebox_tpu.native import available

pytestmark = pytest.mark.skipif(not available(),
                                reason="native lib unavailable")


@pytest.fixture
def feed():
    return DataFeedConfig(slots=(
        SlotConfig("click", type="float", dim=1, is_used=False),
        SlotConfig("s0", type="uint64", max_len=3),
        SlotConfig("s1", type="uint64", max_len=2),
        SlotConfig("dense", type="float", dim=2),
    ), batch_size=4)


@pytest.fixture
def data_files(tmp_path):
    files, gen_feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=2, lines_per_file=200, num_slots=3,
        vocab_per_slot=50, dense_dim=2, seed=3)
    return files, type(gen_feed)(slots=gen_feed.slots, batch_size=32)


def test_native_parser_matches_python(data_files):
    from paddlebox_tpu.data.native_parser import NativeMultiSlotParser
    files, feed = data_files
    py = MultiSlotParser(feed)
    nat = NativeMultiSlotParser(feed)
    for path in files:
        recs = list(py.parse_file(path))
        block = nat.parse_file_columnar(path)
        assert block.n_recs == len(recs)
        np.testing.assert_array_equal(block.labels,
                                      [r.label for r in recs])
        for i, rec in enumerate(recs):
            lo, hi = block.rec_offsets[i], block.rec_offsets[i + 1]
            np.testing.assert_array_equal(block.keys[lo:hi], rec.all_keys())
            np.testing.assert_allclose(block.dense[i], rec.float_slots[0],
                                       rtol=1e-5)


def test_native_parser_drops_malformed(feed, tmp_path):
    from paddlebox_tpu.data.native_parser import NativeMultiSlotParser
    p = tmp_path / "bad.txt"
    p.write_text("1 1 2 11 22 1 33 2 0.5 -1.5\n"   # good
                 "1 1 5 11\n"                        # truncated slot
                 "1 1 2 11 xx 1 3 2 0 0\n"          # non-numeric
                 "\n"                                # empty (skipped)
                 "1 0 1 7 1 8 2 1.0 2.0\n")          # good
    block = NativeMultiSlotParser(feed).parse_file_columnar(str(p))
    assert block.n_recs == 2
    np.testing.assert_array_equal(block.labels, [1, 0])
    np.testing.assert_array_equal(block.keys[:2], [11, 22])


def test_columnar_pack_matches_object_packer(data_files):
    files, feed = data_files
    # object path
    ds_obj = BoxDataset(feed, read_threads=1, columnar=False)
    ds_obj.set_filelist(files)
    ds_obj.load_into_memory()
    # columnar path
    ds_col = BoxDataset(feed, read_threads=1, columnar=True)
    ds_col.set_filelist(files)
    ds_col.load_into_memory()
    assert ds_col.columnar and len(ds_col) == len(ds_obj)

    obj_batches = ds_obj.split_batches(num_workers=2)
    col_batches = ds_col.split_batches(num_workers=2)
    assert len(obj_batches[0]) == len(col_batches[0])
    for w in range(2):
        for bo, bc in zip(obj_batches[w], col_batches[w]):
            np.testing.assert_array_equal(bo.keys, bc.keys)
            np.testing.assert_array_equal(bo.slots, bc.slots)
            np.testing.assert_array_equal(bo.segments, bc.segments)
            np.testing.assert_array_equal(bo.valid, bc.valid)
            np.testing.assert_array_equal(bo.labels, bc.labels)
            np.testing.assert_allclose(bo.dense, bc.dense, rtol=1e-6)


def test_columnar_max_len_truncation(feed):
    block = ColumnarBlock.from_key_rec(
        keys=np.arange(1, 11, dtype=np.uint64),
        key_slot=np.zeros(10, np.int32),  # all slot 0, max_len 3
        key_rec=np.zeros(10, np.int64),
        labels=np.array([1], np.int32))
    b = pack_columnar(block, np.array([0]), feed, kcap=64, num_slots=2,
                      max_lens=np.array([3, 2]))
    assert b.valid.sum() == 3
    np.testing.assert_array_equal(b.keys[:3], [1, 2, 3])


def test_vector_helpers():
    np.testing.assert_array_equal(_run_aranges(np.array([3, 1, 2])),
                                  [0, 1, 2, 0, 0, 1])
    np.testing.assert_array_equal(
        _group_cumcount(np.array([5, 5, 5, 7, 9, 9])),
        [0, 1, 2, 0, 0, 1])


def test_native_host_store_roundtrip():
    import ctypes
    from paddlebox_tpu.native import get_lib
    lib = get_lib()
    W = 8
    s = lib.hs_create(W, 0.75)
    try:
        keys = np.array([5, 1 << 60, 7, 5], dtype=np.uint64)
        rows = np.empty(4, np.int64)
        created = np.empty(4, np.uint8)
        lib.hs_lookup_or_create(
            s, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), 4,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            created.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        assert lib.hs_size(s) == 3
        np.testing.assert_array_equal(created, [1, 1, 1, 0])
        assert rows[0] == rows[3]  # dup key → same row

        vals = np.arange(4 * W, dtype=np.float32).reshape(4, W)
        lib.hs_scatter(s, rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                       4, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        out = np.zeros((4, W), np.float32)
        lib.hs_gather(s, rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                      4, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        np.testing.assert_array_equal(out[1], vals[1])
        np.testing.assert_array_equal(out[0], vals[3])  # dup overwrote

        # erase middle key, probe chain must stay intact
        gone = np.array([1 << 60], dtype=np.uint64)
        n = lib.hs_erase(
            s, gone.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), 1)
        assert n == 1 and lib.hs_size(s) == 2
        r2 = np.empty(4, np.int64)
        lib.hs_lookup(s, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                      4, r2.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        assert r2[1] == -1 and r2[0] >= 0 and r2[2] >= 0
    finally:
        lib.hs_destroy(s)


def test_native_host_store_grows():
    import ctypes
    from paddlebox_tpu.native import get_lib
    lib = get_lib()
    s = lib.hs_create(4, 0.75)
    try:
        n = 200_000
        keys = np.arange(1, n + 1, dtype=np.uint64)
        rows = np.empty(n, np.int64)
        lib.hs_lookup_or_create(
            s, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), None)
        assert lib.hs_size(s) == n
        # re-lookup hits the same rows
        r2 = np.empty(n, np.int64)
        lib.hs_lookup(s, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                      n, r2.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        np.testing.assert_array_equal(rows, r2)
    finally:
        lib.hs_destroy(s)


def test_concurrent_bucketize_parity():
    """Round-12 thread contract: the stager pool calls rt_bucketize on
    ONE route index from several threads concurrently (ctypes drops the
    GIL), so concurrent routings must be bit-identical to serial ones.
    The pre-fix per-INDEX dedup scratch let concurrent callers draw the
    same generation and read each other's seen-marks — a silently
    mis-routed occurrence (the PR-6 show-off-by-one flake class,
    BASELINE.md round 12); this reproduced it in the first few trials.
    Scratch is per-thread now."""
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable

    P, KB, K = 8, 2048, 8192
    rng = np.random.RandomState(0)
    pass_keys = np.unique(
        rng.randint(0, 1 << 30, 1 << 15).astype(np.uint64))
    t = ShardedPassTable(
        TableConfig(embedx_dim=8, pass_capacity=1 << 18,
                    optimizer=SparseOptimizerConfig()),
        num_shards=P, bucket_cap=KB)
    t.begin_feed_pass()
    t.add_keys(pass_keys)
    t.end_feed_pass()
    # distinct batches sharing many keys: the cross-batch scratch
    # collision food the race needed
    batches = [rng.choice(pass_keys, K).astype(np.uint64)
               for _ in range(6)]
    valid = np.ones(K, bool)
    oracle = [t.bucketize(b, valid.copy()) for b in batches]
    pool = ThreadPoolExecutor(4)
    try:
        for _trial in range(30):
            futs = [pool.submit(
                lambda b=b: t.bucketize(b, valid.copy()))
                for b in batches]
            for got, want in zip([f.result() for f in futs], oracle):
                np.testing.assert_array_equal(got.buckets, want.buckets)
                np.testing.assert_array_equal(got.restore, want.restore)
    finally:
        pool.shutdown(wait=False)
