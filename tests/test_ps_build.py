"""GPUPS pass-build composition: sharded trainer with shard stores behind
the distributed CPU PS (PSGPUWrapper BuildPull → device slab → train →
EndPass dump, ps_gpu_wrapper.cc:337-760,907-955,983+).

Parity holds exactly: the PS table shards by key % P with the same
per-shard seeds and sorted-unique creation order as the local host stores,
so the PS-backed run and the local-store oracle produce identical rows.
"""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.embedding.ps_store import PSBackedStore, ps_store_factory
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
from paddlebox_tpu.ps import PSServer, PsLocalClient, TcpPSClient

D = 4
NUM_SLOTS = 4
TABLE_ID = 7


def table_cfg():
    return TableConfig(
        embedx_dim=D, pass_capacity=8 * 512,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("psbuild")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=256, num_slots=NUM_SLOTS,
        vocab_per_slot=100, max_len=3, seed=41)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def run_trainer(files, feed, store_factory=None, passes=3, seed=0):
    from paddlebox_tpu.config import flags
    flags.set_flag("dataset_disable_shuffle", True)  # strict parity
    try:
        trainer = ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,)),
            table_cfg(), feed, TrainerConfig(dense_lr=0.01, scan_chunk=1),
            mesh=device_mesh_1d(8), seed=seed, store_factory=store_factory)
        losses = []
        for _ in range(passes):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(trainer.train_pass(ds)["loss"])
            ds.release_memory()
        return trainer, losses
    finally:
        flags.set_flag("dataset_disable_shuffle", False)


def test_ps_backed_store_roundtrip():
    cl = PsLocalClient()
    cl.create_sparse_table(TABLE_ID, table_cfg(), shard_num=8, seed=0)
    st = PSBackedStore(cl, TABLE_ID, None, table_cfg(), chunk_keys=4)
    from paddlebox_tpu.embedding.accessor import ValueLayout
    st.layout = ValueLayout(D)
    keys = np.array([3, 11, 19, 27, 35, 43], np.uint64)
    rows = st.lookup_or_create(keys)          # chunked (4 + 2) create pull
    assert rows.shape == (6, st.layout.width)
    rows[:, 1] = 9.0                          # SHOW column
    st.write_back(keys, rows)
    back = st.lookup(keys)
    np.testing.assert_allclose(back[:, 1], 9.0)
    assert len(st) == 6
    # lookup of unknown keys reads zero rows and creates nothing
    miss = st.lookup(np.array([999], np.uint64))
    assert (miss == 0).all() and len(st) == 6


def test_gpups_local_client_matches_local_stores(data):
    """Same seeds → identical loss trajectory and identical server-side
    rows vs the local-store oracle."""
    files, feed = data
    oracle, losses_local = run_trainer(files, feed)

    cl = PsLocalClient()
    cl.create_sparse_table(TABLE_ID, table_cfg(), shard_num=8, seed=0)
    ps_trainer, losses_ps = run_trainer(
        files, feed, store_factory=ps_store_factory(cl, TABLE_ID))
    np.testing.assert_allclose(losses_ps, losses_local, rtol=1e-5)

    # rows on the PS equal the oracle's local store rows
    checked = 0
    for s in range(8):
        keys, vals = oracle.table.stores[s].state_items()
        if not keys.size:
            continue
        take = keys[np.argsort(keys)][:4]
        ps_rows = cl.pull_sparse(TABLE_ID, take, create=False)
        local_rows = oracle.table.stores[s].lookup(take)
        np.testing.assert_allclose(ps_rows, local_rows, rtol=1e-5,
                                   atol=1e-7)
        checked += take.size
    assert checked >= 16
    assert cl.sparse_size(TABLE_ID) > 100  # features created server-side


def test_gpups_over_tcp(data):
    """The same composition with the PS behind a real TCP server must be
    bit-equal to the in-process client run (the transport is the only
    difference)."""
    files, feed = data
    local_cl = PsLocalClient()
    local_cl.create_sparse_table(TABLE_ID, table_cfg(), shard_num=8, seed=0)
    _, losses_local = run_trainer(
        files, feed, store_factory=ps_store_factory(local_cl, TABLE_ID),
        passes=2)

    server = PSServer()
    cl = TcpPSClient("127.0.0.1", server.port)
    cl.create_sparse_table(TABLE_ID, table_cfg(), shard_num=8, seed=0)
    trainer, losses = run_trainer(
        files, feed, store_factory=ps_store_factory(cl, TABLE_ID), passes=2)
    np.testing.assert_allclose(losses, losses_local, rtol=1e-6)
    assert cl.sparse_size(TABLE_ID) > 100
    assert cl.sparse_size(TABLE_ID) == local_cl.sparse_size(TABLE_ID)
    cl.stop_server()
    cl.close()
