"""Cross-host instance shuffle + binary archive spill (mirrors the roles of
the reference's ShuffleData/ReceiveSuffleData path, data_set.cc:2438-2602,
and disk preload, data_set.cc:2090-2215; localhost transport testing follows
the test_dist_base.py subprocess-cluster pattern, here with threads)."""

import threading

import numpy as np
import pytest

from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.data.archive import (BinaryArchiveWriter, is_archive,
                                        read_archive)
from paddlebox_tpu.data.shuffle import (LocalShuffleGroup, TcpShuffler,
                                        deserialize_records,
                                        serialize_records)
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.utils.channel import Channel


def _mk_records(n, seed=0):
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        recs.append(SlotRecord(
            label=int(rng.rand() < 0.5),
            uint64_slots={0: rng.randint(0, 1000, rng.randint(1, 4))
                          .astype(np.uint64),
                          1: rng.randint(0, 1000, 2).astype(np.uint64)},
            float_slots={0: rng.rand(3).astype(np.float32)},
            ins_id="ins%d" % i, rank=i % 5, cmatch=i % 3,
            qvalue=float(rng.rand()), search_id=i // 4))
    return recs


def _assert_same_record(a, b):
    assert a.label == b.label and a.ins_id == b.ins_id
    assert a.rank == b.rank and a.cmatch == b.cmatch
    assert a.search_id == b.search_id
    assert abs(a.qvalue - b.qvalue) < 1e-6
    assert set(a.uint64_slots) == set(b.uint64_slots)
    for s in a.uint64_slots:
        np.testing.assert_array_equal(a.uint64_slots[s], b.uint64_slots[s])
    for s in a.float_slots:
        np.testing.assert_allclose(a.float_slots[s], b.float_slots[s])


def test_serialize_roundtrip():
    recs = _mk_records(37)
    out = deserialize_records(serialize_records(recs))
    assert len(out) == len(recs)
    for a, b in zip(recs, out):
        _assert_same_record(a, b)


def test_local_shuffle_group_partitions():
    world = 3
    group = LocalShuffleGroup(world, batch_records=8)
    per_rank_in = [_mk_records(50, seed=r) for r in range(world)]
    channels = [Channel() for _ in range(world)]

    def run(rank):
        sh = group[rank]
        sh.scatter(per_rank_in[rank], channels[rank])
        sh.flush(channels[rank])

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    received = [ch.drain() for ch in channels]
    # conservation: every instance lands on exactly one rank
    assert sum(len(r) for r in received) == world * 50
    # routing: each landed instance hashes to its rank
    for rank, recs in enumerate(received):
        for r in recs:
            assert r.shuffle_hash() % world == rank


def test_tcp_shuffler_two_ranks():
    world = 2
    eps = [("127.0.0.1", 0), ("127.0.0.1", 0)]
    shufflers = []
    for r in range(world):
        sh = TcpShuffler(r, world, eps, batch_records=16)
        eps[r] = ("127.0.0.1", sh.port)  # rebind the ephemeral port
        sh.endpoints = eps  # shared list; peers see the real ports
        shufflers.append(sh)
    for sh in shufflers:
        sh.endpoints = eps
    channels = [Channel() for _ in range(world)]
    inputs = [_mk_records(80, seed=10 + r) for r in range(world)]

    def run(rank):
        shufflers[rank].scatter(inputs[rank], channels[rank])
        shufflers[rank].flush(channels[rank], timeout=30.0)

    threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    received = [ch.drain() for ch in channels]
    assert sum(len(r) for r in received) == world * 80
    for rank, recs in enumerate(received):
        for r in recs:
            assert r.shuffle_hash() % world == rank
    for sh in shufflers:
        sh.close()


def test_archive_roundtrip(tmp_path):
    recs = _mk_records(100)
    w = BinaryArchiveWriter(str(tmp_path / "pass/p0"), max_bytes=4096)
    for i in range(0, 100, 16):
        w.write_records(recs[i:i + 16])
    files = w.close()
    assert len(files) > 1  # rotation kicked in at 4KB
    assert all(is_archive(f) for f in files)
    out = [r for f in files for batch in read_archive(f) for r in batch]
    assert len(out) == 100
    for a, b in zip(recs, out):
        _assert_same_record(a, b)


@pytest.fixture
def feed():
    return DataFeedConfig(slots=(
        SlotConfig("click", type="float", dim=1, is_used=False),
        SlotConfig("s0", type="uint64", max_len=3),
        SlotConfig("s1", type="uint64", max_len=2),
        SlotConfig("s2", type="uint64", max_len=2),
    ), batch_size=16)


def test_dataset_disk_spill_and_reload(tmp_path, feed):
    files, gen_feed = write_synthetic_ctr_files(
        str(tmp_path / "txt"), num_files=3, lines_per_file=60, num_slots=3,
        vocab_per_slot=40, seed=3)
    gen_feed = type(gen_feed)(slots=gen_feed.slots, batch_size=16)
    ds = BoxDataset(gen_feed, read_threads=2, columnar=False)
    ds.set_filelist(files)
    ds.load_into_disk(str(tmp_path / "spill/pass0"), max_bytes=1 << 16)
    assert ds.disk_files and all(is_archive(f) for f in ds.disk_files)
    assert len(ds) == 0  # nothing held in RAM

    ds2 = BoxDataset(gen_feed, read_threads=2)
    ds2.set_filelist(ds.disk_files)
    seen = []
    ds2.load_into_memory(add_keys_fn=lambda k: seen.append(k))
    assert len(ds2) == 180
    assert np.concatenate(seen).size == ds2.all_keys().size


def test_dataset_with_local_shuffler(tmp_path, feed):
    """Two in-process 'hosts' each read their file shard; after shuffle
    every instance lands on the rank its hash selects. Round 17: with
    the native lib present this runs the COLUMNAR path end to end (the
    block codec rides the same transport), so routing is asserted on
    the merged block's vectorized hash."""
    from paddlebox_tpu.data.block_shuffle import block_shuffle_dests
    files, gen_feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=4, lines_per_file=50, num_slots=3,
        vocab_per_slot=30, seed=7)
    gen_feed = type(gen_feed)(slots=gen_feed.slots, batch_size=16)
    world = 2
    group = LocalShuffleGroup(world, batch_records=32)
    datasets = [BoxDataset(gen_feed, read_threads=2, shuffler=group[r])
                for r in range(world)]
    for r, ds in enumerate(datasets):
        ds.set_filelist(ds.my_shard_files(r, world) or files[r::world])

    def load(ds):
        ds.load_into_memory()

    threads = []
    for r, ds in enumerate(datasets):
        ds.set_filelist(files[r::world])
        th = threading.Thread(target=load, args=(ds,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    total = sum(len(ds) for ds in datasets)
    assert total == 200
    for r, ds in enumerate(datasets):
        if ds._load_columnar:
            assert ds.block is not None
            np.testing.assert_array_equal(
                block_shuffle_dests(ds.block, world),
                np.full(len(ds), r, np.int64))
        for rec in ds.records:
            assert rec.shuffle_hash() % world == r
