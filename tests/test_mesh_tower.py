"""MeshTowerTrainer: model-parallel towers (TP wide DeepFM / EP MMoE)
trained end to end through the sparse hot loop, with the TP autodiff
contracts enforced in code — exact parity with the single-device dense
oracle proves no partial/scaled gradient survives."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                          TableConfig, TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.wide_tower import EpMMoE, TpDeepFM
from paddlebox_tpu.parallel.mesh_tower import MeshTowerTrainer


def _setup(tmp_path, lines=192, mb=16):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=lines, num_slots=4,
        vocab_per_slot=100, max_len=3, seed=11)
    return files, dataclasses.replace(feed, batch_size=mb)


def _table(cap=1 << 12):
    return TableConfig(
        embedx_dim=4, pass_capacity=cap,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=1e9,
                                        mf_initial_range=0.0,
                                        feature_learning_rate=0.05,
                                        mf_learning_rate=0.05))


def _spec(feed, D=4):
    return ModelSpec(num_slots=len(feed.used_sparse_slots()),
                     slot_dim=3 + D)


def _first_batch(trainer, files, feed):
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    trainer.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=trainer.table.add_keys)
    trainer.table.end_feed_pass()
    trainer.table.begin_pass()
    return ds.split_batches(num_workers=1)[0][0]


def test_tp_deepfm_matches_dense_oracle(tmp_path):
    """One TP step == the dense (concatenated-shards) step: params AND
    slab. Fails if tp_loss_scale or any tp_fix_grads psum is missing."""
    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    rebuild_uids)
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import build_push_grads, pull_sparse

    files, feed = _setup(tmp_path)
    table_cfg = _table()
    P = 8
    model = TpDeepFM(_spec(feed), n_shards=P, d_wide=64, d_mid=16)
    tr = MeshTowerTrainer(model, table_cfg, feed,
                          TrainerConfig(dense_lr=1e-2), seed=5)
    params0 = {k: np.asarray(v) for k, v in tr.params.items()}
    b = _first_batch(tr, files, feed)
    batch = {k: np.asarray(v) for k, v in tr.host_batch(b).items()}
    slab0 = np.asarray(tr.table.slab)
    prng0 = np.asarray(tr._prng)

    loss_tp = tr.train_batch(b)
    slab_tp = np.asarray(tr.table.slab)

    # ---- dense oracle
    dense = {
        "w1": np.concatenate(list(params0["w1"]), axis=1),
        "b1": np.concatenate(list(params0["b1"])),
        "w2": np.concatenate(list(params0["w2"]), axis=0),
        "b2": params0["b2"], "head_w": params0["head_w"],
        "head_b": params0["head_b"], "fm_out_w": params0["fm_out_w"],
        "fm_out_b": params0["fm_out_b"],
    }
    layout, conf = tr.layout, table_cfg.optimizer
    B = feed.batch_size
    S = tr.num_slots
    key_valid = batch["ids"] != table_cfg.pass_capacity - 1
    D = 4

    def dense_loss(p, emb):
        pooled = fused_seqpool_cvm(
            emb, jnp.asarray(batch["segments"]), jnp.asarray(key_valid),
            B, S, True, sorted_segments=True)
        first = pooled[:, :, 2].sum(axis=1)
        v = pooled[:, :, 3:3 + D]
        sv = v.sum(axis=1)
        fm2 = 0.5 * (sv * sv - (v * v).sum(axis=1)).sum(axis=-1)
        x = pooled.reshape(B, -1)
        mid = jax.nn.relu(
            jnp.maximum(x @ p["w1"] + p["b1"], 0.0) @ p["w2"] + p["b2"])
        deep = mid @ p["head_w"] + p["head_b"]
        logits = (jnp.stack([first, fm2, deep], axis=-1) @ p["fm_out_w"]
                  + p["fm_out_b"])
        lab = jnp.asarray(batch["labels"]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    p0 = {k: jnp.asarray(v) for k, v in dense.items()}
    emb0 = pull_sparse(jnp.asarray(slab0), jnp.asarray(batch["ids"]),
                       layout)
    (loss_d, (dp, demb)) = jax.value_and_grad(
        dense_loss, argnums=(0, 1))(p0, emb0)
    np.testing.assert_allclose(loss_tp, float(loss_d), rtol=1e-5)

    opt = optax.adam(1e-2)
    upd, _ = opt.update(dp, opt.init(p0), p0)
    want = optax.apply_updates(p0, upd)
    got = {k: np.asarray(v) for k, v in tr.params.items()}
    np.testing.assert_allclose(
        np.concatenate(list(got["w1"]), axis=1), np.asarray(want["w1"]),
        rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.concatenate(list(got["b1"])), np.asarray(want["b1"]),
        rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.concatenate(list(got["w2"]), axis=0), np.asarray(want["w2"]),
        rtol=2e-4, atol=1e-6)
    for k in ("b2", "head_w", "head_b", "fm_out_w", "fm_out_b"):
        np.testing.assert_allclose(got[k], np.asarray(want[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    # slab: the push must equal the oracle push with the dense demb
    _, sub = jax.random.split(jnp.asarray(prng0))
    clicks = batch["labels"][batch["segments"] // S]
    pg = build_push_grads(demb, jnp.asarray(batch["segments"] % S),
                          jnp.asarray(clicks), jnp.asarray(key_valid))
    uids = rebuild_uids(jnp.asarray(batch["ids"]),
                        jnp.asarray(batch["perm"]),
                        jnp.asarray(batch["inv"]),
                        table_cfg.pass_capacity)
    want_slab = push_sparse_hostdedup(
        jnp.asarray(slab0), uids, jnp.asarray(batch["perm"]),
        jnp.asarray(batch["inv"]), pg, sub, layout, conf)
    np.testing.assert_allclose(slab_tp, np.asarray(want_slab),
                               rtol=2e-4, atol=1e-6)


def test_ep_mmoe_matches_dense_oracle(tmp_path):
    """One EP step == the dense all-experts step — proves the gate's
    partial grad is psum'd (the documented footgun) and the expert
    shards update exactly."""
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import pull_sparse

    files, feed = _setup(tmp_path)
    table_cfg = _table()
    P = 8
    model = EpMMoE(_spec(feed), n_shards=P, n_experts=8, d_hidden=16,
                   d_out=8)
    tr = MeshTowerTrainer(model, table_cfg, feed,
                          TrainerConfig(dense_lr=1e-2), seed=6)
    params0 = {k: np.asarray(v) for k, v in tr.params.items()}
    b = _first_batch(tr, files, feed)
    batch = {k: np.asarray(v) for k, v in tr.host_batch(b).items()}
    slab0 = np.asarray(tr.table.slab)

    loss_ep = tr.train_batch(b)

    dense = {k: (v.reshape((-1,) + v.shape[2:])
                 if k in ("ew1", "eb1", "ew2", "eb2") else v)
             for k, v in params0.items()}
    layout = tr.layout
    B, S = feed.batch_size, tr.num_slots
    key_valid = batch["ids"] != table_cfg.pass_capacity - 1

    def dense_loss(p, emb):
        pooled = fused_seqpool_cvm(
            emb, jnp.asarray(batch["segments"]), jnp.asarray(key_valid),
            B, S, True, sorted_segments=True)
        x = pooled.reshape(B, -1)
        gates = jax.nn.softmax(x @ p["gate"], axis=-1)
        h = jax.nn.relu(jnp.einsum("bi,eih->beh", x, p["ew1"]) + p["eb1"])
        y = jnp.einsum("beh,eho->beo", h, p["ew2"]) + p["eb2"]
        mix = jnp.einsum("beo,be->bo", y, gates)
        logits = mix @ p["head_w"] + p["head_b"]
        lab = jnp.asarray(batch["labels"]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    p0 = {k: jnp.asarray(v) for k, v in dense.items()}
    emb0 = pull_sparse(jnp.asarray(slab0), jnp.asarray(batch["ids"]),
                       layout)
    loss_d, dp = jax.value_and_grad(dense_loss)(p0, emb0)
    np.testing.assert_allclose(loss_ep, float(loss_d), rtol=1e-5)

    opt = optax.adam(1e-2)
    upd, _ = opt.update(dp, opt.init(p0), p0)
    want = optax.apply_updates(p0, upd)
    got = {k: np.asarray(v) for k, v in tr.params.items()}
    for k in ("ew1", "eb1", "ew2", "eb2"):
        np.testing.assert_allclose(
            got[k].reshape((-1,) + got[k].shape[2:]), np.asarray(want[k]),
            rtol=2e-4, atol=1e-6, err_msg=k)
    for k in ("gate", "head_w", "head_b"):
        np.testing.assert_allclose(got[k], np.asarray(want[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)


@pytest.mark.parametrize("kind", ["tp", "ep"])
def test_mesh_tower_learns(tmp_path, kind):
    """End-to-end pass cadence: loss descends and write-back lands."""
    from paddlebox_tpu.embedding import accessor as acc

    files, feed = _setup(tmp_path, lines=320)
    if kind == "tp":
        model = TpDeepFM(_spec(feed), n_shards=8, d_wide=128, d_mid=16)
    else:
        model = EpMMoE(_spec(feed), n_shards=8, n_experts=8, d_hidden=16,
                       d_out=8)
    tr = MeshTowerTrainer(model, _table(), feed,
                          TrainerConfig(dense_lr=5e-3), seed=0)
    tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                           mask_var="mask")
    losses = []
    for _ in range(4):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
        ds.release_memory()
    assert losses[-1] < losses[0] - 0.01, losses
    keys, vals = tr.table.store.state_items()
    assert keys.size > 50
    assert vals[:, acc.SHOW].sum() > 0
    # metric plumbing: every trained instance streamed once
    msg = tr.metrics.get_metric_msg("auc")
    assert msg["size"] > 0 and 0.0 < msg["actual_ctr"] < 1.0
    # test-mode inference: no push, preds for every valid instance
    show_before = vals[:, acc.SHOW].sum()
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds, labels = tr.predict_batches(ds)
    assert preds.size == labels.size > 100
    assert (preds > 0).all() and (preds < 1).all()
    _k, vals_after = tr.table.store.state_items()
    assert vals_after[:, acc.SHOW].sum() == show_before
    ds.release_memory()


def test_mesh_tower_push_write_rebuild_matches_scatter(tmp_path):
    """rebuild-mode slab write through the TP tower trainer must match the
    scatter path bit-exactly (replicated slab, shared prng)."""
    from paddlebox_tpu.config import flags
    files, feed = _setup(tmp_path, lines=192)
    states = {}
    for mode in ("scatter", "rebuild"):
        flags.set_flag("push_write", mode)
        try:
            model = TpDeepFM(_spec(feed), n_shards=8, d_wide=64, d_mid=8)
            tr = MeshTowerTrainer(model, _table(), feed,
                                  TrainerConfig(dense_lr=5e-3), seed=2)
            assert tr._push_write == mode
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            tr.train_pass(ds)
            states[mode] = tr.table.store.state_items()
        finally:
            flags.set_flag("push_write", "auto")
    np.testing.assert_array_equal(states["scatter"][0], states["rebuild"][0])
    np.testing.assert_array_equal(states["scatter"][1], states["rebuild"][1])
