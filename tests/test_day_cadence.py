"""Day-boundary lifecycle: age unseen_days → shrink → SaveBase, composed
(the python-driven day cadence around box_wrapper's ShrinkTable +
SaveBase(batch, xbox, day); delete rule ctr_accessor's
delete_after_unseen_days)."""

import dataclasses
import os

import numpy as np

from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer, CheckpointManager

D = 4


def _table(delete_days=2.0):
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        delete_after_unseen_days=delete_days,
        # high thresholds so shrink deletes by unseen-days only
        delete_threshold=0.0, show_click_decay_rate=1.0,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))


def test_day_cadence_ages_shrinks_and_checkpoints(tmp_path):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=2, lines_per_file=200,
        num_slots=4, vocab_per_slot=80, max_len=3, seed=9)
    feed = dataclasses.replace(feed, batch_size=32)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)),
                    _table(), feed, TrainerConfig(dense_lr=1e-2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        tr.train_pass(ds)
        day1_keys, day1_vals = tr.table.store.state_items()
        assert day1_keys.size > 50
        assert (day1_vals[:, acc.UNSEEN_DAYS] == 0).all()

        # two day boundaries with NO further sightings of these keys
        deleted_total = 0
        for _ in range(2):
            deleted_total += tr.table.end_day()
        # after day 1: unseen_days=1 (kept); after day 2: aged to 2, then
        # shrink deletes unseen_days > delete_after_unseen_days=2? No —
        # rule is strict '>': 2 > 2 is False, so a third boundary kills
        assert deleted_total == 0
        tr.table.end_day()
        keys_after, _ = tr.table.store.state_items()
        assert keys_after.size == 0, keys_after.size

        # keys seen every day survive the same cadence
        ds2 = BoxDataset(feed)
        ds2.set_filelist(files)
        tr.train_pass(ds2)
        tr.table.end_day()
        ds3 = BoxDataset(feed)
        ds3.set_filelist(files)
        tr.train_pass(ds3)           # re-seen: push resets unseen_days
        tr.table.end_day()
        surviving, vals = tr.table.store.state_items()
        assert surviving.size > 50
        assert (vals[:, acc.UNSEEN_DAYS] <= 1).all()

        # SaveBase at the day boundary + resume keeps the aged state
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "batch"),
                             xbox_model_dir=str(tmp_path / "xbox"),
                             async_save=False),
            tr.table)
        cm.save_base(tr.params, tr.opt_state, day="20260730")
        tr2 = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                                hidden=(16,)),
                         _table(), feed, TrainerConfig(dense_lr=1e-2))
        cm2 = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "batch"),
                             xbox_model_dir=str(tmp_path / "xbox"),
                             async_save=False),
            tr2.table)
        tr2.params, tr2.opt_state, _meta = cm2.load_base(day="20260730")
        keys2, vals2 = tr2.table.store.state_items()
        np.testing.assert_array_equal(np.sort(keys2), np.sort(surviving))
    finally:
        tr.close()


def test_save_base_plus_end_day_single_aging(tmp_path):
    """save_base already ages (update_stat_after_save param=3); the
    combined day boundary must age exactly ONCE (end_day(age=False))."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=1, lines_per_file=100,
        num_slots=4, vocab_per_slot=50, max_len=3, seed=4)
    feed = dataclasses.replace(feed, batch_size=32)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)),
                    _table(delete_days=30.0), feed,
                    TrainerConfig(dense_lr=1e-2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        tr.train_pass(ds)
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                             xbox_model_dir=str(tmp_path / "x"),
                             async_save=False), tr.table)
        cm.save_base(tr.params, tr.opt_state, day="d0")   # ages once
        tr.table.end_day(age=False)                       # must NOT re-age
        _, vals = tr.table.store.state_items()
        assert (vals[:, acc.UNSEEN_DAYS] == 1.0).all(), \
            vals[:, acc.UNSEEN_DAYS].max()
    finally:
        tr.close()


def test_spilled_rows_age_lazily(tmp_path):
    """Spilled rows must keep aging (epoch-based): fault-in adds the days
    slept on disk, and shrink deletes spilled rows by the unseen-days rule
    without faulting them in."""
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.embedding.native_store import make_host_store

    table = dataclasses.replace(
        _table(delete_days=3.0), ssd_dir=str(tmp_path / "ssd"),
        ssd_threshold_mb=0.001)
    layout = ValueLayout(D, "adagrad")
    store = make_host_store(layout, table, seed=0)
    keys = np.arange(1, 41, dtype=np.uint64)
    store.lookup_or_create(keys)
    # make rows 1..20 colder so they become the spill victims
    sk, sv = store.state_items()
    sv[:, acc.UNSEEN_DAYS] = np.where(sk <= 20, 1.0, 0.0)
    store.write_back(sk, sv)
    spilled = store.spill(max_resident=20)
    assert spilled == 20

    # two day boundaries while spilled
    store.age_unseen_days()
    store.age_unseen_days()
    # fault one spilled row back in: 1 (at spill) + 2 missed = 3
    row = store.lookup_or_create(np.array([1], np.uint64))[0]
    assert row[acc.UNSEEN_DAYS] == 3.0, row[acc.UNSEEN_DAYS]

    # one more boundary: remaining spilled rows reach 1+3=4 > 3 → shrink
    # deletes them WITHOUT faulting in; the resident fresh rows survive
    store.age_unseen_days()
    deleted = store.shrink()
    assert deleted >= 19, deleted
    keys_left, _ = store.state_items()
    assert (keys_left > 20).sum() == 20  # warm rows intact


def test_age_false_still_ticks_spill_clock(tmp_path):
    """end_day(age=False) (the save_base cadence) must still advance the
    spilled rows' lazy day clock, and save() must checkpoint spilled rows
    at their EFFECTIVE age."""
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.embedding.host_store import HostEmbeddingStore

    table = dataclasses.replace(
        _table(delete_days=30.0), ssd_dir=str(tmp_path / "ssd"),
        ssd_threshold_mb=0.001)
    layout = ValueLayout(D, "adagrad")
    store = HostEmbeddingStore(layout, table, seed=0)
    keys = np.arange(1, 31, dtype=np.uint64)
    store.lookup_or_create(keys)
    sk, sv = store.state_items()
    sv[:, acc.UNSEEN_DAYS] = np.where(sk <= 15, 1.0, 0.0)
    store.write_back(sk, sv)
    assert store.spill(max_resident=15) == 15

    store.tick_spill_age()   # the age=False day boundary
    store.tick_spill_age()
    # checkpoint now: spilled rows must be written at 1+2=3
    ckpt = str(tmp_path / "store.pkl")
    store.save(ckpt)
    store2 = HostEmbeddingStore(layout, table, seed=0)
    store2.load(ckpt)
    row = store2.lookup(np.array([1], np.uint64))[0]
    assert row[acc.UNSEEN_DAYS] == 3.0, row[acc.UNSEEN_DAYS]

    # all-spilled table: shrink must still run the spilled sweep
    table3 = dataclasses.replace(table, delete_after_unseen_days=1.0)
    store3 = HostEmbeddingStore(layout, table3, seed=0)
    store3.lookup_or_create(keys[:10])
    assert store3.spill(max_resident=0) == 10   # nothing resident
    store3.tick_spill_age()
    store3.tick_spill_age()
    assert store3.shrink() == 10                # 0+2 > 1 → all swept
    assert store3.spilled_count() == 0


def test_run_day_composed_cadence(tmp_path):
    """run_day: per-pass delta saves on cadence, end-of-day base save +
    single aging, preload overlap — the whole day driver in one call."""
    import glob
    from paddlebox_tpu.train.checkpoint import run_day

    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=2, lines_per_file=160,
        num_slots=4, vocab_per_slot=60, max_len=3, seed=6)
    feed = dataclasses.replace(feed, batch_size=32)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)),
                    _table(delete_days=30.0), feed,
                    TrainerConfig(dense_lr=1e-2))
    try:
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                             xbox_model_dir=str(tmp_path / "x"),
                             save_delta_every_passes=1, async_save=False),
            tr.table)
        datasets = []
        for _ in range(3):
            ds = BoxDataset(feed)
            ds.set_filelist(files)
            datasets.append(ds)
        stats, (batch_dir, xbox_dir) = run_day(tr, datasets, cm, "d7")
        assert len(stats) == 3
        assert stats[-1]["loss"] < stats[0]["loss"]
        # 3 delta saves + the base save exist on disk
        deltas = glob.glob(str(tmp_path / "x" / "d7" / "delta-*"))
        assert len(deltas) == 3, deltas
        assert os.path.exists(os.path.join(batch_dir, "DONE"))
        # exactly ONE aging for the whole day (save_base's)
        _, vals = tr.table.store.state_items()
        assert (vals[:, acc.UNSEEN_DAYS] == 1.0).all()
    finally:
        tr.close()


def test_spilled_rows_decay_on_fault_in(tmp_path):
    """A row that slept through N day boundaries faults back with
    show/click multiplied by decay_rate**N (parity with resident rows'
    per-shrink decay)."""
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.embedding.host_store import HostEmbeddingStore

    table = dataclasses.replace(
        _table(delete_days=30.0), show_click_decay_rate=0.5,
        ssd_dir=str(tmp_path / "ssd"), ssd_threshold_mb=0.001)
    layout = ValueLayout(D, "adagrad")
    store = HostEmbeddingStore(layout, table, seed=0)
    keys = np.arange(1, 21, dtype=np.uint64)
    store.lookup_or_create(keys)
    sk, sv = store.state_items()
    sv[:, acc.SHOW] = 8.0
    sv[:, acc.CLICK] = 4.0
    sv[:, acc.UNSEEN_DAYS] = np.where(sk <= 10, 1.0, 0.0)
    store.write_back(sk, sv)
    assert store.spill(max_resident=10) == 10
    store.age_unseen_days()
    store.age_unseen_days()
    row = store.lookup_or_create(np.array([1], np.uint64))[0]
    assert row[acc.SHOW] == 2.0, row[acc.SHOW]     # 8 * 0.5**2
    assert row[acc.CLICK] == 1.0, row[acc.CLICK]   # 4 * 0.5**2


def test_load_ssd_to_mem_promotes_all(tmp_path):
    """PassTable.load_ssd_to_mem (LoadSSD2Mem): after a spill, the warm-up
    promotes every spilled row back to DRAM with its effective age."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=1, lines_per_file=150,
        num_slots=4, vocab_per_slot=60, max_len=3, seed=8)
    feed = dataclasses.replace(feed, batch_size=32)
    table = dataclasses.replace(
        _table(delete_days=30.0), ssd_dir=str(tmp_path / "ssd"),
        ssd_threshold_mb=0.002)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)), table, feed,
                    TrainerConfig(dense_lr=1e-2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        tr.train_pass(ds)   # end_pass spills beyond the tiny budget
        spilled_keys = np.sort(tr.table.store.spilled_keys())
        assert spilled_keys.size > 0
        tr.table.end_day()  # one day on disk for the spilled rows
        promoted = tr.table.load_ssd_to_mem()
        assert promoted == spilled_keys.size
        assert tr.table.store.spilled_count() == 0
        # the PROMOTED rows specifically carry the missed day: resident
        # rows were aged in place to 1.0, spilled rows slept at their
        # spill-time value and got the epoch delta added at promotion
        rows = tr.table.store.lookup(spilled_keys)
        assert (rows[:, acc.UNSEEN_DAYS] >= 1.0).all(), \
            rows[:, acc.UNSEEN_DAYS].min()
    finally:
        tr.close()


def test_ps_backed_aging_primary_once(tmp_path):
    """The PS path ages server-side exactly once per end_day regardless of
    shard count (primary-gated, like shrink)."""
    from paddlebox_tpu.embedding.ps_store import ps_store_factory
    from paddlebox_tpu.ps import PsLocalClient

    cl = PsLocalClient()
    cfg = _table(delete_days=30.0)
    cl.create_sparse_table(3, cfg, shard_num=4, seed=0)
    factory = ps_store_factory(cl, 3)
    layout_table = [(factory(None, cfg, 0)) for _ in range(4)]
    keys = np.arange(1, 30, dtype=np.uint64)
    cl.pull_sparse(3, keys, create=True)
    for st in layout_table:
        st.age_unseen_days()   # only the primary may act
    rows = cl.pull_sparse(3, keys, create=False)
    assert (rows[:, acc.UNSEEN_DAYS] == 1.0).all(), \
        rows[:, acc.UNSEEN_DAYS].max()


def test_save_base_covers_spilled_rows(tmp_path):
    """ADVICE r2 (medium): save_base on a table with an active SSD spill
    tier must cover the spilled rows at their EFFECTIVE age — load_base
    clears the spill index, so a base model built from state_items() alone
    would lose every spilled feature (the reference's SaveBase covers the
    SSD tier)."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=1, lines_per_file=200,
        num_slots=4, vocab_per_slot=80, max_len=3, seed=3)
    feed = dataclasses.replace(feed, batch_size=32)
    # ssd_dir with NO auto-spill threshold: the spill below is manual so
    # the test controls exactly which rows are on the SSD tier at save
    table = dataclasses.replace(_table(delete_days=30.0),
                                ssd_dir=str(tmp_path / "ssd"))
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)),
                    table, feed, TrainerConfig(dense_lr=1e-2))
    try:
        ds = BoxDataset(feed)
        ds.set_filelist(files)
        tr.train_pass(ds)
        store = tr.table.store
        sk, sv = store.state_items()
        n = sk.size
        assert n > 50
        cold_mask = np.arange(n) < n // 2
        sv[:, acc.UNSEEN_DAYS] = np.where(cold_mask, 1.0, 0.0)
        store.write_back(sk, sv)
        cold = sk[sv[:, acc.UNSEEN_DAYS] == 1.0]
        assert store.spill(max_resident=n - n // 2) == n // 2
        store.tick_spill_age()  # one boundary slept through on disk

        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                             xbox_model_dir=str(tmp_path / "x"),
                             async_save=False), tr.table)
        _, xbox_dir = cm.save_base(tr.params, tr.opt_state, day="d0")
        # the serving (xbox) base view covers the spilled rows too
        from paddlebox_tpu.serving.store import read_xbox_view
        xkeys, _xrows = read_xbox_view(xbox_dir)
        assert set(xkeys.tolist()) == set(sk.tolist())

        cm.load_base("d0")
        got, _ = store.state_items()
        assert set(got.tolist()) == set(sk.tolist())
        # the previously-spilled row resumed at effective age 1+1 missed=2
        row = store.lookup(cold[:1])[0]
        assert row[acc.UNSEEN_DAYS] == 2.0, row[acc.UNSEEN_DAYS]
    finally:
        tr.close()


def test_ps_backed_end_day_age_false_still_ages(tmp_path):
    """ADVICE r2: end_day(age=False) on PS-backed shards must still age
    server-side (PS checkpoints never run update_stat_after_save, so the
    save_base path can't have aged them) — exactly once, primary-gated."""
    from paddlebox_tpu.embedding.ps_store import ps_store_factory
    from paddlebox_tpu.ps import PsLocalClient

    cl = PsLocalClient()
    cfg = _table(delete_days=30.0)
    cl.create_sparse_table(7, cfg, shard_num=4, seed=0)
    factory = ps_store_factory(cl, 7)
    stores = [factory(None, cfg, 0) for _ in range(4)]
    keys = np.arange(1, 30, dtype=np.uint64)
    cl.pull_sparse(7, keys, create=True)
    for st in stores:
        st.tick_spill_age()   # the age=False day-boundary path
    rows = cl.pull_sparse(7, keys, create=False)
    assert (rows[:, acc.UNSEEN_DAYS] == 1.0).all(), \
        rows[:, acc.UNSEEN_DAYS].max()


def test_run_day_sharded_trainer(tmp_path):
    """The full day cadence over the SHARDED trainer: cadenced delta
    saves, base save + load_base roundtrip through the store_view facade
    (rows land back in their owning key%P shards), single aging."""
    import glob
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel.mesh import device_mesh_1d
    from paddlebox_tpu.train.checkpoint import run_day

    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=2, lines_per_file=160,
        num_slots=4, vocab_per_slot=60, max_len=3, seed=21)
    feed = dataclasses.replace(feed, batch_size=16)
    table = dataclasses.replace(_table(delete_days=30.0),
                                pass_capacity=1 << 12)
    trainer = ShardedBoxTrainer(
        CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D), hidden=(16,)),
        table, feed, TrainerConfig(dense_lr=1e-2, scan_chunk=1),
        mesh=device_mesh_1d(8), seed=0)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                         xbox_model_dir=str(tmp_path / "x"),
                         async_save=False, save_delta_every_passes=1),
        trainer.table)

    def day_datasets():
        out = []
        for _ in range(2):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            out.append(ds)
        return out

    stats, (batch_dir, xbox_dir) = run_day(trainer, day_datasets(), cm,
                                           day="d0", preload=True)
    assert len(stats) == 2
    assert len(glob.glob(str(tmp_path / "x" / "d0" / "delta-*"))) >= 1
    assert os.path.exists(os.path.join(batch_dir, "DONE"))

    keys_before, vals_before = trainer.table.store_view().state_items()
    assert keys_before.size > 50
    order = np.argsort(keys_before)

    params, opt_state, _ = cm.load_base("d0")
    keys_after, vals_after = trainer.table.store_view().state_items()
    order2 = np.argsort(keys_after)
    np.testing.assert_array_equal(keys_before[order], keys_after[order2])
    # the base blob is the PRE-mutation snapshot: resume rewinds the
    # save-time aging by one day; everything else matches exactly
    b, a = vals_before[order], vals_after[order2]
    np.testing.assert_array_equal(a[:, acc.UNSEEN_DAYS] + 1.0,
                                  b[:, acc.UNSEEN_DAYS])
    cols = [c for c in range(b.shape[1])
            if c not in (acc.UNSEEN_DAYS, acc.DELTA_SCORE)]
    np.testing.assert_allclose(b[:, cols], a[:, cols], rtol=1e-6)
    # every restored key sits in its owning key%P shard store
    for s, st in enumerate(trainer.table.stores):
        k, _ = st.state_items()
        assert (k % np.uint64(8) == np.uint64(s)).all()


def test_xbox_reader_composes_base_and_deltas(tmp_path):
    """Serving handoff: the xbox reader composes a day's base view with
    its cadenced deltas (later wins), matching the trainer's final rows
    for every delta-covered feature."""
    from paddlebox_tpu.train.checkpoint import XboxModelReader, run_day

    files, feed = write_synthetic_ctr_files(
        str(tmp_path / "data"), num_files=2, lines_per_file=160,
        num_slots=4, vocab_per_slot=60, max_len=3, seed=8)
    feed = dataclasses.replace(feed, batch_size=32)
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                           hidden=(16,)),
                    _table(delete_days=30.0), feed,
                    TrainerConfig(dense_lr=1e-2))
    try:
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                             xbox_model_dir=str(tmp_path / "x"),
                             async_save=False, save_delta_every_passes=1),
            tr.table)
        dss = []
        for _ in range(2):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            dss.append(ds)
        run_day(tr, dss, cm, day="d0", preload=False)

        reader = XboxModelReader(str(tmp_path / "x"), "d0")
        assert reader.deltas_applied >= 1
        keys, vals = tr.table.store.state_items()
        assert len(reader) >= keys.size
        lay = tr.table.layout
        got = reader.lookup(keys)
        want = np.concatenate(
            [vals[:, acc.EMBED_W:acc.EMBED_W + 1],
             vals[:, lay.embedx_w:lay.embedx_w + D]], axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
        # unknown key reads as zeros
        assert (reader.lookup(np.array([np.uint64(2**63 + 1)],
                                       np.uint64)) == 0).all()
    finally:
        tr.close()


def test_xbox_reader_mid_day_composition(tmp_path):
    """Mid-day serving: yesterday's completed base + today's streaming
    deltas (today's base DONE absent) compose with the freshest view
    winning by DONE timestamp."""
    import pickle
    import time
    from paddlebox_tpu.train.checkpoint import XboxModelReader

    def write_view(d, keys, val, ts):
        os.makedirs(d, exist_ok=True)
        emb = np.full((len(keys), 1 + D), val, np.float32)
        with open(os.path.join(d, "embedding.pkl"), "wb") as f:
            pickle.dump({"keys": np.asarray(keys, np.uint64),
                         "embedding": emb}, f)
        with open(os.path.join(d, "DONE"), "w") as f:
            f.write(str(ts))

    x = tmp_path / "x"
    t0 = time.time()
    write_view(str(x / "d0"), [1, 2, 3], 1.0, t0)            # base d0
    write_view(str(x / "d1" / "delta-1"), [2], 2.0, t0 + 10)  # today
    write_view(str(x / "d1" / "delta-2"), [3, 4], 3.0, t0 + 20)

    r = XboxModelReader(str(x), "d0", "d1")
    assert r.deltas_applied == 2 and len(r) == 4
    got = r.lookup(np.array([1, 2, 3, 4, 99], np.uint64))
    np.testing.assert_allclose(got[:, 0], [1.0, 2.0, 3.0, 3.0, 0.0])

    # today alone (no base anywhere) refuses
    import pytest
    with pytest.raises(FileNotFoundError):
        XboxModelReader(str(x), "d1")


def test_mmap_xbox_store_matches_reader(tmp_path):
    """Round-5 verdict item 8: the composed view compiled to the
    columnar file and served through the mmap store must agree with the
    RAM reader on hits, misses, and the kEmpty-sentinel key — through
    BOTH lookup tiers (native hash index and the searchsorted
    fallback)."""
    import pickle
    import time
    from paddlebox_tpu.train.checkpoint import (MmapXboxStore,
                                                XboxModelReader)

    rng = np.random.RandomState(7)
    n = 50_000
    keys = np.unique(rng.randint(0, 1 << 62, n).astype(np.uint64))
    keys = np.concatenate([keys, [np.uint64(2**64 - 1)]])  # hash sentinel
    emb = rng.rand(keys.size, 1 + D).astype(np.float32)
    d0 = tmp_path / "x" / "d0"
    os.makedirs(d0)
    with open(d0 / "embedding.pkl", "wb") as f:
        pickle.dump({"keys": keys, "embedding": emb}, f)
    with open(d0 / "DONE", "w") as f:
        f.write(str(time.time()))

    reader = XboxModelReader(str(tmp_path / "x"), "d0")
    path = reader.save_columnar(str(tmp_path / "serve.xbox"))
    store = MmapXboxStore(path)
    assert len(store) == len(reader) and store.dim == reader.dim

    probe = np.concatenate([
        rng.choice(keys, 5000).astype(np.uint64),          # hits
        rng.randint(0, 1 << 62, 1000).astype(np.uint64),   # ~all misses
        np.array([2**64 - 1], np.uint64),                  # sentinel
    ])
    want = reader.lookup(probe)
    np.testing.assert_array_equal(store.lookup(probe), want)
    # searchsorted fallback tier agrees bit-for-bit
    store.close()
    assert store._index is None
    np.testing.assert_array_equal(store.lookup(probe), want)
