"""Device-plane observability (obs/device.py, round 20).

Pins the tentpole's four signals end to end through the unchanged
publication machinery:

  * recompile sentinel — forced shape churn is counted, logged loudly
    EXACTLY once per fn, and scores the rank unhealthy through
    HealthMonitor within one window;
  * donation audit — a deliberately non-donated twin trips
    donation_miss (and a properly donated fn never does, pinned on CPU
    where donation IS honored);
  * HBM live-buffer ledger — owner bucketing, and the leak detector
    fires on an intentionally leaked array across passes while staying
    silent across clean passes;
  * surfaces — StepReport stats deltas, the /device + /metrics
    endpoints, and the flight-recorder seal all carry the device
    snapshot (schemas pinned);

plus the safety contract that makes the wrapper deployable at every
jit site: instrumented-vs-bare bit-parity on the e2e trainer.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.obs import device
from paddlebox_tpu.obs.device import InstrumentedJit, instrument_jit
from paddlebox_tpu.obs.exporter import ObsExporter
from paddlebox_tpu.obs.health import HealthMonitor
from paddlebox_tpu.obs.report import ListSink, StepReporter
from paddlebox_tpu.train import BoxTrainer
from paddlebox_tpu.utils.stats import StatRegistry, stat_get

DEVICE_STATS = ("device_recompiles", "donation_miss", "device_leak_suspect",
                "device_transfer_bytes_h2d", "device_transfer_bytes_d2h")

# big enough to clear the device_donation_min_bytes audit floor (64 KB)
BIG = (64, 1024)


def _reset_device_state():
    reg = StatRegistry.instance()
    snap = reg.snapshot_all()
    names = set(DEVICE_STATS)
    for kind in ("counters", "gauges", "hists"):
        names.update(k for k in snap[kind] if k.startswith("device_"))
    for k in names:
        reg.reset(k)
    device.monitor().reset()


@pytest.fixture(autouse=True)
def _device_isolation():
    """Zero the device-plane stats + monitor around every test: the
    stats are process-global counters and every other suite's trainers
    bump them."""
    _reset_device_state()
    yield
    _reset_device_state()


def _f(x, y):
    return x * 2 + y, x.sum()


def _big(v=1.0):
    return jnp.full(BIG, v, jnp.float32)


# ----------------------------------------------------------- the wrapper

def test_instrumented_jit_matches_bare_jit():
    j = instrument_jit(_f, "parity")
    b = jax.jit(_f)
    x, y = _big(3.0), _big(5.0)
    out_i = j(x, y)
    out_b = b(x, y)
    for a, c in zip(jax.tree_util.tree_leaves(out_i),
                    jax.tree_util.tree_leaves(out_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_compile_counted_once_per_signature():
    j = instrument_jit(_f, "count")
    j(_big(), _big())
    j(_big(2.0), _big(2.0))     # same signature: cache hit
    e = device.snapshot()["entries"]["count"]
    assert e["compiles"] == 1
    assert e["compile_ms"] > 0
    assert e["signatures"] == 1
    assert e["analysis"]["temp_bytes"] >= 0
    assert e["analysis"]["bytes_accessed"] > 0


def test_lower_passthrough_and_shared_analysis():
    """The AOT surface step_audit consumes, and the ONE copy of the
    per-example math."""
    j = instrument_jit(_f, "aot")
    compiled = j.lower(_big(), _big()).compile()
    out = device.analyze_compiled(compiled, examples=64)
    assert out["bytes_accessed_per_example"] == round(
        out["bytes_accessed"] / 64)
    assert out["flops_per_example"] == round(out["flops"] / 64)


def test_static_argnames_dispatch():
    def g(x, n):
        return x * n
    j = instrument_jit(g, "static", static_argnames=("n",))
    np.testing.assert_array_equal(np.asarray(j(jnp.arange(4.0), 3)),
                                  np.arange(4.0) * 3)
    np.testing.assert_array_equal(np.asarray(j(jnp.arange(4.0), 5)),
                                  np.arange(4.0) * 5)
    assert device.snapshot()["entries"]["static"]["compiles"] == 2


# ---------------------------------------------------- recompile sentinel

def test_recompile_sentinel_counts_and_flags_once(monkeypatch):
    warns = []
    from paddlebox_tpu.obs import log as obs_log
    real = obs_log.warning
    monkeypatch.setattr(
        obs_log, "warning",
        lambda msg, **kw: (warns.append(msg) if "recompile" in msg
                           else real(msg, **kw)))
    flags.set_flag("device_recompile_warmup", 2)
    j = instrument_jit(_f, "churny")
    for n in (8, 16, 32, 64, 128):   # 5 distinct signatures
        a = jnp.ones((n,), jnp.float32)
        j(a, a)
    e = device.snapshot()["entries"]["churny"]
    assert e["compiles"] == 5
    # warmup 2 -> compiles 3, 4, 5 are steady-state churn
    assert e["steady_recompiles"] == 3
    assert stat_get("device_recompiles") == 3
    assert e["recompile_flagged"] is True
    assert len(warns) == 1, warns    # loud ONCE per fn


def test_recompile_warmup_override():
    flags.set_flag("device_recompile_warmup", 1)
    j = instrument_jit(_f, "wide", recompile_warmup=16)
    for n in (8, 16, 32, 64):
        a = jnp.ones((n,), jnp.float32)
        j(a, a)
    assert stat_get("device_recompiles") == 0
    assert not device.snapshot()["entries"]["wide"]["recompile_flagged"]


def test_recompiles_scored_unhealthy_by_health_monitor():
    """Acceptance: the sentinel turns the rank unhealthy within 2 report
    windows — the very FIRST window carrying the stat delta scores it."""
    hm = HealthMonitor(world=2)
    merged = {"step": 10, "stale_ranks": [],
              "metrics": {"stats.device_recompiles":
                          {"per_rank": {"0": 3.0}}}}
    rec = hm.update(merged)
    assert rec["ranks"]["0"]["healthy"] is False
    assert "device_recompiles" in rec["ranks"]["0"]["flags"]
    assert rec["ranks"]["1"]["healthy"] is True
    assert 0 in rec["unhealthy_ranks"]


# -------------------------------------------------------- donation audit

def test_donated_entry_point_reuses_buffer():
    """CPU honors donation (trainer.py's documented contract): the
    donated pointer comes back as an output and the audit stays clean."""
    j = instrument_jit(_f, "donated", donate_argnums=(0,))
    for _ in range(3):
        j(_big(), _big())
    d = device.snapshot()["entries"]["donated"]["donation"]
    assert d["supported"] is True
    assert d["checks"] == 3
    assert d["misses"] == 0
    assert stat_get("donation_miss") == 0


def test_non_donated_twin_trips_donation_miss(monkeypatch):
    warns = []
    from paddlebox_tpu.obs import log as obs_log
    real = obs_log.warning
    monkeypatch.setattr(
        obs_log, "warning",
        lambda msg, **kw: (warns.append(msg) if "donation" in msg
                           else real(msg, **kw)))
    j = instrument_jit(_f, "twin", audit_argnums=(0,))  # audited, NOT donated
    for _ in range(3):
        j(_big(), _big())
    d = device.snapshot()["entries"]["twin"]["donation"]
    # every call misses; the debounce counts from the SECOND consecutive
    # miss of the executable (an isolated miss is the one-time copy of a
    # host-staged buffer, not the regime)
    assert d["checks"] == 3
    assert d["misses"] == 2
    assert stat_get("donation_miss") == 2
    assert len(warns) == 1, warns    # loud once per fn

    hm = HealthMonitor(world=1)
    rec = hm.update({"step": 1, "stale_ranks": [],
                     "metrics": {"stats.donation_miss":
                                 {"per_rank": {"0": 2.0}}}})
    assert rec["ranks"]["0"]["healthy"] is False
    assert "donation_miss" in rec["ranks"]["0"]["flags"]


def test_donation_miss_debounced_per_executable():
    """An ISOLATED miss is never counted: the pass's first step donates
    the host-staged slab — a buffer jax zero-copied from numpy memory
    that cannot be aliased in place and is copied exactly once — while
    the regime-step alarm is for the RECURRING per-step copy."""
    # one audited call that misses, then silence: not counted
    j = instrument_jit(_f, "lone", audit_argnums=(0,))
    j(_big(), _big())
    d = device.snapshot()["entries"]["lone"]["donation"]
    assert d["checks"] == 1 and d["misses"] == 0
    assert stat_get("donation_miss") == 0

    # the e2e shape: host-staged first input misses once, the chained
    # device-produced outputs alias cleanly — audit stays at zero
    k = instrument_jit(_f, "staged", donate_argnums=(0,))
    x = jnp.asarray(np.full(BIG, 1.0, np.float32))  # host-backed
    for _ in range(3):
        x, _ = k(x, _big())
    d = device.snapshot()["entries"]["staged"]["donation"]
    assert d["checks"] == 3 and d["misses"] == 0
    assert stat_get("donation_miss") == 0


def test_donation_audit_skips_small_buffers():
    """Buffers under device_donation_min_bytes are aliasing noise —
    never audited, never counted."""
    j = instrument_jit(_f, "tiny", audit_argnums=(0,))
    a = jnp.ones((8,), jnp.float32)
    j(a, a)
    d = device.snapshot()["entries"]["tiny"]["donation"]
    assert d["checks"] == 0
    assert stat_get("donation_miss") == 0


# -------------------------------------------------------- transfer ledger

def test_transfer_ledger_counters_and_hists():
    device.account_h2d(100_000)
    device.account_h2d(50_000)
    device.account_d2h(7_000)
    snap = device.snapshot()["transfers"]
    assert snap["h2d_bytes"] == 150_000
    assert snap["d2h_bytes"] == 7_000
    hists = StatRegistry.instance().snapshot_all()["hists"]
    assert sum(hists["device_h2d_bytes"]) == 2
    assert sum(hists["device_d2h_bytes"]) == 1


def test_tree_nbytes_walks_containers():
    t = {"a": np.zeros(10, np.float32),
         "b": [np.zeros(3, np.int64), (np.zeros(2, np.uint8), None)]}
    assert device.tree_nbytes(t) == 40 + 24 + 2


# ------------------------------------------------------ HBM ledger + leak

def test_ledger_buckets_by_owner():
    keep = _big()  # 256 KB
    device.register_owner("slab", lambda: keep)
    # an entry so the monitor reads active
    j = instrument_jit(_f, "ledgered")
    j(keep, _big())
    sample = device.sample_ledger()
    assert sample["owners"]["slab"] == keep.nbytes
    assert sample["total_bytes"] >= keep.nbytes
    g = StatRegistry.instance().snapshot_all()["gauges"]
    assert g["device_live_bytes_slab"] == float(keep.nbytes)
    assert g["device_live_bytes_total"] == float(sample["total_bytes"])


def test_leak_detector_fires_on_leak_and_stays_silent_when_clean():
    flags.set_flag("device_leak_windows", 3)
    flags.set_flag("device_leak_min_bytes", 100_000)
    leaked = []

    # three clean passes: stable totals, no alarm
    base = _big()
    for _ in range(3):
        device.sample_ledger()
    assert stat_get("device_leak_suspect") == 0

    # leak one ~256 KB array per "pass": 3 consecutive growth windows
    for _ in range(4):
        leaked.append(_big())
        device.sample_ledger()
    assert stat_get("device_leak_suspect") >= 1
    fired = stat_get("device_leak_suspect")

    # growth stopped: streak resets, no further alarms
    for _ in range(3):
        device.sample_ledger()
    assert stat_get("device_leak_suspect") == fired
    del base, leaked


# ------------------------------------------------------- report plumbing

def test_step_report_carries_device_stats_and_ledger_gauges():
    """Acceptance (i): a forced recompile and a donation miss land in
    the StepReport stats delta; the ledger gauges ride the same record."""
    flags.set_flag("device_recompile_warmup", 1)
    j = instrument_jit(_f, "report_churn")
    for n in (8, 16, 32):
        a = jnp.ones((n,), jnp.float32)
        j(a, a)
    t = instrument_jit(_f, "report_twin", audit_argnums=(0,))
    t(_big(), _big())
    t(_big(), _big())  # second consecutive miss crosses the debounce

    sink = ListSink()
    rep = StepReporter(rank=0, every=1, sink=sink)
    rep.note_examples(10)
    rec = rep.maybe_report(1, force=True)
    assert rec["stats"]["device_recompiles"] == 2
    assert rec["stats"]["donation_miss"] == 1
    # ledger sampled at report cadence (monitor is active)
    assert rec["gauges"]["device_live_bytes_total"] > 0
    rep.close()


# ----------------------------------------------------------- HTTP surface

def _get(exp, path):
    r = urllib.request.urlopen(
        "http://127.0.0.1:%d%s" % (exp.port, path), timeout=5.0)
    return r.read().decode("utf-8")


def test_device_endpoint_schema_pinned():
    """Acceptance (ii): the forced signals are visible on /device and
    /metrics."""
    flags.set_flag("device_recompile_warmup", 1)
    j = instrument_jit(_f, "http_churn", donate_argnums=(0,))
    for n in (256, 512, 1024):
        j(jnp.ones((n, 64), jnp.float32), jnp.ones((n, 64), jnp.float32))
    t = instrument_jit(_f, "http_twin", audit_argnums=(0,))
    t(_big(), _big())
    t(_big(), _big())  # second consecutive miss crosses the debounce
    device.account_h2d(12345)

    exp = ObsExporter(port=0)
    try:
        snap = json.loads(_get(exp, "/device"))
        assert snap["type"] == "device_plane"
        assert snap["v"] == 1
        assert snap["active"] is True
        assert snap["rank"] == 0
        e = snap["entries"]["http_churn"]
        for key in ("compiles", "compile_ms", "last_compile_ms",
                    "signatures", "steady_recompiles", "recompile_flagged",
                    "donate_argnums", "donation", "analysis"):
            assert key in e, key
        assert e["compiles"] == 3
        assert e["recompile_flagged"] is True
        assert snap["entries"]["http_twin"]["donation"]["misses"] == 1
        assert snap["recompiles"] == 2
        assert snap["donation_miss"] == 1
        assert snap["transfers"]["h2d_bytes"] == 12345

        text = _get(exp, "/metrics")
        assert "pbtpu_device_recompiles 2" in text
        assert "pbtpu_donation_miss 1" in text
        assert "pbtpu_device_transfer_bytes_h2d 12345" in text
        assert 'pbtpu_device_compile_ms_bucket{le="+Inf"} 4' in text

        # the index advertises the new endpoint
        assert "/device" in json.loads(_get(exp, "/"))["endpoints"]
    finally:
        exp.close()


# ----------------------------------------------------------- flight seal

def test_flight_seal_includes_device_snapshot(tmp_path):
    """Acceptance (iv): a seal carries the device snapshot — the
    postmortem says whether the dying rank was recompiling or copying
    its slab."""
    from paddlebox_tpu.obs.flight import FlightRecorder
    flags.set_flag("device_recompile_warmup", 1)
    j = instrument_jit(_f, "seal_churn")
    for n in (8, 16, 32):
        a = jnp.ones((n,), jnp.float32)
        j(a, a)
    fr = FlightRecorder(str(tmp_path), rank=0)
    try:
        path = fr.seal("test_seal")
        with open(path, "r", encoding="utf-8") as fh:
            manifest = json.load(fh)
        dev = manifest["device"]
        assert dev["type"] == "device_plane"
        assert dev["entries"]["seal_churn"]["recompile_flagged"] is True
        assert dev["recompiles"] == 2
    finally:
        fr.close()


def test_snapshot_reentrant_from_seal_path():
    """The fatal-signal seal calls snapshot() from a handler that can
    interrupt this same thread inside a monitor mutation or stat_add —
    the monitor RLock + lock-free stat peeks must let the dying process
    seal instead of self-deadlocking (the PR-9 tracer._reg_lock class)."""
    from paddlebox_tpu.utils.stats import StatRegistry
    j = instrument_jit(_f, "sealable")
    j(_big(), _big())
    with device.monitor()._lock:          # handler fired mid-register
        snap = device.snapshot()
    assert snap["entries"]["sealable"]["compiles"] == 1
    with StatRegistry.instance()._lock:   # handler fired mid-stat_add
        snap = device.snapshot()
    assert snap["entries"]["sealable"]["compiles"] == 1


# --------------------------------------------------------- e2e bit parity

NUM_SLOTS = 4
D = 8


def _mini_trainer(feed, seed=0):
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 12,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)
    model = CtrDnn(spec, hidden=(16,))
    return BoxTrainer(model, table_cfg, feed,
                      TrainerConfig(dense_lr=3e-3), seed=seed)


def test_e2e_instrumented_vs_bare_bit_parity(tmp_path):
    """The wrapper is a pure twin: a training pass under device_obs on
    vs off (bare jax.jit) produces BIT-identical params and slab — and
    the instrumented pass is recompile/donation-miss clean (the
    steady-state gates the regression probe enforces)."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=400,
        num_slots=NUM_SLOTS, vocab_per_slot=50, max_len=3, seed=3)
    feed = type(feed)(slots=feed.slots, batch_size=64)

    results = {}
    for obs_on in (True, False):
        flags.set_flag("device_obs", obs_on)
        trainer = _mini_trainer(feed)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()
        results[obs_on] = (
            jax.tree_util.tree_map(np.asarray, trainer.params),
            np.asarray(trainer.table._slab),  # resident post-pass slab
        )
        if obs_on:
            # steady state is clean: no sentinel trips, no misses
            assert stat_get("device_recompiles") == 0
            assert stat_get("donation_miss") == 0
            assert device.snapshot()["entries"]["train_step"] is not None
        trainer.close()

    on_leaves = jax.tree_util.tree_leaves(results[True][0])
    off_leaves = jax.tree_util.tree_leaves(results[False][0])
    assert len(on_leaves) == len(off_leaves)
    for a, b in zip(on_leaves, off_leaves):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(results[True][1], results[False][1])


def test_flag_off_returns_bare_jit():
    flags.set_flag("device_obs", False)
    j = instrument_jit(_f, "bare")
    assert not isinstance(j, InstrumentedJit)
    out = j(_big(), _big())
    assert np.asarray(out[1]) == pytest.approx(64 * 1024.0)
    assert "bare" not in device.snapshot()["entries"]
