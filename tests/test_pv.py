"""pv grouping, rank-offset feed, side tables, join-phase model."""

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.data.pv import (build_rank_offset, pack_pv_batch,
                                   preprocess_instance)
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.embedding.side_tables import InputTable, ReplicaCache
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.join_pv import JoinPvDnn


def _rank_offset_oracle(ranks, cmatchs, pv_offsets, max_rank):
    """Literal CopyRankOffsetKernel transcription (data_feed.cu:1319-1369)."""
    n = len(ranks)
    cols = 2 * max_rank + 1
    mat = np.full((n, cols), -1, np.int32)
    for p in range(len(pv_offsets) - 1):
        lo, hi = pv_offsets[p], pv_offsets[p + 1]
        for j in range(lo, hi):
            rank = -1
            if cmatchs[j] in (222, 223) and 0 < ranks[j] <= max_rank:
                rank = ranks[j]
            mat[j, 0] = rank
            if rank > 0:
                for k in range(lo, hi):
                    fast = -1
                    if cmatchs[k] in (222, 223) and 0 < ranks[k] <= max_rank:
                        fast = ranks[k]
                    if fast > 0:
                        m = fast - 1
                        mat[j, 2 * m + 1] = ranks[k]
                        mat[j, 2 * m + 2] = k
    return mat


def test_build_rank_offset_matches_kernel_oracle():
    rng = np.random.RandomState(0)
    # 3 pvs: sizes 3, 1, 2
    pv_offsets = np.array([0, 3, 4, 6])
    ranks = np.array([1, 2, 3, 1, 2, 1], np.int32)
    cmatchs = np.array([222, 223, 222, 110, 222, 223], np.int32)
    got = build_rank_offset(ranks, cmatchs, pv_offsets, max_rank=3)
    ref = _rank_offset_oracle(ranks, cmatchs, pv_offsets, 3)
    np.testing.assert_array_equal(got, ref)


def test_preprocess_instance_groups_by_sid():
    recs = [SlotRecord(search_id=s) for s in (7, 3, 7, 3, 9)]
    pvs = preprocess_instance(recs)
    sids = [{recs[i].search_id for i in pv} for pv in pvs]
    assert all(len(s) == 1 for s in sids)
    assert sorted(next(iter(s)) for s in sids) == [3, 7, 9]
    assert sum(len(pv) for pv in pvs) == 5
    # merge off → one pv per record
    assert len(preprocess_instance(recs, merge_by_sid=False)) == 5


def test_pack_pv_batch_contiguous_order():
    recs = [SlotRecord(search_id=s, rank=r, cmatch=222)
            for s, r in ((1, 1), (2, 1), (1, 2), (2, 2))]
    pvs = preprocess_instance(recs)
    order, mat = pack_pv_batch(recs, pvs, max_rank=3)
    assert sorted(order) == [0, 1, 2, 3]
    assert mat.shape == (4, 7)
    # first pv = sid 1 → rows 0,1 are peers of each other
    assert mat[0, 0] == 1 and mat[1, 0] == 2
    assert mat[0, 4] == 1  # peer with rank 2 sits at batch row 1


def test_replica_cache_roundtrip():
    rc = ReplicaCache(3)
    i0 = rc.add_items(np.array([1.0, 2.0, 3.0]))
    i1 = rc.add_items(np.array([4.0, 5.0, 6.0]))
    assert (i0, i1) == (0, 1)
    out = np.asarray(rc.pull(jnp.asarray(np.array([1, 0], np.int32))))
    np.testing.assert_allclose(out, [[4, 5, 6], [1, 2, 3]])


def test_input_table_miss_maps_to_zero_row():
    t = InputTable(2)
    t.add_index_data("k1", np.array([1.0, 1.0]))
    off_hit = t.get_index_offset("k1")
    off_miss = t.get_index_offset("nope")
    assert off_miss == 0 and t.miss == 1
    out = np.asarray(t.lookup_input(
        jnp.asarray(np.array([off_hit, off_miss], np.int32))))
    np.testing.assert_allclose(out, [[1, 1], [0, 0]])


def test_join_pv_model_runs_and_differentiates():
    B, S, SD = 4, 2, 5
    spec = ModelSpec(num_slots=S, slot_dim=SD)
    model = JoinPvDnn(spec, max_rank=2, att_dim=8, hidden=(16,))
    params = model.init(jax.random.PRNGKey(0))
    pooled = jnp.asarray(np.random.RandomState(0).rand(B, S, SD)
                         .astype(np.float32))
    ro = jnp.asarray(np.array([[1, 1, 0, 2, 1], [2, 1, 0, 2, 1],
                               [1, 1, 2, -1, -1], [-1, -1, -1, -1, -1]],
                              np.int32))
    logits = model.apply(params, pooled, rank_offset=ro)
    assert logits.shape == (B,)

    def loss(params):
        return (model.apply(params, pooled, rank_offset=ro) ** 2).sum()

    g = jax.grad(loss)(params)
    assert np.asarray(g["rank_param"]).any()
    # fallback path without rank_offset also runs
    assert model.apply(params, pooled).shape == (B,)
