"""Round-11 line-rate push: blocked scatter + bf16 slab dtype diet.

Contracts under test:

  * push_write='blocked' (push_blocked_write): bucketize the SORTED uid
    vector into contiguous row blocks, place each touched block with one
    dynamic_update_slice — must be BIT-IDENTICAL to the scatter oracle on
    every wire (host dedup products, uid wire), at chunk>1, multi-pass,
    and through the sharded runners' 2-virtual-process staging. The
    staging side must pin the sorted dedup tier (dedup_ids sort=True):
    the native rt_dedup hash order would silently drop rows.
  * push_blocked_pallas: the Mosaic placement kernel (interpreted off-
    TPU) is a drop-in for the fori_loop of dynamic_update_slices.
  * push_onehot_rows (merge_grads_onehot): MXU one-hot accumulation for
    the hot short tail — exact for integer-representable grads (f32
    accumulation ORDER differs, so the parity pin uses integer grads).
  * slab_embed_dtype='bfloat16' (accessor slab codec): weight columns
    round to bf16 at the slab write; the header and ALL optimizer stats
    round-trip BIT-EXACTLY through encode/decode, the store/checkpoint
    round trip, and a full pass. Training quality is AUC-parity gated
    (no bit oracle — the tolerance is recorded in BASELINE.md round 11).
"""

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("push_blocked_data")
    # small vocab → heavy key recurrence: many touched rows per block,
    # revisited across batches — the blocked write's hard case
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=480, num_slots=NUM_SLOTS,
        vocab_per_slot=120, max_len=3, seed=13)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    return files, feed


# ------------------------------------------------------------- unit tier

def _unit_setup(seed=3, cap=512, K=96, hot_frac=0.0, int_grads=False):
    import jax

    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout

    rng = np.random.RandomState(seed)
    layout = ValueLayout(D, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    push = PushLayout(D)
    slab = rng.rand(cap, layout.width).astype(np.float32)
    if hot_frac:
        # skewed batch: most occurrences hit a few hot keys
        hot = rng.rand(K) < hot_frac
        ids = np.where(hot, rng.randint(0, 4, K),
                       rng.randint(0, cap // 2, K)).astype(np.int32)
    else:
        ids = rng.randint(0, cap // 2, K).astype(np.int32)
    ids[rng.rand(K) < 0.2] = cap - 1              # padding occurrences
    if int_grads:
        grads = rng.randint(-3, 4, (K, push.width)).astype(np.float32)
    else:
        grads = rng.randn(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    grads[ids == cap - 1] = 0.0
    prng = jax.random.PRNGKey(11)
    return layout, conf, push, slab, ids, grads, prng


def test_push_blocked_write_unit_parity():
    """push_sparse_hostdedup/uidwire write='blocked' vs the scatter
    oracle, across block sizes spanning touched<blocks and
    touched==blocks regimes — bit-identical placement."""
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    push_sparse_uidwire)
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted)

    layout, conf, push, slab, ids, grads, prng = _unit_setup()
    cap = slab.shape[0]
    uids, perm, inv = dedup_ids(ids, cap, sort=True)
    assert np.all(np.diff(uids.astype(np.int64)) > 0)
    oracle = push_sparse_hostdedup(
        jnp.asarray(slab), jnp.asarray(uids), jnp.asarray(perm),
        jnp.asarray(inv), jnp.asarray(grads), prng, layout, conf)
    suids = dedup_uids_sorted(ids, cap)
    for block in (8, 64, 256, 512):
        flags.set_flag("push_block_rows", block)
        try:
            got = push_sparse_hostdedup(
                jnp.asarray(slab), jnp.asarray(uids), jnp.asarray(perm),
                jnp.asarray(inv), jnp.asarray(grads), prng, layout, conf,
                write="blocked")
            np.testing.assert_array_equal(np.asarray(oracle),
                                          np.asarray(got),
                                          err_msg=f"hostdedup B={block}")
            got_w = push_sparse_uidwire(
                jnp.asarray(slab), jnp.asarray(suids), jnp.asarray(ids),
                jnp.asarray(grads), prng, layout, conf, write="blocked")
            np.testing.assert_array_equal(np.asarray(oracle),
                                          np.asarray(got_w),
                                          err_msg=f"uidwire B={block}")
        finally:
            flags.set_flag("push_block_rows", 1024)


def test_push_blocked_write_all_pad_and_dense():
    """Degenerate shapes: an all-padding batch writes nothing; a batch
    touching EVERY block still places correctly."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.optimizers import push_blocked_write

    cap, W = 64, 5
    rng = np.random.RandomState(0)
    slab = rng.rand(cap, W).astype(np.float32)
    # all-padding: uids all out of range
    uids = (cap + np.arange(16)).astype(np.int32)
    rows = rng.rand(16, W).astype(np.float32)
    out = push_blocked_write(jnp.asarray(slab), jnp.asarray(uids),
                             jnp.asarray(rows), 16)
    np.testing.assert_array_equal(np.asarray(out), slab)
    # every row touched (uids == arange): blocked == full overwrite
    uids = np.arange(cap, dtype=np.int32)
    rows = rng.rand(cap, W).astype(np.float32)
    out = push_blocked_write(jnp.asarray(slab), jnp.asarray(uids),
                             jnp.asarray(rows), 8)
    np.testing.assert_array_equal(np.asarray(out), rows)
    # non-divisor block fails loud
    with pytest.raises(ValueError, match="divide"):
        jax.jit(lambda s: push_blocked_write(
            s, jnp.asarray(uids), jnp.asarray(rows), 7))(jnp.asarray(slab))


def test_pallas_blocked_write_matches_fori():
    """push_blocked_pallas (interpreted off-TPU): the Mosaic grid
    placement is bit-identical to the XLA fori_loop tier."""
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.optimizers import push_blocked_write

    rng = np.random.RandomState(4)
    cap, W, U = 128, 6, 40
    slab = rng.rand(cap, W).astype(np.float32)
    data = np.sort(rng.choice(cap, U - 8, replace=False)).astype(np.int32)
    uids = np.concatenate([data, cap + np.arange(8, dtype=np.int32)])
    rows = rng.rand(U, W).astype(np.float32)
    base = push_blocked_write(jnp.asarray(slab), jnp.asarray(uids),
                              jnp.asarray(rows), 16)
    flags.set_flag("push_blocked_pallas", True)
    try:
        got = push_blocked_write(jnp.asarray(slab), jnp.asarray(uids),
                                 jnp.asarray(rows), 16)
    finally:
        flags.set_flag("push_blocked_pallas", False)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(got))


def test_resolve_blocked_validation():
    """resolve_push_write: blocked demands a positive divisor block —
    refused at resolve time, not deep in the jit."""
    from paddlebox_tpu.train.trainer import resolve_push_write

    flags.set_flag("push_write", "blocked")
    try:
        flags.set_flag("push_block_rows", 1024)
        assert resolve_push_write(capacity=4096, batch_keys=512) == "blocked"
        with pytest.raises(ValueError, match="divide"):
            resolve_push_write(capacity=1000, batch_keys=512)
        flags.set_flag("push_block_rows", 0)
        with pytest.raises(ValueError, match="push_block_rows"):
            resolve_push_write(capacity=4096, batch_keys=512)
    finally:
        flags.set_flag("push_block_rows", 1024)
        flags.set_flag("push_write", "auto")


def test_merge_grads_onehot_exact_for_integer_grads():
    """push_onehot_rows: the MXU one-hot merge == segment-sum merge
    exactly when grads are integer-representable (f32 addition is exact
    on small integers regardless of order) — and the full uid-wire push
    under the flag stays bit-identical to the oracle on such grads."""
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.optimizers import (merge_grads_onehot,
                                                    push_sparse_uidwire)
    from paddlebox_tpu.embedding.pass_table import dedup_uids_sorted

    layout, conf, push, slab, ids, grads, prng = _unit_setup(
        seed=9, hot_frac=0.7, int_grads=True)
    cap = slab.shape[0]
    K = ids.shape[0]
    suids = dedup_uids_sorted(ids, cap)
    inv = np.searchsorted(suids, ids).astype(np.int32)
    import jax.ops
    ref = jax.ops.segment_sum(jnp.asarray(grads), jnp.asarray(inv),
                              num_segments=K)
    for hot in (1, 4, K):
        got = merge_grads_onehot(jnp.asarray(grads), jnp.asarray(inv), K,
                                 hot)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got),
                                      err_msg=f"hot={hot}")
    oracle = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(suids),
                                 jnp.asarray(ids), jnp.asarray(grads),
                                 prng, layout, conf)
    flags.set_flag("push_onehot_rows", 4)
    try:
        got = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(suids),
                                  jnp.asarray(ids), jnp.asarray(grads),
                                  prng, layout, conf)
    finally:
        flags.set_flag("push_onehot_rows", 0)
    np.testing.assert_array_equal(np.asarray(oracle), np.asarray(got))


def test_dedup_ids_sort_option():
    """dedup_ids(sort=True): strictly ascending uids with consistent
    perm/inv (the blocked-write staging contract), even when the native
    hash-order tier is available and would win the default call."""
    from paddlebox_tpu.embedding.pass_table import dedup_ids

    rng = np.random.RandomState(21)
    for K, space in ((256, 50), (512, 500), (64, 8)):
        ids = rng.randint(0, space, K).astype(np.int32)
        uids, perm, inv = dedup_ids(ids, space, sort=True)
        assert np.all(np.diff(uids.astype(np.int64)) > 0)
        assert np.array_equal(np.sort(perm), np.arange(K))
        assert (np.diff(inv) >= 0).all()
        np.testing.assert_array_equal(uids[inv], ids[perm])
        n_u = np.unique(ids).size
        assert (uids[:n_u] < space).all() and (uids[n_u:] >= space).all()


# ------------------------------------------------------------ codec tier

def _stat_cols(layout):
    """Boolean mask of the NON-weight columns (header + optimizer stats)
    — everything the bf16 diet must preserve bit-exactly."""
    from paddlebox_tpu.embedding.accessor import slab_codec_plan
    return ~slab_codec_plan(layout).bf16_cols


def test_slab_codec_roundtrip_bits():
    """encode→decode: stats/header columns recover their EXACT f32 bits
    (incl. negative zero and denormals); weight columns equal the bf16
    round-trip; numpy and jnp codec twins agree bit for bit."""
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import (ValueLayout,
                                                  decode_slab_rows,
                                                  decode_slab_rows_np,
                                                  encode_slab_rows,
                                                  encode_slab_rows_np)

    rng = np.random.RandomState(6)
    for opt in ("adagrad", "adam"):
        layout = ValueLayout(D, opt, embed_dtype="bfloat16")
        f32 = ValueLayout(D, opt)
        assert layout.device_dtype == np.uint16
        assert f32.device_width == f32.width
        rows = (rng.randn(32, layout.width) * 10).astype(np.float32)
        rows[0, 1] = -0.0
        rows[1, 2] = 1e-42                     # denormal survives the split
        rows[2, 3] = np.float32(np.pi)
        enc_np = encode_slab_rows_np(rows, layout)
        assert enc_np.shape == (32, layout.device_width)
        enc_j = np.asarray(encode_slab_rows(jnp.asarray(rows), layout))
        np.testing.assert_array_equal(enc_np, enc_j)
        dec_np = decode_slab_rows_np(enc_np, layout)
        dec_j = np.asarray(decode_slab_rows(jnp.asarray(enc_j), layout))
        np.testing.assert_array_equal(dec_np, dec_j)
        stats = _stat_cols(layout)
        # stats: exact bit round trip
        np.testing.assert_array_equal(dec_np[:, stats].view(np.uint32),
                                      rows[:, stats].view(np.uint32))
        # weights: exactly the bf16 value (one rounding, no double round)
        w = ~stats
        expect = np.asarray(jnp.asarray(rows[:, w]).astype(
            jnp.bfloat16).astype(jnp.float32))
        np.testing.assert_array_equal(dec_np[:, w], expect)
        # f32 layout: both directions are identity
        np.testing.assert_array_equal(encode_slab_rows_np(rows, f32), rows)
        np.testing.assert_array_equal(decode_slab_rows_np(rows, f32), rows)


def test_bf16_pass_table_store_roundtrip():
    """A full begin_pass/end_pass cycle under the bf16 slab with NO
    training: stats/header columns come back to the store bit-exact;
    weight columns come back as their bf16 rounding, once (idempotent on
    a second cycle — no double rounding drift)."""
    from paddlebox_tpu.embedding.pass_table import PassTable

    keys = np.arange(1, 120, dtype=np.uint64)

    def cycle(table, n=2):
        for _ in range(n):
            table.begin_feed_pass()
            table.add_keys(keys)
            table.end_feed_pass()
            table.begin_pass()
            table.end_pass()
        k, v = table.store.state_items()
        order = np.argsort(k)
        return k[order], v[order]

    cfg = TableConfig(embedx_dim=D, pass_capacity=256)
    base = PassTable(cfg, seed=1)
    k_f32, v_f32 = cycle(base, n=1)
    flags.set_flag("slab_embed_dtype", "bfloat16")
    try:
        diet = PassTable(cfg, seed=1)
        assert diet.layout.embed_dtype == "bfloat16"
        k_b, v_b = cycle(diet, n=1)
        np.testing.assert_array_equal(k_f32, k_b)
        stats = _stat_cols(base.layout)
        np.testing.assert_array_equal(v_f32[:, stats].view(np.uint32),
                                      v_b[:, stats].view(np.uint32))
        import jax.numpy as jnp
        expect = np.asarray(jnp.asarray(v_f32[:, ~stats]).astype(
            jnp.bfloat16).astype(jnp.float32))
        np.testing.assert_array_equal(v_b[:, ~stats], expect)
        # second cycle: already-bf16 weights are fixed points — no drift
        k_b2, v_b2 = cycle(diet, n=1)
        np.testing.assert_array_equal(v_b, v_b2)
    finally:
        flags.set_flag("slab_embed_dtype", "float32")


def test_bf16_differentiable_pull_fails_loud():
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.ops.sparse import pull_sparse_differentiable

    layout = ValueLayout(D, "adagrad", embed_dtype="bfloat16")
    with pytest.raises(ValueError, match="float32 slab"):
        pull_sparse_differentiable(jnp.zeros((8, layout.device_width),
                                             jnp.uint16),
                                   jnp.zeros((4,), jnp.int32), layout)


# -------------------------------------------------------------- e2e tier

def run_mode(files, feed, mode, wire=None, block=256, passes=2,
             embed_dtype="float32", seed=0):
    """Train the single-host trainer; returns (losses, store keys/values,
    dense params). wire None = full host products, 'uid' = uid wire."""
    flags.set_flag("push_write", mode)
    flags.set_flag("push_block_rows", block)
    flags.set_flag("slab_embed_dtype", embed_dtype)
    if wire is not None:
        flags.set_flag("h2d_lean", True)
        flags.set_flag("h2d_uid_wire", wire == "uid")
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(
                mf_create_thresholds=0.0, mf_initial_range=1e-3))
        from paddlebox_tpu.train import BoxTrainer
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        tr = BoxTrainer(model, table, feed, TrainerConfig(scan_chunk=2),
                        seed=seed)
        losses = []
        for _ in range(passes):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(tr.train_pass(ds)["loss"])
            ds.release_memory()
        keys, vals = tr.table.store.state_items()
        order = np.argsort(keys)
        params = tr.params
        tr.close()
        return losses, keys[order], vals[order], params
    finally:
        flags.set_flag("push_write", "auto")
        flags.set_flag("push_block_rows", 1024)
        flags.set_flag("slab_embed_dtype", "float32")
        flags.set_flag("h2d_lean", False)
        flags.set_flag("h2d_uid_wire", True)


def assert_identical(a, b):
    la, ka, va, pa = a
    lb, kb, vb, pb = b
    assert la == lb
    assert np.array_equal(ka, kb)
    assert np.array_equal(va, vb)
    import jax
    for xa, xb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.slow
def test_blocked_e2e_matches_scatter_full_wire(data):
    """push_write=blocked on the FULL host wire (sorted dedup staging) at
    chunk>1 over 2 passes: bit-identical training to scatter.

    Slow tier (round 14, budget): a 2-pass composition of contracts
    tier-1 keeps pinned individually — unit blocked-vs-scatter parity,
    the uid-wire e2e below (the default wire), and the dedup sort=True
    staging contract in test_wire_modes."""
    files, feed = data
    base = run_mode(files, feed, "scatter")
    blocked = run_mode(files, feed, "blocked")
    assert_identical(base, blocked)


def test_blocked_e2e_matches_scatter_uid_wire(data):
    """push_write=blocked on the uid wire (device-derived maps over the
    sorted staged uids): bit-identical to the scatter uid wire."""
    files, feed = data
    base = run_mode(files, feed, "scatter", wire="uid", passes=1)
    blocked = run_mode(files, feed, "blocked", wire="uid", passes=1)
    assert_identical(base, blocked)


@pytest.mark.slow
def test_blocked_bf16_matches_scatter_bf16(data):
    """The two tentpole layers compose: under the bf16 slab diet the
    write placement is still bit-identical between scatter and blocked
    (same encoded rows, different placement) — so the diet's AUC gate
    transfers to the blocked path for free.

    Slow tier (round 14, budget): pure composition — the codec's bit
    round-trip, bf16 AUC parity, and blocked-vs-scatter parity each
    stay pinned in tier-1 on their own."""
    files, feed = data
    base = run_mode(files, feed, "scatter", embed_dtype="bfloat16",
                    passes=1)
    blocked = run_mode(files, feed, "blocked", embed_dtype="bfloat16",
                       passes=1)
    assert_identical(base, blocked)


def test_bf16_slab_trains_with_auc_parity(data):
    """The bf16 AUC-parity gate (no bit oracle: weights round at every
    slab write): same data, same seeds, slab f32 vs bf16 — streaming AUC
    must stay within the recorded tolerance (measured |Δ| ≈ 2e-6 on this
    container at this shape, gated at 0.01; BASELINE.md round 11) and
    both clearly above chance."""
    from paddlebox_tpu.train import BoxTrainer

    files, feed = data

    def train_auc(embed_dtype):
        flags.set_flag("slab_embed_dtype", embed_dtype)
        try:
            table = TableConfig(
                embedx_dim=D, pass_capacity=2048,
                optimizer=SparseOptimizerConfig(
                    mf_create_thresholds=0.0, mf_initial_range=1e-3,
                    feature_learning_rate=0.1, mf_learning_rate=0.1))
            model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                           hidden=(32, 16))
            tr = BoxTrainer(model, table, feed,
                            TrainerConfig(dense_lr=3e-3, scan_chunk=2),
                            seed=0)
            assert tr.table.layout.embed_dtype == embed_dtype
            tr.metrics.init_metric("auc", "label", "pred",
                                   table_size=1 << 14, mask_var="mask")
            for _ in range(4):
                ds = BoxDataset(feed, read_threads=1)
                ds.set_filelist(files)
                tr.train_pass(ds)
                ds.release_memory()
            auc = tr.metrics.get_metric_msg("auc")["auc"]
            tr.close()
            return auc
        finally:
            flags.set_flag("slab_embed_dtype", "float32")

    auc_f32 = train_auc("float32")
    auc_b16 = train_auc("bfloat16")
    # streaming AUC mixes the untrained first pass; the gate is signal
    # clearly above chance, not the fully-trained test_e2e bar
    assert auc_f32 > 0.55 and auc_b16 > 0.55, (auc_f32, auc_b16)
    assert abs(auc_f32 - auc_b16) < 0.01, (auc_f32, auc_b16)


def test_bf16_checkpoint_roundtrip(data, tmp_path):
    """Checkpoint save/load under the bf16 slab: the store (host f32)
    round-trips bit-exactly — optimizer stats included — and the
    restored trainer keeps training on the dieted slab."""
    from paddlebox_tpu.config.configs import CheckpointConfig
    from paddlebox_tpu.train import BoxTrainer, CheckpointManager

    files, feed = data
    flags.set_flag("slab_embed_dtype", "bfloat16")
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                            mf_initial_range=1e-3))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        tr = BoxTrainer(model, table, feed, TrainerConfig(scan_chunk=2),
                        seed=2)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        tr.train_pass(ds)
        ds.release_memory()
        cfg = CheckpointConfig(batch_model_dir=str(tmp_path / "batch"),
                               xbox_model_dir=str(tmp_path / "xbox"))
        cm = CheckpointManager(cfg, tr.table)
        # snapshot BEFORE save: save_base's synchronous post-save stat
        # mutation (clear delta score, age unseen days) changes the live
        # store right after the file snapshot is taken
        k0, v0 = tr.table.store.state_items()
        k0, v0 = k0.copy(), v0.copy()
        order0 = np.argsort(k0)
        cm.save_base(tr.params, tr.opt_state, "d0")
        cm.wait()
        tr.close()

        tr2 = BoxTrainer(model, table, feed, TrainerConfig(scan_chunk=2),
                         seed=2)
        cm2 = CheckpointManager(cfg, tr2.table)
        tr2.params, tr2.opt_state, _ = cm2.load_base("d0")
        k1, v1 = tr2.table.store.state_items()
        order1 = np.argsort(k1)
        np.testing.assert_array_equal(k0[order0], k1[order1])
        np.testing.assert_array_equal(v0[order0].view(np.uint32),
                                      v1[order1].view(np.uint32))
        # and the restored table still trains on the dieted slab
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files[:1])
        loss = tr2.train_pass(ds)["loss"]
        assert np.isfinite(loss)
        ds.release_memory()
        tr2.close()
    finally:
        flags.set_flag("slab_embed_dtype", "float32")


# -------------------------------------------------------------- sharded

@pytest.mark.slow
def test_sharded_blocked_matches_scatter(data):
    """The 8-shard trainer with push_write=blocked (per-shard sorted
    staging via stage_push_dedup sort_uids; block must divide SHARD
    capacity) trains bit-identically to scatter — full wire AND uid
    wire."""
    from paddlebox_tpu.parallel import ShardedBoxTrainer

    files, feed = data
    states = {}
    for mode, uid in (("scatter", False), ("blocked", False),
                      ("scatter", True), ("blocked", True)):
        flags.set_flag("push_write", mode)
        flags.set_flag("push_block_rows", 128)   # shard_cap = 512
        flags.set_flag("h2d_uid_wire", uid)
        try:
            table_cfg = TableConfig(
                embedx_dim=D, pass_capacity=8 * (1 << 9),
                optimizer=SparseOptimizerConfig(
                    mf_create_thresholds=0.0, mf_initial_range=1e-3,
                    feature_learning_rate=0.1, mf_learning_rate=0.1))
            model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                           hidden=(16,))
            trainer = ShardedBoxTrainer(model, table_cfg, feed,
                                        TrainerConfig(dense_lr=3e-3),
                                        seed=4)
            assert trainer._push_write == mode
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            trainer.train_pass(ds)
            states[(mode, uid)] = [st.state_items()
                                   for st in trainer.table.stores]
            trainer.close()
        finally:
            flags.set_flag("push_write", "auto")
            flags.set_flag("push_block_rows", 1024)
            flags.set_flag("h2d_uid_wire", True)
    for uid in (False, True):
        for (k_b, v_b), (k_s, v_s) in zip(states[("blocked", uid)],
                                          states[("scatter", uid)]):
            np.testing.assert_array_equal(k_b, k_s)
            np.testing.assert_array_equal(v_b, v_s)


def test_two_virtual_process_blocked_staging():
    """2-virtual-process staging for the blocked write: sort_uids=True
    through the multiprocess bucket exchange delivers per-destination
    SORTED full products identical to single-process, and the blocked
    write over them matches the scatter oracle bit for bit."""
    import concurrent.futures

    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
    from paddlebox_tpu.embedding.optimizers import push_sparse_hostdedup
    from paddlebox_tpu.parallel.sharded_table import stage_push_dedup

    P, KB, shard_cap = 8, 16, 128
    rng = np.random.RandomState(8)
    buckets = np.full((P, P, KB), shard_cap - 1, np.int32)
    for s in range(P):
        for d in range(P):
            n = rng.randint(2, KB)
            buckets[s, d, :n] = rng.randint(0, shard_cap - 1, n)
    pool = concurrent.futures.ThreadPoolExecutor(2)
    try:
        single = stage_push_dedup(list(buckets), list(range(P)), P,
                                  shard_cap, multiprocess=False,
                                  all_gather=None, rebuild=False, pool=pool,
                                  sort_uids=True)
        for d in range(P):
            assert np.all(np.diff(
                single["push_uids"][d].astype(np.int64)) > 0), d

        def payload_of(bl, positions):
            bl = np.ascontiguousarray(bl, np.int32)
            header = np.array([len(positions), P, KB] + list(positions),
                              np.int32)
            return np.concatenate([header, bl.ravel()])

        parts = [payload_of(buckets[0:4], [0, 1, 2, 3]),
                 payload_of(buckets[4:8], [4, 5, 6, 7])]
        out = {}
        for lo, positions in ((0, [0, 1, 2, 3]), (4, [4, 5, 6, 7])):
            staged = stage_push_dedup(
                list(buckets[lo:lo + 4]), positions, P, shard_cap,
                multiprocess=True, all_gather=lambda payload: parts,
                rebuild=False, pool=pool, sort_uids=True)
            for i, d in enumerate(positions):
                out[d] = tuple(staged[k][i] for k in
                               ("push_uids", "push_perm", "push_inv"))
        layout = ValueLayout(D, "adagrad")
        conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                     mf_initial_range=1e-3)
        push = PushLayout(D)
        flags.set_flag("push_block_rows", 32)
        try:
            for d in range(P):
                uids, perm, inv = out[d]
                np.testing.assert_array_equal(uids, single["push_uids"][d])
                incoming = np.concatenate([buckets[s][d] for s in range(P)])
                grads = rng.randn(incoming.size,
                                  push.width).astype(np.float32)
                grads[:, push.SHOW] = 1.0
                grads[incoming == shard_cap - 1] = 0.0
                slab = rng.rand(shard_cap, layout.width).astype(np.float32)
                prng = jax.random.PRNGKey(d)
                oracle = push_sparse_hostdedup(
                    jnp.asarray(slab), jnp.asarray(uids), jnp.asarray(perm),
                    jnp.asarray(inv), jnp.asarray(grads), prng, layout,
                    conf)
                got = push_sparse_hostdedup(
                    jnp.asarray(slab), jnp.asarray(uids), jnp.asarray(perm),
                    jnp.asarray(inv), jnp.asarray(grads), prng, layout,
                    conf, write="blocked")
                np.testing.assert_array_equal(np.asarray(oracle),
                                              np.asarray(got),
                                              err_msg=f"dest {d}")
        finally:
            flags.set_flag("push_block_rows", 1024)
    finally:
        pool.shutdown(wait=False)
