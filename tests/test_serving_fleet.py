"""Multi-box serving fleet (round 21): client-side routing parity vs
the single-box oracle, pull coalescing, replica failover backoff,
shard-filtered views, the journal-fed freshness path, and the segment
tailer it rides on. Everything here is in-process over loopback except
the slow-marked spawn smoke."""

import os
import socket
import struct
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.parallel.sharding import KeyModPolicy, partition_pull
from paddlebox_tpu.serving.client import (BACKOFF_SKIP_CAP, FleetClient,
                                          ServingClient)
from paddlebox_tpu.serving.refresh import JournalDeltaSource, ViewManager
from paddlebox_tpu.serving.server import ServingServer
from paddlebox_tpu.serving.store import (MmapViewStack, ShardSpec,
                                         read_hot_keys, write_hot_keys,
                                         write_xbox_columnar)
from paddlebox_tpu.utils import journal_format as jf
from paddlebox_tpu.utils.stats import stat_get

EMBEDX = 4
DIM = 1 + EMBEDX      # embed_w + embedx: the served xbox row width
WIDTH = 7 + 1 + EMBEDX  # header + adagrad state + embedx (store row)


def make_view(tmp_path, n=2000, seed=0, name="view.xcol", lo=1):
    rng = np.random.RandomState(seed)
    keys = np.unique(rng.randint(lo, 1 << 40, n).astype(np.uint64))
    rows = rng.randn(keys.size, DIM).astype(np.float32)
    path = os.path.join(str(tmp_path), name)
    write_xbox_columnar(path, keys, rows)
    return path, keys, rows


def shard_server(full_path, index, policy, hot=None):
    """In-process box: shard-filtered stack behind a real RPC server."""
    spec = ShardSpec(index, policy, hot_keys=hot)
    stack = MmapViewStack([], shard_spec=spec, extra_files=(full_path,))
    return ServingServer(manager=ViewManager(stack), watch=False)


def mixed_probe(rng, keys, n_hit=200, n_miss=30):
    probe = np.concatenate([
        rng.choice(keys, n_hit, replace=True),
        rng.randint(1 << 41, 1 << 42, n_miss).astype(np.uint64)])
    rng.shuffle(probe)
    return probe


def bits(a):
    return np.ascontiguousarray(a, np.float32).view(np.uint32)


# ------------------------------------------------------------- partition


def test_partition_pull_is_permutation_and_owner_correct():
    policy = KeyModPolicy(4)
    keys = np.random.RandomState(0).randint(
        0, 1 << 40, 500).astype(np.uint64)
    parts = partition_pull(policy, keys)
    got = np.sort(np.concatenate(parts))
    assert np.array_equal(got, np.arange(keys.size))
    for s, idx in enumerate(parts):
        assert (policy.shard_of(keys[idx]) == s).all()


def test_partition_pull_reroutes_hot_keys():
    policy = KeyModPolicy(4)
    keys = np.arange(1, 101, dtype=np.uint64)
    hot = np.array([4, 8], np.uint64)      # owned by shard 0
    parts = partition_pull(policy, keys, hot_keys=hot, hot_dest=3)
    assert set(keys[parts[3]]) >= {4, 8}   # rerouted off the owner
    # non-hot keys still with their owners
    non_hot3 = [k for k in keys[parts[3]] if k not in (4, 8)]
    assert (policy.shard_of(np.array(non_hot3, np.uint64)) == 3).all()


def test_hot_keys_file_roundtrip(tmp_path):
    path = os.path.join(str(tmp_path), "hot.keys")
    write_hot_keys(path, np.array([9, 3, 3, 7], np.uint64))
    assert np.array_equal(read_hot_keys(path),
                          np.array([3, 7, 9], np.uint64))


# ------------------------------------------------------------ fleet parity


def test_fleet_parity_key_mod_bit_exact(tmp_path):
    """A 3-box fleet answers any pull BIT-identically to one box
    serving the full view — hits, misses and duplicates included."""
    full, keys, _rows = make_view(tmp_path)
    policy = KeyModPolicy(3)
    servers = [shard_server(full, s, policy) for s in range(3)]
    oracle = MmapViewStack([], extra_files=(full,))
    fc = FleetClient([[("127.0.0.1", s.port)] for s in servers],
                     policy=policy)
    try:
        rng = np.random.RandomState(1)
        for _ in range(3):
            probe = mixed_probe(rng, keys)
            assert np.array_equal(bits(fc.pull(probe)),
                                  bits(oracle.lookup(probe)))
    finally:
        fc.close()
        for s in servers:
            s.drain(timeout=2)


def test_fleet_parity_hot_tier_any_box(tmp_path):
    """Hot-tier keys are answered bit-exactly by WHICHEVER box the
    rotating router picks — every box replicated them."""
    full, keys, _rows = make_view(tmp_path)
    rng = np.random.RandomState(2)
    hot = np.sort(rng.choice(keys, 16, replace=False))
    policy = KeyModPolicy(2)
    servers = [shard_server(full, s, policy, hot=hot) for s in range(2)]
    oracle = MmapViewStack([], extra_files=(full,))
    fc = FleetClient([[("127.0.0.1", s.port)] for s in servers],
                     policy=policy, hot_keys=hot)
    try:
        for _ in range(4):             # rotation lands on both boxes
            probe = np.concatenate([hot, mixed_probe(rng, keys, 50, 5)])
            assert np.array_equal(bits(fc.pull(probe)),
                                  bits(oracle.lookup(probe)))
        assert stat_get("serving_fleet_hot_routed") >= 4 * hot.size
    finally:
        fc.close()
        for s in servers:
            s.drain(timeout=2)


def test_fleet_parity_across_mid_pull_swap(tmp_path):
    """Pulls racing a generation swap on every box return rows that are
    bit-exact against EITHER generation's oracle — never a torn row."""
    full_a, keys, rows_a = make_view(tmp_path, seed=3, name="a.xcol")
    path_b = os.path.join(str(tmp_path), "b.xcol")
    rows_b = rows_a + 1.0
    write_xbox_columnar(path_b, keys, rows_b)
    policy = KeyModPolicy(2)
    servers = [shard_server(full_a, s, policy) for s in range(2)]
    fc = FleetClient([[("127.0.0.1", s.port)] for s in servers],
                     policy=policy)
    got, errs = [], []

    def puller():
        rng = np.random.RandomState(threading.get_ident() % 9999)
        try:
            for _ in range(12):
                probe = rng.choice(keys, 64, replace=False)
                got.append((probe, fc.pull(probe)))
        except Exception as e:     # surfaced below
            errs.append(e)

    threads = [threading.Thread(target=puller) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for idx, s in enumerate(servers):   # swap every box mid-traffic
            stack = MmapViewStack([], shard_spec=ShardSpec(idx, policy),
                                  extra_files=(path_b,))
            s.manager.swap(stack)
        for t in threads:
            t.join()
    finally:
        fc.close()
        for s in servers:
            s.drain(timeout=2)
    assert not errs, errs
    lut_a = dict(zip(keys.tolist(), rows_a))
    lut_b = dict(zip(keys.tolist(), rows_b))
    for probe, out in got:
        for k, row in zip(probe.tolist(), out):
            ok = (np.array_equal(bits(row), bits(lut_a[k]))
                  or np.array_equal(bits(row), bits(lut_b[k])))
            assert ok, f"torn row for key {k}"


def test_shard_validation_refuses_misrouted_pull(tmp_path):
    """A sharded box refuses a pull the client routed to a DIFFERENT
    box index — topology permutation fails loudly, not as silent
    all-zero misses."""
    full, keys, _rows = make_view(tmp_path)
    flags.set_flag("serving_shard_index", 1)
    flags.set_flag("serving_num_shards", 2)
    flags.set_flag("serving_shard_policy", "key-mod")
    spec = ShardSpec(1, KeyModPolicy(2))
    stack = MmapViewStack([], shard_spec=spec, extra_files=(full,))
    server = ServingServer(manager=ViewManager(stack), watch=False)
    client = ServingClient([("127.0.0.1", server.port)])
    try:
        client.pull(keys[:4], shard=1)              # correct: accepted
        with pytest.raises(RuntimeError, match="shard"):
            client.pull(keys[:4], shard=0)          # misrouted: refused
        client.pull(keys[:4])                       # undeclared: accepted
    finally:
        client.close()
        server.drain(timeout=2)


# ------------------------------------------------------------- coalescing


def test_coalescer_reduces_per_shard_rpcs(tmp_path):
    """At concurrency 8 the per-box RPC count is measurably below one
    RPC per pull per box (the coalescer merges whatever queued during
    each flight) and every answer stays bit-exact."""
    full, keys, _rows = make_view(tmp_path)
    policy = KeyModPolicy(2)
    servers = [shard_server(full, s, policy) for s in range(2)]
    oracle = MmapViewStack([], extra_files=(full,))
    fc = FleetClient([[("127.0.0.1", s.port)] for s in servers],
                     policy=policy)
    base = stat_get("serving_requests")
    n_threads, n_pulls = 8, 15
    errs = []

    def worker():
        rng = np.random.RandomState(threading.get_ident() % 9999)
        try:
            for _ in range(n_pulls):
                probe = rng.choice(keys, 128)
                assert np.array_equal(bits(fc.pull(probe)),
                                      bits(oracle.lookup(probe)))
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        fc.close()
        for s in servers:
            s.drain(timeout=2)
    assert not errs, errs
    rpcs = stat_get("serving_requests") - base
    ceiling = 2 * n_threads * n_pulls          # one RPC per pull per box
    assert rpcs < 0.75 * ceiling, (rpcs, ceiling)
    assert stat_get("serving_fleet_coalesced") > 0


def test_coalesce_off_sends_one_rpc_per_pull(tmp_path):
    full, keys, _rows = make_view(tmp_path, n=300)
    policy = KeyModPolicy(1)
    servers = [shard_server(full, 0, policy)]
    fc = FleetClient([[("127.0.0.1", servers[0].port)]],
                     policy=policy, coalesce=False)
    base = stat_get("serving_requests")
    try:
        for _ in range(5):
            fc.pull(keys[:32])
    finally:
        fc.close()
        servers[0].drain(timeout=2)
    assert stat_get("serving_requests") - base == 5


# ------------------------------------------------------ failover backoff


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_client_backoff_skips_dead_replica_then_reprobes(tmp_path):
    """Satellite 1: a dead replica is skipped on an exponential
    attempt-denominated backoff (bounded by BACKOFF_SKIP_CAP), pulls
    keep succeeding on the live sibling, and once the replica comes
    back ONE bounded probe re-dials it and resets the backoff."""
    full, keys, _rows = make_view(tmp_path, n=300)
    live = shard_server(full, 0, KeyModPolicy(1))
    dead_port = _free_port()
    client = ServingClient([("127.0.0.1", dead_port),
                            ("127.0.0.1", live.port)])
    revived = None
    try:
        for _ in range(12):            # failures grow the streak
            client.pull(keys[:8])
        with client._lock:
            streak = client._fail_streak[0]
        assert streak >= 2
        assert client._skip_left[0] <= BACKOFF_SKIP_CAP
        skips = stat_get("serving_client_skips")
        assert skips > 0
        # replica recovers on the SAME endpoint
        stack = MmapViewStack([], extra_files=(full,))
        revived = ServingServer(manager=ViewManager(stack), watch=False,
                                port=dead_port)
        for _ in range(2 * BACKOFF_SKIP_CAP + 4):
            client.pull(keys[:8])
        with client._lock:
            assert client._fail_streak[0] == 0   # re-probe succeeded
        assert stat_get("serving_client_reprobes") >= 1
    finally:
        client.close()
        live.drain(timeout=2)
        if revived is not None:
            revived.drain(timeout=2)


def test_fleet_survives_one_dead_replica(tmp_path):
    """One box has a dead replica + a live one: every pull succeeds
    (failover inside the box's ServingClient), zero caller errors."""
    full, keys, _rows = make_view(tmp_path)
    policy = KeyModPolicy(2)
    s0 = shard_server(full, 0, policy)
    s1 = shard_server(full, 1, policy)
    oracle = MmapViewStack([], extra_files=(full,))
    fc = FleetClient(
        [[("127.0.0.1", _free_port()), ("127.0.0.1", s0.port)],
         [("127.0.0.1", s1.port)]], policy=policy)
    try:
        rng = np.random.RandomState(5)
        for _ in range(6):
            probe = mixed_probe(rng, keys, 80, 8)
            assert np.array_equal(bits(fc.pull(probe)),
                                  bits(oracle.lookup(probe)))
    finally:
        fc.close()
        s0.drain(timeout=2)
        s1.drain(timeout=2)


# --------------------------------------------------------- segment tailer


def _frame(kind, payload):
    return jf.FRAME.pack(kind, len(payload)) + payload


def _header_payload(epoch=0, seq=1):
    import json
    return json.dumps({"version": 1, "width": WIDTH,
                       "embedx_dim": EMBEDX, "optimizer": "adagrad",
                       "epoch": epoch, "seq": seq}).encode()


def _rows_payload(keys, vals):
    keys = np.asarray(keys, np.uint64)
    vals = np.asarray(vals, np.float32)
    return (struct.pack("<qq", keys.size, vals.shape[1])
            + keys.tobytes() + vals.tobytes())


def _write_seg(dirpath, name, frames, torn_tail=b""):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, name), "wb") as f:
        f.write(jf.SEG_MAGIC)
        for fr in frames:
            f.write(fr)
        f.write(torn_tail)


def test_tailer_incremental_and_torn_tail(tmp_path):
    d = str(tmp_path / "j")
    vals = np.ones((2, WIDTH), np.float32)
    _write_seg(d, "seg-0000-000001.open",
               [_frame(jf.KIND_HEADER, _header_payload()),
                _frame(jf.KIND_ROWS, _rows_payload([1, 2], vals))],
               torn_tail=jf.FRAME.pack(jf.KIND_ROWS, 999))   # torn
    t = jf.SegmentTailer(d)
    recs, reset = t.poll()
    assert not reset
    assert [k for k, _ in recs] == [jf.KIND_HEADER, jf.KIND_ROWS]
    # the torn frame is NOT consumed; nothing new until it completes
    recs2, reset2 = t.poll()
    assert recs2 == [] and not reset2
    # the writer replaces the torn tail with a whole frame
    with open(os.path.join(d, "seg-0000-000001.open"), "r+b") as f:
        f.seek(-jf.FRAME.size, os.SEEK_END)
        f.truncate()
        f.seek(0, os.SEEK_END)
        f.write(_frame(jf.KIND_EVENT, struct.pack("<I", jf.EV_SHRINK)))
    recs3, reset3 = t.poll()
    assert [k for k, _ in recs3] == [jf.KIND_EVENT] and not reset3


def test_tailer_offsets_survive_seal_rename(tmp_path):
    d = str(tmp_path / "j")
    vals = np.ones((1, WIDTH), np.float32)
    _write_seg(d, "seg-0000-000001.open",
               [_frame(jf.KIND_HEADER, _header_payload()),
                _frame(jf.KIND_ROWS, _rows_payload([1], vals))])
    t = jf.SegmentTailer(d)
    recs, _ = t.poll()
    assert len(recs) == 2
    with open(os.path.join(d, "seg-0000-000001.open"), "ab") as f:
        f.write(_frame(jf.KIND_ROWS, _rows_payload([2], vals)))
    os.rename(os.path.join(d, "seg-0000-000001.open"),
              os.path.join(d, "seg-0000-000001.jrnl"))
    recs2, reset2 = t.poll()
    assert not reset2
    assert [k for k, _ in recs2] == [jf.KIND_ROWS]   # only the new one


def test_tailer_resets_on_epoch_bump_and_vanish(tmp_path):
    d = str(tmp_path / "j")
    vals = np.ones((1, WIDTH), np.float32)
    _write_seg(d, "seg-0000-000001.jrnl",
               [_frame(jf.KIND_HEADER, _header_payload()),
                _frame(jf.KIND_ROWS, _rows_payload([1], vals))])
    t = jf.SegmentTailer(d)
    t.poll()
    # anchor_full: old epoch swept, new epoch appears
    os.remove(os.path.join(d, "seg-0000-000001.jrnl"))
    _write_seg(d, "seg-0001-000001.open",
               [_frame(jf.KIND_HEADER, _header_payload(epoch=1)),
                _frame(jf.KIND_ROWS, _rows_payload([2], vals))])
    recs, reset = t.poll()
    assert reset and len(recs) == 2    # full re-read of the survivors
    # a tailed segment vanishing mid-epoch also resets
    _write_seg(d, "seg-0001-000002.open",
               [_frame(jf.KIND_HEADER, _header_payload(epoch=1, seq=2))])
    t.poll()
    os.remove(os.path.join(d, "seg-0001-000001.open"))
    _recs, reset2 = t.poll()
    assert reset2
    # an emptied dir after tailing resets too (rows fell off disk)
    os.remove(os.path.join(d, "seg-0001-000002.open"))
    recs3, reset3 = t.poll()
    assert reset3 and recs3 == []


# ------------------------------------------------------ journal delta feed


def journal_writer(tmp_path, name="_journal"):
    from paddlebox_tpu.train.journal import TouchedRowJournal
    layout = types.SimpleNamespace(width=WIDTH, embedx_dim=EMBEDX,
                                   optimizer="adagrad")
    return TouchedRowJournal(os.path.join(str(tmp_path), name),
                             layout, None)


def test_xbox_embed_cols_pins_value_layout():
    """The jax-free column math serves EXACTLY the columns the real
    ValueLayout says the xbox view holds, for every optimizer."""
    from paddlebox_tpu.embedding.accessor import EMBED_W, ValueLayout
    for opt in ("adagrad", "adam", "adam_shared", "naive"):
        layout = ValueLayout(embedx_dim=EMBEDX, optimizer=opt)
        expect = np.concatenate([
            [EMBED_W],
            np.arange(layout.embedx_w,
                      layout.embedx_w + EMBEDX)]).astype(np.int64)
        assert np.array_equal(jf.xbox_embed_cols(EMBEDX, opt), expect), opt


def test_journal_source_rows_events_and_updates(tmp_path):
    j = journal_writer(tmp_path)
    src = JournalDeltaSource([j.dir])
    try:
        keys = np.array([11, 7], np.uint64)
        vals = np.arange(2 * WIDTH, dtype=np.float32).reshape(2, WIDTH)
        j.append_rows(keys, vals)
        assert src.poll()
        cols = jf.xbox_embed_cols(EMBEDX, "adagrad")
        overlay = src.compile_overlay()
        stack = MmapViewStack([], extra_files=(overlay,))
        assert np.array_equal(bits(stack.lookup(keys)), bits(vals[:, cols]))
        assert not src.poll()                      # idempotent
        # newest touch wins
        vals2 = vals + 100
        j.append_rows(keys[:1], vals2[:1])
        assert src.poll()
        stack2 = MmapViewStack([], extra_files=(src.compile_overlay(),))
        assert np.array_equal(bits(stack2.lookup(keys[:1])),
                              bits(vals2[:1, cols]))
        # stat-save events do NOT drop the overlay (header cols only)
        j.append_event(jf.EV_STAT_SAVE_DELTA)
        src.poll()
        assert src.compile_overlay() is not None
        # shrink DOES (out-of-band value mutation)
        j.append_event(jf.EV_SHRINK)
        assert src.poll()
        assert src.compile_overlay() is None
    finally:
        src.close()
        j.close()


def test_journal_source_multi_dir_and_layout_mismatch(tmp_path):
    j0 = journal_writer(tmp_path, "j0")
    j1 = journal_writer(tmp_path, "j1")
    src = JournalDeltaSource([j0.dir, j1.dir])
    try:
        v = np.ones((1, WIDTH), np.float32)
        j0.append_rows(np.array([1], np.uint64), v)
        j1.append_rows(np.array([2], np.uint64), v * 2)
        assert src.poll()
        stack = MmapViewStack([], extra_files=(src.compile_overlay(),))
        out = stack.lookup(np.array([1, 2], np.uint64))
        assert out[0, 0] == 1.0 and out[1, 0] == 2.0
    finally:
        src.close()
        j0.close()
        j1.close()
    # disagreeing projections must raise, not mix layouts
    from paddlebox_tpu.train.journal import TouchedRowJournal
    other = TouchedRowJournal(
        os.path.join(str(tmp_path), "j2"),
        types.SimpleNamespace(width=WIDTH + 2, embedx_dim=EMBEDX + 2,
                              optimizer="adagrad"), None)
    other.append_rows(np.array([3], np.uint64),
                      np.ones((1, WIDTH + 2), np.float32))
    src2 = JournalDeltaSource([j0.dir, other.dir])
    try:
        with pytest.raises(ValueError, match="projection"):
            src2.poll()
    finally:
        src2.close()
        other.close()


def test_journal_fed_server_lands_rows_in_seconds(tmp_path):
    """E2E freshness: a touched row is served (bit-exact) ONE refresh
    poll after the trainer flushes it — no SaveDelta involved."""
    full, keys, _rows = make_view(tmp_path)
    root = str(tmp_path / "xbox")
    day = os.path.join(root, "day0")
    os.makedirs(day)
    os.replace(full, os.path.join(day, "view.xcol"))
    with open(os.path.join(day, "DONE"), "w") as f:
        f.write(str(time.time()))
    j = journal_writer(tmp_path)
    flags.set_flag("serving_journal_dir", j.dir)
    flags.set_flag("serving_refresh_secs", 0.1)
    server = ServingServer(root, days=["day0"])
    client = ServingClient([("127.0.0.1", server.port)])
    try:
        tk = keys[:3]
        tv = np.arange(3 * WIDTH, dtype=np.float32).reshape(3, WIDTH) + 9
        cols = jf.xbox_embed_cols(EMBEDX, "adagrad")
        expect = np.ascontiguousarray(tv[:, cols])
        t0 = time.time()
        j.append_rows(tk, tv)
        deadline = t0 + 10.0
        while time.time() < deadline:
            if np.array_equal(bits(client.pull(tk)), bits(expect)):
                break
            time.sleep(0.02)
        else:
            raise AssertionError("journal rows never reached serving")
        landed = time.time() - t0
        assert landed < 5.0, landed    # typically ~2 poll intervals
        # untouched keys still come from the on-disk view
        probe = keys[10:20]
        oracle = MmapViewStack(
            [], extra_files=(os.path.join(day, "view.xcol"),))
        assert np.array_equal(bits(client.pull(probe)),
                              bits(oracle.lookup(probe)))
    finally:
        client.close()
        server.drain(timeout=2)
        j.close()


# ------------------------------------------------------------ jax freedom


def test_serving_import_stays_jax_free():
    """Satellite 5: a serving replica process must never pay for (or
    inherit) jax — the fleet spawn path depends on it."""
    code = ("import sys; import paddlebox_tpu.serving; "
            "assert 'jax' not in sys.modules, 'jax leaked'; "
            "assert 'paddlebox_tpu.train' not in sys.modules; "
            "print('ok')")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "ok" in out.stdout


# ---------------------------------------------------------- spawn (slow)


@pytest.mark.slow
def test_multibox_fleet_spawn_kill_one_box(tmp_path):
    """Real B=2×R=2 spawned grid: routing parity, then SIGKILL one
    replica of one box — the client error rate stays within budget
    (failover absorbs the dead replica) and parity holds throughout."""
    from paddlebox_tpu.serving.fleet import MultiBoxFleet
    full, keys, _rows = make_view(tmp_path)
    root = str(tmp_path / "xbox")
    day = os.path.join(root, "day0")
    os.makedirs(day)
    os.replace(full, os.path.join(day, "view.xcol"))
    with open(os.path.join(day, "DONE"), "w") as f:
        f.write(str(time.time()))
    oracle = MmapViewStack(
        [], extra_files=(os.path.join(day, "view.xcol"),))
    fleet = MultiBoxFleet(root, days=["day0"], boxes=2, replicas=2,
                          start_timeout=120.0)
    try:
        fc = fleet.client(timeout=10.0)
        rng = np.random.RandomState(7)
        probe = mixed_probe(rng, keys)
        assert np.array_equal(bits(fc.pull(probe)),
                              bits(oracle.lookup(probe)))
        fleet.boxes[0]._procs[0].kill()      # one replica of box 0 dies
        errors = 0
        total = 40
        for _ in range(total):
            probe = mixed_probe(rng, keys, 60, 6)
            try:
                assert np.array_equal(bits(fc.pull(probe)),
                                      bits(oracle.lookup(probe)))
            except (ConnectionError, RuntimeError):
                errors += 1
        assert errors <= total * 0.1, f"{errors}/{total} failed"
        health = fleet.health()
        assert health["type"] == "serving_fleet"
        assert health["boxes"] == 2
        fc.close()
    finally:
        fleet.close()
