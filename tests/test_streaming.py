"""Streaming continuous training (round 19): micro-pass pipeline.

Pins the tentpole contracts: torn/in-progress-file safety + the
consumed-file ledger (restart never double-consumes), socket-feed
spooling through the same file plane, micro-pass AUC parity vs batch
passes (|dAUC| <= 0.01 gate), drift-refused windows never mutating the
store, micro-checkpoint replay bit-parity through >=3 micro-pass
journal segments, the overlap no-stall bound, and (slow) the
2-process feed->shuffle->train->serve freshness leg."""

import dataclasses
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.data.streaming import (DirectoryWatcher, FileLedger,
                                          SocketFeedServer, StreamingDataset)
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.train.streaming_runner import StreamingRunner
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4


def _table():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))


def _trainer(feed, seed=0):
    return BoxTrainer(CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                             hidden=(16,)),
                      _table(), feed, TrainerConfig(dense_lr=0.01), seed=seed)


def _drop(src_files, watch_dir, start=0):
    """Publish files into the watch dir via write-temp-then-rename."""
    import shutil
    os.makedirs(watch_dir, exist_ok=True)
    out = []
    for i, f in enumerate(src_files):
        dst = os.path.join(watch_dir, "drop-%04d.txt" % (start + i))
        shutil.copy(f, dst + ".tmp")
        os.replace(dst + ".tmp", dst)
        out.append(dst)
    return out


def _auc(preds, labels):
    """Rank-statistic AUC (no ties expected from float preds)."""
    preds = np.asarray(preds, np.float64).ravel()
    labels = np.asarray(labels, np.float64).ravel() > 0.5
    order = np.argsort(preds, kind="mergesort")
    ranks = np.empty(preds.size, np.float64)
    ranks[order] = np.arange(1, preds.size + 1)
    n_pos = int(labels.sum())
    n_neg = labels.size - n_pos
    assert n_pos and n_neg
    return (ranks[labels].sum() - n_pos * (n_pos + 1) / 2.0) \
        / (n_pos * n_neg)


@pytest.fixture(autouse=True)
def _fast_stream():
    flags.set_flag("dataset_disable_shuffle", True)
    flags.set_flag("streaming_poll_secs", 0.02)
    flags.set_flag("streaming_stable_polls", 2)
    yield


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("streamdata")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=6, lines_per_file=200, num_slots=NUM_SLOTS,
        vocab_per_slot=80, max_len=3, seed=13)
    feed = dataclasses.replace(feed, batch_size=32)
    return files, feed


# --------------------------------------------------------------- watcher
def test_watcher_torn_write_rename_and_ledger(tmp_path):
    """The round-19 fix: in-progress writers are invisible (temp names
    skipped outright, bare files need a size-stable streak), and the
    consumed-file ledger survives a restart without double-consuming."""
    watch = tmp_path / "watch"
    watch.mkdir()
    ledger = FileLedger(str(tmp_path / "journal" / "consumed.json"))
    w = DirectoryWatcher(str(watch), ledger, stable_polls=2)

    # temp-suffixed / hidden names: never ready, no matter how stable
    (watch / "a.txt.tmp").write_text("1 1 1 5\n")
    (watch / ".hidden.txt").write_text("1 1 1 5\n")
    (watch / "_scratch.txt").write_text("1 1 1 5\n")
    for _ in range(4):
        assert w.poll() == []

    # an in-place appender: size must hold still for stable_polls polls
    torn = watch / "b.txt"
    with open(torn, "w") as fh:
        fh.write("1 1 1 5\n")
        fh.flush()
        assert w.poll() == []           # first sighting: streak 1
        fh.write("1 0 1 6\n")
        fh.flush()
        assert w.poll() == []           # size moved: streak resets to 1
    assert w.poll() == [str(torn)]      # unchanged again: streak 2, sealed
    assert w.poll() == []               # never yielded twice

    # the rename convention publishes atomically: ready after the streak
    os.replace(watch / "a.txt.tmp", watch / "a.txt")
    w.poll()
    assert w.poll() == [str(watch / "a.txt")]

    # restart: a fresh watcher + the persisted ledger skips consumed
    ledger.mark([str(torn)])
    ledger2 = FileLedger(str(tmp_path / "journal" / "consumed.json"))
    assert ledger2.consumed(str(torn))
    w2 = DirectoryWatcher(str(watch), ledger2, stable_polls=2)
    w2.poll()
    ready = w2.poll()
    assert str(torn) not in ready       # no double-consume across restart
    assert ready == [str(watch / "a.txt")]


def test_socket_feed_spools_through_file_plane(tmp_path, data):
    """Socket-feed mode: pushed lines land as rename-published spool
    files and form a micro-pass window through the same watcher."""
    files, feed = data
    watch = tmp_path / "watch"
    stream = StreamingDataset(feed, str(watch),
                              ledger_dir=str(tmp_path / "led"),
                              micro_pass_instances=200,
                              socket_port=0)
    try:
        with open(files[0], "rb") as fh:
            payload = fh.read()
        with socket.create_connection(("127.0.0.1", stream.socket_port),
                                      timeout=10) as conn:
            conn.sendall(payload)
        win = stream.next_window(deadline=time.time() + 30)
        assert win is not None
        assert win.instances == 200
        win.dataset.load_into_memory()
        assert len(win.dataset) == 200
        win.dataset.release_memory()
    finally:
        stream.stop()


# -------------------------------------------------- parity + no-stall
def test_micro_pass_auc_parity_vs_batch(tmp_path, data):
    """The same 1200 instances trained as 3 batch passes vs tailed as 3
    streaming micro-passes: AUC on the full set within the 0.01 gate
    (and losses numerically close — same windows, same math)."""
    files, feed = data

    batch = _trainer(feed)
    try:
        batch_losses = []
        for i in range(0, 6, 2):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[i:i + 2])
            batch_losses.append(batch.train_pass(ds)["loss"])
        eval_ds = BoxDataset(feed, read_threads=1)
        eval_ds.set_filelist(files)
        eval_ds.load_into_memory()
        preds_b, labels_b = batch.predict_batches(eval_ds)
        eval_ds.release_memory()
        auc_b = _auc(preds_b, labels_b)
    finally:
        batch.close()

    watch = str(tmp_path / "watch")
    _drop(files, watch)              # the whole drop is ahead of training
    stream = StreamingDataset(feed, watch, ledger_dir=str(tmp_path / "led"),
                              read_threads=1, micro_pass_instances=400)
    tr = _trainer(feed)
    try:
        runner = StreamingRunner(tr, stream, cm=None)
        res = runner.run(idle_timeout=1.5)
        assert res["admitted"] == 3 and res["refused"] == 0
        assert [p["instances"] for p in res["passes"]] == [400, 400, 400]
        np.testing.assert_allclose([p["loss"] for p in res["passes"]],
                                   batch_losses, rtol=1e-5)
        eval_ds = BoxDataset(feed, read_threads=1)
        eval_ds.set_filelist(files)
        eval_ds.load_into_memory()
        preds_s, labels_s = tr.predict_batches(eval_ds)
        eval_ds.release_memory()
        auc_s = _auc(preds_s, labels_s)
        assert abs(auc_s - auc_b) <= 0.01, (auc_s, auc_b)

        # overlap no-stall: with the drop fully ahead of the pipeline,
        # the train thread never blocks longer than one micro-pass on
        # ingest (pass 0 pays the pipeline fill, so it is exempt)
        one_micro_pass = max(p["train_secs"] for p in res["passes"])
        for p in res["passes"][1:]:
            assert p["ingest_wait_secs"] <= one_micro_pass + 0.25, \
                (p, one_micro_pass)
    finally:
        tr.close()


# ------------------------------------------------------------ admission
def _write_poison(path_dir, lines=400):
    """A poisoned drop: label collapse (all clicks) + cardinality
    collapse (every slot pinned to one feasign)."""
    os.makedirs(path_dir, exist_ok=True)
    path = os.path.join(path_dir, "poison-0000.txt")
    toks = " ".join("1 %d" % (si * 80) for si in range(NUM_SLOTS))
    with open(path + ".tmp", "w") as fh:
        for _ in range(lines):
            fh.write("1 1 " + toks + "\n")
    os.replace(path + ".tmp", path)
    return path


def test_drift_refused_window_never_mutates_store(tmp_path, data):
    """Admission gate: the poisoned window is refused BEFORE it trains —
    store bit-identical, journal untouched, ledger still commits the
    window so a restart won't re-ingest the poison."""
    files, feed = data
    watch = str(tmp_path / "watch")
    stream = StreamingDataset(feed, watch, ledger_dir=str(tmp_path / "led"),
                              read_threads=1, micro_pass_instances=400)
    tr = _trainer(feed)
    try:
        runner = StreamingRunner(tr, stream, cm=None,
                                 admission_max_drift=0.45)
        _drop(files[:4], watch)
        res = runner.run(idle_timeout=1.0)
        assert res["admitted"] == 2 and res["refused"] == 0

        keys_ref, vals_ref = tr.table.store.state_items()
        order = np.argsort(keys_ref)
        keys_ref, vals_ref = keys_ref[order], vals_ref[order].copy()

        _write_poison(watch)
        res2 = runner.run(idle_timeout=1.0)
        assert res2["refused"] == 1 and res2["admitted"] == 0
        assert res2["passes"][0]["drift_score"] >= 0.45

        keys_now, vals_now = tr.table.store.state_items()
        order = np.argsort(keys_now)
        np.testing.assert_array_equal(keys_now[order], keys_ref)
        np.testing.assert_array_equal(vals_now[order], vals_ref)

        # refused != retried: the window is ledger-committed, and a
        # fresh scan of the same dir yields nothing
        assert stream.ledger.consumed(os.path.join(watch,
                                                   "poison-0000.txt"))
        w2 = DirectoryWatcher(watch, FileLedger(stream.ledger.path),
                              stable_polls=1)
        assert w2.poll() == []
    finally:
        tr.close()


# -------------------------------------------------- micro-checkpoints
def test_micro_checkpoint_replay_bit_parity(tmp_path, data):
    """Decimated save_base(mode='auto'): the first admitted pass anchors
    a full base, then >=3 micro-passes publish journal segments, and the
    decimated touched save at pass 4 replays back bit-exact (modulo the
    documented post-save stat mutation, which the checkpoint is
    deliberately 'before')."""
    files, feed = data
    watch = str(tmp_path / "watch")
    _drop(files[:4], watch)
    stream = StreamingDataset(feed, watch,
                              ledger_dir=str(tmp_path / "batch"),
                              read_threads=1, micro_pass_instances=200)
    tr = _trainer(feed)
    try:
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=str(tmp_path / "batch"),
                             xbox_model_dir=str(tmp_path / "xbox"),
                             async_save=False), tr.table)
        runner = StreamingRunner(tr, stream, cm=cm, base_every=4)
        res = runner.run(idle_timeout=1.0)
        assert res["admitted"] == 4
        cm.wait()
        # base at window 0 (full anchor) + decimated touched save at
        # window 3 whose manifest carries the >=3 segments since
        last = os.path.join(str(tmp_path / "batch"), "stream-000003")
        manifest = json.load(open(os.path.join(last, "sparse.xman")))
        assert manifest["mode"] == "journal"
        assert len(manifest["segments"]) >= 3

        keys_live, vals_live = tr.table.store.state_items()
        order = np.argsort(keys_live)
        keys_live, vals_live = keys_live[order], vals_live[order]

        tr2 = _trainer(feed, seed=1)
        try:
            cm2 = CheckpointManager(
                CheckpointConfig(batch_model_dir=str(tmp_path / "batch"),
                                 xbox_model_dir=str(tmp_path / "xbox"),
                                 async_save=False), tr2.table)
            tr2.params, tr2.opt_state, _ = cm2.load_base("stream-000003")
            # the snapshot is pre-mutation by design; applying the same
            # post-save stat rewrite the live store received must make
            # them BIT-identical
            from paddlebox_tpu.train import journal as jr
            jr.apply_stat_after_save(tr2.table.store, tr2.table.config, 1)
            jr.apply_stat_after_save(tr2.table.store, tr2.table.config, 3)
            keys2, vals2 = tr2.table.store.state_items()
            order = np.argsort(keys2)
            np.testing.assert_array_equal(keys2[order], keys_live)
            np.testing.assert_array_equal(vals2[order], vals_live)
        finally:
            tr2.close()
    finally:
        tr.close()


# ------------------------------------------------------- freshness (2p)
_SERVE_LEG = r"""
import json, sys, time
sys.path.insert(0, sys.argv[1])
from paddlebox_tpu.serving.refresh import JournalDeltaSource
src = JournalDeltaSource([sys.argv[2]])
deadline = time.time() + float(sys.argv[3])
while time.time() < deadline:
    if src.poll():
        n = sum(len(r) for r in src._rows)
        if n:
            print(json.dumps({"detect_ts": time.time(), "rows": n}),
                  flush=True)
            break
    time.sleep(0.05)
else:
    print(json.dumps({"detect_ts": None}), flush=True)
src.close()
"""


@pytest.mark.slow
def test_two_process_feed_train_serve_freshness(tmp_path, data):
    """The full streaming story across two processes: this process
    feeds the watch dir and trains micro-passes; a separate serving
    process tails the journal dir (JournalDeltaSource) and reports the
    wall time at which trained rows became servable. Freshness =
    serve-side detect time - drop time, asserted within one generous
    CPU-container micro-pass bound."""
    files, feed = data
    watch = str(tmp_path / "watch")
    batch_dir = str(tmp_path / "batch")
    stream = StreamingDataset(feed, watch, ledger_dir=batch_dir,
                              read_threads=1, micro_pass_instances=400)
    tr = _trainer(feed)
    proc = None
    try:
        cm = CheckpointManager(
            CheckpointConfig(batch_model_dir=batch_dir,
                             xbox_model_dir=str(tmp_path / "xbox"),
                             async_save=False), tr.table)
        assert cm.journal is not None
        jdir = cm.journal.dir
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", _SERVE_LEG, repo, jdir, "120"],
            stdout=subprocess.PIPE, text=True, env=env)
        drop_ts = time.time()
        _drop(files[:2], watch)
        runner = StreamingRunner(tr, stream, cm=cm, base_every=0)
        res = runner.run(idle_timeout=2.0)
        assert res["admitted"] == 1
        out, _ = proc.communicate(timeout=120)
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["detect_ts"] is not None, "serve leg never saw rows"
        freshness = doc["detect_ts"] - drop_ts
        # the bound is one micro-pass interval: dominated on this
        # 1-core container by the first-pass jit compile inside
        # train_pass; the serve side adds only its 50ms poll
        one_micro_pass = (res["passes"][0]["ingest_wait_secs"]
                          + res["passes"][0]["train_secs"])
        assert 0 < freshness <= one_micro_pass + 5.0, \
            (freshness, one_micro_pass)
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        tr.close()
