"""Round 16: the columnar SSD spill tier (embedding/ssd_tier.py).

Block mechanics (columnar part files, batched fault-in, live-fraction
compaction, stale-block construction sweep), span-decomposed lazy aging
(the f32 parity core), the journal MOVE cadence end to end (spill →
tick → train → touched save → replay == live, bit for bit), and the
bounded-RSS scale claim (100M+ keys against a ~1M-row DRAM budget)."""

import dataclasses
import json
import os
import resource

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig,
                                          TableConfig)
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding.host_store import HostEmbeddingStore
from paddlebox_tpu.embedding.ssd_tier import (SpillTier, apply_missed_days,
                                              sweep_stale_blocks)
from paddlebox_tpu.train import journal as jr

D = 4


def table_cfg(**kw):
    kw.setdefault("embedx_dim", D)
    kw.setdefault("optimizer", SparseOptimizerConfig(
        mf_create_thresholds=0.0, mf_initial_range=1e-3))
    return TableConfig(**kw)


def mk_tier(dirpath=None, decay=0.98):
    return SpillTier(ValueLayout(D).width, dirpath, "t0", decay)


def rows_for(keys, width, stamp=1.0):
    vals = np.zeros((keys.size, width), np.float32)
    vals[:, acc.SHOW] = keys.astype(np.float32)
    vals[:, acc.CLICK] = stamp
    return vals


# ------------------------------------------------------------- block tier


@pytest.mark.parametrize("on_disk", [False, True])
def test_tier_spill_read_pop_and_peek(tmp_path, on_disk):
    tier = mk_tier(str(tmp_path / "ssd") if on_disk else None)
    w = ValueLayout(D).width
    keys = np.arange(1, 101, dtype=np.uint64)
    tier.spill_rows(keys, rows_for(keys, w))
    assert len(tier) == 100
    assert tier.contains(keys).all()
    # peek: values come back, nothing moves
    got = tier.read(keys[10:20], pop=False)
    np.testing.assert_array_equal(got[:, acc.SHOW], keys[10:20])
    assert len(tier) == 100
    # pop: entries are consumed
    got = tier.read(keys[:30], pop=True)
    np.testing.assert_array_equal(got[:, acc.SHOW], keys[:30])
    assert len(tier) == 70
    assert not tier.contains(keys[:30]).any()
    with pytest.raises(KeyError):
        tier.read(keys[:1], pop=False)


def test_tier_batched_fault_in_groups_blocks(tmp_path):
    """One read spanning several spill blocks returns every row exactly
    — the by-file grouping is internal, the contract is batched
    correctness (no per-key file opens to observe, by design)."""
    tier = mk_tier(str(tmp_path / "ssd"))
    w = ValueLayout(D).width
    for wave in range(5):
        keys = np.arange(wave * 100 + 1, wave * 100 + 101, dtype=np.uint64)
        tier.spill_rows(keys, rows_for(keys, w, stamp=float(wave)))
    assert len(os.listdir(tmp_path / "ssd")) == 5
    rng = np.random.RandomState(0)
    probe = rng.permutation(np.arange(1, 501, dtype=np.uint64))[:300]
    got = tier.read(probe, pop=True)
    np.testing.assert_array_equal(got[:, acc.SHOW], probe)
    np.testing.assert_array_equal(got[:, acc.CLICK],
                                  ((probe - 1) // 100).astype(np.float32))
    assert len(tier) == 200


def test_stale_block_sweep_on_construction(tmp_path):
    """A reused ssd_dir sheds blocks whose creator pid is dead — and
    ONLY those (a live sibling shard's blocks survive)."""
    ssd = tmp_path / "ssd"
    ssd.mkdir()
    # dead creator: pid 1 is init, never a train process... use a pid
    # that cannot exist instead (beyond pid_max)
    dead = 0x3FFFFFFF
    for name in (f"spill_{dead:x}_ab_00000000.part",
                 f"nspill_{dead:x}_ab_7.npy",
                 f"spill_{dead:x}_ab_00000001.part.123.tmp"):
        (ssd / name).write_bytes(b"x")
    alive = f"spill_{os.getpid():x}_cd_00000000.part"
    (ssd / alive).write_bytes(b"x")
    (ssd / "unrelated.bin").write_bytes(b"x")
    assert sweep_stale_blocks(str(ssd)) == 3
    left = sorted(os.listdir(ssd))
    assert left == sorted([alive, "unrelated.bin"])
    # store construction runs the same sweep
    (ssd / f"spill_{dead:x}_ab_00000002.part").write_bytes(b"x")
    HostEmbeddingStore(ValueLayout(D), table_cfg(ssd_dir=str(ssd)))
    assert not any(f"{dead:x}" in n for n in os.listdir(ssd))


def test_block_compaction_rewrites_and_gc(tmp_path):
    """A big block less than half alive is rewritten live-rows-only
    (raw bytes preserved); an all-dead block is unlinked."""
    ssd = tmp_path / "ssd"
    tier = mk_tier(str(ssd))
    w = ValueLayout(D).width
    keys = np.arange(1, 5001, dtype=np.uint64)
    tier.spill_rows(keys, rows_for(keys, w))
    first = tier.block_files()
    assert len(first) == 1
    sz_before = os.path.getsize(first[0])
    tier.read(keys[:3000], pop=True)  # 2000/5000 live → rewrite
    second = tier.block_files()
    assert len(second) == 1 and second != first
    assert not os.path.exists(first[0])
    # the rewritten block holds the 2000 live rows, not all 5000
    assert os.path.getsize(second[0]) < sz_before * 0.6
    got = tier.read(keys[3000:], pop=False)
    np.testing.assert_array_equal(got[:, acc.SHOW], keys[3000:])
    tier.read(keys[3000:], pop=True)  # block empties → unlink
    assert tier.block_files() == []
    assert not os.listdir(ssd)


def test_span_decay_applies_per_rebase_interval():
    """f32 decay**(a+b) != decay**a * decay**b in general: effective
    values must apply each [rebase, rebase) span sequentially, exactly
    like a replayed store that crossed a save anchor mid-sleep."""
    tier = mk_tier(decay=0.98)
    w = ValueLayout(D).width
    keys = np.arange(1, 11, dtype=np.uint64)
    raw = rows_for(keys, w)
    raw[:, acc.SHOW] = 7.7
    raw[:, acc.CLICK] = 3.3
    tier.spill_rows(keys, raw.copy())
    tier.tick()
    tier.tick()           # 2 days sleep
    tier.rebase()         # full-save anchor lands here
    tier.tick()
    tier.tick()
    tier.tick()           # 3 more days
    expect = raw.copy()
    apply_missed_days(expect, np.float32(2.0), 0.98)
    apply_missed_days(expect, np.float32(3.0), 0.98)
    got = tier.read(keys, pop=False)
    np.testing.assert_array_equal(got, expect)
    # snapshot returns the same effective values
    skeys, svals = tier.snapshot()
    order = np.argsort(skeys)
    np.testing.assert_array_equal(svals[order], expect)


def test_sweep_kills_by_lazy_age_without_reading():
    tier = mk_tier()
    w = ValueLayout(D).width
    keys = np.arange(1, 101, dtype=np.uint64)
    vals = rows_for(keys, w)
    vals[:40, acc.UNSEEN_DAYS] = 9.0   # old at spill time
    tier.spill_rows(keys, vals)
    tier.tick()
    tier.tick()
    # dead iff unseen-at-spill + days slept > lifetime: 9+2 > 10, 0+2 ≤ 10
    assert tier.sweep(10.0) == 40
    assert len(tier) == 60
    assert not tier.contains(keys[:40]).any()
    assert tier.contains(keys[40:]).all()


# --------------------------------------------------- journal MOVE cadence


def drive_pass(table, keys, grad_scale=0.05):
    import jax.numpy as jnp
    table.begin_feed_pass()
    table.add_keys(keys)
    table.end_feed_pass()
    table.begin_pass()
    pl = table.push_layout
    ids = table.lookup_ids(keys[: max(1, keys.size // 2)])
    g = np.zeros((ids.size, pl.width), np.float32)
    g[:, pl.SHOW] = 1.0
    g[:, pl.EMBED_G] = grad_scale
    g[:, pl.embedx_g:] = 0.01
    table.push(jnp.asarray(ids), jnp.asarray(g))
    table.end_pass()


def test_touched_save_bit_parity_across_spill_and_tick(tmp_path):
    """The ISSUE-16 acceptance cadence: full anchor → spill → day tick →
    train (faults rows back) → touched save → replay-over-base equals
    the live store (resident + tier, effective values) BIT-exactly."""
    from paddlebox_tpu.embedding.pass_table import PassTable
    from paddlebox_tpu.train.checkpoint import (SPARSE_MANIFEST,
                                                CheckpointManager)

    t = PassTable(table_cfg(pass_capacity=1 << 10,
                            ssd_dir=str(tmp_path / "ssd")), seed=13)
    cfg = CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                           xbox_model_dir=str(tmp_path / "x"),
                           async_save=False)
    cm = CheckpointManager(cfg, t)
    keys = np.arange(1, 400, dtype=np.uint64) * 17
    drive_pass(t, keys)
    cm.save_base({}, {}, day="d0")              # full anchor
    with t.store_lock:
        assert t.store.spill(max_resident=100) > 0
    t.end_day(age=False)                         # EV_TICK_SPILL_AGE
    drive_pass(t, keys[::3])                     # faults a third back in
    t.end_day(age=True)                          # EV_AGE_DAYS + tick
    drive_pass(t, keys[::5])
    assert cm.journal.snapshot_ready()
    # live pre-save state: resident + tier at effective values
    lk, lv = t.store.state_items()
    sk, sv = t.store.spilled_snapshot()
    assert sk.size > 0, "cadence must leave rows on the tier"
    lk, lv = np.concatenate([lk, sk]), np.vstack([lv, sv])
    lo = np.argsort(lk, kind="stable")
    bdir, _ = cm.save_base({}, {}, day="d1", mode="touched")
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "journal"
    t2 = PassTable(table_cfg(pass_capacity=1 << 10), seed=77)
    cm2 = CheckpointManager(dataclasses.replace(cfg), t2)
    cm2.load_base("d1")
    rk, rv = t2.store.state_items()
    ro = np.argsort(rk, kind="stable")
    np.testing.assert_array_equal(rk[ro], lk[lo])
    np.testing.assert_array_equal(rv[ro], lv[lo])


def test_replay_scratch_never_touches_live_ssd_dir(tmp_path):
    """reconstruct_blob builds its scratch store with ssd_dir=None —
    a replayed MV_SPILL lands in in-RAM blocks, and the live dir's
    block files are untouched by the reconstruction."""
    from paddlebox_tpu.embedding.pass_table import PassTable
    from paddlebox_tpu.train.checkpoint import CheckpointManager

    ssd = tmp_path / "ssd"
    t = PassTable(table_cfg(pass_capacity=1 << 10, ssd_dir=str(ssd)),
                  seed=5)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=str(tmp_path / "b"),
                         xbox_model_dir=str(tmp_path / "x"),
                         async_save=False), t)
    keys = np.arange(1, 200, dtype=np.uint64) * 3
    drive_pass(t, keys)
    cm.save_base({}, {}, day="d0")
    with t.store_lock:
        assert t.store.spill(max_resident=50) > 0
    blocks = sorted(os.listdir(ssd))
    mtimes = [os.path.getmtime(os.path.join(ssd, b)) for b in blocks]
    refs = cm.journal.snapshot_refs()
    base = cm._read_base_files(refs["parts"])
    blob = jr.reconstruct_blob(base, refs["segments"], t.layout, t.config)
    # reconstruction covered the tier rows...
    assert np.isin(t.store.spilled_keys(), blob["keys"]).all()
    # ...without writing or removing anything under the live ssd_dir
    assert sorted(os.listdir(ssd)) == blocks
    assert [os.path.getmtime(os.path.join(ssd, b))
            for b in blocks] == mtimes


# ------------------------------------------------------------ scale tier


@pytest.mark.slow
def test_bounded_rss_beyond_dram_budget_100m_keys(tmp_path):
    """The billion-key direction at CI scale: 100M keys pushed through
    a ~1M-row resident budget must keep RSS pinned near the tier-index
    cost (~3.5 GB: 21 B/key sorted index + block key/age metadata),
    far under the ≥7 GB a fully-resident run needs. Native store only —
    the python dict index is exactly what this tier replaced."""
    from paddlebox_tpu.embedding.native_store import NativeHostEmbeddingStore

    cfg = table_cfg(ssd_dir=str(tmp_path / "ssd"))
    try:
        st = NativeHostEmbeddingStore(ValueLayout(D), cfg, seed=0)
    except RuntimeError:
        pytest.skip("native library unavailable")
    total, wave_n, budget = 100_000_000, 2_000_000, 1_000_000
    wave_vals = np.zeros((wave_n, st.layout.width), np.float32)
    n_seen = 0
    while n_seen < total:
        keys = np.arange(n_seen + 1, n_seen + wave_n + 1, dtype=np.uint64)
        st.assign(keys, wave_vals)          # create-or-overwrite, no rng
        st.spill(max_resident=budget)
        n_seen += wave_n
    assert len(st) <= budget
    assert len(st) + st.spilled_count() == total
    # spot-check fault-in correctness at scale
    probe = np.linspace(1, total, 1000).astype(np.uint64)
    got, found = st.lookup_present(probe)
    assert found.all()
    rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    assert rss_gb < 6.0, f"RSS {rss_gb:.1f} GB — tier is not bounding DRAM"
