"""Fleet control plane: KV store, collectives, elastic heartbeats, and the
subprocess launcher (the test_dist_base.py localhost-cluster pattern)."""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.fleet import (ElasticManager, Fleet, KVStoreServer,
                                 RoleMaker, TcpStoreClient)


@pytest.fixture
def store():
    s = KVStoreServer(host="127.0.0.1")
    yield s
    s.stop()


def test_store_set_get_wait_add(store):
    cl = TcpStoreClient("127.0.0.1", store.port)
    assert cl.get("k") is None
    cl.set("k", b"v1")
    assert cl.get("k") == b"v1"
    assert cl.add("c", 2) == 2
    assert cl.add("c") == 3

    got = {}

    def waiter():
        got["v"] = cl2.wait("late", timeout=10)

    cl2 = TcpStoreClient("127.0.0.1", store.port)
    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.05)
    cl.set("late", b"arrived")
    th.join(5)
    assert got["v"] == b"arrived"
    cl.delete("k")
    assert cl.get("k") is None
    cl.close()
    cl2.close()


def test_store_rejects_pickled_classes(store):
    import pickle
    import socket
    import struct
    s = socket.create_connection(("127.0.0.1", store.port))
    evil = pickle.dumps({"op": "set", "key": "x",
                         "value": RoleMaker(rank=0, world=1)})
    s.sendall(struct.pack("<I", len(evil)) + evil)
    hdr = s.recv(4)
    (n,) = struct.unpack("<I", hdr)
    resp = pickle.loads(s.recv(n))
    assert not resp["ok"] and "refusing to unpickle" in resp["error"]
    s.close()


def test_fleet_collectives_two_ranks(store):
    results = {}

    def run(rank):
        fl = Fleet().init(RoleMaker(
            rank=rank, world=2,
            store_endpoint="127.0.0.1:%d" % store.port))
        fl.barrier_worker(timeout=30)
        s = fl.all_reduce(np.array([rank + 1.0, 10.0]), "sum", timeout=30)
        m = fl.all_reduce(np.array([rank], np.int64), "max", timeout=30)
        g = fl.all_gather(np.full(2, rank, np.int32), timeout=30)
        eq = fl.equalize_batches()(5 if rank == 0 else 9)
        results[rank] = (s, m, g, eq)
        fl.stop()

    ths = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for rank in (0, 1):
        s, m, g, eq = results[rank]
        np.testing.assert_allclose(s, [3.0, 20.0])
        assert m[0] == 1
        np.testing.assert_array_equal(g[0], [0, 0])
        np.testing.assert_array_equal(g[1], [1, 1])
        assert eq == 9


def test_elastic_detects_dead_rank(store):
    cl0 = TcpStoreClient("127.0.0.1", store.port)
    cl1 = TcpStoreClient("127.0.0.1", store.port)
    faults = []
    em0 = ElasticManager(cl0, rank=0, world=2, heartbeat_interval=0.1,
                         stale_after=0.5, on_fault=faults.append)
    em1 = ElasticManager(cl1, rank=1, world=2, heartbeat_interval=0.1,
                         stale_after=0.5)
    em0.start()
    em1.start()
    time.sleep(0.3)
    assert not em0.dead_ranks
    em1.stop()  # rank 1 "dies" (stops heartbeating)
    deadline = time.time() + 5
    while not em0.dead_ranks and time.time() < deadline:
        time.sleep(0.1)
    assert em0.dead_ranks == [1]
    assert faults == [[1]]
    with pytest.raises(Exception):
        em0.check()
    em0.stop()
    cl0.close()
    cl1.close()


_WORKER = """
import numpy as np
from paddlebox_tpu.fleet import fleet
fleet.init()
rank = fleet.worker_index()
total = fleet.all_reduce(np.array([rank + 1.0]))
assert total[0] == 3.0, total
fleet.barrier_worker()
print("rank", rank, "ok")
"""


def test_launch_two_processes(tmp_path):
    import os
    import paddlebox_tpu
    repo_root = os.path.dirname(os.path.dirname(paddlebox_tpu.__file__))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    from paddlebox_tpu.fleet.launch import launch
    rc = launch(2, [str(script)],
                env_extra={"JAX_PLATFORMS": "cpu",
                           "PYTHONPATH": repo_root})
    assert rc == 0
