"""Multi-host runtime: 2-process localhost cluster vs single-process oracle.

The missing tier VERDICT r1 called out: a real jax.distributed multi-process
mesh exercised by subprocess workers (the reference validates its MPI/NCCL
tier the same way — subprocess localhost clusters, test_dist_base.py:
896-1012). Strict parity holds because the per-device batch streams are
identical: 8 files × 128 lines, batch 32 → single-process worker w trains
file w; 2-process: process p's local worker j trains file 4p+j on global
device 4p+j.
"""

import json
import os
import subprocess
import sys
import uuid

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer

D = 4
NUM_SLOTS = 4
PASSES = 2

# jax 0.4.x cannot run multi-controller collectives on the CPU backend —
# every worker dies with "Multiprocess computations aren't implemented on
# the CPU backend" after ~30 s of cluster bring-up per test. Skip the
# whole module there rather than burn ~4 min of tier-1 budget on doomed
# subprocess clusters (BASELINE.md round-7 drift note); the tests run
# unchanged on real multi-host TPU and on jax >= 0.5 CPU.
_jax_major_minor = tuple(int(x) for x in
                         __import__("jax").__version__.split(".")[:2])
pytestmark = pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "") == "cpu" and _jax_major_minor < (0, 5),
    reason="jax 0.4.x CPU backend: multiprocess collectives unimplemented")


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("mh_data")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=8, lines_per_file=128, num_slots=NUM_SLOTS,
        vocab_per_slot=120, max_len=3, seed=23)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


@pytest.fixture(scope="module")
def oracle(data):
    """ONE single-process oracle run shared by every cluster test in the
    module (each used to recompute the identical 2-pass training run)."""
    files, feed = data
    return run_single_process_oracle(files, feed)


def run_single_process_oracle(files, feed):
    """The same training run on the in-process 8-device mesh."""
    from paddlebox_tpu.config import flags
    flags.set_flag("dataset_disable_shuffle", True)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    trainer = ShardedBoxTrainer(
        CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
               hidden=(32, 16)),
        table_cfg, feed, TrainerConfig(dense_lr=0.01, scan_chunk=1),
        mesh=device_mesh_1d(8), seed=0)
    trainer.metrics.init_metric("auc", "label", "pred",
                                table_size=1 << 14, mask_var="mask")
    losses = []
    for _ in range(PASSES):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(trainer.train_pass(ds)["loss"])
        ds.release_memory()
    msg = trainer.metrics.get_metric_msg("auc")
    rows = {}
    for s in range(8):
        keys, vals = trainer.table.stores[s].state_items()
        order = np.argsort(keys)
        for k, v in zip(keys[order[:3]], vals[order[:3]]):
            rows[str(int(k))] = np.asarray(v, np.float64)
    flags.set_flag("dataset_disable_shuffle", False)
    return losses, msg, rows


def run_cluster(files, extra_cfg=None, world=2,
                            devs_per_proc=4, worker_script=None,
                            extra_env=None):
    """Spawn a `world`-process localhost cluster (subprocess pattern,
    test_dist_base.py:896-1012) and collect each rank's RESULT line."""
    from paddlebox_tpu.fleet.store import KVStoreServer
    server = KVStoreServer(host="127.0.0.1")
    cfg = {"files": files, "embedx_dim": D, "num_slots": NUM_SLOTS,
           "batch_size": 32, "max_len": 3, "passes": PASSES}
    cfg.update(extra_cfg or {})
    cfg = json.dumps(cfg)
    worker = os.path.join(os.path.dirname(__file__),
                          worker_script or "multihost_worker.py")
    run_id = uuid.uuid4().hex[:8]
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # worker sets its own device flag
            repo_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
                "PYTHONPATH", "")
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(world),
                "PBTPU_DEVS_PER_PROC": str(devs_per_proc),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "PBTPU_RUN_ID": run_id,
            })
            env.update(extra_env or {})
            procs.append(subprocess.Popen(
                [sys.executable, worker, cfg], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        results = {}
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["rank"]] = r
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    return results


def test_two_process_cluster_matches_single_process(data, oracle, tmp_path):
    files, feed = data
    ref_losses, ref_msg, ref_rows = oracle
    results = run_cluster(files)

    assert set(results) == {0, 1}
    # losses identical across ranks (replicated pmean) and vs the oracle
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], ref_losses, rtol=1e-4,
                               err_msg="2-process losses diverge from "
                                       "single-process oracle")
    # allreduced AUC covers all instances and matches the oracle
    assert results[0]["size"] == ref_msg["size"] == PASSES * 8 * 128
    np.testing.assert_allclose(results[0]["auc"], ref_msg["auc"], rtol=1e-6)
    # store rows written back by each owning process match the oracle's
    merged_rows = {**results[0]["rows"], **results[1]["rows"]}
    assert merged_rows, "no store rows sampled"
    checked = 0
    for k, v in merged_rows.items():
        if k in ref_rows:
            np.testing.assert_allclose(np.asarray(v), ref_rows[k],
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"row mismatch key {k}")
            checked += 1
    assert checked >= 8, f"only {checked} rows overlapped for comparison"
    # cross-host instance shuffle conserved every instance and still trains
    for r in results.values():
        assert r["total_after_shuffle"] == 8 * 128, r
        assert 0 < r["local_after_shuffle"] < 8 * 128, r
        assert np.isfinite(r["shuffled_loss"]), r


def test_two_process_rebuild_matches_oracle(data, oracle):
    """Round-5 verdict item 2: push_write=rebuild at process_count > 1.
    The per-step bucket exchange (exchange_outgoing_buckets) makes every
    shard's incoming ids host-known, so the scatter-free pos-map write
    runs in the multi-process flagship shape too — and must reproduce
    the single-process (scatter-mode) oracle's rows."""
    files, feed = data
    ref_losses, ref_msg, ref_rows = oracle
    results = run_cluster(files,
                          extra_env={"PBTPU_PUSH_WRITE": "rebuild"})
    assert set(results) == {0, 1}
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], ref_losses, rtol=1e-4)
    merged_rows = {**results[0]["rows"], **results[1]["rows"]}
    checked = 0
    for k, v in merged_rows.items():
        if k in ref_rows:
            np.testing.assert_allclose(np.asarray(v), ref_rows[k],
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"row mismatch key {k}")
            checked += 1
    assert checked >= 8, f"only {checked} rows overlapped for comparison"


def test_two_process_pipeline_rebuild(data, pipeline_cluster):
    """The sharded pipeline's multi-process fast push (round-5 verdict
    item 2): forced push_write=rebuild across 2 processes must reproduce
    the default-mode cluster run exactly (same losses, same replicated
    stage params) — the exchanged pos maps change the write strategy,
    never the numbers."""
    files, _feed = data
    base = pipeline_cluster
    results = run_cluster(files, {"n_micro": PIPE_N_MICRO}, world=2,
                          devs_per_proc=4,
                          worker_script="multihost_pipeline_worker.py",
                          extra_env={"PBTPU_PUSH_WRITE": "rebuild"})
    assert set(results) == {0, 1}
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], base[0]["losses"],
                               rtol=1e-5,
                               err_msg="rebuild-mode cluster diverges "
                                       "from default-mode cluster")
    np.testing.assert_allclose(results[0]["blk_head"], base[0]["blk_head"],
                               rtol=1e-5)


def test_two_process_gpups_over_central_ps(data, oracle):
    """The 1T-param composition: a 2-process pod mesh whose shard stores
    ALL live on one central CPU PS over TCP (distributed full store →
    per-pass HBM slabs, built/dumped at pass boundaries —
    ps_gpu_wrapper.cc:337-760,983). Losses must match the local-store
    oracle (server-side row init is key-deterministic) and the features
    must exist server-side afterwards."""
    files, feed = data
    ref_losses, ref_msg, _ref_rows = oracle

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.ps import PSServer, TcpPSClient
    server = PSServer()
    admin = TcpPSClient("127.0.0.1", server.port)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    try:
        admin.create_sparse_table(7, table_cfg, shard_num=8, seed=0)
        results = run_cluster(
            files, {"ps_endpoint": "127.0.0.1:%d" % server.port,
                    "ps_table_id": 7})
        assert set(results) == {0, 1}
        np.testing.assert_allclose(results[0]["losses"],
                                   results[1]["losses"], rtol=1e-6)
        np.testing.assert_allclose(results[0]["losses"], ref_losses,
                                   rtol=1e-4,
                                   err_msg="GPUPS cluster diverges from "
                                           "local-store oracle")
        assert results[0]["ps_rows"] and results[0]["ps_rows"] > 100
    finally:
        admin.stop_server()
        admin.close()


def test_two_process_hierarchical_mesh(data, oracle):
    """2D ("node","chip") mesh across the REAL process boundary (VERDICT
    r2 #4): node axis = the 2 processes (DCN), chip axis = each process's
    4 devices (ICI). Hierarchical dense sync must reproduce the flat-mesh
    single-process oracle."""
    files, feed = data
    ref_losses, ref_msg, ref_rows = oracle
    results = run_cluster(files, {"mesh_2d": True})

    assert set(results) == {0, 1}
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], ref_losses, rtol=1e-4,
                               err_msg="2D-mesh cluster diverges from the "
                                       "flat single-process oracle")
    np.testing.assert_allclose(results[0]["auc"], ref_msg["auc"], rtol=1e-6)
    merged_rows = {**results[0]["rows"], **results[1]["rows"]}
    checked = 0
    for k, v in merged_rows.items():
        if k in ref_rows:
            np.testing.assert_allclose(np.asarray(v), ref_rows[k],
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"row mismatch key {k}")
            checked += 1
    assert checked >= 8, f"only {checked} rows overlapped"


def test_four_process_gpups_spill_and_day_boundary(data, tmp_path):
    """4-process cluster (VERDICT r2 #8): GPUPS store_factory + an active
    SSD spill budget + a day boundary. Catches the ownership/primary-
    gating bug class 2 processes can't: aging and the shrink decay must
    hit the central PS EXACTLY once (not world x), and the spill must run
    once through the primary, with spilled rows faulting back through the
    next pass's server pull."""
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.embedding import accessor as acc
    from paddlebox_tpu.embedding.accessor import ValueLayout
    from paddlebox_tpu.ps import PSServer, TcpPSClient

    files, feed = data
    width = ValueLayout(D, "adagrad").width
    budget_rows = 128
    ssd = {"ssd_dir": str(tmp_path / "ps_ssd"),
           "ssd_threshold_mb": budget_rows * width * 4 / (1 << 20)}
    overrides = dict(ssd, show_click_decay_rate=0.5,
                     delete_after_unseen_days=30.0, delete_threshold=0.0)
    server = PSServer()
    admin = TcpPSClient("127.0.0.1", server.port)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1),
        **overrides)
    try:
        admin.create_sparse_table(9, table_cfg, shard_num=8, seed=0)
        results = run_cluster(
            files, {"ps_endpoint": "127.0.0.1:%d" % server.port,
                    "ps_table_id": 9, "spill_and_day": True,
                    "skip_shuffle_phase": True,
                    "table_overrides": overrides},
            world=4, devs_per_proc=2)
        assert set(results) == {0, 1, 2, 3}
        # the spill ran exactly once, through rank 0's primary store
        assert results[0]["spilled"] > 0, results[0]
        for r in (1, 2, 3):
            assert results[r]["spilled"] == 0, results[r]
        # training continued after the spill on every rank (fault-in works)
        for r in results.values():
            assert np.isfinite(r["post_spill_loss"]), r
        # day boundary hit the server exactly once: unseen aged 0 -> 1 and
        # the show decay applied once (0.5x), not world x
        key = np.array([results[0]["probe_key"]], np.uint64)
        row = admin.pull_sparse(9, key, create=False)[0]
        assert row[acc.UNSEEN_DAYS] == 1.0, row[acc.UNSEEN_DAYS]
        np.testing.assert_allclose(row[acc.SHOW],
                                   results[0]["show_before"] * 0.5,
                                   rtol=1e-6)
        assert admin.sparse_size(9) > 100
    finally:
        admin.stop_server()
        admin.close()


def test_two_process_device_auc_matches_host(data, oracle):
    """mode_collect_in_device at the multi-process tier: each process
    merges its OWN device shards' bucket tables once per pass; the
    cross-process allreduce at get_metric_msg completes the reduction —
    AUC must match the host-collected oracle."""
    files, feed = data
    _losses, ref_msg, _rows = oracle
    results = run_cluster(files, {"device_auc": True,
                                  "skip_shuffle_phase": True})
    assert set(results) == {0, 1}
    # guard against a silent fallback to the host path: the workers must
    # report an ACTIVE device-collect table size
    for r in results.values():
        assert r["collect_T"] == 1 << 14, r["collect_T"]
    assert results[0]["size"] == ref_msg["size"]
    np.testing.assert_allclose(results[0]["auc"], ref_msg["auc"], rtol=2e-3)
    np.testing.assert_allclose(results[0]["auc"], results[1]["auc"],
                               rtol=1e-6)


PIPE_N_MICRO = 4


@pytest.fixture(scope="module")
def pipeline_cluster(data):
    """ONE local-store 2-process pipeline cluster run shared by the
    pipeline cluster tests (the `oracle` fixture pattern)."""
    files, _feed = data
    return run_cluster(files, {"n_micro": PIPE_N_MICRO}, world=2,
                       devs_per_proc=4,
                       worker_script="multihost_pipeline_worker.py")


def test_two_process_sharded_pipeline(data, pipeline_cluster):
    """Pipeline parallelism at a REAL process boundary: a (dp=2, stage=4)
    mesh where each process owns one pipeline row and the pass table
    key-mod-shards over all 8 devices — every pull/push a2a crosses the
    process boundary. Parity vs a single-process run of the same mesh fed
    the identical per-row batch streams."""
    from jax.sharding import Mesh
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.parallel.pipeline import (STAGE_AXIS,
                                                 ShardedCtrPipelineRunner)

    files, feed = data
    N_MICRO = PIPE_N_MICRO
    results = pipeline_cluster
    assert set(results) == {0, 1}
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    # dp-replicated stage params must agree across the process boundary
    np.testing.assert_allclose(results[0]["blk_head"],
                               results[1]["blk_head"], rtol=1e-6)

    # ---- single-process oracle on the same (2, 4) mesh: row r consumes
    # process r's file half, groups in file order (shuffle disabled)
    flags.set_flag("dataset_disable_shuffle", True)
    import jax as _jax
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    mesh = Mesh(np.array(_jax.devices()[:8]).reshape(2, 4),
                ("dp", STAGE_AXIS))
    runner = ShardedCtrPipelineRunner(
        table_cfg, feed, n_stages=4, d_model=24, layers_per_stage=1,
        lr=1e-2, n_micro=N_MICRO, mesh=mesh, seed=0)
    ref_losses = []
    for _ in range(PASSES):
        halves = []
        runner.table.begin_feed_pass()
        for lo in (0, 4):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[lo:lo + 4])
            ds.load_into_memory(add_keys_fn=runner.table.add_keys)
            halves.append(ds.split_batches(num_workers=1)[0])
        runner.table.end_feed_pass()
        runner.begin_pass()
        n_groups = min(len(h) for h in halves) // N_MICRO
        losses = []
        for g in range(n_groups):
            group = (halves[0][g * N_MICRO:(g + 1) * N_MICRO]
                     + halves[1][g * N_MICRO:(g + 1) * N_MICRO])
            losses.append(runner.train_step(group))
        runner.end_pass()
        ref_losses.append(float(np.mean(losses)))
    np.testing.assert_allclose(results[0]["losses"], ref_losses,
                               rtol=2e-4,
                               err_msg="2-process sharded pipeline "
                                       "diverges from the single-process "
                                       "composition")
    # store rows: every cluster-trained row must match the oracle's store
    sk, sv = runner.table.store_view().state_items()
    order = np.argsort(sk)
    sk, sv = sk[order], sv[order]
    checked = 0
    for r in (0, 1):
        for k_str, v in results[r]["rows"].items():
            i = np.searchsorted(sk, np.uint64(int(k_str)))
            assert i < sk.size and sk[i] == np.uint64(int(k_str)), k_str
            np.testing.assert_allclose(sv[i], np.asarray(v, np.float64),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=f"key {k_str}")
            checked += 1
    assert checked >= 4


def test_two_process_pipeline_over_central_ps(data, pipeline_cluster):
    """The deepest composition: pipeline parallelism at 2 real process
    boundaries with every shard store fronting ONE central CPU PS over
    TCP — section programs over the distributed PS across the cluster.
    Losses must agree across ranks and match the local-store 2-process
    pipeline run (parity holds because embed-row init is all-zeros:
    SparseOptimizerConfig.initial_range defaults to 0.0 — with a nonzero
    initial_range the two ranks' interleaved pulls would create keys in
    nondeterministic order and draw different init values than the
    local-store run); features must exist server-side afterwards."""
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.ps import PSServer, TcpPSClient

    files, feed = data
    N_MICRO = PIPE_N_MICRO
    # local-store reference cluster (already parity-pinned to the
    # single-process composition by test_two_process_sharded_pipeline)
    ref = pipeline_cluster

    server = PSServer()
    admin = TcpPSClient("127.0.0.1", server.port)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=8 * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    try:
        admin.create_sparse_table(11, table_cfg, shard_num=8, seed=0)
        results = run_cluster(
            files, {"n_micro": N_MICRO,
                    "ps_endpoint": "127.0.0.1:%d" % server.port,
                    "ps_table_id": 11},
            world=2, devs_per_proc=4,
            worker_script="multihost_pipeline_worker.py")
        assert set(results) == {0, 1}
        np.testing.assert_allclose(results[0]["losses"],
                                   results[1]["losses"], rtol=1e-6)
        np.testing.assert_allclose(results[0]["losses"],
                                   ref[0]["losses"], rtol=1e-4,
                                   err_msg="GPUPS pipeline cluster "
                                           "diverges from local stores")
        assert results[0]["ps_rows"] and results[0]["ps_rows"] > 100
    finally:
        admin.stop_server()
        admin.close()


def test_four_process_hierarchical_mesh(data, oracle):
    """The 2D mesh at 4 real process boundaries: node axis = 4 processes
    (DCN), chip axis = each process's 2 devices — the node psum now spans
    4 ranks. Must still reproduce the flat single-process oracle."""
    files, feed = data
    ref_losses, _msg, _rows = oracle
    results = run_cluster(files, {"mesh_2d": True,
                                  "skip_shuffle_phase": True},
                          world=4, devs_per_proc=2)
    assert set(results) == {0, 1, 2, 3}
    for r in (1, 2, 3):
        np.testing.assert_allclose(results[0]["losses"],
                                   results[r]["losses"], rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], ref_losses, rtol=1e-4,
                               err_msg="4-node 2D mesh diverges from the "
                                       "flat oracle")
