"""push_write='log' — the log-structured slab write (round 5).

Contract under test: with the write redirected to a fixed-size log
(push_sparse_log) and pulls reading through the host-staged combined
index (pull_rows_combined), training is BIT-IDENTICAL to the scatter
write at every merge cadence — including mid-pass merges forced by a
tiny log, the per-step tail path, and multi-pass runs. The measured
motivation (write cost flat in slab size) is tools/write_probe.py /
BASELINE.md round 5."""

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer
from paddlebox_tpu.train.trainer import LogStageState, resolve_log_batches

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("log_push_data")
    # small vocab → heavy key recurrence across batches: read-after-write
    # through the log (and across merge boundaries) is exercised hard
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=480, num_slots=NUM_SLOTS,
        vocab_per_slot=120, max_len=3, seed=11)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    return files, feed


def run_mode(files, feed, mode, log_batches=0, scan_chunk=2, passes=2,
             optimizer="adagrad"):
    flags.set_flag("push_write", mode)
    flags.set_flag("log_batches", log_batches)
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(
                optimizer=optimizer, mf_create_thresholds=0.0,
                mf_initial_range=1e-3))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        tr = BoxTrainer(model, table, feed, TrainerConfig(
            scan_chunk=scan_chunk), seed=0)
        losses = []
        for p in range(passes):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(tr.train_pass(ds)["loss"])
            ds.release_memory()
        keys, vals = tr.table.store.state_items()
        order = np.argsort(keys)
        params = tr.params
        tr.close()
        return losses, keys[order], vals[order], params
    finally:
        flags.set_flag("push_write", "auto")
        flags.set_flag("log_batches", 0)


def assert_identical(a, b):
    la, ka, va, pa = a
    lb, kb, vb, pb = b
    assert la == lb
    assert np.array_equal(ka, kb)
    assert np.array_equal(va, vb)
    import jax
    for xa, xb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


def test_log_matches_scatter_tiny_log(data):
    """log_batches=3 < batches/pass forces multiple mid-pass merges; the
    15-batch pass (scan_chunk=2) also exercises the per-step tail."""
    files, feed = data
    base = run_mode(files, feed, "scatter")
    log = run_mode(files, feed, "log", log_batches=3)
    assert_identical(base, log)


def test_log_matches_rebuild_large_log(data):
    """A log larger than the pass: no mid-pass merge, one final fold."""
    files, feed = data
    base = run_mode(files, feed, "rebuild")
    log = run_mode(files, feed, "log", log_batches=64)
    assert_identical(base, log)


def test_log_per_step_only(data):
    """scan_chunk=1 routes every batch through the per-step tail path
    (merge checks + src staging inline)."""
    files, feed = data
    base = run_mode(files, feed, "scatter", scan_chunk=1, passes=1)
    log = run_mode(files, feed, "log", log_batches=3, scan_chunk=1,
                   passes=1)
    assert_identical(base, log)


def test_log_adam_optimizer(data):
    """In-table adam carries 4 state columns through the log."""
    files, feed = data
    base = run_mode(files, feed, "scatter", passes=1, optimizer="adam")
    log = run_mode(files, feed, "log", log_batches=3, passes=1,
                   optimizer="adam")
    assert_identical(base, log)


def test_log_stage_state_unit():
    """Host bookkeeping: src resolves to the latest version at assign
    time (pre-batch view), slots advance, merge resets."""
    st = LogStageState(capacity=100, key_capacity=4, log_batches=2)
    ids = np.array([5, 7, 5, 99], np.int32)          # 99 = trash row
    uids = np.array([5, 7, 99, 100], np.int32)       # 100 = padding
    src0 = st.assign(ids, uids)
    # first batch: nothing logged yet -> src = slab ids
    assert np.array_equal(src0, ids)
    assert st.cur == 4
    # second batch re-reads key 5 -> its log slot (100 + 0)
    ids2 = np.array([5, 8, 8, 99], np.int32)
    uids2 = np.array([5, 8, 99, 101], np.int32)
    src2 = st.assign(ids2, uids2)
    assert src2[0] == 100 + 0           # key 5 logged at slot 0
    assert src2[1] == 8                 # key 8 unseen -> slab
    assert src2[3] == 100 + 2           # trash row logged too (slot 2)
    assert st.need_merge()
    mpos = st.take_mpos()
    assert mpos[5] == 4                 # latest write of key 5 = slot 4
    assert mpos[8] == 5
    assert mpos[7] == 1
    assert mpos[99] == 6                # trash row's latest slot
    assert (mpos >= 0).sum() == 4       # 5, 7, 8, 99 (padding uids skip)
    assert st.cur == 0 and not st.need_merge()
    # after merge everything resolves to the slab again
    src3 = st.assign(ids, uids)
    assert np.array_equal(src3, ids)


def test_log_stage_guards():
    st = LogStageState(capacity=100, key_capacity=4, log_batches=1)
    ids = np.array([1, 2, 3, 99], np.int32)
    uids = np.array([1, 2, 3, 99], np.int32)
    st.assign(ids, uids)
    with pytest.raises(RuntimeError, match="log full"):
        st.assign(ids, uids)
    with pytest.raises(ValueError, match="key capacity"):
        st.assign(ids, np.array([1, 2], np.int32))


def test_resolve_log_batches_validation():
    assert resolve_log_batches(1 << 20, 1024, scan_chunk=8) == \
        max(16, min(256, (1 << 20) // (8 * 1024)))
    flags.set_flag("log_batches", 4)
    try:
        with pytest.raises(ValueError, match="scan_chunk"):
            resolve_log_batches(1 << 20, 1024, scan_chunk=8)
        assert resolve_log_batches(1 << 20, 1024, scan_chunk=4) == 4
    finally:
        flags.set_flag("log_batches", 0)


def test_grouped_h2d_matches_per_chunk(data):
    """h2d_stack_chunks>1 (round-5 verdict item 4): G chunks sharing one
    transfer per leaf — with device-side slicing back to per-chunk views
    — must be bit-identical to per-chunk transfers, in both the log and
    scatter write modes (including the mid-pass merge cadence and the
    per-step tail)."""
    files, feed = data
    for mode, lb in (("scatter", 0), ("log", 3)):
        base = run_mode(files, feed, mode, log_batches=lb)
        flags.set_flag("h2d_stack_chunks", 4)
        try:
            grouped = run_mode(files, feed, mode, log_batches=lb)
        finally:
            flags.set_flag("h2d_stack_chunks", 1)
        assert_identical(base, grouped)


def test_h2d_lean_matches_host_dedup(data):
    """h2d_lean (round-5 item 4 follow-on): device-side dedup with the
    minimal wire must train bit-identically to the host-dedup scatter
    path — the content-addressed lazy-init randoms make created rows
    independent of WHERE the dedup ran."""
    files, feed = data
    base = run_mode(files, feed, "scatter", passes=1)
    flags.set_flag("h2d_lean", True)
    try:
        lean = run_mode(files, feed, "auto", passes=1)
    finally:
        flags.set_flag("h2d_lean", False)
    assert_identical(base, lean)


def test_h2d_lean_rejects_host_map_modes(data):
    files, feed = data
    flags.set_flag("h2d_lean", True)
    try:
        with pytest.raises(ValueError, match="h2d_lean"):
            run_mode(files, feed, "rebuild", passes=1)
    finally:
        flags.set_flag("h2d_lean", False)


def test_push_write_log_rejected_where_unsupported(data):
    """Explicit push_write=log on an unsupported path fails loud at
    construction, not deep in a staging thread."""
    files, feed = data
    flags.set_flag("push_write", "log")
    try:
        table = TableConfig(
            embedx_dim=D, pass_capacity=2048,
            optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        with pytest.raises(ValueError, match="push_write=log"):
            BoxTrainer(model, table, feed,
                       TrainerConfig(sparse_chunk_sync=True, scan_chunk=2),
                       seed=0)
    finally:
        flags.set_flag("push_write", "auto")
