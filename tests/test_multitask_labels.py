"""Per-task labels + cmatch-rank metric variants (VERDICT r1 missing #5).

The round-1 packer aliased every task's label to the click label, so ESMM
trained cvr on clicks. Now: task_label_slots routes designated label slots
through parser → SlotRecord.extra_labels → PackedBatch.task_labels →
labels_<task>, and the metric registry grows the cmatch-rank/multi-task
variants of metrics.h:327-568."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.data.packer import BatchPacker
from paddlebox_tpu.data.parser import MultiSlotParser
from paddlebox_tpu.data.shuffle import deserialize_records, serialize_records
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.metrics.auc import (BasicAucCalculator, MetricRegistry,
                                       parse_cmatch_rank)
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.esmm import ESMM
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def conv_data(tmp_path_factory):
    out = tmp_path_factory.mktemp("mtl")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=400, num_slots=NUM_SLOTS,
        vocab_per_slot=80, max_len=3, seed=77, conversion=True)
    feed = type(feed)(slots=feed.slots, batch_size=32,
                      task_label_slots=feed.task_label_slots)
    return files, feed


def test_parser_extracts_task_labels(conv_data):
    files, feed = conv_data
    parser = MultiSlotParser(feed)
    recs = list(parser.parse_file(files[0]))
    assert recs, "no records parsed"
    convs = np.array([r.extra_labels.get("cvr", -1) for r in recs])
    clicks = np.array([r.label for r in recs])
    assert (convs >= 0).all()
    # conversion implies click, and the labels genuinely differ
    assert ((convs == 1) <= (clicks == 1)).all()
    assert (convs != clicks).any()


def test_packer_fills_task_labels_and_cmatch_rank(conv_data):
    files, feed = conv_data
    parser = MultiSlotParser(feed)
    recs = list(parser.parse_file(files[0]))[:16]
    for i, r in enumerate(recs):
        r.cmatch = 222 if i % 2 == 0 else 223
        r.rank = (i % 3) + 1
    packer = BatchPacker(type(feed)(slots=feed.slots, batch_size=16,
                                    task_label_slots=feed.task_label_slots))
    b = packer.pack(recs)
    assert b.task_labels is not None and "cvr" in b.task_labels
    np.testing.assert_array_equal(
        b.task_labels["cvr"][:16],
        [r.extra_labels["cvr"] for r in recs])
    cm, rk = parse_cmatch_rank(b.cmatch_rank[:16])
    np.testing.assert_array_equal(cm, [r.cmatch for r in recs])
    np.testing.assert_array_equal(rk, [r.rank for r in recs])


def test_shuffle_codec_roundtrips_extra_labels():
    r = SlotRecord(label=1, uint64_slots={0: np.array([5], np.uint64)},
                   extra_labels={"cvr": 1, "pay": 0}, cmatch=222, rank=2)
    out = deserialize_records(serialize_records([r]))[0]
    assert out.extra_labels == {"cvr": 1, "pay": 0}
    assert out.cmatch == 222 and out.rank == 2


def test_esmm_trains_cvr_on_conversion_label(conv_data):
    """The cvr head must see the conversion label: its predictions rank
    conversions (given click) better than the click predictor does."""
    files, feed = conv_data
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.2,
                                        mf_learning_rate=0.2))
    trainer = BoxTrainer(
        ESMM(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D), tower=(16,)),
        table_cfg, feed, TrainerConfig(dense_lr=0.01), seed=0)
    trainer.metrics.init_metric("ctcvr_auc", "label_cvr", "pred_ctcvr",
                                table_size=1 << 14, mask_var="mask")
    for _ in range(8):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        trainer.train_pass(ds)
        ds.release_memory()
    msg = trainer.metrics.get_metric_msg("ctcvr_auc")
    assert msg["auc"] > 0.6, msg
    # the labels the metric consumed were conversions, not clicks:
    # conversion rate < click rate by construction
    assert msg["actual_ctr"] < 0.45, msg


def test_cmatch_rank_metric_filters():
    reg = MetricRegistry()
    reg.init_cmatch_rank_metric("join_auc", "label", "pred",
                                cmatch_rank_group="222_1,223_2")
    reg.init_cmatch_rank_metric("cmatch_auc", "label", "pred",
                                cmatch_rank_group="222", ignore_rank=True)
    rng = np.random.RandomState(0)
    n = 512
    cmatch = rng.choice([222, 223, 224], n)
    rank = rng.randint(1, 4, n)
    label = rng.randint(0, 2, n)
    pred = np.where(label == 1, rng.rand(n) * 0.5 + 0.5, rng.rand(n) * 0.5)
    enc = (cmatch.astype(np.uint64) << np.uint64(32)) | rank.astype(np.uint64)
    reg.add_batch({"label": label, "pred": pred, "cmatch_rank": enc})

    sel = ((cmatch == 222) & (rank == 1)) | ((cmatch == 223) & (rank == 2))
    oracle = BasicAucCalculator(1 << 14)
    oracle.add_data(pred[sel], label[sel])
    oracle.compute()
    msg = reg.get_metric_msg("join_auc")
    assert msg["size"] == sel.sum()
    np.testing.assert_allclose(msg["auc"], oracle.auc(), rtol=1e-9)

    msg2 = reg.get_metric_msg("cmatch_auc")
    assert msg2["size"] == (cmatch == 222).sum()


def test_multi_task_metric_selects_pred_per_pair():
    reg = MetricRegistry()
    reg.init_multi_task_metric("mt_auc", "label", ["pred_a", "pred_b"],
                               cmatch_rank_group="222_1 223_1")
    rng = np.random.RandomState(1)
    n = 256
    cmatch = rng.choice([222, 223], n)
    rank = np.ones(n, np.int64)
    label = rng.randint(0, 2, n)
    # pred_a is informative, pred_b is noise
    pred_a = np.where(label == 1, 0.9, 0.1)
    pred_b = rng.rand(n)
    enc = (cmatch.astype(np.uint64) << np.uint64(32)) | rank.astype(np.uint64)
    reg.add_batch({"label": label, "pred_a": pred_a, "pred_b": pred_b,
                   "cmatch_rank": enc})
    oracle = BasicAucCalculator(1 << 14)
    oracle.add_data(pred_a[cmatch == 222], label[cmatch == 222])
    oracle.add_data(pred_b[cmatch == 223], label[cmatch == 223])
    oracle.compute()
    msg = reg.get_metric_msg("mt_auc")
    assert msg["size"] == n
    np.testing.assert_allclose(msg["auc"], oracle.auc(), rtol=1e-9)


def test_columnar_path_carries_task_labels(conv_data):
    """The native columnar fast path must emit the same per-task labels as
    the record path (psr_parse_file2)."""
    from paddlebox_tpu.native.build import available

    if not available():
        pytest.skip("native library unavailable")
    files, feed = conv_data
    ds_col = BoxDataset(feed, read_threads=1, columnar=True)
    assert ds_col.columnar, "columnar path should engage for task labels"
    ds_col.set_filelist(files)
    ds_col.load_into_memory()
    ds_rec = BoxDataset(feed, read_threads=1, columnar=False)
    ds_rec.set_filelist(files)
    ds_rec.load_into_memory()
    assert len(ds_col) == len(ds_rec)
    b_col = ds_col.split_batches(num_workers=1)[0][0]
    b_rec = ds_rec.split_batches(num_workers=1)[0][0]
    assert b_col.task_labels is not None
    np.testing.assert_array_equal(b_col.task_labels["cvr"],
                                  b_rec.task_labels["cvr"])
    np.testing.assert_array_equal(b_col.labels, b_rec.labels)
