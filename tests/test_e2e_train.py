"""End-to-end spine test: synthetic CTR data → dataset load/feed-pass →
pass-table → fused train step → streaming AUC lift → checkpoint/resume.
The Python analog of running the reference's full BoxPS cadence without the
closed binary (SURVEY.md §4's missing tier)."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.metrics import BasicAucCalculator
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer, CheckpointManager

D = 8
NUM_SLOTS = 4


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("ctr_data")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=3, lines_per_file=800, num_slots=NUM_SLOTS,
        vocab_per_slot=200, max_len=3, seed=7)
    feed = type(feed)(slots=feed.slots, batch_size=128)
    return files, feed


def make_trainer(feed, seed=0):
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)
    model = CtrDnn(spec, hidden=(64, 32))
    return BoxTrainer(model, table_cfg, feed,
                      TrainerConfig(dense_lr=3e-3), seed=seed)


def test_e2e_auc_lift(data):
    files, feed = data
    trainer = make_trainer(feed)
    trainer.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                                mask_var="mask")

    for epoch in range(6):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = trainer.train_pass(ds)
        assert stats["instances"] == 2400
        ds.release_memory()

    msg = trainer.metrics.get_metric_msg("auc")
    # streaming AUC mixes all passes (incl. the untrained first one); the
    # learnable signal must still pull it clearly above chance
    assert msg["auc"] > 0.6, msg
    assert msg["size"] == 6 * 2400

    # fresh-eval AUC must beat 0.65 after training
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    trainer.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=trainer.table.add_keys)
    trainer.table.end_feed_pass()
    preds, labels = trainer.predict_batches(ds)
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    assert calc.auc() > 0.7, calc.auc()


def test_checkpoint_resume(data, tmp_path):
    files, feed = data
    trainer = make_trainer(feed)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files[:1])
    trainer.train_pass(ds)

    ckpt_cfg = CheckpointConfig(
        batch_model_dir=str(tmp_path / "batch"),
        xbox_model_dir=str(tmp_path / "xbox"),
        async_save=False)
    cm = CheckpointManager(ckpt_cfg, trainer.table)
    batch_dir, xbox_dir = cm.save_base(trainer.params, trainer.opt_state,
                                       day="20260729")

    # resume into a fresh trainer and verify predictions match
    trainer2 = make_trainer(feed, seed=123)
    cm2 = CheckpointManager(ckpt_cfg, trainer2.table)
    params, opt_state, _ = cm2.load_base("20260729")
    trainer2.params = params
    trainer2.opt_state = opt_state

    ds_eval = BoxDataset(feed, read_threads=1)
    ds_eval.set_filelist(files[:1])
    t1 = trainer
    t1.table.begin_feed_pass()
    ds_eval.load_into_memory(add_keys_fn=t1.table.add_keys)
    t1.table.end_feed_pass()
    p1, _ = t1.predict_batches(ds_eval)

    ds_eval2 = BoxDataset(feed, read_threads=1)
    ds_eval2.set_filelist(files[:1])
    trainer2.table.begin_feed_pass()
    ds_eval2.load_into_memory(add_keys_fn=trainer2.table.add_keys)
    trainer2.table.end_feed_pass()
    p2, _ = trainer2.predict_batches(ds_eval2)

    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_delta_save_covers_touched_keys(data, tmp_path):
    files, feed = data
    trainer = make_trainer(feed)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files[:1])
    trainer.train_pass(ds)

    from paddlebox_tpu.serving.store import read_xbox_view
    ckpt_cfg = CheckpointConfig(
        batch_model_dir=str(tmp_path / "batch"),
        xbox_model_dir=str(tmp_path / "xbox"),
        async_save=False)
    cm = CheckpointManager(ckpt_cfg, trainer.table)
    xbox_dir = cm.save_delta("20260729", delta_id=1)
    keys1, emb1 = read_xbox_view(xbox_dir)
    # every trained feature crossed delta_threshold=0.25 (each occurrence
    # adds >= nonclk_coeff*1=0.1... clicks add 1.0), so delta covers most
    assert keys1.size > 0
    assert emb1.shape[1] == 1 + D
    # second delta immediately after: nothing new crossed the threshold
    xbox_dir2 = cm.save_delta("20260729", delta_id=2)
    keys2, _emb2 = read_xbox_view(xbox_dir2)
    assert keys2.size < keys1.size


def test_push_write_rebuild_matches_scatter(data):
    """push_write='rebuild' (gather-rebuild slab write; the TPU-side
    default via 'auto') must train bit-identically to the scatter path —
    whole pass, real feed, host dedup + pos staged per batch."""
    from paddlebox_tpu.config import flags
    files, feed = data
    slabs = {}
    for mode in ("scatter", "rebuild"):
        flags.set_flag("push_write", mode)
        try:
            trainer = make_trainer(feed, seed=9)
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            trainer.train_pass(ds)
            keys = np.sort(trainer.table._pass_keys)
            slabs[mode] = (keys, trainer.table.store.lookup(keys).copy())
        finally:
            flags.set_flag("push_write", "auto")
    np.testing.assert_array_equal(slabs["scatter"][0], slabs["rebuild"][0])
    np.testing.assert_array_equal(slabs["scatter"][1], slabs["rebuild"][1])


def test_push_write_auto_heuristic(monkeypatch):
    """'auto' picks by the measured crossover on tpu backends (rebuild's
    full-slab rewrite loses once the slab dwarfs the per-batch key
    budget) and always scatters on CPU."""
    import jax as _jax
    from paddlebox_tpu.train.trainer import resolve_push_write
    assert resolve_push_write(1 << 20, 131072) == "scatter"  # cpu backend
    monkeypatch.setattr(_jax, "default_backend", lambda: "tpu")
    assert resolve_push_write(1 << 20, 131072) == "rebuild"
    assert resolve_push_write(1 << 22, 131072) == "scatter"  # 32x keys
    assert resolve_push_write(None, None) == "rebuild"       # no hints


def test_chunk_prefetch_matches_inline(data):
    """The chunk-staging prefetch thread (chunk_prefetch_depth) must be
    invisible to results: bit-identical trained state vs inline staging,
    and a staging error must surface at the caller, not die on the
    producer thread."""
    from paddlebox_tpu.config import flags
    states = {}
    for depth in (0, 2):
        flags.set_flag("chunk_prefetch_depth", depth)
        try:
            files, feed = data
            trainer = make_trainer(feed, seed=21)
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files[:1])
            trainer.train_pass(ds)
            keys = np.sort(trainer.table._pass_keys)
            states[depth] = (keys, trainer.table.store.lookup(keys).copy())
        finally:
            flags.set_flag("chunk_prefetch_depth", 1)
    np.testing.assert_array_equal(states[0][0], states[2][0])
    np.testing.assert_array_equal(states[0][1], states[2][1])

    # producer-thread staging errors surface at the consumer
    from paddlebox_tpu.train.trainer import run_scan_chunks

    def bad_stack(group):
        raise RuntimeError("staging boom")

    with pytest.raises(RuntimeError, match="staging boom"):
        run_scan_chunks(lambda c, s: (c, None, None), list(range(8)), 4,
                        bad_stack, (), lambda *a: None, prefetch_depth=1)


def test_chunk_prefetch_stager_stops_on_consumer_error():
    """A consumer-side error (e.g. the nan guard) must STOP the producer
    thread — a zombie stager would keep reading the table into the
    caller's next pass (the shard_batches race)."""
    import threading
    import time as _time
    from paddlebox_tpu.train.trainer import run_scan_chunks

    staged = []

    def slow_stack(group):
        staged.append(group)
        _time.sleep(0.05)
        return group

    calls = []

    def scan_call(carry, stacked):
        calls.append(stacked)
        if len(calls) == 2:
            raise FloatingPointError("nan guard")
        return carry, np.zeros(4), None

    before = threading.active_count()
    with pytest.raises(FloatingPointError):
        run_scan_chunks(scan_call, list(range(64)), 4, slow_stack, (),
                        lambda *a: None, prefetch_depth=2)
    # the producer must wind down promptly, not stage all 16 chunks
    _time.sleep(0.5)
    assert threading.active_count() <= before
    assert len(staged) < 16
