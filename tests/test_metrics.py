"""AUC calculator parity vs a direct numpy oracle.

Mirrors the role of the reference's metric correctness reliance: the bucketed
streaming AUC must converge to exact pairwise AUC as table_size grows, and
the side stats (mae/rmse/ctrs) must match closed forms.
"""

import numpy as np
import pytest

from paddlebox_tpu.metrics import BasicAucCalculator, MetricRegistry


def exact_auc(pred, label):
    """O(n^2)-free exact AUC via rank statistic with tie correction."""
    pred = np.asarray(pred, dtype=np.float64)
    label = np.asarray(label)
    pos = pred[label == 1]
    neg = pred[label == 0]
    if len(pos) == 0 or len(neg) == 0:
        return -0.5
    # count pairs pos > neg plus half ties
    wins = 0.0
    for p in pos:
        wins += np.sum(p > neg) + 0.5 * np.sum(p == neg)
    return wins / (len(pos) * len(neg))


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def test_auc_matches_exact_when_buckets_resolve(rng):
    n = 4000
    # predictions quantized to bucket grid so bucketing is lossless
    table = 1 << 14
    pred = rng.randint(0, table, n).astype(np.float64) / table
    prob = 0.2 + 0.6 * pred
    label = (rng.rand(n) < prob).astype(np.int64)

    calc = BasicAucCalculator()
    calc.init(table)
    # stream in chunks like per-batch AddAucMonitor
    for i in range(0, n, 256):
        calc.add_data(pred[i:i + 256], label[i:i + 256])
    calc.compute()

    np.testing.assert_allclose(calc.auc(), exact_auc(pred, label), atol=1e-9)
    np.testing.assert_allclose(calc.mae(), np.abs(pred - label).mean(), atol=1e-12)
    np.testing.assert_allclose(
        calc.rmse(), np.sqrt(((pred - label) ** 2).mean()), atol=1e-12)
    np.testing.assert_allclose(calc.actual_ctr(), label.mean(), atol=1e-12)
    np.testing.assert_allclose(calc.predicted_ctr(), pred.mean(), atol=1e-12)
    assert calc.size() == n


def test_auc_all_one_class():
    calc = BasicAucCalculator()
    calc.init(1024)
    calc.add_data(np.array([0.1, 0.9]), np.array([1, 1]))
    calc.compute()
    assert calc.auc() == -0.5  # reference sentinel for degenerate data


def test_auc_mask(rng):
    calc = BasicAucCalculator()
    calc.init(1 << 12)
    pred = np.array([0.9, 0.1, 0.5, 0.7])
    label = np.array([1, 0, 1, 0])
    mask = np.array([1, 1, 0, 0])
    calc.add_data(pred, label, mask=mask)
    calc.compute()
    assert calc.size() == 2
    np.testing.assert_allclose(calc.auc(), 1.0)


def test_allreduce_hook_merges_workers(rng):
    """Simulate 2 workers; allreduce hook must reproduce single-worker AUC."""
    table = 1 << 12
    pred = rng.randint(0, table, 1000).astype(np.float64) / table
    label = (rng.rand(1000) < 0.3).astype(np.int64)

    whole = BasicAucCalculator()
    whole.init(table)
    whole.add_data(pred, label)
    whole.compute()

    w0, w1 = BasicAucCalculator(), BasicAucCalculator()
    w0.init(table)
    w1.init(table)
    w0.add_data(pred[:500], label[:500])
    w1.add_data(pred[500:], label[500:])

    # fake 2-node allreduce: sum both workers' contributions
    other = {"t": None}

    def fake_allreduce_factory(mine, theirs):
        def f(arr):
            if arr.ndim == 2:
                return mine._table + theirs._table
            return np.array([
                mine._local_abserr + theirs._local_abserr,
                mine._local_sqrerr + theirs._local_sqrerr,
                mine._local_pred + theirs._local_pred,
            ])
        return f

    w0.compute(fake_allreduce_factory(w0, w1))
    np.testing.assert_allclose(w0.auc(), whole.auc(), atol=1e-12)
    np.testing.assert_allclose(w0.mae(), whole.mae(), atol=1e-12)


def test_wuauc_per_user(rng):
    calc = BasicAucCalculator()
    calc.init(1 << 12)
    # user 1: perfect ranking; user 2: inverted
    uid = np.array([1, 1, 1, 1, 2, 2, 2, 2], dtype=np.uint64)
    pred = np.array([0.9, 0.8, 0.2, 0.1, 0.1, 0.2, 0.8, 0.9])
    label = np.array([1, 1, 0, 0, 1, 1, 0, 0])
    calc.add_uid_data(pred, label, uid)
    calc.compute_wuauc()
    assert calc.user_cnt() == 2
    np.testing.assert_allclose(calc.uauc(), 0.5)   # mean(1.0, 0.0)
    np.testing.assert_allclose(calc.wuauc(), 0.5)  # equal ins weights


def test_wuauc_tie_handling():
    calc = BasicAucCalculator()
    calc.init(1 << 12)
    uid = np.array([7, 7, 7, 7], dtype=np.uint64)
    pred = np.array([0.5, 0.5, 0.5, 0.5])
    label = np.array([1, 0, 1, 0])
    calc.add_uid_data(pred, label, uid)
    calc.compute_wuauc()
    np.testing.assert_allclose(calc.uauc(), 0.5, atol=1e-6)  # all ties → 0.5


def test_nan_inf_counter():
    calc = BasicAucCalculator()
    calc.init(16)
    calc.add_nan_inf_data(np.array([1.0, np.nan, np.inf, 0.5]))
    calc.compute_nan_inf()
    np.testing.assert_allclose(calc.nan_inf_rate(), 0.5)


def test_metric_registry_phases():
    reg = MetricRegistry()
    reg.init_metric("join_auc", "label", "pred", metric_phase=1, table_size=1 << 10)
    reg.init_metric("update_auc", "label", "pred", metric_phase=0, table_size=1 << 10)
    reg.phase = 1
    tensors = {
        "pred": np.array([0.9, 0.1]),
        "label": np.array([1, 0]),
    }
    reg.add_batch(tensors)
    msg = reg.get_metric_msg("join_auc")
    assert msg["auc"] == 1.0
    assert msg["size"] == 2.0


def test_bucket_error_smoke(rng):
    """bucket_error is small for calibrated predictions, larger when biased."""
    table = 1 << 10
    n = 200_000
    pred = rng.rand(n)
    label = (rng.rand(n) < pred).astype(np.int64)  # perfectly calibrated
    calib = BasicAucCalculator()
    calib.init(table)
    calib.add_data(pred, label)
    calib.compute()

    biased = BasicAucCalculator()
    biased.init(table)
    biased.add_data(np.clip(pred * 0.5, 0, 1), label)  # under-predicts
    biased.compute()

    assert calib.bucket_error() < 0.1
    assert biased.bucket_error() > calib.bucket_error()


def test_bucket_error_sparse_matches_dense_oracle(rng):
    """sparse span-cascade scan == literal metrics.cc:345-380 transcription."""
    n = 1 << 12
    calc = BasicAucCalculator(n)
    for trial in range(5):
        neg = np.zeros(n)
        pos = np.zeros(n)
        # sparse clusters with long empty runs between them
        idx = rng.choice(n, size=rng.randint(1, 200), replace=False)
        neg[idx] = rng.randint(0, 50, idx.size)
        pos[idx] = rng.randint(0, 10, idx.size)
        got = calc._calculate_bucket_error(neg, pos)
        want = calc._calculate_bucket_error_dense(neg, pos)
        np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_wuauc_uid_above_2_53_not_merged():
    calc = BasicAucCalculator(1 << 10)
    base = np.uint64(1) << np.uint64(60)
    uid = np.array([base, base, base + np.uint64(1), base + np.uint64(1)],
                   dtype=np.uint64)
    pred = np.array([0.9, 0.1, 0.2, 0.8])
    label = np.array([1, 0, 1, 0])
    calc.add_uid_data(pred, label, uid)
    calc.compute_wuauc()
    assert calc.user_cnt() == 2  # float64 storage would merge them into 1
    np.testing.assert_allclose(calc.uauc(), 0.5)


def test_nan_inf_metric_kind():
    reg = MetricRegistry()
    reg.init_metric("guard", "label", "pred", table_size=16, kind="nan_inf")
    reg.add_batch({"pred": np.array([1.0, np.nan, np.inf, 0.5]),
                   "label": np.array([0, 0, 0, 0])})
    msg = reg.get_metric_msg("guard")
    assert msg == {"nan_inf_rate": 0.5}


def test_continue_metric_kind():
    reg = MetricRegistry()
    reg.init_metric("q", "label", "pred", table_size=16, kind="continue")
    pred = np.array([1.0, 2.0, 3.0])
    label = np.array([1.5, 2.5, 2.0])
    reg.add_batch({"pred": pred, "label": label})
    msg = reg.get_metric_msg("q")
    np.testing.assert_allclose(msg["mae"], np.abs(pred - label).mean())
    np.testing.assert_allclose(msg["rmse"], np.sqrt(((pred - label) ** 2).mean()))
    np.testing.assert_allclose(msg["predicted_value"], pred.mean())
    np.testing.assert_allclose(msg["actual_value"], label.mean())
    assert msg["size"] == 3.0
