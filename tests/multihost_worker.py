"""Worker script for the 2-process localhost cluster test (the subprocess
cluster pattern of the reference's test_dist_base.py:896-1012).

Each process: 4 virtual CPU devices → 8-device global mesh via
jax.distributed; loads its own half of the files; trains the sharded
trainer with cross-process feed-key union, equalized batch counts, and
metric allreduce. Prints ONE json line of results for the parent to check
against the single-process oracle.

Run via tests/test_multihost.py, never directly by pytest.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    _devs = os.environ.get("PBTPU_DEVS_PER_PROC", "4")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=" + _devs).strip()
os.environ["PBTPU_DATASET_DISABLE_SHUFFLE"] = "1"  # strict parity

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.fleet.fleet import fleet
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel.mesh import device_mesh_1d, device_mesh_2d
    from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer

    cfg = json.loads(sys.argv[1])
    fleet.init()
    fleet.init_distributed()   # store-based coordinator rendezvous
    rank, world = fleet.worker_index(), fleet.worker_num()
    assert jax.process_count() == world, (jax.process_count(), world)
    n_devs = len(jax.devices())
    want = world * int(os.environ.get("PBTPU_DEVS_PER_PROC", "4"))
    assert n_devs == want, (n_devs, want)

    # GPUPS variant: every process's shard stores live on ONE central CPU
    # PS over TCP (the distributed-full-store → per-pass-HBM-slab
    # composition, ps_gpu_wrapper.cc:337-760); the parent created the table
    ps_client = None
    store_factory = None
    if cfg.get("ps_endpoint"):
        from paddlebox_tpu.embedding.ps_store import ps_store_factory
        from paddlebox_tpu.ps import TcpPSClient
        host, port = cfg["ps_endpoint"].rsplit(":", 1)
        ps_client = TcpPSClient(host, int(port))
        store_factory = ps_store_factory(ps_client, cfg["ps_table_id"],
                                         process_primary=(rank == 0))

    assert len(cfg["files"]) % world == 0, (len(cfg["files"]), world)
    nf = len(cfg["files"]) // world
    files = cfg["files"][rank * nf:(rank + 1) * nf]
    D = cfg["embedx_dim"]
    feed = default_feed_config(num_slots=cfg["num_slots"],
                               batch_size=cfg["batch_size"],
                               max_len=cfg["max_len"])
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=n_devs * 1024,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1),
        **(cfg.get("table_overrides") or {}))
    # mesh_2d: the node axis spans the processes (real DCN boundary)
    # and the chip axis the in-process devices — hierarchical dense sync
    mesh = (device_mesh_2d(world, n_devs // world) if cfg.get("mesh_2d")
            else device_mesh_1d(n_devs))
    trainer = ShardedBoxTrainer(
        CtrDnn(ModelSpec(num_slots=cfg["num_slots"], slot_dim=3 + D),
               hidden=(32, 16)),
        table_cfg, feed,
        TrainerConfig(dense_lr=0.01,
                      sync_mode=cfg.get("sync_mode", "step")),
        mesh=mesh, seed=0, fleet=fleet,
        store_factory=store_factory)
    trainer.metrics.init_metric(
        "auc", "label", "pred", table_size=1 << 14, mask_var="mask",
        mode_collect_in_device=bool(cfg.get("device_auc")))

    losses = []
    for _ in range(cfg["passes"]):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = trainer.train_pass(ds)
        losses.append(stats["loss"])
        ds.release_memory()

    msg = trainer.metrics.get_metric_msg(
        "auc", allreduce=fleet.metric_allreduce())

    # sample rows from OWNED stores for the parity check (PS-backed shards
    # keep their rows server-side; the parent samples via its own client)
    rows = {}
    if ps_client is None:
        for s in trainer.local_positions:
            st = trainer.table.stores[s]
            keys, vals = st.state_items()
            order = np.argsort(keys)
            take = order[:3]
            for k, v in zip(keys[take], vals[take]):
                rows[str(int(k))] = [round(float(x), 6) for x in v]

    # ---- cross-host instance shuffle phase (ShuffleData/PaddleShuffler):
    # re-enable shuffle, route the load through the TcpShuffler, train one
    # more pass; instance totals must be conserved across the cluster
    local_after_shuffle = total_after_shuffle = shuffled_loss = None
    if not cfg.get("skip_shuffle_phase"):
        from paddlebox_tpu.config import flags as pbx_flags
        pbx_flags.set_flag("dataset_disable_shuffle", False)
        shuffler = fleet.make_shuffler(batch_records=64)
        ds = BoxDataset(feed, read_threads=1, shuffler=shuffler)
        ds.set_filelist(files)
        shuffled_stats = trainer.train_pass(ds)
        local_after_shuffle = len(ds)
        total_after_shuffle = int(fleet.all_reduce(
            np.asarray([local_after_shuffle], np.int64), "sum")[0])
        shuffled_loss = shuffled_stats["loss"]
        ds.release_memory()
        if shuffler is not None:
            shuffler.close()
        pbx_flags.set_flag("dataset_disable_shuffle", True)

    # ---- GPUPS spill + day boundary leg (4-proc composition test):
    # apply the table-wide DRAM budget (primary-gated limit_mem), train one
    # more pass so spilled rows fault back through the server pull, then
    # run the day boundary — aging and the shrink decay must hit the
    # server EXACTLY once across the whole cluster (process_primary
    # gating; the Px-decay bug class ps_store.py defends against)
    spilled = post_spill_loss = probe_key = show_before = None
    if cfg.get("spill_and_day") and ps_client is not None:
        # train_pass applies the budget at every pass end already (the
        # CheckNeedLimitMem cadence); one more pass proves spilled rows
        # fault back through the server pull, and the accumulated stat
        # shows the limit ran ONLY through this process's primary
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        post_spill_loss = trainer.train_pass(ds)["loss"]
        ds.release_memory()
        from paddlebox_tpu.utils.stats import stat_get
        spilled = int(stat_get("ps_rows_spilled"))
        if rank == 0:
            # a key this rank owns and trained in the last pass
            probe_key = int(trainer.table._shard_keys[
                trainer.local_positions[0]][0])
            from paddlebox_tpu.embedding import accessor as acc
            show_before = float(ps_client.pull_sparse(
                cfg["ps_table_id"], np.array([probe_key], np.uint64),
                create=False)[0, acc.SHOW])
        fleet.barrier_worker()         # probe read before any decay
        trainer.table.end_day(age=True)
        fleet.barrier_worker()         # boundary done on every rank

    ps_rows = (int(ps_client.sparse_size(cfg["ps_table_id"]))
               if ps_client is not None else None)
    print("RESULT " + json.dumps({
        "rank": rank, "losses": losses, "auc": msg["auc"],
        "size": msg["size"], "rows": rows,
        "collect_T": trainer._collect_T,
        "local_after_shuffle": local_after_shuffle,
        "total_after_shuffle": total_after_shuffle,
        "shuffled_loss": shuffled_loss,
        "ps_rows": ps_rows,
        "spilled": spilled, "post_spill_loss": post_spill_loss,
        "probe_key": probe_key, "show_before": show_before,
    }), flush=True)
    if ps_client is not None:
        ps_client.close()
    fleet.stop()


if __name__ == "__main__":
    main()
