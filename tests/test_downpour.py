"""Downpour CPU-PS training loop (DownpourWorker::TrainFiles role) against
both the in-process and the TCP PS (the two test mechanisms of SURVEY §4)."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.ps import PSServer, PsLocalClient, TcpPSClient
from paddlebox_tpu.ps.worker import (Communicator, DownpourTrainer,
                                     PullDenseWorker)

D = 4


def table_cfg():
    return TableConfig(embedx_dim=D, optimizer=SparseOptimizerConfig(
        mf_create_thresholds=0.0, mf_initial_range=1e-3,
        feature_learning_rate=0.2, mf_learning_rate=0.2))


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("downpour")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=300, num_slots=4,
        vocab_per_slot=100, max_len=3, seed=31)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def test_communicator_merges_and_flushes():
    cl = PsLocalClient()
    cl.create_sparse_table(0, table_cfg(), shard_num=2)
    from paddlebox_tpu.embedding.accessor import PushLayout
    push = PushLayout(D)
    comm = Communicator(cl, 0, push.width, send_batch_threshold=100,
                        send_interval=10.0)  # only explicit flush sends
    g = np.zeros((2, push.width), np.float32)
    g[:, push.SHOW] = 1
    g[:, push.EMBED_G] = 0.5
    comm.push(np.array([5, 5], np.uint64), g)
    comm.push(np.array([5, 9], np.uint64), g)
    comm.flush()
    rows = cl.pull_sparse(0, np.array([5, 9], np.uint64))
    from paddlebox_tpu.embedding import accessor as acc
    assert rows[0, acc.SHOW] == 3.0  # three merged occurrences of key 5
    assert rows[1, acc.SHOW] == 1.0
    comm.stop()


def test_pull_dense_worker_refreshes():
    cl = PsLocalClient()
    cl.create_dense_table("w", size=4, rule="sgd", lr=1.0)
    pw = PullDenseWorker(cl, "w", interval=0.02)
    assert (pw.value == 0).all()
    cl.push_dense("w", np.ones(4, np.float32))
    import time
    deadline = time.time() + 5
    while (pw.value == 0).all() and time.time() < deadline:
        time.sleep(0.02)
    np.testing.assert_allclose(pw.value, -1.0)
    pw.stop()


@pytest.mark.slow
def test_downpour_local_client_learns(data):
    """Slow tier (round 14, budget): an 8-pass convergence leg + eval
    drive; tier-1 keeps test_downpour_over_tcp (3-pass loss-decreases
    over the real transport) and the push/pull mechanics tests."""
    files, feed = data
    tr = DownpourTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                                hidden=(16,)),
                         table_cfg(), feed, PsLocalClient(),
                         TrainerConfig(dense_lr=0.01),
                         sync_comm=True)  # deterministic (async variant is
                                          # timing-sensitive under CI load)
    tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                           mask_var="mask")
    losses = []
    for _ in range(8):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
    assert np.isfinite(losses).all()
    # the streaming metric pools every pass incl. the untrained first ones,
    # so the learning assertion uses a fresh test-mode eval (SetTestMode
    # semantics, box_wrapper.cc:183) — verified >0.75 across 5 seeds
    msg = tr.metrics.get_metric_msg("auc")
    assert msg["size"] == 8 * 600
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds, labels = tr.predict_pass(ds)
    from paddlebox_tpu.metrics.auc import BasicAucCalculator
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    assert calc.auc() > 0.75, calc.auc()
    # features were created server-side
    assert tr.client.sparse_size(DownpourTrainer.SPARSE_TABLE) > 100
    tr.close()


def test_downpour_over_tcp(data):
    files, feed = data
    server = PSServer()
    cl = TcpPSClient("127.0.0.1", server.port)
    tr = DownpourTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                                hidden=(16,)),
                         table_cfg(), feed, cl, TrainerConfig(dense_lr=0.001))
    losses = []
    for _ in range(3):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
    assert losses[-1] < losses[0]
    tr.close()
    cl.stop_server()
    cl.close()
