"""Dense sync modes: async host table (BoxPSAsynDenseTable analog,
boxps_worker.cc:57-366), ZeRO-1 sharding (cc:582-751), and K-step sync
(cc:1169-1236), on the 8-device virtual CPU mesh."""

import numpy as np
import jax
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel import ShardedBoxTrainer
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.train.async_dense import AsyncDenseTable
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4


def table_cfg():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 12,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("modes_data")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=400, num_slots=4,
        vocab_per_slot=120, max_len=3, seed=21)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


# ---------------------------------------------------------------- unit table
def test_async_dense_table_adam_matches_reference_math():
    rng = np.random.RandomState(0)
    p0 = rng.randn(32).astype(np.float32)
    tab = AsyncDenseTable(p0, lr=0.1)
    g = rng.randn(32).astype(np.float32)
    tab.push(g)
    tab.wait_drained()
    # one adam step by hand
    m = 0.1 * g
    v = 0.001 * g * g
    expect = p0 - 0.1 * (m / (1 - 0.9)) / (np.sqrt(v / (1 - 0.999)) + 1e-8)
    np.testing.assert_allclose(tab.pull(), expect, rtol=1e-5)
    tab.stop()


def test_async_dense_table_summary_mask_accumulates():
    p0 = np.zeros(4, np.float32)
    mask = np.array([True, False, True, False])
    tab = AsyncDenseTable(p0, lr=0.1, summary_mask=mask)
    tab.push(np.array([1.0, 1.0, 2.0, 2.0], np.float32))
    tab.wait_drained()
    got = tab.pull()
    # summary slots add the raw grad (running-sum semantics)
    np.testing.assert_allclose(got[[0, 2]], [1.0, 2.0], rtol=1e-6)
    assert (got[[1, 3]] < 0).all()  # adam moved against positive grad
    tab.stop()


def test_async_dense_table_merges_queued_grads():
    tab = AsyncDenseTable(np.zeros(2, np.float32), lr=0.01, merge_limit=4)
    for _ in range(8):
        tab.push(np.ones(2, np.float32))
    tab.wait_drained()
    assert 2 <= tab.steps_applied <= 8  # merged bursts, never dropped
    tab.stop()


# ------------------------------------------------------------- e2e per mode
def _run_single(files, feed, cfg, passes=4, seed=0):
    spec = ModelSpec(num_slots=4, slot_dim=3 + D)
    model = CtrDnn(spec, hidden=(16,))
    tr = BoxTrainer(model, table_cfg(), feed, cfg, seed=seed)
    losses = []
    for _ in range(passes):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
    return tr, losses


def test_box_trainer_async_mode_learns(data):
    files, feed = data
    tr, losses = _run_single(
        files, feed, TrainerConfig(sync_mode="async", dense_lr=0.01))
    assert tr.async_table is not None
    assert tr.async_table.steps_applied > 0
    assert losses[-1] < losses[0]
    tr.async_table.stop()


def _run_sharded(files, feed, cfg, passes=3, seed=0):
    spec = ModelSpec(num_slots=4, slot_dim=3 + D)
    model = CtrDnn(spec, hidden=(16,))
    tr = ShardedBoxTrainer(model, table_cfg(), feed, cfg,
                           mesh=device_mesh_1d(8), seed=seed)
    losses = []
    for _ in range(passes):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
    return tr, losses


def test_zero1_sharding_matches_replicated_adam(data):
    """ZeRO-1 partitions the optimizer but must compute the SAME update as
    replicated adam (modulo float assoc) — run both 2 passes, compare."""
    files, feed = data
    tr_ref, _ = _run_sharded(files, feed,
                             TrainerConfig(dense_lr=0.01), passes=2)
    tr_sh, _ = _run_sharded(files, feed,
                            TrainerConfig(dense_lr=0.01, sharding=True),
                            passes=2)
    ref_flat = jax.flatten_util.ravel_pytree(tr_ref.params)[0]
    sh_flat = jax.flatten_util.ravel_pytree(tr_sh.params)[0]
    np.testing.assert_allclose(np.asarray(ref_flat), np.asarray(sh_flat),
                               rtol=2e-2, atol=2e-3)


def test_zero1_sharding_learns(data):
    files, feed = data
    tr, losses = _run_sharded(
        files, feed, TrainerConfig(dense_lr=0.01, sharding=True), passes=4)
    assert losses[-1] < losses[0]


def test_k_step_sync_replicas_converge(data):
    files, feed = data
    tr, losses = _run_sharded(
        files, feed,
        TrainerConfig(dense_lr=0.01, sync_mode="k_step", sync_weight_step=4),
        passes=3)
    assert losses[-1] < losses[0]
    # pass boundary synced: all 8 replicas identical
    leaf = jax.tree.leaves(tr.params)[0]
    arr = np.asarray(leaf)
    for d in range(1, arr.shape[0]):
        np.testing.assert_allclose(arr[0], arr[d], rtol=1e-6)
    # merged_params drops the replica dim
    merged = tr.merged_params()
    assert jax.tree.leaves(merged)[0].shape == arr.shape[1:]


def test_threaded_staging_matches_serial(data):
    """The stack_threads pool must stage chunks bit-identically to the
    serial path (order-preserving map; lookup/dedup are read-only over the
    shared pass index)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train.trainer import BoxTrainer
    from tools.bench_util import make_ctr_batches

    from paddlebox_tpu.data.generator import default_feed_config
    feed = default_feed_config(num_slots=8, batch_size=64, max_len=3)
    table = TableConfig(embedx_dim=4, pass_capacity=1 << 14,
                        optimizer=SparseOptimizerConfig(
                            mf_create_thresholds=0.0))
    model = DeepFM(ModelSpec(num_slots=8, slot_dim=7), hidden=(16,))
    tr = BoxTrainer(model, table, feed, TrainerConfig())
    batches = make_ctr_batches(feed, 6, 8, 3, seed=1)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    try:
        threaded = tr._stack_batches(batches)
        old = flags.get_flag("stack_threads")
        flags.set_flag("stack_threads", 1)
        try:
            # live flag change takes effect on the SAME trainer
            serial = tr._stack_batches(batches)
        finally:
            flags.set_flag("stack_threads", old)
        for k in threaded:
            np.testing.assert_array_equal(np.asarray(threaded[k]),
                                          np.asarray(serial[k]), err_msg=k)
        tr.table.end_pass()
    finally:
        tr.close()
