"""Preload overlap (BoxHelper PreLoadIntoMemory/WaitFeedPassDone cadence):
pipelined passes must train identically to sequential passes."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
from paddlebox_tpu.train.preload import PassPreloader, run_preloaded_passes
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4


def table_cfg():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("preload")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=200, num_slots=NUM_SLOTS,
        vocab_per_slot=80, max_len=3, seed=13)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


@pytest.fixture(autouse=True)
def no_shuffle():
    from paddlebox_tpu.config import flags
    flags.set_flag("dataset_disable_shuffle", True)
    yield
    flags.set_flag("dataset_disable_shuffle", False)


def datasets(files, feed, n):
    out = []
    for _ in range(n):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        out.append(ds)
    return out


def test_box_trainer_preload_parity(data):
    files, feed = data
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)

    seq = BoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                     TrainerConfig(dense_lr=0.01), seed=0)
    seq_losses = []
    for ds in datasets(files, feed, 3):
        seq_losses.append(seq.train_pass(ds)["loss"])

    pipe = BoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                      TrainerConfig(dense_lr=0.01), seed=0)
    stats = run_preloaded_passes(pipe, datasets(files, feed, 3))
    np.testing.assert_allclose([s["loss"] for s in stats], seq_losses,
                               rtol=1e-6)
    assert all(s["instances"] == 400 for s in stats)


def test_sharded_trainer_preload_parity(data):
    files, feed = data
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)

    seq = ShardedBoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                            TrainerConfig(dense_lr=0.01, scan_chunk=1),
                            mesh=device_mesh_1d(8), seed=0)
    seq_losses = []
    for ds in datasets(files, feed, 3):
        seq_losses.append(seq.train_pass(ds)["loss"])

    pipe = ShardedBoxTrainer(CtrDnn(spec, hidden=(16,)), table_cfg(), feed,
                             TrainerConfig(dense_lr=0.01, scan_chunk=1),
                             mesh=device_mesh_1d(8), seed=0)
    stats = run_preloaded_passes(pipe, datasets(files, feed, 3))
    np.testing.assert_allclose([s["loss"] for s in stats], seq_losses,
                               rtol=1e-6)


def test_preloader_guards(data):
    files, feed = data
    tr = BoxTrainer(CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                           hidden=(16,)),
                    table_cfg(), feed, TrainerConfig(), seed=0)
    pre = PassPreloader(tr.table)
    ds1, ds2 = datasets(files, feed, 2)
    pre.preload(ds1)
    with pytest.raises(RuntimeError):
        pre.preload(ds2)          # one in-flight preload at a time
    with pytest.raises(RuntimeError):
        pre.wait(ds2)             # wait() must match the preloaded dataset
    pre.wait(ds1)
    tr.table.begin_pass()
    tr.table.end_pass()
