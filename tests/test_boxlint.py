"""boxlint: fixture unit tests for all four passes + the tier-1 gate.

The gate test at the bottom is the point of the whole tool: it runs the
real checker over the real tree against the committed baseline, so any
NEW violation of the jit-purity / collective-axis / flag-hygiene /
lock-discipline invariants fails tier-1 — the mechanical replacement for
the reference's static-graph checks, gflags registry, and NCCL comm
groups (ARCHITECTURE.md "Enforced invariants").

These tests are pure-stdlib (ast only): no jax import, no devices.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tools.boxlint.core import (
    SourceFile, Violation, diff_against_baseline, format_baseline,
    load_baseline, load_tree, run_passes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code, passes, name="snippet.py", extra=()):
    """Run selected passes over an inline fixture; returns violations."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    paths = [str(p)] + [str(e) for e in extra]
    files, errors = load_tree(paths, root=str(tmp_path))
    assert not errors, errors
    return run_passes(files, passes)


def codes(violations):
    return [v.code for v in violations]


# ---------------------------------------------------------------- purity

PURE_FIXTURE = """
    import jax, jax.numpy as jnp, numpy as np
    from jax import lax

    @jax.jit
    def step(x, y):
        z = jnp.dot(x, y)
        u = jnp.unique(z, size=8, fill_value=0)
        host_n = int(x.shape[0])            # static: fine
        return z * host_n + u.sum()
"""

IMPURE_FIXTURE = """
    import jax, jax.numpy as jnp, numpy as np
    from jax import lax

    @jax.jit
    def step(x, y):
        v = x.sum().item()                  # BX101
        print(v)                            # BX101
        f = float(x)                        # BX102
        a = np.maximum(x, 0)                # BX103
        u = jnp.unique(y)                   # BX104
        m = x[y > 0]                        # BX105
        return v, f, a, u, m

    def helper(z):
        return z.item()                     # BX101 via the call graph

    @jax.jit
    def outer(z):
        return helper(z)

    def scan_body(carry, t):
        jax.device_get(carry)               # BX101 via lax.scan seeding
        return carry, t

    def run(xs):
        return lax.scan(scan_body, 0.0, xs)
"""


def test_purity_clean_fixture(tmp_path):
    assert lint_snippet(tmp_path, PURE_FIXTURE, ["purity"]) == []


def test_purity_flags_all_codes(tmp_path):
    got = codes(lint_snippet(tmp_path, IMPURE_FIXTURE, ["purity"]))
    for expect in ("BX101", "BX102", "BX103", "BX104", "BX105"):
        assert expect in got, (expect, got)
    # transitive: helper reached from the jitted outer, body from lax.scan
    assert got.count("BX101") >= 4


def test_purity_ignores_host_code(tmp_path):
    got = lint_snippet(tmp_path, """
        import numpy as np

        def host_only(x):
            return float(np.asarray(x).sum())   # never traced: fine
    """, ["purity"])
    assert got == []


def test_purity_excludes_callback_bodies(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def step(x):
            def on_host(v):
                return np.asarray(v).sum()      # host by contract: fine
            return jax.pure_callback(on_host, x, x)
    """, ["purity"])
    assert got == []


def test_purity_control_flow_wrappers_seed_correct_args(tmp_path):
    # fori_loop's body is args[2], cond's branches are args[1:2] — and the
    # bound/predicate args must NOT be seeded as traced functions
    got = lint_snippet(tmp_path, """
        from jax import lax

        def body(i, c):
            return c + c.item()            # BX101 (fori_loop body)

        def on_true(x):
            return x.item()                # BX101 (cond branch)

        def pred(x):
            return float(x) > 0            # host predicate: NOT seeded

        def run(x):
            y = lax.fori_loop(0, 10, body, x)
            z = lax.cond(True, on_true, lambda v: v, y)
            return lax.switch(0, [on_true, lambda v: v], z)
    """, ["purity"])
    assert codes(got) == ["BX101", "BX101"]
    assert all("pred" not in v.message for v in got)


# ------------------------------------------------------------ collectives

def test_collective_axis_known_vs_unknown(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax, numpy as np
        from jax import lax
        from jax.sharding import Mesh

        BOX_AXIS = "dp"
        mesh = Mesh(np.array(jax.devices()), ("dp",))

        def inside(x):
            good = lax.psum(x, "dp")
            also_good = lax.pmean(x, BOX_AXIS)
            bad = lax.psum(x, "dpp")            # BX201 (typo'd axis)
            return good + also_good + bad
    """, ["collectives"])
    assert codes(got) == ["BX201"]
    assert "dpp" in got[0].message


def test_collective_axis_dynamic_is_trusted(tmp_path):
    got = lint_snippet(tmp_path, """
        from jax import lax

        class T:
            def step(self, x, mesh):
                a = lax.psum(x, self.axis)           # dynamic: trusted
                b = lax.pmean(x, mesh.axis_names[0])  # dynamic: trusted
                return a + b
    """, ["collectives"])
    assert got == []


def test_collective_axis_default_param_resolves(tmp_path):
    got = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh
        import numpy as np

        mesh = Mesh(np.empty(1, object), ("dp",))

        def f(x, axis="nope"):              # BX201: default resolves
            return lax.all_gather(x, axis, tiled=True)
    """, ["collectives"])
    assert codes(got) == ["BX201"]


def test_repo_axis_vocabulary_includes_mesh_axes():
    from tools.boxlint.collectives import collect_axis_vocabulary
    files, _ = load_tree([os.path.join(REPO, "paddlebox_tpu")], root=REPO)
    vocab = collect_axis_vocabulary(files)
    # the canonical axes from parallel/mesh.py must all be declared,
    # including the round-13 2-D sparse-parallelism grid axes
    assert {"dp", "node", "data", "model", "pipeline",
            "table", "row"} <= vocab


def test_collective_axis_grid_pair(tmp_path):
    """Round-13 satellite: the 2-D grid's table/row axes are declared
    vocabulary (positive), while a typo'd policy axis still fails the
    gate (negative) — a PartitionSpec or collective over 'tabel' would
    otherwise only die at dispatch on pod hardware."""
    good = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh, PartitionSpec
        import numpy as np

        TABLE_AXIS = "table"
        ROW_AXIS = "row"
        mesh = Mesh(np.empty((2, 4), object), ("table", "row"))
        spec = PartitionSpec(("table", "row"))

        def step(x):
            a = lax.psum(x, "table")
            b = lax.pmean(x, ("table", "row"))
            return a + b
    """, ["collectives"])
    assert good == []
    bad = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh
        import numpy as np

        mesh = Mesh(np.empty((2, 4), object), ("table", "row"))

        def step(x):
            return lax.psum(x, "tabel")     # BX201: typo'd grid axis
    """, ["collectives"])
    assert codes(bad) == ["BX201"]
    assert "tabel" in bad[0].message


# ----------------------------------------------------------------- flags

FLAGS_DECL = """
    def define_flag(name, default, help=""):
        pass

    define_flag("alive", 1, "read somewhere")
    define_flag("dead_flag", 0, "never read")        # BX302
    define_flag("helpless", 0)                        # BX303
    define_flag("alive", 2, "dup")                    # BX304
"""

FLAGS_READER = """
    from config import flags

    def use():
        a = flags.get_flag("alive")
        b = flags.get_flag("mystery")                 # BX301
        return a, b
"""


def test_flags_all_codes(tmp_path):
    cfg = tmp_path / "config"
    cfg.mkdir()
    (cfg / "flags.py").write_text(textwrap.dedent(FLAGS_DECL))
    (tmp_path / "reader.py").write_text(textwrap.dedent(FLAGS_READER))
    files, errors = load_tree([str(tmp_path)], root=str(tmp_path))
    assert not errors
    got = run_passes(files, ["flags"])
    by_code = {c: [v for v in got if v.code == c]
               for c in ("BX301", "BX302", "BX303", "BX304")}
    assert len(by_code["BX301"]) == 1 and "mystery" in by_code["BX301"][0].message
    assert {"dead_flag", "helpless"} == {
        v.message.split("'")[1] for v in by_code["BX302"]}
    assert len(by_code["BX303"]) == 1 and "helpless" in by_code["BX303"][0].message
    assert len(by_code["BX304"]) == 1


def test_flags_silent_without_registry_file(tmp_path):
    # linting a subtree that lacks config/flags.py must not fabricate
    # BX301 for every read
    got = lint_snippet(tmp_path, """
        from paddlebox_tpu.config import flags

        def f():
            return flags.get_flag("whatever")
    """, ["flags"])
    assert got == []


def test_repo_flags_registry_is_clean():
    files, _ = load_tree([os.path.join(REPO, "paddlebox_tpu"),
                          os.path.join(REPO, "tools")], root=REPO)
    assert run_passes(files, ["flags"]) == []


# ----------------------------------------------------------------- locks

LOCKS_FIXTURE = """
    import threading

    class Shared:
        def __init__(self):
            self._q = []          # guarded-by: _lock
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.worker)

        def worker(self):
            with self._lock:
                self._q.append(1)          # locked: fine

        def racy(self):
            return len(self._q)            # BX401

        def boundary(self):  # boxlint: disable=BX401
            return list(self._q)           # suppressed: fine
"""


def test_lock_discipline(tmp_path):
    got = lint_snippet(tmp_path, LOCKS_FIXTURE, ["locks"])
    assert codes(got) == ["BX401"]
    assert "racy" not in got[0].message  # message names class.attr
    assert "_q" in got[0].message and "_lock" in got[0].message


def test_lock_stale_annotation(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._x = 0       # guarded-by: _gone
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert codes(got) == ["BX402"]


def test_lock_unannotated_thread_class(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert codes(got) == ["BX403"]


def test_lock_with_inside_except_handler_is_seen(tmp_path):
    # ExceptHandler is not an ast.stmt: the walk must still recurse into
    # it statement-wise or a correctly-locked access spuriously flags
    got = lint_snippet(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._d = []  # guarded-by: _lock
                self._lock = threading.Lock()
                self._t = threading.Thread(target=None)

            def m(self):
                try:
                    x = 1
                except Exception:
                    with self._lock:
                        self._d.append(1)      # locked: fine
                match x:
                    case 1:
                        with self._lock:
                            self._d.append(2)  # locked: fine
                    case _:
                        return len(self._d)    # BX401
    """, ["locks"])
    assert codes(got) == ["BX401"]


def test_lock_init_is_exempt(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._x = 0       # guarded-by: _lock
                self._lock = threading.Lock()
                self._x = 1       # later in __init__: still exempt
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert got == []


# ---------------------------------------------------- suppression syntax

def test_line_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            v = x.sum().item()   # boxlint: disable=BX101
            w = x.min().item()   # boxlint: disable
            return v + w
    """, ["purity"])
    assert got == []


def test_suppression_is_code_specific(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.sum().item()   # boxlint: disable=BX999
    """, ["purity"])
    assert codes(got) == ["BX101"]


# ------------------------------------------------------ baseline machinery

def test_baseline_roundtrip(tmp_path):
    vs = [Violation("a.py", 3, "BX101", "host sync"),
          Violation("b.py", 7, "BX301", "mystery flag")]
    bl = tmp_path / "baseline.txt"
    bl.write_text(format_baseline(vs))
    entries = load_baseline(str(bl))
    assert len(entries) == 2
    # same violations at DIFFERENT lines still match (line-drift immunity)
    moved = [Violation("a.py", 30, "BX101", "host sync"),
             Violation("b.py", 1, "BX301", "mystery flag")]
    new, stale = diff_against_baseline(moved, entries)
    assert new == [] and stale == []
    # a genuinely new violation surfaces; a fixed one reports stale
    new, stale = diff_against_baseline(
        moved + [Violation("c.py", 1, "BX104", "fresh")], entries)
    assert [v.code for v in new] == ["BX104"]
    new, stale = diff_against_baseline(moved[:1], entries)
    assert new == [] and len(stale) == 1


def test_baseline_multiset_counts(tmp_path):
    # two identical violations need two baseline entries
    v = Violation("a.py", 1, "BX101", "dup site")
    bl = tmp_path / "b.txt"
    bl.write_text(format_baseline([v]))
    new, _ = diff_against_baseline([v, Violation("a.py", 9, "BX101",
                                                 "dup site")],
                                   load_baseline(str(bl)))
    assert len(new) == 1


# ------------------------------------------------------------ CLI contract

def run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.boxlint"] + args,
                          cwd=cwd, capture_output=True, text=True)


def test_cli_exit_0_clean_tree():
    r = run_cli(["paddlebox_tpu/", "tools/"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_exit_1_on_new_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """))
    r = run_cli([str(bad)])
    assert r.returncode == 1
    assert "BX101" in r.stdout


def test_cli_exit_2_on_bad_args():
    r = run_cli(["--passes", "nonsense", "paddlebox_tpu/"])
    assert r.returncode == 2


def test_cli_fix_baseline_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    bl = tmp_path / "bl.txt"
    r = run_cli(["--baseline", str(bl), "--fix-baseline", str(bad)])
    assert r.returncode == 0 and bl.exists()
    # gate is green against the fresh baseline, red without it
    assert run_cli(["--baseline", str(bl), str(bad)]).returncode == 0
    assert run_cli(["--no-baseline", str(bad)]).returncode == 1


# ----------------------------------------------------------- spans (BX502)

SPAN_BAD_FIXTURE = """
    from paddlebox_tpu.obs import span as obs_span


    class Runner:
        def step(self, tracer):
            tracer.span("shard_step")     # bare expression: records NOTHING
            obs_span("host_stage")        # bare module-helper form


    def run(tracer, obs):
        obs.span("pull")                  # bare attribute form
"""

SPAN_GOOD_FIXTURE = """
    from paddlebox_tpu.obs import span as obs_span
    from paddlebox_tpu.obs.tracer import record_span


    def run(tracer, consume):
        with tracer.span("shard_step"):
            pass
        with obs_span("host_stage"):
            pass
        s = tracer.span("later")          # stored, entered below
        with s:
            pass
        record_span("post_hoc", 0.0, 1.0)  # post-hoc form, exempt
        consume(tracer.span("arg"))        # passed on, not discarded
"""


def test_span_bare_expression_flags(tmp_path):
    """The BX502 positive fixture: every bare-expression span() call —
    method, module-helper, attribute — flags once."""
    got = lint_snippet(tmp_path, SPAN_BAD_FIXTURE, ["spans"])
    assert codes(got) == ["BX502"] * 3


def test_span_proper_uses_clean(tmp_path):
    """Negative fixture: with-statements, stored managers, record_span
    and argument positions never flag."""
    assert lint_snippet(tmp_path, SPAN_GOOD_FIXTURE, ["spans"]) == []


def test_span_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        def run(tracer):
            tracer.span("x")  # boxlint: disable=BX502
    """, ["spans"])
    assert got == []


# ------------------------------------------------------------ the gate

def test_boxlint_gate_no_new_violations():
    """Tier-1 gate: the real tree lints clean against the committed
    baseline. A failure here means a NEW invariant violation (or a fix
    that should shrink baseline.txt via --fix-baseline)."""
    files, errors = load_tree([os.path.join(REPO, "paddlebox_tpu"),
                               os.path.join(REPO, "tools")], root=REPO)
    assert not errors, [e.render() for e in errors]
    violations = run_passes(files)
    baseline = load_baseline(os.path.join(REPO, "tools", "boxlint",
                                          "baseline.txt"))
    new, _stale = diff_against_baseline(violations, baseline)
    assert not new, "NEW boxlint violations:\n" + "\n".join(
        v.render() for v in new)
