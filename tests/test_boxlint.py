"""boxlint: fixture unit tests for all four passes + the tier-1 gate.

The gate test at the bottom is the point of the whole tool: it runs the
real checker over the real tree against the committed baseline, so any
NEW violation of the jit-purity / collective-axis / flag-hygiene /
lock-discipline invariants fails tier-1 — the mechanical replacement for
the reference's static-graph checks, gflags registry, and NCCL comm
groups (ARCHITECTURE.md "Enforced invariants").

These tests are pure-stdlib (ast only): no jax import, no devices.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from tools.boxlint.core import (
    SourceFile, Violation, diff_against_baseline, format_baseline,
    load_baseline, load_tree, run_passes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code, passes, name="snippet.py", extra=()):
    """Run selected passes over an inline fixture; returns violations."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    paths = [str(p)] + [str(e) for e in extra]
    files, errors = load_tree(paths, root=str(tmp_path))
    assert not errors, errors
    return run_passes(files, passes)


def codes(violations):
    return [v.code for v in violations]


# ---------------------------------------------------------------- purity

PURE_FIXTURE = """
    import jax, jax.numpy as jnp, numpy as np
    from jax import lax

    @jax.jit
    def step(x, y):
        z = jnp.dot(x, y)
        u = jnp.unique(z, size=8, fill_value=0)
        host_n = int(x.shape[0])            # static: fine
        return z * host_n + u.sum()
"""

IMPURE_FIXTURE = """
    import jax, jax.numpy as jnp, numpy as np
    from jax import lax

    @jax.jit
    def step(x, y):
        v = x.sum().item()                  # BX101
        print(v)                            # BX101
        f = float(x)                        # BX102
        a = np.maximum(x, 0)                # BX103
        u = jnp.unique(y)                   # BX104
        m = x[y > 0]                        # BX105
        return v, f, a, u, m

    def helper(z):
        return z.item()                     # BX101 via the call graph

    @jax.jit
    def outer(z):
        return helper(z)

    def scan_body(carry, t):
        jax.device_get(carry)               # BX101 via lax.scan seeding
        return carry, t

    def run(xs):
        return lax.scan(scan_body, 0.0, xs)
"""


def test_purity_clean_fixture(tmp_path):
    assert lint_snippet(tmp_path, PURE_FIXTURE, ["purity"]) == []


def test_purity_flags_all_codes(tmp_path):
    got = codes(lint_snippet(tmp_path, IMPURE_FIXTURE, ["purity"]))
    for expect in ("BX101", "BX102", "BX103", "BX104", "BX105"):
        assert expect in got, (expect, got)
    # transitive: helper reached from the jitted outer, body from lax.scan
    assert got.count("BX101") >= 4


def test_purity_ignores_host_code(tmp_path):
    got = lint_snippet(tmp_path, """
        import numpy as np

        def host_only(x):
            return float(np.asarray(x).sum())   # never traced: fine
    """, ["purity"])
    assert got == []


def test_purity_excludes_callback_bodies(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def step(x):
            def on_host(v):
                return np.asarray(v).sum()      # host by contract: fine
            return jax.pure_callback(on_host, x, x)
    """, ["purity"])
    assert got == []


def test_purity_control_flow_wrappers_seed_correct_args(tmp_path):
    # fori_loop's body is args[2], cond's branches are args[1:2] — and the
    # bound/predicate args must NOT be seeded as traced functions
    got = lint_snippet(tmp_path, """
        from jax import lax

        def body(i, c):
            return c + c.item()            # BX101 (fori_loop body)

        def on_true(x):
            return x.item()                # BX101 (cond branch)

        def pred(x):
            return float(x) > 0            # host predicate: NOT seeded

        def run(x):
            y = lax.fori_loop(0, 10, body, x)
            z = lax.cond(True, on_true, lambda v: v, y)
            return lax.switch(0, [on_true, lambda v: v], z)
    """, ["purity"])
    assert codes(got) == ["BX101", "BX101"]
    assert all("pred" not in v.message for v in got)


# ------------------------------------------------------------ collectives

def test_collective_axis_known_vs_unknown(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax, numpy as np
        from jax import lax
        from jax.sharding import Mesh

        BOX_AXIS = "dp"
        mesh = Mesh(np.array(jax.devices()), ("dp",))

        def inside(x):
            good = lax.psum(x, "dp")
            also_good = lax.pmean(x, BOX_AXIS)
            bad = lax.psum(x, "dpp")            # BX201 (typo'd axis)
            return good + also_good + bad
    """, ["collectives"])
    assert codes(got) == ["BX201"]
    assert "dpp" in got[0].message


def test_collective_axis_dynamic_is_trusted(tmp_path):
    got = lint_snippet(tmp_path, """
        from jax import lax

        class T:
            def step(self, x, mesh):
                a = lax.psum(x, self.axis)           # dynamic: trusted
                b = lax.pmean(x, mesh.axis_names[0])  # dynamic: trusted
                return a + b
    """, ["collectives"])
    assert got == []


def test_collective_axis_default_param_resolves(tmp_path):
    got = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh
        import numpy as np

        mesh = Mesh(np.empty(1, object), ("dp",))

        def f(x, axis="nope"):              # BX201: default resolves
            return lax.all_gather(x, axis, tiled=True)
    """, ["collectives"])
    assert codes(got) == ["BX201"]


def test_repo_axis_vocabulary_includes_mesh_axes():
    from tools.boxlint.collectives import collect_axis_vocabulary
    files, _ = load_tree([os.path.join(REPO, "paddlebox_tpu")], root=REPO)
    vocab = collect_axis_vocabulary(files)
    # the canonical axes from parallel/mesh.py must all be declared,
    # including the round-13 2-D sparse-parallelism grid axes
    assert {"dp", "node", "data", "model", "pipeline",
            "table", "row"} <= vocab


def test_collective_axis_grid_pair(tmp_path):
    """Round-13 satellite: the 2-D grid's table/row axes are declared
    vocabulary (positive), while a typo'd policy axis still fails the
    gate (negative) — a PartitionSpec or collective over 'tabel' would
    otherwise only die at dispatch on pod hardware."""
    good = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh, PartitionSpec
        import numpy as np

        TABLE_AXIS = "table"
        ROW_AXIS = "row"
        mesh = Mesh(np.empty((2, 4), object), ("table", "row"))
        spec = PartitionSpec(("table", "row"))

        def step(x):
            a = lax.psum(x, "table")
            b = lax.pmean(x, ("table", "row"))
            return a + b
    """, ["collectives"])
    assert good == []
    bad = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh
        import numpy as np

        mesh = Mesh(np.empty((2, 4), object), ("table", "row"))

        def step(x):
            return lax.psum(x, "tabel")     # BX201: typo'd grid axis
    """, ["collectives"])
    assert codes(bad) == ["BX201"]
    assert "tabel" in bad[0].message


# ----------------------------------------------------------------- flags

FLAGS_DECL = """
    def define_flag(name, default, help=""):
        pass

    define_flag("alive", 1, "read somewhere")
    define_flag("dead_flag", 0, "never read")        # BX302
    define_flag("helpless", 0)                        # BX303
    define_flag("alive", 2, "dup")                    # BX304
"""

FLAGS_READER = """
    from config import flags

    def use():
        a = flags.get_flag("alive")
        b = flags.get_flag("mystery")                 # BX301
        return a, b
"""


def test_flags_all_codes(tmp_path):
    cfg = tmp_path / "config"
    cfg.mkdir()
    (cfg / "flags.py").write_text(textwrap.dedent(FLAGS_DECL))
    (tmp_path / "reader.py").write_text(textwrap.dedent(FLAGS_READER))
    files, errors = load_tree([str(tmp_path)], root=str(tmp_path))
    assert not errors
    got = run_passes(files, ["flags"])
    by_code = {c: [v for v in got if v.code == c]
               for c in ("BX301", "BX302", "BX303", "BX304")}
    assert len(by_code["BX301"]) == 1 and "mystery" in by_code["BX301"][0].message
    assert {"dead_flag", "helpless"} == {
        v.message.split("'")[1] for v in by_code["BX302"]}
    assert len(by_code["BX303"]) == 1 and "helpless" in by_code["BX303"][0].message
    assert len(by_code["BX304"]) == 1


def test_flags_silent_without_registry_file(tmp_path):
    # linting a subtree that lacks config/flags.py must not fabricate
    # BX301 for every read
    got = lint_snippet(tmp_path, """
        from paddlebox_tpu.config import flags

        def f():
            return flags.get_flag("whatever")
    """, ["flags"])
    assert got == []


def test_repo_flags_registry_is_clean():
    files, _ = load_tree([os.path.join(REPO, "paddlebox_tpu"),
                          os.path.join(REPO, "tools")], root=REPO)
    assert run_passes(files, ["flags"]) == []


# ----------------------------------------------------------------- locks

LOCKS_FIXTURE = """
    import threading

    class Shared:
        def __init__(self):
            self._q = []          # guarded-by: _lock
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.worker)

        def worker(self):
            with self._lock:
                self._q.append(1)          # locked: fine

        def racy(self):
            return len(self._q)            # BX401

        def boundary(self):  # boxlint: disable=BX401
            return list(self._q)           # suppressed: fine
"""


def test_lock_discipline(tmp_path):
    got = lint_snippet(tmp_path, LOCKS_FIXTURE, ["locks"])
    assert codes(got) == ["BX401"]
    assert "racy" not in got[0].message  # message names class.attr
    assert "_q" in got[0].message and "_lock" in got[0].message


def test_lock_stale_annotation(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._x = 0       # guarded-by: _gone
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert codes(got) == ["BX402"]


def test_lock_unannotated_thread_class(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert codes(got) == ["BX403"]


def test_lock_with_inside_except_handler_is_seen(tmp_path):
    # ExceptHandler is not an ast.stmt: the walk must still recurse into
    # it statement-wise or a correctly-locked access spuriously flags
    got = lint_snippet(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._d = []  # guarded-by: _lock
                self._lock = threading.Lock()
                self._t = threading.Thread(target=None)

            def m(self):
                try:
                    x = 1
                except Exception:
                    with self._lock:
                        self._d.append(1)      # locked: fine
                match x:
                    case 1:
                        with self._lock:
                            self._d.append(2)  # locked: fine
                    case _:
                        return len(self._d)    # BX401
    """, ["locks"])
    assert codes(got) == ["BX401"]


def test_lock_init_is_exempt(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._x = 0       # guarded-by: _lock
                self._lock = threading.Lock()
                self._x = 1       # later in __init__: still exempt
                self._t = threading.Thread(target=None)
    """, ["locks"])
    assert got == []


# ---------------------------------------------------- suppression syntax

def test_line_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            v = x.sum().item()   # boxlint: disable=BX101
            w = x.min().item()   # boxlint: disable
            return v + w
    """, ["purity"])
    assert got == []


def test_suppression_is_code_specific(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.sum().item()   # boxlint: disable=BX999
    """, ["purity"])
    assert codes(got) == ["BX101"]


# ------------------------------------------------------ baseline machinery

def test_baseline_roundtrip(tmp_path):
    vs = [Violation("a.py", 3, "BX101", "host sync"),
          Violation("b.py", 7, "BX301", "mystery flag")]
    bl = tmp_path / "baseline.txt"
    bl.write_text(format_baseline(vs))
    entries = load_baseline(str(bl))
    assert len(entries) == 2
    # same violations at DIFFERENT lines still match (line-drift immunity)
    moved = [Violation("a.py", 30, "BX101", "host sync"),
             Violation("b.py", 1, "BX301", "mystery flag")]
    new, stale = diff_against_baseline(moved, entries)
    assert new == [] and stale == []
    # a genuinely new violation surfaces; a fixed one reports stale
    new, stale = diff_against_baseline(
        moved + [Violation("c.py", 1, "BX104", "fresh")], entries)
    assert [v.code for v in new] == ["BX104"]
    new, stale = diff_against_baseline(moved[:1], entries)
    assert new == [] and len(stale) == 1


def test_baseline_multiset_counts(tmp_path):
    # two identical violations need two baseline entries
    v = Violation("a.py", 1, "BX101", "dup site")
    bl = tmp_path / "b.txt"
    bl.write_text(format_baseline([v]))
    new, _ = diff_against_baseline([v, Violation("a.py", 9, "BX101",
                                                 "dup site")],
                                   load_baseline(str(bl)))
    assert len(new) == 1


# ------------------------------------------------------------ CLI contract

def run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.boxlint"] + args,
                          cwd=cwd, capture_output=True, text=True)


def test_cli_exit_0_clean_tree():
    r = run_cli(["paddlebox_tpu/", "tools/"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_exit_1_on_new_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """))
    r = run_cli([str(bad)])
    assert r.returncode == 1
    assert "BX101" in r.stdout


def test_cli_exit_2_on_bad_args():
    r = run_cli(["--passes", "nonsense", "paddlebox_tpu/"])
    assert r.returncode == 2


def test_cli_fix_baseline_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n")
    bl = tmp_path / "bl.txt"
    r = run_cli(["--baseline", str(bl), "--fix-baseline", str(bad)])
    assert r.returncode == 0 and bl.exists()
    # gate is green against the fresh baseline, red without it
    assert run_cli(["--baseline", str(bl), str(bad)]).returncode == 0
    assert run_cli(["--no-baseline", str(bad)]).returncode == 1


# ----------------------------------------------------------- spans (BX502)

SPAN_BAD_FIXTURE = """
    from paddlebox_tpu.obs import span as obs_span


    class Runner:
        def step(self, tracer):
            tracer.span("shard_step")     # bare expression: records NOTHING
            obs_span("host_stage")        # bare module-helper form


    def run(tracer, obs):
        obs.span("pull")                  # bare attribute form
"""

SPAN_GOOD_FIXTURE = """
    from paddlebox_tpu.obs import span as obs_span
    from paddlebox_tpu.obs.tracer import record_span


    def run(tracer, consume):
        with tracer.span("shard_step"):
            pass
        with obs_span("host_stage"):
            pass
        s = tracer.span("later")          # stored, entered below
        with s:
            pass
        record_span("post_hoc", 0.0, 1.0)  # post-hoc form, exempt
        consume(tracer.span("arg"))        # passed on, not discarded
"""


def test_span_bare_expression_flags(tmp_path):
    """The BX502 positive fixture: every bare-expression span() call —
    method, module-helper, attribute — flags once."""
    got = lint_snippet(tmp_path, SPAN_BAD_FIXTURE, ["spans"])
    assert codes(got) == ["BX502"] * 3


def test_span_proper_uses_clean(tmp_path):
    """Negative fixture: with-statements, stored managers, record_span
    and argument positions never flag."""
    assert lint_snippet(tmp_path, SPAN_GOOD_FIXTURE, ["spans"]) == []


def test_span_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        def run(tracer):
            tracer.span("x")  # boxlint: disable=BX502
    """, ["spans"])
    assert got == []


# ----------------------------------------------------------- jitreg (BX901)

JITREG_BAD_FIXTURE = """
    import functools
    import jax


    def build_step(fn):
        return jax.jit(fn, donate_argnums=(0,))      # direct call form


    @jax.jit
    def eval_step(x):                                # decorator form
        return x * 2


    promote = functools.partial(jax.jit, static_argnames=("layout",))
"""

JITREG_GOOD_FIXTURE = """
    from paddlebox_tpu.obs.device import instrument_jit


    def build_step(fn):
        return instrument_jit(fn, "train_step", donate_argnums=(0,))
"""


def test_jitreg_bare_jit_flags_every_form(tmp_path):
    """BX901 positive: the direct call, the decorator and the
    functools.partial argument form all contain the same jax.jit
    attribute node — three violations."""
    got = lint_snippet(tmp_path, JITREG_BAD_FIXTURE, ["jitreg"])
    assert codes(got) == ["BX901"] * 3


def test_jitreg_instrumented_clean(tmp_path):
    assert lint_snippet(tmp_path, JITREG_GOOD_FIXTURE, ["jitreg"]) == []


def test_jitreg_import_spellings_flagged(tmp_path):
    """BX901 positive: `from jax import jit` (plain and aliased) builds
    bare jits with no Attribute node at the call site — the IMPORT line
    is the violation; `import jax as j; j.jit` resolves the alias."""
    got = lint_snippet(tmp_path, """
        from jax import jit
        from jax import numpy as jnp, jit as fast_jit
        import jax as j


        step = jit(lambda x: x)
        estep = fast_jit(lambda x: x)
        pstep = j.jit(lambda x: x)
    """, ["jitreg"])
    assert codes(got) == ["BX901"] * 3
    assert [v.line for v in got] == [2, 3, 9]


def test_jitreg_import_spellings_negative(tmp_path):
    """`from jax import numpy` / `from jax.experimental import ...` /
    a local function named jit stay clean."""
    assert lint_snippet(tmp_path, """
        from jax import numpy as jnp
        from jax.experimental import shard_map


        def jit(fn):
            return fn


        step = jit(lambda x: x)
    """, ["jitreg"]) == []


def test_jitreg_exempt_paths(tmp_path):
    """tools/tests/examples components (probes build bare jits as
    oracles) and the implementing module itself are out of scope."""
    import textwrap
    for sub in ("tools", "tests", "obs"):
        (tmp_path / sub).mkdir()
    code = textwrap.dedent("""
        import jax
        j = jax.jit(lambda x: x)
    """)
    (tmp_path / "tools" / "probe.py").write_text(code)
    (tmp_path / "obs" / "device.py").write_text(code)
    files, errors = load_tree([str(tmp_path / "tools" / "probe.py"),
                               str(tmp_path / "obs" / "device.py")],
                              root=str(tmp_path))
    assert not errors
    assert run_passes(files, ["jitreg"]) == []


def test_jitreg_suppression_with_rationale(tmp_path):
    got = lint_snippet(tmp_path, """
        import jax
        j = jax.jit(lambda x: x)  # boxlint: disable=BX901 (oracle twin)
    """, ["jitreg"])
    assert got == []


# ------------------------------------------------------------ the gate

def test_boxlint_gate_no_new_violations():
    """Tier-1 gate: the real tree lints clean against the committed
    baseline. A failure here means a NEW invariant violation (or a fix
    that should shrink baseline.txt via --fix-baseline)."""
    files, errors = load_tree([os.path.join(REPO, "paddlebox_tpu"),
                               os.path.join(REPO, "tools")], root=REPO)
    assert not errors, [e.render() for e in errors]
    violations = run_passes(files)
    baseline = load_baseline(os.path.join(REPO, "tools", "boxlint",
                                          "baseline.txt"))
    new, stale = diff_against_baseline(violations, baseline)
    assert not new, "NEW boxlint violations:\n" + "\n".join(
        v.render() for v in new)
    # the ratchet: a baselined finding that no longer fires is stale —
    # delete it (shrinking baseline.txt is progress) or the suppression
    # file fossilizes into a list of findings nobody can audit
    assert not stale, "STALE baseline entries (run --fix-baseline):\n" + \
        "\n".join(f"{p}: {c} {m}" for p, c, m in stale)


# ======================================================= round-19 passes
# BX503 silent swallow, BX6xx blocking-under-lock, BX7xx lock-order
# graph, BX8xx handler reentrancy — interprocedural passes on the
# package-wide call graph (tools/boxlint/callgraph.py), plus their three
# HISTORICAL-BUG fixtures: each reproduces a finding a human reviewer
# caught by hand in PRs 7/9/13, and pins that the pass now catches it
# mechanically.

SWALLOW_BAD = """
    def f():
        try:
            risky()
        except Exception:
            pass

    def g():
        for i in range(3):
            try:
                risky()
            except:
                continue
"""

SWALLOW_GOOD = """
    def f():
        try:
            risky()
        except Exception:  # rationale: teardown guard, interpreter may be dying
            pass

    def g():
        try:
            risky()
        except Exception as e:
            log_warning("risky failed", err=e)   # loud: not silent

    def h():
        try:
            risky()
        except ValueError:   # narrow catch: not the swallow class
            pass
"""


def test_swallow_positive(tmp_path):
    got = lint_snippet(tmp_path, SWALLOW_BAD, ["swallow"])
    assert codes(got) == ["BX503", "BX503"]


def test_swallow_negatives(tmp_path):
    assert lint_snippet(tmp_path, SWALLOW_GOOD, ["swallow"]) == []


def test_swallow_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        def f():
            try:
                risky()
            except Exception:  # boxlint: disable=BX503
                pass
    """, ["swallow"])
    assert got == []


# ------------------------------------------------------------ BX601

BLOCKING_BAD = """
    import threading, time, socket

    class T:
        def __init__(self):
            self._lock = threading.Lock()
            self._peer = ("h", 1)

        def direct(self):
            with self._lock:
                time.sleep(0.5)              # BX601: direct sink

        def transitive(self):
            with self._lock:
                self.helper()                # BX601: via helper -> sendall

        def helper(self):
            self._sock.sendall(b"x")
"""

BLOCKING_GOOD = """
    import threading, time

    class T:
        def __init__(self):
            self._lock = threading.Lock()
            self._x = 0

        def fine(self):
            with self._lock:
                self._x += 1
            time.sleep(0.5)                  # outside the lock: fine

        def math_under_lock(self):
            with self._lock:
                return self._x * 2           # compute-only: fine


    class Chan:
        def __init__(self):
            self._mutex = threading.Lock()
            self._cv = threading.Condition(self._mutex)
            self._q = []

        def get(self):
            with self._mutex:
                while not self._q:
                    self._cv.wait()          # bound-lock wait: the pattern
                return self._q.pop()

        def get_via_helper(self):
            with self._mutex:
                self._wait_locked()          # bound lock travels the chain
                return self._q.pop()

        def _wait_locked(self):
            self._cv.wait()
"""


def test_blocking_positive_direct_and_transitive(tmp_path):
    got = lint_snippet(tmp_path, BLOCKING_BAD, ["blocking"])
    assert codes(got) == ["BX601", "BX601"]
    assert "time.sleep" in got[0].message
    assert "helper" in got[1].message and "sendall" in got[1].message


def test_blocking_negatives_including_condition_wait(tmp_path):
    """Compute under lock, sinks outside locks, and Condition.wait on
    its OWN bound lock (directly or through a *_locked helper) never
    flag — the wait releases exactly that lock."""
    assert lint_snippet(tmp_path, BLOCKING_GOOD, ["blocking"]) == []


def test_blocking_suppression(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading, time

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def deliberate(self):
                with self._lock:
                    time.sleep(0.1)  # boxlint: disable=BX601
    """, ["blocking"])
    assert got == []


HISTORICAL_DIAL = """
    import socket, threading

    class Client:
        def __init__(self, host, port):
            self._sock = socket.create_connection((host, port), timeout=5)

    class Mesh:
        def __init__(self):
            self._conn_lock = threading.Lock()
            self._clients = {}

        def send_obs(self, rank, ep):
            with self._conn_lock:
                cl = self._clients.get(rank)
                if cl is None:
                    cl = Client(ep[0], ep[1])     # the PR-7 r3 bug
                    self._clients[rank] = cl
            return cl
"""


def test_historical_dial_under_conn_lock_flags(tmp_path):
    """PR 7 r3 hand-review finding, regression-pinned: a FramedClient
    DIAL (socket.create_connection in the ctor) inside _conn_lock froze
    every thread's pulls for the whole connect timeout. The BX601 pass
    must reach the sink THROUGH the constructor."""
    got = lint_snippet(tmp_path, HISTORICAL_DIAL, ["blocking"])
    assert codes(got) == ["BX601"]
    assert "_conn_lock" in got[0].message
    assert "socket.connect" in got[0].message


HISTORICAL_AUC = """
    import threading
    import numpy as np

    def trapezoid_auc(table):
        return float(np.sum(table))

    class Quality:
        def __init__(self):
            self._lock = threading.Lock()
            self._table = np.zeros((2, 8))

        def add(self, x):
            with self._lock:
                self._table += x

        def report(self):
            with self._lock:
                return {"auc": trapezoid_auc(self._table)}  # the PR-13 bug
"""


def test_historical_auc_compute_under_add_lock_flags(tmp_path):
    """PR 13 hand-review finding, regression-pinned: the quality report
    ran the trapezoid-AUC math UNDER the add-path lock, so a scrape storm
    stalled every training-thread add. trapezoid_auc/table_auc are
    curated heavy-compute sinks exactly for this shape (this round's
    sweep found and fixed the same bug live in metrics/auc.py compute)."""
    got = lint_snippet(tmp_path, HISTORICAL_AUC, ["blocking"])
    assert codes(got) == ["BX601"]
    assert "AUC" in got[0].message


# ------------------------------------------------------------ BX701

LOCKORDER_CYCLE = """
    import threading

    LA = threading.Lock()
    LB = threading.Lock()

    def fa():
        with LA:
            nested_b()

    def nested_b():
        with LB:
            pass

    def fb():
        with LB:
            nested_a()

    def nested_a():
        with LA:
            pass
"""

LOCKORDER_CLEAN = """
    import threading

    LA = threading.Lock()
    LB = threading.Lock()

    def f1():
        with LA:
            g()

    def f2():
        with LA:
            g()

    def g():
        with LB:
            pass
"""


def test_lockorder_cycle_flags(tmp_path):
    got = lint_snippet(tmp_path, LOCKORDER_CYCLE, ["lockorder"])
    assert codes(got) == ["BX701"]
    assert "LA" in got[0].message and "LB" in got[0].message


def test_lockorder_consistent_order_clean(tmp_path):
    assert lint_snippet(tmp_path, LOCKORDER_CLEAN, ["lockorder"]) == []


def test_lockorder_inventory_renders_edges(tmp_path):
    from tools.boxlint import lockorder
    from tools.boxlint.core import load_tree as _lt
    p = tmp_path / "inv.py"
    p.write_text(textwrap.dedent(LOCKORDER_CLEAN))
    files, _ = _lt([str(p)], root=str(tmp_path))
    text = lockorder.render_inventory(files)
    assert "inv.LA -> inv.LB" in text
    assert "1 edges, 0 cycles" in text


def test_lockorder_self_nesting_not_flagged(tmp_path):
    """Same-identity nesting (per-shard lock loops, *_locked helpers) is
    BX401's territory and the runtime twin's; BX701 only flags >=2-lock
    cycles."""
    got = lint_snippet(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """, ["lockorder"])
    assert got == []


# ------------------------------------------------------------ BX8xx

HISTORICAL_EXCEPTHOOK = """
    import sys, threading

    class Tracer:
        def __init__(self):
            self._reg_lock = threading.Lock()
            self._rings = []

        def all_spans(self):
            with self._reg_lock:
                return list(self._rings)

    TRACER = Tracer()

    def _seal_hook(exc_type, exc, tb):
        TRACER.all_spans()

    sys.excepthook = _seal_hook

    def training_path():
        return TRACER.all_spans()
"""


def test_historical_plain_lock_in_excepthook_flags(tmp_path):
    """PR 9 r2 hand-review finding, regression-pinned: the fatal-signal
    seal read last_spans from the excepthook while the interrupted
    thread could hold the PLAIN _reg_lock — the dying process deadlocked
    instead of sealing (the fix made it an RLock). BX801 must trace
    excepthook -> module singleton -> method -> plain-lock acquire."""
    got = lint_snippet(tmp_path, HISTORICAL_EXCEPTHOOK, ["reentrancy"])
    assert codes(got) == ["BX801"]
    assert "_reg_lock" in got[0].message
    assert "excepthook" in got[0].message


def test_reentrancy_rlock_clean(tmp_path):
    got = lint_snippet(tmp_path, """
        import sys, threading

        class Tracer:
            def __init__(self):
                self._reg_lock = threading.RLock()
                self._rings = []

            def all_spans(self):
                with self._reg_lock:
                    return list(self._rings)

        TRACER = Tracer()

        def _seal_hook(exc_type, exc, tb):
            TRACER.all_spans()

        sys.excepthook = _seal_hook

        def training_path():
            return TRACER.all_spans()
    """, ["reentrancy"])
    assert got == []


def test_reentrancy_handler_only_lock_clean(tmp_path):
    """A plain lock acquired ONLY on handler paths has no training-path
    contender to deadlock with."""
    got = lint_snippet(tmp_path, """
        import sys, threading

        class Sealer:
            def __init__(self):
                self._lock = threading.Lock()

            def seal(self):
                with self._lock:
                    pass

        S = Sealer()

        def hook(t, e, tb):
            S.seal()

        sys.excepthook = hook
    """, ["reentrancy"])
    assert got == []


def test_reentrancy_del_join_without_timeout_flags(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._t = threading.Thread(target=None)

            def close(self):
                self._t.join()               # BX802: unbounded from __del__

            def __del__(self):
                self.close()
    """, ["reentrancy"])
    assert codes(got) == ["BX802"]
    assert "Thread.join" in got[0].message


def test_reentrancy_join_none_positional_flags(tmp_path):
    """join(None) is the unbounded wait spelled positionally — it must
    not slip past the zero-arg heuristic (review find, pinned)."""
    got = lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._t = threading.Thread(target=None)

            def close(self):
                self._t.join(None)           # BX802: unbounded, spelled out

            def __del__(self):
                self.close()
    """, ["reentrancy"])
    assert codes(got) == ["BX802"]


def test_reentrancy_bounded_join_clean(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._t = threading.Thread(target=None)

            def close(self):
                self._t.join(timeout=10.0)   # bounded: resolves when dying

            def __del__(self):
                self.close()
    """, ["reentrancy"])
    assert got == []


def test_reentrancy_watchdog_fire_is_a_root(tmp_path):
    got = lint_snippet(tmp_path, """
        import threading

        class StallWatchdog:
            def __init__(self):
                self._lock = threading.Lock()

            def fire(self, label, age):
                with self._lock:
                    pass

        def training(w):
            with w._lock:                    # unresolved receiver: the
                pass                         # OUTSIDE acquirer is below

        class Runner:
            def __init__(self):
                self._dog = StallWatchdog()

            def step(self):
                with self._dog._lock:
                    pass
    """, ["reentrancy"])
    # Runner.step acquires StallWatchdog._lock outside the handler set
    assert codes(got) == ["BX801"]
    assert "fire path" in got[0].message


# ----------------------------------------------- new codes: machinery

def test_new_codes_baseline_roundtrip(tmp_path):
    vs = [Violation("a.py", 3, "BX601", "blocking call under X._lock"),
          Violation("b.py", 7, "BX701", "cycle A -> B -> A"),
          Violation("c.py", 9, "BX801", "non-reentrant lock on handler"),
          Violation("d.py", 2, "BX503", "silent swallow")]
    bl = tmp_path / "baseline.txt"
    bl.write_text(format_baseline(vs))
    moved = [Violation(v.path, v.line + 40, v.code, v.message) for v in vs]
    new, stale = diff_against_baseline(moved, load_baseline(str(bl)))
    assert new == [] and stale == []


# ------------------------------------------------- cache + --changed

def test_result_cache_roundtrip_and_invalidation(tmp_path):
    from tools.boxlint import cache as cachemod
    src = [("a", "a.py", "x = 1\n"), ("b", "b.py", "y = 2\n")]
    d1 = cachemod.tree_digest(src, ["purity"])
    # digest is content- and pass-sensitive
    assert d1 != cachemod.tree_digest(src, ["locks"])
    src2 = [("a", "a.py", "x = 1\n"), ("b", "b.py", "y = 3\n")]
    assert d1 != cachemod.tree_digest(src2, ["purity"])
    path = str(tmp_path / "cache.json")
    vs = [Violation("a.py", 1, "BX503", "msg")]
    cachemod.store_cached(d1, vs, path=path)
    got = cachemod.load_cached(d1, path=path)
    assert got is not None and got[0].key() == vs[0].key() \
        and got[0].line == 1
    assert cachemod.load_cached("deadbeef", path=path) is None


def test_cli_cache_hit_matches_cold_run(tmp_path):
    """Cold and warm CLI runs agree on the verdict; the warm run reads
    the result from the cache file it wrote."""
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    try:\n        g()\n"
                   "    except Exception:\n        pass\n")
    env = dict(os.environ)
    # redirect the result cache: the test must never clobber the
    # working tree's warm .cache.json (BOXLINT_CACHE override)
    env["BOXLINT_CACHE"] = str(tmp_path / "cache.json")
    cold = subprocess.run(
        [sys.executable, "-m", "tools.boxlint", "--no-baseline",
         str(bad)], cwd=REPO, capture_output=True, text=True, env=env)
    assert (tmp_path / "cache.json").exists()
    warm = subprocess.run(
        [sys.executable, "-m", "tools.boxlint", "--no-baseline",
         str(bad)], cwd=REPO, capture_output=True, text=True, env=env)
    assert cold.returncode == 1 and warm.returncode == 1
    assert "BX503" in cold.stdout and "BX503" in warm.stdout


def test_changed_files_vs_git(tmp_path):
    import subprocess as sp
    repo = tmp_path / "r"
    repo.mkdir()

    def git(*a):
        sp.run(["git"] + list(a), cwd=repo, check=True,
               capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (repo / "clean.py").write_text("x = 1\n")
    (repo / "edited.py").write_text("y = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    (repo / "edited.py").write_text("y = 2\n")
    (repo / "fresh.py").write_text("z = 1\n")
    # a whole NEW directory: porcelain collapses it to `?? sub/`, which
    # used to hide every .py inside from the changed set (review find)
    (repo / "sub").mkdir()
    (repo / "sub" / "inner.py").write_text("w = 1\n")
    from tools.boxlint.cache import changed_files
    got = changed_files(root=str(repo))
    assert got == {"edited.py", "fresh.py", "sub/inner.py"}


# ================================================== round-16 tierbudget

TIERBUDGET_FIXTURE = """
    import pytest

    N_KEYS = 500_000_000          # module constant: helper scope, exempt

    def make_keys():              # not a test function: exempt
        return list(range(100_000_000))

    def test_pasted_scale():      # BX951: unmarked 100M in tier-1
        total = 100_000_000
        assert total > 0

    @pytest.mark.slow
    def test_marked_scale():      # marked: the slow suite owns it
        total = 100_000_000
        assert total > 0

    def test_sentinels_ok():      # 2**k / 2**k - 1: masks, not work
        kmax = 0xFFFFFFFFFFFFFFFF
        dead = 0x3FFFFFFF
        cap = 1 << 34
        assert kmax > dead > 0 and cap

    def test_small_scale():       # under the floor
        assert sum(range(1_000_000)) > 0
"""


def test_tierbudget_fixture(tmp_path):
    got = lint_snippet(tmp_path, TIERBUDGET_FIXTURE, ["tierbudget"],
                       name="test_fixture.py")
    assert codes(got) == ["BX951"]
    assert "test_pasted_scale" in got[0].message


def test_tierbudget_only_fires_on_test_files(tmp_path):
    # the same 100M literal in library code is none of this pass's
    # business (scale constants are legitimate outside the suite)
    got = lint_snippet(tmp_path, TIERBUDGET_FIXTURE, ["tierbudget"],
                       name="library.py")
    assert got == []


def test_tierbudget_gate_suite_stays_inside_budget():
    """Tier-1 gate twin for the 870 s wall clock: every scale test in
    tests/ (>= 10M-literal work sizes) must be @pytest.mark.slow so the
    default `-m 'not slow'` run never inherits it. No baseline — the
    suite starts clean and stays clean."""
    files, errors = load_tree([os.path.join(REPO, "tests")], root=REPO)
    assert not errors, [e.render() for e in errors]
    got = run_passes(files, ["tierbudget"])
    assert not got, "scale tests missing @pytest.mark.slow:\n" + "\n".join(
        v.render() for v in got)


# ===================================================== device contracts
# BX911 recompile hazards, BX921 donation contract, BX931 hidden host
# sync, BX941 replay determinism — the static twins of the PR-15 device
# plane (recompile sentinel / donation audit / transfer ledger / journal
# parity), built on the traced-value taint layer (tools/boxlint/taint.py).
# Per family: one true positive, one near-miss negative, one case that
# only resolves through the cross-module call/binding closure.

DEVICE_PASSES = ["recompile", "donation", "hostsync", "determinism"]

JIT_PRELUDE = """
    import numpy as np
    from paddlebox_tpu.obs.device import instrument_jit

    def _impl(state, batch):
        return state, batch

"""


def lint_device(tmp_path, body, name="runner.py", extra=()):
    return lint_snippet(tmp_path, JIT_PRELUDE + body, DEVICE_PASSES,
                        name=name, extra=extra)


# ------------------------------------------------------- BX911 recompile

def test_recompile_scalar_literal_at_traced_position(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", static_argnums=(1,))

    def run(x):
        return step(0.5, x)
    """)
    assert codes(got) == ["BX911"]
    assert "python scalar literal" in got[0].message


def test_recompile_literal_at_static_position_is_fine(tmp_path):
    # near-miss: the literal lands on a STATIC position — that is
    # exactly where a python scalar belongs
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", static_argnums=(1,))

    def run(x):
        return step(x, 4)
    """)
    assert got == []


def test_recompile_set_ordered_static_key(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", static_argnums=(1,))

    def run(x, slots):
        return step(x, tuple({8, 16, 32}))
    """)
    assert codes(got) == ["BX911"]
    assert "sorted" in got[0].message


def test_recompile_sorted_static_key_is_fine(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", static_argnums=(1,))

    def run(x, slots):
        return step(x, tuple(sorted(slots)))
    """)
    assert got == []


def test_recompile_mutable_module_state_in_jitted_body(tmp_path):
    got = lint_device(tmp_path, """
    SCALE = {"k": 2.0}

    def _scaled(x):
        return x * SCALE["k"]

    step2 = instrument_jit(_scaled, "fx_scaled")
    """)
    assert codes(got) == ["BX911"]
    assert "SCALE" in got[0].message


def test_recompile_entry_bound_through_factory(tmp_path):
    # closure case: the jit entry reaches the call site through a
    # factory return, not a direct binding
    got = lint_device(tmp_path, """
    def make_step():
        return instrument_jit(_impl, "fx_step")

    step = make_step()

    def run(x):
        return step(1.5, x)
    """)
    assert codes(got) == ["BX911"]


# -------------------------------------------------------- BX921 donation

def test_donation_read_after_donated_call(tmp_path):
    got = lint_device(tmp_path, """
    push = instrument_jit(_impl, "fx_push", donate_argnums=(0,))

    def run(slab, ids):
        out = push(slab, ids)
        return out, slab.sum()
    """)
    assert codes(got) == ["BX921"]
    assert "`slab`" in got[0].message


def test_donation_rebound_in_statement_is_fine(tmp_path):
    got = lint_device(tmp_path, """
    push = instrument_jit(_impl, "fx_push", donate_argnums=(0,))

    def run(slab, ids):
        slab, extra = push(slab, ids)
        return slab.sum()
    """)
    assert got == []


def test_donation_setter_convention_counts_as_rebind(tmp_path):
    # table.set_slab(out) rebinds table.slab for the read that follows
    got = lint_device(tmp_path, """
    push = instrument_jit(_impl, "fx_push", donate_argnums=(0,))

    def run(table, ids):
        out, extra = push(table.slab, ids)
        table.set_slab(out)
        return table.slab.sum()
    """)
    assert got == []


def test_donation_loop_without_rebind(tmp_path):
    got = lint_device(tmp_path, """
    push = instrument_jit(_impl, "fx_push", donate_argnums=(0,))

    def run(slab, batches):
        for b in batches:
            out = push(slab, b)
        return out
    """)
    assert codes(got) == ["BX921"]
    assert "loop" in got[0].message


def test_donation_step_shape_without_donation(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step")

    class Tr:
        def run(self, batch):
            self.params, self.opt_state = step(self.params,
                                               self.opt_state)
            return self.params
    """)
    assert codes(got) == ["BX921"]
    assert "declares no donation" in got[0].message


def test_donation_partial_donation_is_a_reviewed_choice(tmp_path):
    # near-miss: an entry that donates SOME positions already made the
    # call — the step-shape heuristic stays quiet
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(1,))

    class Tr:
        def run(self, batch):
            self.params, self.opt_state = step(batch, self.opt_state)
            return self.params
    """)
    assert got == []


def test_donation_entry_resolved_cross_module(tmp_path):
    # closure case: the entry is constructed in another module and
    # imported by name
    mk = tmp_path / "mk.py"
    mk.write_text(textwrap.dedent(JIT_PRELUDE + """
    push = instrument_jit(_impl, "fx_push", donate_argnums=(0,))
    """))
    got = lint_snippet(tmp_path, """
        from mk import push

        def run(slab, ids):
            out = push(slab, ids)
            return out, slab.sum()
    """, DEVICE_PASSES, name="caller.py", extra=[mk])
    assert codes(got) == ["BX921"]


# -------------------------------------------------------- BX931 hostsync

def test_hostsync_float_in_loop(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))

    def train(state, batches):
        losses = []
        for b in batches:
            state, loss = step(state, b)
            losses.append(float(loss))
        return losses
    """)
    assert codes(got) == ["BX931"]
    assert "loop" in got[0].message


def test_hostsync_boundary_conversion_is_fine(tmp_path):
    # near-miss: same float(), but AFTER the loop — the pass-boundary
    # sync is the blessed place
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))

    def train(state, batches):
        loss = None
        for b in batches:
            state, loss = step(state, b)
        return float(loss)
    """)
    assert got == []


def test_hostsync_under_lock(tmp_path):
    got = lint_device(tmp_path, """
    import threading
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))
    _lock = threading.Lock()

    def serve(state, b):
        with _lock:
            state, loss = step(state, b)
            return float(loss)
    """)
    assert codes(got) == ["BX931"]
    assert "lock" in got[0].message


def test_hostsync_through_helper_closure(tmp_path):
    # closure case: the sync lives in a helper in ANOTHER module; the
    # finding lands at the loop-resident call site with a witness chain
    helper = tmp_path / "hostutil.py"
    helper.write_text(textwrap.dedent("""
        import numpy as np

        def to_host(x):
            return np.asarray(x)
    """))
    got = lint_snippet(tmp_path, JIT_PRELUDE + """
    from hostutil import to_host

    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))

    def train(state, batches):
        out = []
        for b in batches:
            state, preds = step(state, b)
            out.append(to_host(preds))
        return out
    """, DEVICE_PASSES, name="runner.py", extra=[helper])
    assert codes(got) == ["BX931"]
    assert "via to_host" in got[0].message


def test_hostsync_reasoned_waiver_suppresses(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))

    def train(state, batches):
        losses = []
        for b in batches:
            state, loss = step(state, b)
            losses.append(float(loss))  # boxlint: BX931 ok (per-step nan guard)
        return losses
    """)
    assert got == []


def test_hostsync_bare_waiver_is_bx932_and_does_not_suppress(tmp_path):
    got = lint_device(tmp_path, """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,))

    def train(state, batches):
        losses = []
        for b in batches:
            state, loss = step(state, b)
            losses.append(float(loss))  # boxlint: BX931 ok
        return losses
    """)
    assert sorted(codes(got)) == ["BX931", "BX932"]


# ----------------------------------------------------- BX941 determinism

def test_determinism_accumulation_over_set(tmp_path):
    got = lint_snippet(tmp_path, """
        def total(keys):
            t = 0.0
            for k in set(keys):
                t += k
            return t
    """, ["determinism"])
    assert codes(got) == ["BX941"]
    assert "sorted" in got[0].message


def test_determinism_sorted_iteration_is_fine(tmp_path):
    got = lint_snippet(tmp_path, """
        def total(keys):
            t = 0.0
            for k in sorted(set(keys)):
                t += k
            return t
    """, ["determinism"])
    assert got == []


def test_determinism_setish_through_helper(tmp_path):
    # closure case: the set is built by a helper in another module
    src = tmp_path / "picksrc.py"
    src.write_text(textwrap.dedent("""
        def pick(xs):
            return {x for x in xs if x > 0}
    """))
    got = lint_snippet(tmp_path, """
        from picksrc import pick

        def total(xs):
            t = 0.0
            for k in pick(xs):
                t += k
            return t
    """, ["determinism"], name="acc.py", extra=[src])
    assert codes(got) == ["BX941"]


def test_determinism_global_rng_draw(tmp_path):
    got = lint_snippet(tmp_path, """
        import numpy as np

        def jitter():
            return np.random.uniform(0, 1)
    """, ["determinism"])
    assert codes(got) == ["BX941"]
    assert "seeded" in got[0].message


def test_determinism_seeded_generator_is_fine(tmp_path):
    got = lint_snippet(tmp_path, """
        import numpy as np

        def jitter(rng):
            return rng.uniform(0, 1)
    """, ["determinism"])
    assert got == []


def test_determinism_time_into_journal(tmp_path):
    got = lint_snippet(tmp_path, """
        import time

        def record(journal, rows):
            stamp = time.time()
            journal.append_rows(rows, stamp)
    """, ["determinism"])
    assert codes(got) == ["BX941"]
    assert "clock" in got[0].message


# ------------------------------------------------- CLI / cache / changed

def run_cli_at(args, cwd):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run([sys.executable, "-m", "tools.boxlint"] + args,
                          cwd=cwd, capture_output=True, text=True, env=env)


def test_check_baseline_fails_on_fossil(tmp_path):
    """A baseline entry whose finding no longer fires is a fossil:
    --check-baseline turns it into exit 1 (the tests gate runs the same
    check via diff_against_baseline)."""
    (tmp_path / "clean.py").write_text("x = 1\n")
    bl = tmp_path / "baseline.txt"
    bl.write_text(format_baseline([
        Violation("clean.py", 3, "BX501", "ghost print from a past age")]))
    ok = run_cli_at(["--baseline", str(bl), "clean.py"], cwd=str(tmp_path))
    assert ok.returncode == 0, ok.stdout + ok.stderr
    r = run_cli_at(["--baseline", str(bl), "--check-baseline", "clean.py"],
                   cwd=str(tmp_path))
    assert r.returncode == 1
    assert "stale" in r.stderr


def test_list_rules_prints_inventory():
    r = run_cli(["--list-rules"])
    assert r.returncode == 0
    for code in ("BX101", "BX601", "BX911", "BX921", "BX931", "BX941"):
        assert code in r.stdout


def test_device_contracts_artifact(tmp_path):
    from tools.boxlint.taint import render_inventory
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(JIT_PRELUDE + """
    step = instrument_jit(_impl, "fx_step", donate_argnums=(0,),
                          static_argnames=("layout",))

    def train(state, b):
        return float(step(state, b)[0])  # boxlint: BX931 ok (boundary)
    """))
    files, errors = load_tree([str(p)], root=str(tmp_path))
    assert not errors
    txt = render_inventory(files)
    assert "fx_step" in txt and "donate=(0,)" in txt
    assert "boundary" in txt                       # the reasoned waiver
    assert "# 1 jit entries (1 donating, 1 static-keyed)" in txt


def test_cache_digest_tracks_pass_versions(tmp_path, monkeypatch):
    from tools.boxlint import cache as cachemod
    from tools.boxlint import core
    src = [(str(tmp_path / "a.py"), "a.py", "x = 1\n")]
    d1 = cachemod.tree_digest(src, ["purity"])
    monkeypatch.setitem(core.PASS_VERSIONS, "purity",
                        core.PASS_VERSIONS["purity"] + 1)
    d2 = cachemod.tree_digest(src, ["purity"])
    assert d1 != d2


def test_changed_reverse_import_closure(tmp_path):
    from tools.boxlint.callgraph import reverse_dependents
    (tmp_path / "base.py").write_text("X = 1\n")
    (tmp_path / "mid.py").write_text("import base\nY = base.X\n")
    (tmp_path / "top.py").write_text("from mid import Y\nZ = Y\n")
    (tmp_path / "lone.py").write_text("W = 3\n")
    files, errors = load_tree([str(tmp_path)], root=str(tmp_path))
    assert not errors
    got = reverse_dependents(files, {"base.py"})
    assert {"base.py", "mid.py", "top.py"} <= got
    assert "lone.py" not in got
