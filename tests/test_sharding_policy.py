"""Round-13 tentpole: pluggable sharding policies (key-mod x table-wise
x 2d-grid) — routing parity vs the numpy oracle per policy, key-mod
bit-parity vs the pre-policy path, policy-owned dest plans, the
replicated hot-key tier, and the grid device layout."""

import concurrent.futures
import types

import numpy as np
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.parallel import sharded_table as stmod
from paddlebox_tpu.parallel.sharded_table import (ShardedPassTable,
                                                  stage_push_dedup)
from paddlebox_tpu.parallel.sharding import (KeyModPolicy, ReplicatedHotTier,
                                             TableWisePolicy, TwoDGridPolicy,
                                             default_dest_plan,
                                             resolve_sharding_policy)

P = 8


def table_cfg(cap_per_shard=1 << 11):
    return TableConfig(
        embedx_dim=4, pass_capacity=P * cap_per_shard,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))


def grid_keys(rng, n=2048, tables=8, shift=48):
    """Feasigns with the table id in the high bits (the reference's
    packing; sharding_table_shift default)."""
    t = rng.randint(0, tables, n).astype(np.uint64)
    low = rng.randint(0, 1 << 30, n).astype(np.uint64)
    return np.unique((t << np.uint64(shift)) | low)


def policies():
    return [KeyModPolicy(P),
            TableWisePolicy(P, num_tables=8, table_shift=48),
            TwoDGridPolicy(P, num_tables=8, rows=2, table_shift=48)]


# ----------------------------------------------------------------- route

def test_keymod_shard_of_is_key_mod():
    """The parity oracle: KeyModPolicy.shard_of IS key % P, bit-for-bit
    (the pre-policy routing on every host-side twin)."""
    rng = np.random.RandomState(0)
    keys = rng.randint(0, 1 << 62, 4096).astype(np.uint64)
    keys[-1] = np.uint64(0xFFFFFFFFFFFFFFFF)
    pol = KeyModPolicy(P)
    np.testing.assert_array_equal(pol.shard_of(keys),
                                  (keys % np.uint64(P)).astype(np.int64))


@pytest.mark.parametrize("pol", policies(), ids=lambda p: p.name)
def test_bucketize_parity_native_vs_numpy(pol):
    """Per-policy routing parity: the native tier (rt_bucketize for
    key-mod, the policy-parameterized rt_bucketize_sharded otherwise)
    and the vectorized numpy fallback must produce equivalent routing —
    same local id per occurrence, same shard per occurrence (== the
    policy's shard_of), same bucket membership."""
    if stmod._route_lib() is None:
        pytest.skip("native router unavailable")
    rng = np.random.RandomState(3)
    keys = grid_keys(rng)
    t = ShardedPassTable(table_cfg(), P, bucket_cap=512, policy=pol)
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()
    probe = rng.choice(keys, 1024).astype(np.uint64)
    v_n = np.ones(probe.size, bool)
    idx_n = t.bucketize(probe, v_n)
    orig = stmod._route_lib
    stmod._route_lib = lambda: None
    try:
        v_p = np.ones(probe.size, bool)
        idx_p = t.bucketize(probe, v_p)
    finally:
        stmod._route_lib = orig
    assert idx_n.overflow == idx_p.overflow == 0
    np.testing.assert_array_equal(
        idx_n.buckets.reshape(-1)[idx_n.restore],
        idx_p.buckets.reshape(-1)[idx_p.restore])
    np.testing.assert_array_equal(idx_n.restore // t.bucket_cap,
                                  idx_p.restore // t.bucket_cap)
    # the shard every occurrence routed to IS the policy's shard_of
    np.testing.assert_array_equal(idx_n.restore // t.bucket_cap,
                                  pol.shard_of(probe))
    # local ids resolve back to the routed keys
    for i in (0, 17, probe.size - 1):
        s = int(pol.shard_of(probe[i:i + 1])[0])
        local = int(idx_n.buckets.reshape(-1)[idx_n.restore[i]])
        assert t._shard_keys[s][local] == probe[i]


def test_policy_shard_assignment_owns_feed_pass():
    """end_feed_pass assigns each key to policy.shard_of(key)'s list —
    and the shard lists stay sorted (the searchsorted contract)."""
    rng = np.random.RandomState(5)
    keys = grid_keys(rng)
    for pol in policies():
        t = ShardedPassTable(table_cfg(), P, bucket_cap=256, policy=pol)
        t.begin_feed_pass()
        t.add_keys(keys)
        t.end_feed_pass()
        seen = 0
        shard = pol.shard_of(keys)
        for s in range(P):
            ks = t._shard_keys[s]
            seen += ks.size
            assert (np.diff(ks.astype(np.int64)) > 0).all() or ks.size <= 1
            np.testing.assert_array_equal(np.sort(keys[shard == s]), ks)
        assert seen == keys.size


def test_policy_world_mismatch_raises():
    with pytest.raises(ValueError, match="policy built for"):
        ShardedPassTable(table_cfg(), P, bucket_cap=64,
                         policy=KeyModPolicy(4))


def test_resolve_sharding_policy_flag():
    from paddlebox_tpu.config import flags
    assert resolve_sharding_policy(P).name == "key-mod"
    flags.set_flag("sharding_policy", "table-wise")
    assert resolve_sharding_policy(P).name == "table-wise"
    flags.set_flag("sharding_policy", "2d-grid")
    pol = resolve_sharding_policy(P)
    assert pol.name == "2d-grid" and pol.rows == 2  # auto: sqrt-ish
    flags.set_flag("sharding_policy", "keymod-typo")
    with pytest.raises(ValueError, match="sharding_policy"):
        resolve_sharding_policy(P)
    with pytest.raises(ValueError, match="divide"):
        TwoDGridPolicy(P, 8, rows=3)


# ------------------------------------------------------------- dest plan

def fake_mesh(world=2, rank=0, positions=None):
    positions = positions or {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
    m = types.SimpleNamespace(rank=rank, world=world,
                              positions_of=dict(positions))
    m.rank_of_position = lambda: {p: r for r, ps in m.positions_of.items()
                                  for p in ps}
    return m


@pytest.mark.parametrize("pol", policies(), ids=lambda p: p.name)
def test_dest_plan_validation(pol):
    """Every position exactly one owner per policy; incomplete or
    mismatched ownership fails loud (the silent-shard-drop guard)."""
    m = fake_mesh()
    plan = pol.dest_plan(m, [0, 1, 2, 3], P)
    assert len(plan) == 2
    covered = sorted(d for dests in plan for d in dests)
    assert covered == list(range(P))   # exactly one owner each
    # missing owner
    m2 = fake_mesh(positions={0: [0, 1, 2], 1: [4, 5, 6, 7]})
    with pytest.raises(RuntimeError, match="no owning rank"):
        pol.dest_plan(m2, [0, 1, 2], P)
    # staging for positions this rank did not rendezvous
    with pytest.raises(RuntimeError, match="staging for"):
        pol.dest_plan(fake_mesh(), [0, 1], P)
    # the default plan and the policy plan agree (owner-map plan)
    assert plan == default_dest_plan(m, [0, 1, 2, 3], P)


# ------------------------------------------------- staging parity (wires)

def make_buckets(rng, shard_cap, KB=16):
    buckets = np.full((P, P, KB), shard_cap - 1, np.int32)
    for s in range(P):
        for d in range(P):
            n = rng.randint(2, KB)
            buckets[s, d, :n] = rng.randint(0, shard_cap - 1, n)
    return buckets


def test_keymod_policy_staging_bit_parity_both_wires():
    """stage_push_dedup with the key-mod policy must produce BIT-identical
    products to the policy-less (pre-round-13) call on both wire modes —
    the tentpole's compatibility bar."""
    rng = np.random.RandomState(7)
    shard_cap = 128
    buckets = make_buckets(rng, shard_cap)
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        for uid_only in (True, False):
            legacy = stage_push_dedup(
                list(buckets), list(range(P)), P, shard_cap,
                multiprocess=False, all_gather=None, rebuild=not uid_only,
                pool=pool, uid_only=uid_only)
            poly = stage_push_dedup(
                list(buckets), list(range(P)), P, shard_cap,
                multiprocess=False, all_gather=None, rebuild=not uid_only,
                pool=pool, uid_only=uid_only, policy=KeyModPolicy(P))
            assert set(legacy) == set(poly)
            for k in legacy:
                for a, b in zip(legacy[k], poly[k]):
                    np.testing.assert_array_equal(a, b, err_msg=k)


@pytest.mark.parametrize("pol", policies(), ids=lambda p: p.name)
def test_two_virtual_process_staging_parity(pol):
    """Per policy: 2-virtual-process p2p staging (uid wire, the policy's
    dest plan + hot filter) reproduces the single-process staging
    bit-for-bit. The hot tier is inactive here (nothing frozen) — the
    active-hot composition has its own test below."""
    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    from paddlebox_tpu.parallel.sharded_table import exchange_push_uids_p2p
    rng = np.random.RandomState(11)
    shard_cap = 256
    buckets = make_buckets(rng, shard_cap, KB=32)
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        single = stage_push_dedup(
            list(buckets), list(range(P)), P, shard_cap,
            multiprocess=False, all_gather=None, rebuild=False,
            pool=pool, uid_only=True, policy=pol)
        meshes = [MeshComm(r, 2) for r in range(2)]
        eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
        pos = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
        try:
            for m in meshes:
                m.connect(eps)
                m.positions_of = dict(pos)
            f = pool.submit(exchange_push_uids_p2p, buckets[4:8],
                            [4, 5, 6, 7], P, shard_cap, meshes[1],
                            None, pol)
            out0 = exchange_push_uids_p2p(buckets[0:4], [0, 1, 2, 3], P,
                                          shard_cap, meshes[0],
                                          policy=pol)
            out1 = f.result()
        finally:
            for m in meshes:
                m.close()
    for d, uids in {**out0, **out1}.items():
        np.testing.assert_array_equal(uids, single["push_uids"][d],
                                      err_msg=f"{pol.name} dest {d}")


def test_hot_tier_wire_filter_parity_and_bytes():
    """The 2d-grid replicated hot tier on the p2p uid wire: hot local
    ids never travel (measured: fewer exchange bytes than the unfiltered
    run) and the owner's re-added set makes the staged product
    BIT-identical to the unfiltered staging whenever the hot ids occur
    in the step — the replication premise."""
    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    from paddlebox_tpu.parallel.sharded_table import exchange_push_uids_p2p
    rng = np.random.RandomState(13)
    shard_cap = 256
    buckets = make_buckets(rng, shard_cap, KB=64)
    # hot ids: a handful of local ids present in EVERY source's column
    # for every destination (hot = occurs every step, everywhere)
    hot = {d: np.array([1, 2, 5], np.int32) for d in range(P)}
    for s in range(P):
        for d in range(P):
            buckets[s, d, :3] = hot[d]
    pol = TwoDGridPolicy(P, num_tables=8, rows=2, hot_threshold=2)
    pol._hot_local = dict(hot)  # frozen state, set directly for the unit

    def run(policy):
        meshes = [MeshComm(r, 2) for r in range(2)]
        eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
        pos = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
        try:
            for m in meshes:
                m.connect(eps)
                m.positions_of = dict(pos)
            with concurrent.futures.ThreadPoolExecutor(1) as pool:
                f = pool.submit(exchange_push_uids_p2p, buckets[4:8],
                                [4, 5, 6, 7], P, shard_cap, meshes[1],
                                None, policy)
                out0 = exchange_push_uids_p2p(
                    buckets[0:4], [0, 1, 2, 3], P, shard_cap, meshes[0],
                    policy=policy)
                out1 = f.result()
            return {**out0, **out1}, meshes[0].bytes_sent
        finally:
            for m in meshes:
                m.close()

    plain, plain_bytes = run(None)
    hot_out, hot_bytes = run(pol)
    for d in range(P):
        np.testing.assert_array_equal(hot_out[d], plain[d],
                                      err_msg=f"dest {d}")
    assert hot_bytes < plain_bytes  # replicated ids never traveled


def test_hot_overapprox_is_push_noop():
    """A frozen hot id that does NOT occur in a step still rides the
    staged uid vector (the owner re-adds its whole set). Its merged
    gradients are zero, so the uid-wire push leaves the slab
    BIT-identical — the over-approximation is value-free."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
    from paddlebox_tpu.embedding.optimizers import push_sparse_uidwire
    from paddlebox_tpu.embedding.pass_table import dedup_uids_sorted
    rng = np.random.RandomState(17)
    cap, K = 128, 64
    layout = ValueLayout(4, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    push = PushLayout(4)
    ids = rng.randint(0, 40, K).astype(np.int32)
    assert 99 not in ids
    grads = rng.randn(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    slab = rng.rand(cap, layout.width).astype(np.float32)
    prng = jax.random.PRNGKey(2)
    uids = dedup_uids_sorted(ids, cap)
    # splice the absent hot id 99 in (sorted position), dropping one
    # padding slot — exactly what the owner-side union produces
    n = int((uids < cap).sum())
    uids_hot = np.concatenate([uids[:n], [np.int32(99)],
                               uids[n:-1]]).astype(np.int32)
    a = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(uids),
                            jnp.asarray(ids), jnp.asarray(grads), prng,
                            layout, conf)
    b = push_sparse_uidwire(jnp.asarray(slab), jnp.asarray(uids_hot),
                            jnp.asarray(ids), jnp.asarray(grads), prng,
                            layout, conf)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------ replicated reads

def test_replicated_hot_tier_read_parity():
    """sketch -> freeze -> mirror -> lookup: the replicated tier serves
    hot keys' rows bit-identical to a direct owner-store read, and
    reports found=False for everything it doesn't hold."""
    from paddlebox_tpu.embedding.native_store import make_host_store
    rng = np.random.RandomState(19)
    keys = grid_keys(rng, n=512)
    pol = TwoDGridPolicy(P, num_tables=8, rows=2, hot_threshold=3)
    t = ShardedPassTable(table_cfg(), P, bucket_cap=64, policy=pol)
    # the sketch sees a skewed stream: 16 keys dominate
    hotset = rng.choice(keys, 16, replace=False).astype(np.uint64)
    for _ in range(4):
        pol.observe(hotset)
    pol.observe(rng.choice(keys, 64).astype(np.uint64))  # cold tail x1
    t.begin_feed_pass()
    t.add_keys(keys)
    t.end_feed_pass()                 # freezes the hot tier
    frozen = pol.hot_keys_frozen()
    assert set(hotset.tolist()) <= set(frozen.tolist())
    # materialize rows in the owner stores, then mirror
    for s in range(P):
        ks = t._shard_keys[s]
        if ks.size:
            t.stores[s].lookup_or_create(ks)
    tier = ReplicatedHotTier(pol)
    assert tier.refresh(t.stores) == frozen.size
    rows, found = tier.lookup(hotset)
    assert found.all()
    for i, k in enumerate(hotset):
        s = int(pol.shard_of(np.array([k], np.uint64))[0])
        direct = t.stores[s].lookup(np.array([k], np.uint64))[0]
        np.testing.assert_array_equal(rows[i], direct)
    # a cold key misses
    cold = keys[~np.isin(keys, frozen)][:4]
    _, found = tier.lookup(cold)
    assert not found.any()
    # per-shard hot sets are sorted int32 local ids (the wire contract)
    for d in range(P):
        h = pol.hot_local_ids(d)
        if h is not None:
            assert h.dtype == np.int32
            assert (np.diff(h.astype(np.int64)) > 0).all() or h.size <= 1


def test_hot_tier_production_feed_and_merge():
    """The production wiring: add_keys feeds the sketch (reader-thread
    stream), end_feed_pass merges the rank-local sketches over the SAME
    allgather that unions the pass keys, and the frozen hot sets come
    out identical on every rank — including keys that are hot only when
    SUMMED across ranks."""
    rng = np.random.RandomState(29)
    keys = grid_keys(rng, n=256)
    hot_key = keys[7:8]
    streams = {  # rank-local: each rank alone sees hot_key only twice
        0: [np.concatenate([hot_key, hot_key, keys[:64]]), keys[64:128]],
        1: [np.concatenate([hot_key, hot_key, keys[128:]]), keys[:32]],
    }
    tables, payloads, key_parts = {}, {}, {}
    for r in (0, 1):
        pol = TwoDGridPolicy(P, num_tables=8, rows=2, hot_threshold=4)
        assert pol.wants_observe
        t = ShardedPassTable(table_cfg(), P, bucket_cap=64, policy=pol)
        t.begin_feed_pass()
        for chunk in streams[r]:
            t.add_keys(chunk)          # observe rides add_keys now
        tables[r] = t
        ks, cs = pol.sketch.items()
        payloads[r] = np.concatenate(
            [np.array([ks.size], np.uint64), ks, cs.view(np.uint64)])
        key_parts[r] = np.unique(np.concatenate(streams[r]))

    for r in (0, 1):
        calls = iter([list(key_parts.values()),      # key union
                      list(payloads.values())])      # sketch merge
        tables[r].end_feed_pass(allgather=lambda _p, c=calls: next(c))
    frozen0 = tables[0].policy.hot_keys_frozen()
    frozen1 = tables[1].policy.hot_keys_frozen()
    np.testing.assert_array_equal(frozen0, frozen1)
    # 2+2 observations cross the threshold only after the merge
    assert hot_key[0] in frozen0.tolist()
    for d in range(P):
        a, b = (tables[0].policy.hot_local_ids(d),
                tables[1].policy.hot_local_ids(d))
        if a is None or b is None:
            assert a is None and b is None
        else:
            np.testing.assert_array_equal(a, b)
    # NO W-fold inflation across passes: the merge must not fold the
    # global sum back into the local sketches — a second pass with no
    # new observations re-merges the SAME local histories and freezes
    # the SAME set (an overwrite-style merge would double every count
    # per pass and eventually replicate cold keys)
    for r in (0, 1):
        ks, cs = tables[r].policy.sketch.items()
        order = np.argsort(ks)
        p0 = np.asarray(payloads[r], np.uint64)
        n = int(p0[0])
        ks0, cs0 = p0[1:1 + n], p0[1 + n:1 + 2 * n].view(np.int64)
        o0 = np.argsort(ks0)
        np.testing.assert_array_equal(ks[order], ks0[o0])
        np.testing.assert_array_equal(cs[order], cs0[o0])
        calls = iter([list(key_parts.values()),
                      list(payloads.values())])
        tables[r].begin_feed_pass()
        for chunk in streams[r]:
            tables[r]._feed_keys.append(chunk)  # keys only, no observe
        tables[r].end_feed_pass(allgather=lambda _p, c=calls: next(c))
        np.testing.assert_array_equal(
            tables[r].policy.hot_keys_frozen(), frozen0)


def test_hot_cap_is_enforced():
    pol = TwoDGridPolicy(P, num_tables=8, rows=2, hot_threshold=1,
                         hot_cap=2)
    keys = np.arange(64, dtype=np.uint64) * np.uint64(8)  # all shard 0
    pol.observe(keys)
    with pytest.raises(ValueError, match="hot_cap"):
        pol.freeze_hot([np.sort(keys)] + [np.empty(0, np.uint64)] * (P - 1))


# ---------------------------------------------------------- device layout

def test_grid_slab_sharding_matches_flat_placement():
    """The GSPMD grid layout: a [P, C, W] slab stack sharded over
    (table, row) on the grid mesh places shard t*R + r on the SAME
    device as P(axis) on the flat mesh — the linearization
    TwoDGridPolicy.shard_of bakes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from paddlebox_tpu.parallel.mesh import device_mesh_1d, device_mesh_grid
    pol = TwoDGridPolicy(P, num_tables=8, rows=4)
    grid = device_mesh_grid(2, 4)
    flat = device_mesh_1d(P)
    spec = pol.slab_spec(grid, "dp")
    assert spec == PartitionSpec(("table", "row"))
    sh_grid = pol.slab_sharding(grid, "dp")
    sh_flat = NamedSharding(flat, PartitionSpec("dp"))
    arr = np.arange(P * 4 * 2, dtype=np.float32).reshape(P, 4, 2)
    a = jax.device_put(arr, sh_grid)
    b = jax.device_put(arr, sh_flat)
    dev_of = lambda x: {  # noqa: E731 — shard row -> device id
        int(s.index[0].start or 0): s.device.id for s in x.addressable_shards}
    assert dev_of(a) == dev_of(b)
    # on a mesh WITHOUT grid axes the policy keeps the flat layout
    assert pol.slab_spec(flat, "dp") == PartitionSpec("dp")


# ------------------------------------------------------------- rendezvous

def test_rendezvous_policy_mismatch_fails_loud():
    """Ranks publishing different policy identities must die at
    bring-up (MeshPolicyMismatch), not corrupt the first exchange."""
    from paddlebox_tpu.fleet.mesh_comm import MeshComm, MeshPolicyMismatch
    from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient
    server = KVStoreServer(host="127.0.0.1")
    try:
        c0 = TcpStoreClient("127.0.0.1", server.port)
        c1 = TcpStoreClient("127.0.0.1", server.port)
        m0, m1 = MeshComm(0, 2), MeshComm(1, 2)
        try:
            with concurrent.futures.ThreadPoolExecutor(1) as pool:
                f = pool.submit(m1.rendezvous, c1, "ns", "127.0.0.1",
                                [4, 5, 6, 7], 20.0,
                                KeyModPolicy(P).describe())
                with pytest.raises(MeshPolicyMismatch, match="mismatch"):
                    m0.rendezvous(c0, "ns", "127.0.0.1", [0, 1, 2, 3],
                                  20.0,
                                  policy_id=TableWisePolicy(
                                      P, 8).describe())
                with pytest.raises(MeshPolicyMismatch):
                    f.result()
        finally:
            m0.close()
            m1.close()
            c0.close()
            c1.close()
    finally:
        server.stop()


def test_validate_policy_agreement_store_plane():
    """The store host plane never rendezvouses, so the runners validate
    the policy identity with one fleet allgather — mismatched ranks
    raise MeshPolicyMismatch, agreeing ranks pass."""
    from paddlebox_tpu.fleet.mesh_comm import MeshPolicyMismatch
    from paddlebox_tpu.parallel.sharding import validate_policy_agreement
    me = KeyModPolicy(P)
    enc = lambda s: np.frombuffer(s.encode(), np.uint8).copy()  # noqa: E731
    ok_fleet = types.SimpleNamespace(
        all_gather=lambda p: [enc(me.describe()), enc(me.describe())])
    validate_policy_agreement(ok_fleet, me)
    bad_fleet = types.SimpleNamespace(
        all_gather=lambda p: [enc(me.describe()),
                              enc(TableWisePolicy(P, 8).describe())])
    with pytest.raises(MeshPolicyMismatch, match="identically"):
        validate_policy_agreement(bad_fleet, me)


# --------------------------------------------------------- slow e2e legs

@pytest.mark.slow
def test_sharded_trainer_trains_under_each_policy():
    """One real pass of the 8-shard trainer per policy (table shift 0 so
    synthetic low-bit keys spread): finite loss, rows land in the
    policy's owner stores."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel.mesh import device_mesh_1d
    import tempfile
    out = tempfile.mkdtemp(prefix="pbx_pole2e_")
    files, feed = write_synthetic_ctr_files(
        out, num_files=2, lines_per_file=200, num_slots=4,
        vocab_per_slot=120, max_len=3, seed=23)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    flags.set_flag("sharding_table_shift", 0)
    flags.set_flag("sharding_num_tables", 53)
    for name in ("key-mod", "table-wise", "2d-grid"):
        flags.set_flag("sharding_policy", name)
        tr = ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + 4), hidden=(16,)),
            table_cfg(1 << 9), feed, mesh=device_mesh_1d(8))
        assert tr.policy.name == name
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = tr.train_pass(ds)
        assert np.isfinite(stats["loss"])
        for s, st in enumerate(tr.table.stores):
            ks, _ = st.state_items()
            if ks.size:
                assert (tr.policy.shard_of(ks) == s).all()
        tr.close()


@pytest.mark.slow
def test_hostplane_probe_policy_parity_two_ranks():
    """The probe's policy leg at a REAL 2-process cluster, parity-only:
    per policy, the p2p uid staging must match the store-path product."""
    from tools.hostplane_probe import run_world
    r = run_world(2, kb=2048, steps=1, runs=1, parity_only=True,
                  policies=True)
    assert r["tiers"] == {"parity": "ok"}
