"""Data pipeline: parser, packer, dataset load/split (mirrors
test_dataset.py / test_paddlebox_datafeed.py roles)."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import (BatchPacker, BoxDataset, MultiSlotParser,
                                write_synthetic_ctr_files)
from paddlebox_tpu.data.slot_record import SlotRecord


@pytest.fixture
def feed():
    return DataFeedConfig(slots=(
        SlotConfig("click", type="float", dim=1, is_used=False),
        SlotConfig("s0", type="uint64", max_len=3),
        SlotConfig("s1", type="uint64", max_len=2),
        SlotConfig("dense", type="float", dim=2),
    ), batch_size=4)


def test_parser_roundtrip(feed):
    p = MultiSlotParser(feed)
    rec = p.parse_line("1 1 2 11 22 1 33 2 0.5 -1.5")
    assert rec.label == 1
    np.testing.assert_array_equal(rec.uint64_slots[0], [11, 22])
    np.testing.assert_array_equal(rec.uint64_slots[1], [33])
    np.testing.assert_allclose(rec.float_slots[0], [0.5, -1.5])


def test_parser_malformed_dropped(feed):
    p = MultiSlotParser(feed)
    assert p.parse_line("") is None
    assert p.parse_line("1 1 5 11") is None          # truncated slot
    assert p.parse_line("1 1 2 11 xx 1 3 2 0 0") is None  # non-numeric


def test_packer_layout(feed):
    packer = BatchPacker(feed)
    recs = [
        SlotRecord(label=1,
                   uint64_slots={0: np.array([7, 8], np.uint64),
                                 1: np.array([9], np.uint64)},
                   float_slots={0: np.array([1.0, 2.0], np.float32)}),
        SlotRecord(label=0, uint64_slots={0: np.array([7], np.uint64)}),
    ]
    b = packer.pack(recs)
    assert b.n_ins == 2
    assert b.keys.shape[0] == feed.key_capacity()
    got = b.keys[b.valid]
    np.testing.assert_array_equal(got, [7, 8, 9, 7])
    np.testing.assert_array_equal(b.segments[b.valid], [0, 0, 1, 2])
    np.testing.assert_array_equal(b.labels[:2], [1, 0])
    np.testing.assert_array_equal(b.ins_valid[:2], [True, True])
    assert not b.ins_valid[2:].any()
    np.testing.assert_allclose(b.dense[0], [1.0, 2.0])


def test_packer_max_len_truncation(feed):
    packer = BatchPacker(feed)
    rec = SlotRecord(label=0, uint64_slots={
        0: np.arange(10, dtype=np.uint64) + 1})  # max_len=3
    b = packer.pack([rec])
    assert b.valid.sum() == 3


def test_dataset_load_and_split(tmp_path, feed):
    files, gen_feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=4, lines_per_file=100, num_slots=3,
        vocab_per_slot=50, seed=1)
    gen_feed = type(gen_feed)(slots=gen_feed.slots, batch_size=32)
    ds = BoxDataset(gen_feed, read_threads=3)
    ds.set_filelist(files)
    keys_seen = []
    ds.load_into_memory(add_keys_fn=lambda k: keys_seen.append(k))
    assert len(ds) == 400
    all_keys = np.concatenate(keys_seen)
    # every record's keys were registered with the feed-pass agent
    assert all_keys.size == ds.all_keys().size

    # equalized split: every worker gets the same batch count
    per_worker = ds.split_batches(num_workers=3)
    counts = [len(b) for b in per_worker]
    assert len(set(counts)) == 1
    # instances covered ≥ dataset size (wrap-around duplicates allowed)
    total = sum(b.n_ins for w in per_worker for b in w)
    assert total >= 400


def test_dataset_shard_files(feed):
    ds = BoxDataset(feed)
    ds.set_filelist([f"f{i}" for i in range(10)])
    assert ds.my_shard_files(0, 3) == ["f0", "f3", "f6", "f9"]
    assert ds.my_shard_files(2, 3) == ["f2", "f5", "f8"]


def test_dataset_load_error_surfaces(feed, tmp_path):
    bad = tmp_path / "nope.txt"
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist([str(bad)])
    with pytest.raises(RuntimeError):
        ds.load_into_memory()
