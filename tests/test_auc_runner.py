"""AUC-runner mode (box_wrapper.h:895-998): shuffling an informative slot
must degrade replay AUC; shuffling a pure-noise slot must not.

Replay happens on a HELD-OUT file: on the training data even a noise slot
is "important" (memorized instance fingerprints), which is a property of
the model, not the data — the held-out replay separates the two. The
noise slot's feasigns come from a range also present in training so its
embeddings are trained-but-uncorrelated (no unseen-key distribution
shift)."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train.auc_runner import (AucRunner, _eval_auc,
                                            maybe_run_auc_runner)
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4
NOISE_SLOT = 3


def _inject_noise_slot(ds, rng):
    """Overwrite the last slot with feasigns uncorrelated with the label,
    drawn from one shared range (trained but carrying no signal)."""
    base = np.uint64(NUM_SLOTS * 50 + 1000)
    for r in ds.records:
        n = rng.randint(1, 4)
        r.uint64_slots[NOISE_SLOT] = base + rng.randint(
            0, 500, n).astype(np.uint64)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    out = tmp_path_factory.mktemp("aucrun")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=5, lines_per_file=600, num_slots=NUM_SLOTS,
        vocab_per_slot=50, max_len=3, seed=3)
    feed = type(feed)(slots=feed.slots, batch_size=64)
    rng = np.random.RandomState(9)
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist(files[:4])
    ds.load_into_memory()
    _inject_noise_slot(ds, rng)
    eval_ds = BoxDataset(feed, read_threads=1, columnar=False)
    eval_ds.set_filelist(files[4:])
    eval_ds.load_into_memory()
    _inject_noise_slot(eval_ds, rng)

    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 15,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    trainer = BoxTrainer(CtrDnn(ModelSpec(num_slots=NUM_SLOTS,
                                          slot_dim=3 + D), hidden=(32, 16)),
                         table_cfg, feed, TrainerConfig(dense_lr=0.005),
                         seed=0)
    for _ in range(8):
        trainer.table.begin_feed_pass()
        trainer.table.add_keys(ds.all_keys())
        trainer.table.end_feed_pass()
        trainer.train_pass(ds, preloaded=True)
    return trainer, eval_ds


def test_auc_runner_slot_importance(trained):
    trainer, eval_ds = trained
    runner = AucRunner(trainer, seed=5)
    report = runner.run(eval_ds, slots=[0, 1, NOISE_SLOT])
    assert report["base_auc"] > 0.53, report
    # informative slots: clear degradation when shuffled
    assert report["slot_0"] > 0.015, report
    assert report["slot_1"] > 0.015, report
    # noise slot: no degradation (shuffling uncorrelated features is free)
    assert report[f"slot_{NOISE_SLOT}"] < 0.01, report
    # the probe restored the dataset: replay matches the base AUC again
    np.testing.assert_allclose(_eval_auc(trainer, eval_ds),
                               report["base_auc"], rtol=1e-9)


def test_auc_runner_flag_gate(trained):
    trainer, eval_ds = trained
    from paddlebox_tpu.config import flags
    assert maybe_run_auc_runner(trainer, eval_ds) is None  # flag off
    flags.set_flag("auc_runner_mode", True)
    try:
        report = maybe_run_auc_runner(trainer, eval_ds, slots=[0])
        assert report is not None and "slot_0" in report
    finally:
        flags.set_flag("auc_runner_mode", False)
