"""TP (Megatron MLP split) and EP (expert-parallel MoE) primitives vs the
single-device dense oracle — forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.tensor_parallel import (ep_experts_apply,
                                                    ep_experts_init,
                                                    tp_mlp_apply,
                                                    tp_mlp_init)


def test_tp_mlp_matches_dense():
    mesh = device_mesh_1d(8, axis="mp")
    rng = np.random.RandomState(0)
    p = tp_mlp_init(rng, 8, d_in=12, d_hidden=32, d_out=6)
    # randomize the biases (init zeros would let a mis-placed bias pass)
    p["b1"] = rng.randn(*p["b1"].shape).astype(np.float32) * 0.1
    p["b2"] = rng.randn(*p["b2"].shape).astype(np.float32) * 0.1
    x = rng.randn(16, 12).astype(np.float32)

    # dense oracle: concatenate the column/row shards
    w1 = np.concatenate(list(p["w1"]), axis=1)       # [d_in, d_h]
    b1 = np.concatenate(list(p["b1"]))
    w2 = np.concatenate(list(p["w2"]), axis=0)       # [d_h, d_out]
    want = np.maximum(x @ w1 + b1, 0.0) @ w2 + p["b2"]

    specs = {"w1": P("mp"), "b1": P("mp"), "w2": P("mp"), "b2": P()}

    def fn(p, x):
        local = {k: (v[0] if k != "b2" else v) for k, v in p.items()}
        return tp_mlp_apply(local, x, "mp")

    y = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False))(p, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5, atol=1e-5)

    # gradients flow through the psum (row/column transposes); the
    # replicated per-device loss divides by axis_size per the documented
    # autodiff contract (the psum transpose otherwise scales grads by P)
    def loss_fn(p, x):
        local = {k: (v[0] if k != "b2" else v) for k, v in p.items()}
        return (jnp.sum(jnp.square(tp_mlp_apply(local, x, "mp"))) * 1e-3
                / jax.lax.axis_size("mp"))

    g = jax.jit(jax.shard_map(
        lambda p, x: jax.grad(loss_fn)(p, x), mesh=mesh,
        in_specs=(specs, P()), out_specs=specs,
        check_vma=False))(p, jnp.asarray(x))

    def dense_loss(w1, b1, w2, b2, x):
        return jnp.sum(jnp.square(
            jnp.maximum(x @ w1 + b1, 0.0) @ w2 + b2)) * 1e-3

    gw1, gb1, gw2, gb2 = jax.grad(dense_loss, argnums=(0, 1, 2, 3))(
        jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2),
        jnp.asarray(p["b2"]), jnp.asarray(x))
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g["w1"])), axis=1), np.asarray(gw1),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g["w2"])), axis=0), np.asarray(gw2),
        rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.concatenate(list(np.asarray(g["b1"]))), np.asarray(gb1),
        rtol=1e-4, atol=1e-6)
    # b2 sits AFTER the psum: its cotangent does not pass the psum
    # transpose, so the /axis_size loss scaling shows up directly (a
    # replicated-param grad is 1/P of dense; a TP trainer psums it)
    np.testing.assert_allclose(np.asarray(g["b2"]) * 8.0, np.asarray(gb2),
                               rtol=1e-4, atol=1e-6)


def test_ep_experts_match_dense():
    mesh = device_mesh_1d(8, axis="ep")
    rng = np.random.RandomState(1)
    E, d_in, d_h, d_out = 16, 10, 12, 4
    p = ep_experts_init(rng, E, d_in, d_h, d_out)
    p["eb1"] = rng.randn(*p["eb1"].shape).astype(np.float32) * 0.1
    p["eb2"] = rng.randn(*p["eb2"].shape).astype(np.float32) * 0.1
    x = rng.randn(8, d_in).astype(np.float32)

    # dense oracle over all experts
    gates = np.exp(x @ p["gate"])
    gates = gates / gates.sum(-1, keepdims=True)
    h = np.maximum(np.einsum("bi,eih->beh", x, p["ew1"]) + p["eb1"], 0.0)
    y = np.einsum("beh,eho->beo", h, p["ew2"]) + p["eb2"]
    want = np.einsum("beo,be->bo", y, gates)

    # shard the 16 experts over 8 devices (2 each); gate replicated
    specs = {"ew1": P("ep"), "eb1": P("ep"), "ew2": P("ep"),
             "eb2": P("ep"), "gate": P()}
    got = jax.jit(jax.shard_map(
        lambda p, x: ep_experts_apply(p, x, "ep"), mesh=mesh,
        in_specs=(specs, P()), out_specs=P(), check_vma=False))(
        p, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_ep_experts_gradients_match_dense():
    """EP autodiff contract: expert-block grads are shard-local; the
    replicated gate's grad is PARTIAL per device and must psum across
    the axis (documented on ep_experts_apply)."""
    mesh = device_mesh_1d(8, axis="ep")
    rng = np.random.RandomState(2)
    E, d_in, d_h, d_out = 16, 10, 12, 4
    p = ep_experts_init(rng, E, d_in, d_h, d_out)
    p["eb1"] = rng.randn(*p["eb1"].shape).astype(np.float32) * 0.1
    p["eb2"] = rng.randn(*p["eb2"].shape).astype(np.float32) * 0.1
    x = rng.randn(8, d_in).astype(np.float32)
    specs = {"ew1": P("ep"), "eb1": P("ep"), "ew2": P("ep"),
             "eb2": P("ep"), "gate": P()}

    def grads(p, x):
        def loss(p, x):
            return (jnp.sum(jnp.square(ep_experts_apply(p, x, "ep")))
                    * 1e-3 / jax.lax.axis_size("ep"))
        g = jax.grad(loss)(p, x)
        return dict(g, gate=jax.lax.psum(g["gate"], "ep"))

    g = jax.jit(jax.shard_map(
        grads, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))(p, jnp.asarray(x))

    def dense_loss(p, x):
        gates = jax.nn.softmax(x @ p["gate"], axis=-1)
        h = jax.nn.relu(jnp.einsum("bi,eih->beh", x, p["ew1"]) + p["eb1"])
        y = jnp.einsum("beh,eho->beo", h, p["ew2"]) + p["eb2"]
        return jnp.sum(jnp.square(
            jnp.einsum("beo,be->bo", y, gates))) * 1e-3

    gd = jax.grad(dense_loss)({k: jnp.asarray(v) for k, v in p.items()},
                              jnp.asarray(x))
    for k in ("ew1", "eb1", "ew2", "eb2"):
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gd[k]),
                                   rtol=1e-4, atol=1e-6, err_msg=k)
    # under the /axis_size loss, the psum'd gate grad equals the dense
    # grad 1:1 (measured contract — the gate cotangent reaches each
    # device through ITS mix partial, i.e. through the psum transpose,
    # exactly like the expert leaves; unlike TP's post-psum b2)
    np.testing.assert_allclose(np.asarray(g["gate"]), np.asarray(gd["gate"]),
                               rtol=1e-4, atol=1e-6)
