"""Side tables consumed THROUGH the feed path (round-3 verdict item 7):
InputTable offsets translate from ins_id at pack time (InputTableDataFeed,
data_feed.h:2221-2252), ReplicaCache indexes ride SlotRecord.cache_idx
(pull_cache_value), and CtrDnnAux gathers the frozen rows on device."""

import os

import jax
import numpy as np
import pytest

from paddlebox_tpu.config.configs import (DataFeedConfig, SlotConfig,
                                          SparseOptimizerConfig,
                                          TableConfig, TrainerConfig)
from paddlebox_tpu.data import BoxDataset
from paddlebox_tpu.data.packer import BatchPacker
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.embedding.side_tables import InputTable, ReplicaCache
from paddlebox_tpu.models.aux_input import CtrDnnAux
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train.trainer import BoxTrainer

AUX_DIM = 4
NUM_SLOTS = 2
VOCAB = 40


def _feed(mb=32):
    slots = [SlotConfig("click", type="float", dim=1, is_used=False)]
    for i in range(NUM_SLOTS):
        slots.append(SlotConfig(f"slot_{i}", type="uint64", max_len=2))
    return DataFeedConfig(slots=tuple(slots), batch_size=mb,
                          parse_ins_id=True)


def _write_files(tmp_path, n_lines=512, n_items=8, seed=0):
    """ins_id-prefixed MultiSlot lines where the CLICK depends ONLY on the
    item's hidden group — learnable solely through the aux row."""
    rng = np.random.RandomState(seed)
    item_group = (np.arange(n_items) % 2).astype(np.float32)  # 0/1 groups
    path = os.path.join(str(tmp_path), "part-00000.txt")
    with open(path, "w") as f:
        for _ in range(n_lines):
            item = rng.randint(n_items)
            p = 0.9 if item_group[item] else 0.1
            click = int(rng.rand() < p)
            toks = [f"item{item}", f"1 {click}"]
            for si in range(NUM_SLOTS):
                n = rng.randint(1, 3)
                feas = rng.randint(0, VOCAB, n) + si * VOCAB
                toks.append(str(n) + " " + " ".join(map(str, feas)))
            f.write(" ".join(toks) + "\n")
    return [path], item_group


def _table_cfg():
    return TableConfig(
        embedx_dim=4, pass_capacity=1 << 10,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=1e9,
                                        mf_initial_range=0.0))


def _aux_table(item_group, rows_for):
    t = InputTable(AUX_DIM)
    for i in rows_for:
        row = np.zeros(AUX_DIM, np.float32)
        row[0] = 1.0 if item_group[i] else -1.0
        t.add_index_data(f"item{i}", row)
    return t


def test_parse_ins_id_and_pack_offsets(tmp_path):
    """The feed translates ins_id → offset at pack time; misses → 0."""
    files, item_group = _write_files(tmp_path, n_lines=64)
    feed = _feed()
    table = _aux_table(item_group, rows_for=range(4))  # items 4..7 miss
    ds = BoxDataset(feed, read_threads=1, input_table=table)
    ds.set_filelist(files)
    ds.load_into_memory()
    b = ds.split_batches(num_workers=1)[0][0]
    assert b.aux_offset is not None and b.aux_offset.shape[0] == 32
    for j in range(b.n_ins):
        ins = b.ins_ids[j]
        assert ins.startswith("item")
        item = int(ins[4:])
        if item < 4:
            assert b.aux_offset[j] == table.get_index_offset(ins) > 0
        else:
            assert b.aux_offset[j] == 0
    assert table.miss > 0


def test_input_table_model_e2e_learns_from_aux(tmp_path):
    """The signal lives ONLY in the aux row: with the populated table the
    model separates the groups; with an empty table (all-miss → zero
    rows) it cannot — proof the model consumes the rows through the
    feed path, not incidentally."""
    files, item_group = _write_files(tmp_path, n_lines=512)
    feed = _feed()
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + 4)

    def run(table):
        model = CtrDnnAux(spec, aux_dim=AUX_DIM, aux_capacity=64,
                          hidden=(32, 16))
        tr = BoxTrainer(model, _table_cfg(), feed,
                        TrainerConfig(dense_lr=5e-3, scan_chunk=1),
                        seed=0, aux_source=table)
        ds = BoxDataset(feed, read_threads=1, input_table=table)
        ds.set_filelist(files)
        losses = [tr.train_pass(ds)["loss"] for _ in range(4)]
        return losses

    with_aux = run(_aux_table(item_group, rows_for=range(8)))
    without = run(_aux_table(item_group, rows_for=()))
    assert with_aux[-1] < with_aux[0] - 0.05, with_aux
    # ~0.33 is the label-marginal entropy floor without the aux signal
    assert with_aux[-1] < without[-1] - 0.1, (with_aux, without)


def test_aux_rows_not_trained(tmp_path):
    """aux_rows is a frozen leaf: the optimizer must never move it (the
    dn_summary zero-grad contract)."""
    files, item_group = _write_files(tmp_path, n_lines=128)
    feed = _feed()
    table = _aux_table(item_group, rows_for=range(8))
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + 4)
    model = CtrDnnAux(spec, aux_dim=AUX_DIM, aux_capacity=64,
                      hidden=(16,))
    tr = BoxTrainer(model, _table_cfg(), feed,
                    TrainerConfig(dense_lr=5e-3), seed=0, aux_source=table)
    ds = BoxDataset(feed, read_threads=1, input_table=table)
    ds.set_filelist(files)
    tr.train_pass(ds)
    want = np.asarray(table.to_device(64))
    np.testing.assert_array_equal(np.asarray(tr.params["aux_rows"]), want)


def test_replica_cache_idx_feed_path():
    """pull_cache_value flow: records carry cache_idx, the packer emits
    the offsets, the model's logits respond to the cached rows."""
    feed = _feed(mb=8)
    rc = ReplicaCache(AUX_DIM)
    i_neg = rc.add_items(np.array([-2.0, 0, 0, 0], np.float32))
    i_pos = rc.add_items(np.array([2.0, 0, 0, 0], np.float32))
    rng = np.random.RandomState(3)
    recs = []
    for j in range(8):
        slots = {si: rng.randint(0, VOCAB, 2).astype(np.uint64)
                 for si in range(NUM_SLOTS)}
        recs.append(SlotRecord(label=j % 2, uint64_slots=slots,
                               cache_idx=(i_pos if j % 2 else i_neg)))
    packer = BatchPacker(feed, use_cache_idx=True)
    b = packer.pack(recs)
    np.testing.assert_array_equal(b.aux_offset[:8],
                                  [i_neg, i_pos] * 4)

    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + 4)
    model = CtrDnnAux(spec, aux_dim=AUX_DIM, aux_capacity=16, hidden=(8,))
    tr = BoxTrainer(model, _table_cfg(), feed,
                    TrainerConfig(dense_lr=1e-2), seed=1, aux_source=rc)
    tr.table.begin_feed_pass()
    tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.params = dict(tr.params, aux_rows=rc.to_device(16))
    tr.table.begin_pass()
    ids = tr.table.lookup_ids(b.keys, b.valid)
    batch = tr.device_batch(b, ids)
    preds_a = np.asarray(
        tr.fns.eval_step(tr.table.slab, tr.params, batch)["ctr"])

    # different cache contents must change the logits (the gather is live)
    rc2 = ReplicaCache(AUX_DIM)
    rc2.add_items(np.array([5.0, 5.0, 5.0, 5.0], np.float32))
    rc2.add_items(np.array([-5.0, 5.0, -5.0, 5.0], np.float32))
    tr.params = dict(tr.params, aux_rows=rc2.to_device(16))
    preds_b = np.asarray(
        tr.fns.eval_step(tr.table.slab, tr.params, batch)["ctr"])
    assert np.abs(preds_a - preds_b).max() > 1e-4
