import threading
import time

import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.utils import Channel, ChannelClosed, StatRegistry, Timer, TimerScope


def test_flags_defaults_and_set():
    assert flags.get_flag("enable_pullpush_dedup_keys") is True
    assert flags.get_flag("record_pool_max_size") == 2_000_000
    flags.set_flag("record_pool_max_size", 123)
    assert flags.get_flag("record_pool_max_size") == 123
    flags.set_flag("record_pool_max_size", 2_000_000)
    with pytest.raises(KeyError):
        flags.get_flag("nonexistent_flag")


def test_flag_redefine_rejected():
    with pytest.raises(ValueError):
        flags.define_flag("enable_pullpush_dedup_keys", False)


def test_timer_accumulates():
    t = Timer()
    with TimerScope(t):
        time.sleep(0.01)
    with TimerScope(t):
        time.sleep(0.01)
    assert t.count == 2
    assert 0.015 < t.elapsed_sec() < 1.0


def test_stats():
    reg = StatRegistry.instance()
    reg.reset()
    reg.add("STAT_gpu0_mem", 100)
    reg.add("STAT_gpu0_mem", -30)
    assert reg.get("STAT_gpu0_mem") == 70
    assert reg.snapshot() == {"STAT_gpu0_mem": 70}


def test_channel_mpmc_and_close():
    ch = Channel(capacity=4)
    results = []

    def consumer():
        for item in ch:
            results.append(item)

    threads = [threading.Thread(target=consumer) for _ in range(3)]
    for th in threads:
        th.start()
    for i in range(100):
        ch.put(i)
    ch.close()
    for th in threads:
        th.join()
    assert sorted(results) == list(range(100))
    with pytest.raises(ChannelClosed):
        ch.put(1)
    with pytest.raises(ChannelClosed):
        ch.get()


def test_channel_get_many():
    ch = Channel()
    ch.put_many(range(10))
    got = ch.get_many(4)
    assert got == [0, 1, 2, 3]
    assert len(ch) == 6
