import threading
import time

import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.utils import Channel, ChannelClosed, StatRegistry, Timer, TimerScope


def test_flags_defaults_and_set():
    assert flags.get_flag("dataset_disable_shuffle") is False
    assert flags.get_flag("stack_threads") == 4
    flags.set_flag("stack_threads", 2)
    assert flags.get_flag("stack_threads") == 2
    flags.set_flag("stack_threads", 4)
    with pytest.raises(KeyError):
        flags.get_flag("nonexistent_flag")


def test_flag_redefine_rejected():
    with pytest.raises(ValueError):
        flags.define_flag("dataset_disable_shuffle", True)


def test_flag_wiring():
    """Flags that claim behavior must actually drive it."""
    from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig, \
        TrainerConfig
    feed = DataFeedConfig(slots=(SlotConfig("a", max_len=4),), batch_size=8)
    base = feed.key_capacity()
    flags.set_flag("padbox_max_batch_keys", 999)
    try:
        assert feed.key_capacity() == 999
    finally:
        flags.set_flag("padbox_max_batch_keys", 0)
    assert feed.key_capacity() == base

    flags.set_flag("check_nan_inf", True)
    try:
        assert TrainerConfig().check_nan_inf is True
    finally:
        flags.set_flag("check_nan_inf", False)
    assert TrainerConfig().check_nan_inf is False


def test_timer_accumulates():
    t = Timer()
    with TimerScope(t):
        time.sleep(0.01)
    with TimerScope(t):
        time.sleep(0.01)
    assert t.count == 2
    assert 0.015 < t.elapsed_sec() < 1.0


def test_stats():
    reg = StatRegistry.instance()
    reg.reset()
    reg.add("STAT_gpu0_mem", 100)
    reg.add("STAT_gpu0_mem", -30)
    assert reg.get("STAT_gpu0_mem") == 70
    assert reg.snapshot() == {"STAT_gpu0_mem": 70}


def test_channel_mpmc_and_close():
    ch = Channel(capacity=4)
    results = []

    def consumer():
        for item in ch:
            results.append(item)

    threads = [threading.Thread(target=consumer) for _ in range(3)]
    for th in threads:
        th.start()
    for i in range(100):
        ch.put(i)
    ch.close()
    for th in threads:
        th.join()
    assert sorted(results) == list(range(100))
    with pytest.raises(ChannelClosed):
        ch.put(1)
    with pytest.raises(ChannelClosed):
        ch.get()


def test_channel_get_many():
    ch = Channel()
    ch.put_many(range(10))
    got = ch.get_many(4)
    assert got == [0, 1, 2, 3]
    assert len(ch) == 6
