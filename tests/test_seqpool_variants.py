"""fused_seqpool_cvm variant semantics vs a literal numpy oracle of the
reference CUDA kernels (fused_seqpool_cvm_with_{credit,pcoc,diff_thres}_op,
fused_seqpool_cvm_tradew_op)."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddlebox_tpu.ops import (fused_seqpool_cvm_tradew,
                               fused_seqpool_cvm_with_credit,
                               fused_seqpool_cvm_with_diff_thres,
                               fused_seqpool_cvm_with_pcoc)

B, S, E = 4, 3, 2


def _mk(width, seed=0, k_per_seg=2):
    rng = np.random.RandomState(seed)
    K = B * S * k_per_seg
    segments = np.repeat(np.arange(B * S), k_per_seg).astype(np.int32)
    emb = rng.rand(K, width).astype(np.float32) * 3
    valid = rng.rand(K) < 0.8
    return emb, segments, valid


def _pool(emb, segments, valid):
    out = np.zeros((B * S, emb.shape[1]), np.float32)
    for e, s, v in zip(emb, segments, valid):
        if v:
            out[s] += e
    return out.reshape(B, S, -1)


def test_credit_variant():
    emb, segments, valid = _mk(4 + E)
    got = np.asarray(fused_seqpool_cvm_with_credit(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid), B, S))
    pooled = _pool(emb, segments, valid)
    want = np.concatenate([np.log(pooled[..., :4] + 1), pooled[..., 4:]],
                          axis=-1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # show_filter drops col 0
    got_f = np.asarray(fused_seqpool_cvm_with_credit(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid), B, S,
        show_filter=True))
    np.testing.assert_allclose(got_f, want[..., 1:], rtol=1e-5)
    # no cvm drops all four
    got_n = np.asarray(fused_seqpool_cvm_with_credit(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid), B, S,
        use_cvm=False))
    np.testing.assert_allclose(got_n, pooled[..., 4:], rtol=1e-5)


def test_pcoc_variant():
    pclk = 3
    emb, segments, valid = _mk(4 + pclk + E, seed=1)
    got = np.asarray(fused_seqpool_cvm_with_pcoc(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid), B, S,
        pclk_num=pclk))
    pooled = _pool(emb, segments, valid)
    lg = np.log(pooled[..., :4 + pclk] + 1)
    want = np.concatenate([
        lg[..., 0:1],
        lg[..., 1:2] - lg[..., 0:1],
        lg[..., 4:] - lg[..., 2:3],
        lg[..., 4:] - lg[..., 3:4],
        pooled[..., 4 + pclk:],
    ], axis=-1)
    assert got.shape == (B, S, 2 + 2 * pclk + E)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_tradew_variant():
    tn = 2
    emb, segments, valid = _mk(2 + tn + E, seed=2)
    # weighted by trade 1's weight column
    got = np.asarray(fused_seqpool_cvm_tradew(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid), B, S,
        trade_num=tn, trade_id=1))
    w = emb[:, 2 + 1:2 + 2]
    weighted = np.concatenate([emb[:, :2], emb[:, 2 + tn:] * w], axis=1)
    pooled = _pool(weighted, segments, valid)
    want = np.concatenate([
        np.log(pooled[..., 0:1] + 1),
        np.log(pooled[..., 1:2] + 1) - np.log(pooled[..., 0:1] + 1),
        pooled[..., 2:],
    ], axis=-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_diff_thres_variant():
    emb, segments, valid = _mk(2 + E, seed=3)
    slots = (segments % S).astype(np.int32)
    thres = np.array([0.5, 100.0, 0.0], np.float32)  # slot 1 filters all
    got = np.asarray(fused_seqpool_cvm_with_diff_thres(
        jnp.asarray(emb), jnp.asarray(segments), jnp.asarray(valid),
        jnp.asarray(slots), B, S, slot_thresholds=thres,
        show_coeff=0.2, clk_coeff=1.0))
    score = (emb[:, 0] - emb[:, 1]) * 0.2 + emb[:, 1] * 1.0
    keep = valid & (score >= thres[slots])
    pooled = _pool(emb, segments, keep)
    want = np.concatenate([
        np.log(pooled[..., 0:1] + 1),
        np.log(pooled[..., 1:2] + 1) - np.log(pooled[..., 0:1] + 1),
        pooled[..., 2:],
    ], axis=-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # slot 1's pooled embedding must be all-zero (every key filtered)
    np.testing.assert_allclose(got[:, 1, 2:], 0.0)
