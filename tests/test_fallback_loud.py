"""Degraded-mode loudness: a missing native lib must WARN and bump a stat
(VERDICT r2 weak #4) — a silently slower python path would otherwise never
show up in CI."""

import logging

import numpy as np
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.utils.stats import stat_get, stat_reset


def _table():
    return TableConfig(
        embedx_dim=4, pass_capacity=1 << 10,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))


def test_host_store_python_fallback_is_loud(monkeypatch, caplog):
    import paddlebox_tpu.embedding.native_store as ns
    from paddlebox_tpu.embedding.host_store import HostEmbeddingStore

    monkeypatch.setattr("paddlebox_tpu.native.get_lib", lambda: None)
    stat_reset("host_store_python_fallback")
    with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
        store = ns.make_host_store(ValueLayout(4, "adagrad"), _table())
    assert isinstance(store, HostEmbeddingStore)
    assert stat_get("host_store_python_fallback") == 1
    assert any("native lib unavailable" in r.message for r in caplog.records)


def test_route_numpy_fallback_is_loud(monkeypatch, caplog):
    import paddlebox_tpu.parallel.sharded_table as st

    monkeypatch.setattr("paddlebox_tpu.native.build.get_lib", lambda: None)
    monkeypatch.setattr(st, "_warned_numpy_route", False)
    stat_reset("route_numpy_fallback")
    with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
        assert st._route_lib() is None
        assert st._route_lib() is None  # warn once, not per batch
    assert stat_get("route_numpy_fallback") == 1
    assert sum("numpy bucketize" in r.message for r in caplog.records) == 1


def test_numpy_route_fallback_still_correct(monkeypatch):
    """The numpy fallback must produce the same routing as the native path
    (it is the correctness oracle the native router was tested against —
    keep it honest in the degraded mode the warning flags)."""
    import paddlebox_tpu.parallel.sharded_table as st

    table = st.ShardedPassTable(_table(), num_shards=4, bucket_cap=64)
    keys = np.array([8, 12, 16, 8, 9, 21], np.uint64)
    table.begin_feed_pass()
    table.add_keys(keys)
    table.end_feed_pass()

    valid_a = np.ones(keys.size, bool)
    native_idx = table.bucketize(keys.copy(), valid_a)
    monkeypatch.setattr(st, "_route_lib", lambda: None)
    valid_b = np.ones(keys.size, bool)
    numpy_idx = table.bucketize(keys.copy(), valid_b)
    np.testing.assert_array_equal(native_idx.restore, numpy_idx.restore)
    np.testing.assert_array_equal(valid_a, valid_b)
    np.testing.assert_array_equal(native_idx.buckets, numpy_idx.buckets)
