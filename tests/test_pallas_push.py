"""Pallas in-table adagrad kernel vs the XLA apply_push oracle (interpret
mode on the CPU mesh; on-chip execution is covered by bench/driver runs)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.embedding.optimizers import apply_push
from paddlebox_tpu.embedding.pallas_push import pallas_apply_push

D = 8


def conf(create_thres=1e9):
    return SparseOptimizerConfig(mf_create_thresholds=create_thres,
                                 mf_initial_range=1e-3,
                                 feature_learning_rate=0.1,
                                 mf_learning_rate=0.05)


def _rows_and_grads(n, seed=0, with_mf=True):
    layout = ValueLayout(embedx_dim=D, optimizer="adagrad")
    push = PushLayout(D)
    rng = np.random.RandomState(seed)
    rows = layout.new_rows(n, rng, conf())
    rows[:, acc.SLOT] = rng.randint(0, 5, n)
    rows[:, acc.SHOW] = rng.randint(1, 30, n)
    rows[:, acc.CLICK] = rng.randint(0, 5, n)
    rows[:, acc.UNSEEN_DAYS] = rng.randint(0, 3, n)
    if with_mf:
        rows[:, acc.MF_SIZE] = D
        rows[:, layout.embedx_w:layout.embedx_w + D] = (
            rng.randn(n, D).astype(np.float32) * 0.01)
        rows[:, layout.embedx_state] = rng.rand(n)
    grads = np.zeros((n, push.width), np.float32)
    grads[:, push.SLOT] = rows[:, acc.SLOT]
    grads[:, push.SHOW] = rng.randint(0, 4, n)  # zero-show rows included
    grads[:, push.CLICK] = np.minimum(grads[:, push.SHOW],
                                      rng.randint(0, 2, n))
    grads[:, push.EMBED_G] = rng.randn(n).astype(np.float32) * 0.2
    grads[:, push.embedx_g:push.embedx_g + D] = (
        rng.randn(n, D).astype(np.float32) * 0.2)
    return layout, rows.astype(np.float32), grads


def test_pallas_push_matches_xla_no_create():
    """mf already exists everywhere and creation threshold is huge, so the
    PRNG never matters — the update must be bit-comparable to apply_push."""
    layout, rows, grads = _rows_and_grads(300, with_mf=True)
    c = conf(create_thres=1e9)
    want = np.asarray(apply_push(jnp.asarray(rows), jnp.asarray(grads),
                                 jax.random.PRNGKey(0), layout, c))
    got = np.asarray(pallas_apply_push(jnp.asarray(rows), jnp.asarray(grads),
                                       7, layout, c, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pallas_push_lazy_create_range():
    """Fresh rows past the score threshold get embedx drawn in
    [0, mf_initial_range) and mf_size set; inactive rows untouched."""
    layout, rows, grads = _rows_and_grads(300, seed=3, with_mf=False)
    c = conf(create_thres=0.0)
    got = np.asarray(pallas_apply_push(jnp.asarray(rows), jnp.asarray(grads),
                                       11, layout, c, interpret=True))
    push = PushLayout(D)
    active = grads[:, push.SHOW] > 0
    xw = layout.embedx_w
    created = got[active]
    assert (created[:, acc.MF_SIZE] == D).all()
    x = created[:, xw:xw + D]
    assert (x >= 0).all() and (x < c.mf_initial_range).all()
    # at least some spread (PRNG actually ran)
    assert np.unique(np.round(x / c.mf_initial_range, 4)).size > 10
    np.testing.assert_allclose(got[~active], rows[~active], rtol=1e-6)


def test_pallas_push_rejects_unsupported_layout():
    layout = ValueLayout(embedx_dim=D, optimizer="adam")
    with pytest.raises(ValueError):
        pallas_apply_push(jnp.zeros((8, layout.width)),
                          jnp.zeros((8, PushLayout(D).width)), 0, layout,
                          conf(), interpret=True)


def test_pallas_create_randoms_content_addressed():
    """The same slab row must draw the same creation randoms regardless of
    its position in the batch (row_ids keying, not positional)."""
    from paddlebox_tpu.embedding.pallas_push import pallas_apply_push
    layout, rows, grads = _rows_and_grads(32, seed=9, with_mf=False)
    c = conf(create_thres=0.0)
    ids = np.arange(32, dtype=np.int32)
    fwd = pallas_apply_push(jnp.asarray(rows), jnp.asarray(grads), 7, layout,
                            c, interpret=True, row_ids=jnp.asarray(ids))
    perm = np.random.RandomState(0).permutation(32)
    rev = pallas_apply_push(jnp.asarray(rows[perm]), jnp.asarray(grads[perm]),
                            7, layout, c, interpret=True,
                            row_ids=jnp.asarray(ids[perm]))
    np.testing.assert_array_equal(np.asarray(fwd)[perm], np.asarray(rev))


def test_flagged_push_sparse_dedup_roundtrip():
    """End-to-end through push_sparse_dedup with the flag on (interpreted
    pallas on CPU)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.embedding.optimizers import push_sparse_dedup
    layout, rows, grads = _rows_and_grads(64, seed=5, with_mf=True)
    c = conf(create_thres=1e9)
    slab = jnp.asarray(np.vstack([rows, np.zeros((1, layout.width),
                                                 np.float32)]))
    ids = jnp.asarray(np.arange(64, dtype=np.int64))
    flags.set_flag("use_pallas_push", True)
    try:
        # interpret path: monkeypatch via direct call comparison instead —
        # on CPU the real kernel needs interpret, so compare the underlying
        # update fns (the flag wiring itself is exercised by tracing)
        import paddlebox_tpu.embedding.pallas_push as pp
        orig = pp.pallas_apply_push
        pp.pallas_apply_push = lambda v, g, s, l, cf, **kw: orig(
            v, g, s, l, cf, interpret=True, **kw)
        try:
            out = push_sparse_dedup(slab, ids, jnp.asarray(grads),
                                    jax.random.PRNGKey(0), layout, c)
        finally:
            pp.pallas_apply_push = orig
    finally:
        flags.set_flag("use_pallas_push", False)
    want = push_sparse_dedup(slab, ids, jnp.asarray(grads),
                             jax.random.PRNGKey(0), layout, c)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
