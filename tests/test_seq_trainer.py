"""Sequence-parallel behavior-sequence CTR (BST): ring/Ulysses attention
consumed by a real trained model — exact parity with the single-device
full-attention oracle (params AND slab), plus end-to-end learning."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                          TableConfig, TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.bst import BstSeqCtr
from paddlebox_tpu.parallel.seq_trainer import SeqCtrTrainer

D = 4
NUM_SLOTS = 3
SEQ_LEN = 16          # divides the 8-device mesh


def _setup(tmp_path, lines=192, mb=16):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=lines,
        num_slots=NUM_SLOTS, vocab_per_slot=80, max_len=6, seed=13)
    return files, dataclasses.replace(feed, batch_size=mb)


def _table():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 11,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=1e9,
                                        mf_initial_range=0.0,
                                        feature_learning_rate=0.05,
                                        mf_learning_rate=0.05))


def _spec():
    return ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_seq_trainer_matches_dense_oracle(tmp_path, attn):
    """One sequence-parallel step == the dense full-attention step —
    params (loss-scale + psum contracts) and slab (combined pooled+seq
    push) both exact."""
    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    rebuild_uids)
    from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
    from paddlebox_tpu.ops.sparse import build_push_grads, pull_sparse

    files, feed = _setup(tmp_path)
    table_cfg = _table()
    model = BstSeqCtr(_spec(), seq_len=SEQ_LEN, n_shards=8, heads=8,
                      d_head=4, d_seq=8, hidden=(16,), attn=attn)
    tr = SeqCtrTrainer(model, table_cfg, feed,
                       TrainerConfig(dense_lr=1e-2), seq_slot=1, seed=4)
    params0 = {k: np.asarray(v) for k, v in tr.params.items()}
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    tr.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=tr.table.add_keys)
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    b = ds.split_batches(num_workers=1)[0][0]
    batch = {k: np.asarray(v) for k, v in tr.host_batch(b).items()}
    slab0 = np.asarray(tr.table.slab)
    prng0 = np.asarray(tr._prng)

    loss_sp = tr.train_batch(b)
    slab_sp = np.asarray(tr.table.slab)

    # ---- dense oracle
    layout, conf = tr.layout, table_cfg.optimizer
    B, S, T = feed.batch_size, tr.num_slots, SEQ_LEN
    key_valid = batch["ids"] != table_cfg.pass_capacity - 1
    seq_valid = batch["seq_valid"]

    def dense_loss(p, emb_pool, emb_seq):
        pooled = fused_seqpool_cvm(
            emb_pool, jnp.asarray(batch["segments"]),
            jnp.asarray(key_valid), B, S, True, sorted_segments=True)
        logits = model.oracle_logits(p, pooled, emb_seq,
                                     jnp.asarray(seq_valid))
        lab = jnp.asarray(batch["labels"]).astype(jnp.float32)
        iv = jnp.asarray(batch["ins_valid"])
        bce = optax.sigmoid_binary_cross_entropy(logits, lab)
        return jnp.where(iv, bce, 0.0).sum() / jnp.maximum(iv.sum(), 1.0)

    p0 = {k: jnp.asarray(v) for k, v in params0.items()}
    emb_pool0 = pull_sparse(jnp.asarray(slab0), jnp.asarray(batch["ids"]),
                            layout)
    emb_seq0 = pull_sparse(
        jnp.asarray(slab0), jnp.asarray(batch["seq_ids"].reshape(-1)),
        layout).reshape(B, T, -1)
    loss_d, (dp, demb_pool, demb_seq) = jax.value_and_grad(
        dense_loss, argnums=(0, 1, 2))(p0, emb_pool0, emb_seq0)
    np.testing.assert_allclose(loss_sp, float(loss_d), rtol=1e-5)

    opt = optax.adam(1e-2)
    upd, _ = opt.update(dp, opt.init(p0), p0)
    want = optax.apply_updates(p0, upd)
    for k in want:
        np.testing.assert_allclose(np.asarray(tr.params[k]),
                                   np.asarray(want[k]),
                                   rtol=3e-4, atol=2e-6, err_msg=k)

    # slab: combined pooled+seq push with the same prng stream
    _, sub = jax.random.split(jnp.asarray(prng0))
    clicks = batch["labels"][batch["segments"] // S]
    pg_pool = build_push_grads(demb_pool,
                               jnp.asarray(batch["segments"] % S),
                               jnp.asarray(clicks),
                               jnp.asarray(key_valid))
    seq_clicks = np.broadcast_to(batch["labels"][:, None],
                                 (B, T)).reshape(-1)
    pg_seq = build_push_grads(demb_seq.reshape(B * T, -1),
                              jnp.full((B * T,), 1, jnp.int32),
                              jnp.asarray(seq_clicks),
                              jnp.asarray(seq_valid.reshape(-1)))
    # sequence rows are gradient-only (stats count once via pooled rows)
    pg_seq = pg_seq.at[:, 1:3].set(0.0)
    pg = jnp.concatenate([pg_pool, pg_seq], axis=0)
    uids = rebuild_uids(jnp.asarray(batch["push_ids"]),
                        jnp.asarray(batch["perm"]),
                        jnp.asarray(batch["inv"]),
                        table_cfg.pass_capacity)
    want_slab = push_sparse_hostdedup(
        jnp.asarray(slab0), uids, jnp.asarray(batch["perm"]),
        jnp.asarray(batch["inv"]), pg, sub, layout, conf)
    np.testing.assert_allclose(slab_sp, np.asarray(want_slab),
                               rtol=3e-4, atol=2e-6)


def test_seq_trainer_learns(tmp_path):
    """End-to-end pass cadence with the attended history: loss descends
    and the sequence keys' rows train (show counts accumulate for the
    history slot too)."""
    from paddlebox_tpu.embedding import accessor as acc

    files, feed = _setup(tmp_path, lines=320)
    model = BstSeqCtr(_spec(), seq_len=SEQ_LEN, n_shards=8, heads=4,
                      d_head=4, d_seq=8, hidden=(32, 16), attn="ring")
    tr = SeqCtrTrainer(model, _table(), feed,
                       TrainerConfig(dense_lr=5e-3), seq_slot=0, seed=0)
    tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                           mask_var="mask")
    losses = []
    for _ in range(4):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(tr.train_pass(ds)["loss"])
        ds.release_memory()
    msg = tr.metrics.get_metric_msg("auc")
    assert msg["size"] > 0 and 0.0 < msg["actual_ctr"] < 1.0
    # test-mode inference with the attended history: no push
    from paddlebox_tpu.embedding import accessor as _acc
    _k0, _v0 = tr.table.store.state_items()
    show_pre_eval = _v0[:, _acc.SHOW].sum()
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds_ev, labels_ev = tr.predict_batches(ds)
    assert preds_ev.size == labels_ev.size > 100
    _k1, _v1 = tr.table.store.state_items()
    assert _v1[:, _acc.SHOW].sum() == show_pre_eval
    ds.release_memory()
    assert losses[-1] < losses[0] - 0.01, losses
    keys, vals = tr.table.store.state_items()
    assert keys.size > 50
    assert vals[:, acc.SHOW].sum() > 0
    # show statistics count each data occurrence ONCE even though the
    # history slot's keys push through both the pooled and the sequence
    # path (gradient-only seq rows): total show == total valid key
    # occurrences over the trained passes
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    ds.load_into_memory()
    occ = sum(int(b.valid.sum())
              for b in ds.split_batches(num_workers=1)[0])
    assert vals[:, acc.SHOW].sum() == pytest.approx(4 * occ), (
        vals[:, acc.SHOW].sum(), occ)


def test_seq_ids_extraction(tmp_path):
    """seq_ids_of keeps per-instance order, truncates at T, pads with the
    trash row."""
    files, feed = _setup(tmp_path, lines=64)
    model = BstSeqCtr(_spec(), seq_len=SEQ_LEN, n_shards=8, heads=4,
                      d_head=4, hidden=(8,))
    tr = SeqCtrTrainer(model, _table(), feed,
                       TrainerConfig(dense_lr=1e-2), seq_slot=1, seed=0)
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    tr.table.begin_feed_pass()
    ds.load_into_memory(add_keys_fn=tr.table.add_keys)
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    b = ds.split_batches(num_workers=1)[0][0]
    ids = tr.table.lookup_ids(b.keys, b.valid)
    seq_ids, seq_valid = tr.seq_ids_of(b, ids)
    B, S = feed.batch_size, tr.num_slots
    pad = tr.table.config.pass_capacity - 1
    for bi in range(B):
        mask = (b.slots == 1) & b.valid & (b.segments // S == bi)
        expect = ids[np.nonzero(mask)[0]][:SEQ_LEN]
        got = seq_ids[bi][seq_valid[bi]]
        np.testing.assert_array_equal(got, expect)
        assert (seq_ids[bi][~seq_valid[bi]] == pad).all()