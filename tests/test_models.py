"""Model zoo: shapes, grad flow, and multi-task output contracts."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.models import MODEL_ZOO, CtrDnn, DeepFM, WideDeep, DLRM, MMoE, ESMM
from paddlebox_tpu.models.base import ModelSpec

B, S, D = 4, 6, 8
SPEC = ModelSpec(num_slots=S, slot_dim=3 + D, dense_dim=5)
SPEC_NODENSE = ModelSpec(num_slots=S, slot_dim=3 + D, dense_dim=0)


@pytest.fixture
def inputs():
    rng = np.random.RandomState(0)
    pooled = jnp.asarray(rng.rand(B, S, 3 + D).astype(np.float32))
    dense = jnp.asarray(rng.rand(B, 5).astype(np.float32))
    return pooled, dense


@pytest.mark.parametrize("cls", [CtrDnn, DeepFM, WideDeep, DLRM])
def test_single_task_models(cls, inputs):
    pooled, dense = inputs
    model = cls(SPEC)
    params = model.init(jax.random.PRNGKey(0))
    logits = model.apply(params, pooled, dense)
    assert logits.shape == (B,)
    # grads flow to every param leaf
    g = jax.grad(lambda p: model.apply(p, pooled, dense).sum())(params)
    for name, leaf in g.items():
        assert np.isfinite(np.asarray(leaf)).all(), name
        assert np.abs(np.asarray(leaf)).sum() > 0, f"dead param {name}"


@pytest.mark.parametrize("cls", [CtrDnn, DeepFM, WideDeep, DLRM])
def test_models_without_dense(cls, inputs):
    pooled, _ = inputs
    model = cls(SPEC_NODENSE)
    params = model.init(jax.random.PRNGKey(0))
    assert model.apply(params, pooled, None).shape == (B,)


@pytest.mark.parametrize("cls", [MMoE, ESMM])
def test_multi_task_models(cls, inputs):
    pooled, dense = inputs
    model = cls(SPEC)
    params = model.init(jax.random.PRNGKey(0))
    out = model.apply(params, pooled, dense)
    assert set(out) == set(model.task_names)
    for t, lg in out.items():
        assert lg.shape == (B,)
    g = jax.grad(lambda p: sum(v.sum() for v in
                               model.apply(p, pooled, dense).values()))(params)
    for name, leaf in g.items():
        assert np.abs(np.asarray(leaf)).sum() > 0, f"dead param {name}"


def test_zoo_registry():
    assert set(MODEL_ZOO) == {"ctr_dnn", "deepfm", "wide_deep", "dlrm",
                              "mmoe", "esmm", "join_pv_dnn"}


def test_esmm_entire_space_loss():
    """loss_mode='esmm' composes pCTCVR = pCTR*pCVR (entire-space loss)."""
    import jax.numpy as jnp
    from paddlebox_tpu.train.trainer import _multi_task_loss

    logits = {"ctr": jnp.array([0.5, -1.0]), "cvr": jnp.array([0.2, 0.3])}
    labels = {"ctr": jnp.array([1, 0]), "cvr": jnp.array([1, 0])}
    ins_valid = jnp.array([True, True])
    loss, preds = _multi_task_loss(logits, labels, ins_valid, "esmm")
    assert set(preds) == {"ctr", "cvr", "ctcvr"}
    np.testing.assert_allclose(
        np.asarray(preds["ctcvr"]),
        np.asarray(preds["ctr"]) * np.asarray(preds["cvr"]), rtol=1e-6)
    assert np.isfinite(float(loss))
    # independent-sum mode differs from entire-space mode
    loss_sum, _ = _multi_task_loss(logits, labels, ins_valid, "sum")
    assert abs(float(loss) - float(loss_sum)) > 1e-6
