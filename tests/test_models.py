"""Model zoo: shapes, grad flow, and multi-task output contracts."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.models import MODEL_ZOO, CtrDnn, DeepFM, WideDeep, DLRM, MMoE, ESMM
from paddlebox_tpu.models.base import ModelSpec

B, S, D = 4, 6, 8
SPEC = ModelSpec(num_slots=S, slot_dim=3 + D, dense_dim=5)
SPEC_NODENSE = ModelSpec(num_slots=S, slot_dim=3 + D, dense_dim=0)


@pytest.fixture
def inputs():
    rng = np.random.RandomState(0)
    pooled = jnp.asarray(rng.rand(B, S, 3 + D).astype(np.float32))
    dense = jnp.asarray(rng.rand(B, 5).astype(np.float32))
    return pooled, dense


@pytest.mark.parametrize("cls", [CtrDnn, DeepFM, WideDeep, DLRM])
def test_single_task_models(cls, inputs):
    pooled, dense = inputs
    model = cls(SPEC)
    params = model.init(jax.random.PRNGKey(0))
    logits = model.apply(params, pooled, dense)
    assert logits.shape == (B,)
    # grads flow to every param leaf
    g = jax.grad(lambda p: model.apply(p, pooled, dense).sum())(params)
    for name, leaf in g.items():
        assert np.isfinite(np.asarray(leaf)).all(), name
        assert np.abs(np.asarray(leaf)).sum() > 0, f"dead param {name}"


@pytest.mark.parametrize("cls", [CtrDnn, DeepFM, WideDeep, DLRM])
def test_models_without_dense(cls, inputs):
    pooled, _ = inputs
    model = cls(SPEC_NODENSE)
    params = model.init(jax.random.PRNGKey(0))
    assert model.apply(params, pooled, None).shape == (B,)


@pytest.mark.parametrize("cls", [MMoE, ESMM])
def test_multi_task_models(cls, inputs):
    pooled, dense = inputs
    model = cls(SPEC)
    params = model.init(jax.random.PRNGKey(0))
    out = model.apply(params, pooled, dense)
    assert set(out) == set(model.task_names)
    for t, lg in out.items():
        assert lg.shape == (B,)
    g = jax.grad(lambda p: sum(v.sum() for v in
                               model.apply(p, pooled, dense).values()))(params)
    for name, leaf in g.items():
        assert np.abs(np.asarray(leaf)).sum() > 0, f"dead param {name}"


def test_zoo_registry():
    assert set(MODEL_ZOO) == {"ctr_dnn", "deepfm", "wide_deep", "dlrm",
                              "mmoe", "esmm", "join_pv_dnn",
                              "ctr_dnn_expand", "ctr_dnn_aux",
                              "bst_seq_ctr", "tp_deepfm", "ep_mmoe"}


def test_esmm_entire_space_loss():
    """loss_mode='esmm' composes pCTCVR = pCTR*pCVR (entire-space loss)."""
    import jax.numpy as jnp
    from paddlebox_tpu.train.trainer import _multi_task_loss

    logits = {"ctr": jnp.array([0.5, -1.0]), "cvr": jnp.array([0.2, 0.3])}
    labels = {"ctr": jnp.array([1, 0]), "cvr": jnp.array([1, 0])}
    ins_valid = jnp.array([True, True])
    loss, preds = _multi_task_loss(logits, labels, ins_valid, "esmm")
    assert set(preds) == {"ctr", "cvr", "ctcvr"}
    np.testing.assert_allclose(
        np.asarray(preds["ctcvr"]),
        np.asarray(preds["ctr"]) * np.asarray(preds["cvr"]), rtol=1e-6)
    assert np.isfinite(float(loss))
    # independent-sum mode differs from entire-space mode
    loss_sum, _ = _multi_task_loss(logits, labels, ins_valid, "sum")
    assert abs(float(loss) - float(loss_sum)) > 1e-6


@pytest.mark.parametrize("cls", [WideDeep, DLRM])
def test_zoo_models_learn_e2e(cls, tmp_path):
    """Every single-task zoo model must LEARN through the full fused-step
    pipeline, not just produce shapes (ctr_dnn/deepfm have their own e2e
    suites; this covers the rest of the zoo)."""
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.train import BoxTrainer
    import dataclasses

    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=2, lines_per_file=400, num_slots=6,
        vocab_per_slot=300, max_len=3, seed=5)
    feed = dataclasses.replace(feed, batch_size=64)
    table = TableConfig(embedx_dim=D, pass_capacity=1 << 13,
                        optimizer=SparseOptimizerConfig(
                            mf_create_thresholds=0.0, mf_initial_range=1e-3,
                            feature_learning_rate=0.1, mf_learning_rate=0.1))
    model = cls(ModelSpec(num_slots=6, slot_dim=3 + D))
    tr = BoxTrainer(model, table, feed, TrainerConfig(dense_lr=3e-3,
                                                      scan_chunk=2))
    try:
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses = [tr.train_pass(ds)["loss"] for _ in range(4)]
        # architectures converge at different rates (DLRM's dot-interaction
        # warms slower than the MLP towers): require a clear decrease
        assert losses[-1] < losses[0] - 0.005, (cls.__name__, losses)
    finally:
        tr.close()
