"""Sparse table numeric parity vs a literal NumPy oracle of the reference
optimizer semantics (optimizer.cuh.h:31-145 adagrad, :148-330 adam), plus
pass-lifecycle and host-store behavior (mirrors ctr_accessor_test.cc /
sparse_sgd_rule_test.cc roles)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config.configs import SparseOptimizerConfig, TableConfig
from paddlebox_tpu.embedding import PassTable, HostEmbeddingStore
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.embedding.optimizers import apply_push

D = 4


def oracle_adagrad_row(row, grad, conf, layout):
    """Literal dy_mf_update_value for SparseAdagradOptimizer."""
    row = row.astype(np.float64).copy()
    push = PushLayout(layout.embedx_dim)
    g_show = grad[push.SHOW]
    g_click = grad[push.CLICK]
    if g_show <= 0:
        return row.astype(np.float32)
    row[acc.SLOT] = grad[push.SLOT]
    row[acc.SHOW] += g_show
    row[acc.CLICK] += g_click
    row[acc.DELTA_SCORE] += (conf.nonclk_coeff * (g_show - g_click)
                             + conf.clk_coeff * g_click)
    row[acc.UNSEEN_DAYS] = 0.0

    def update_value_work(w, g2sum, g, scale, lr):
        add_g2sum = 0.0
        ratio = lr * np.sqrt(conf.mf_initial_g2sum /
                             (conf.mf_initial_g2sum + g2sum))
        for i in range(len(w)):
            scaled = g[i] / scale
            w[i] += scaled * ratio
            w[i] = np.clip(w[i], conf.mf_min_bound, conf.mf_max_bound)
            add_g2sum += scaled * scaled
        return g2sum + add_g2sum / len(w)

    slot = row[acc.SLOT]
    lr = (conf.mf_learning_rate if slot == conf.nodeid_slot
          else conf.feature_learning_rate)
    w = [row[acc.EMBED_W]]
    row[layout.embed_state] = update_value_work(
        w, row[layout.embed_state], [grad[push.EMBED_G]], g_show, lr)
    row[acc.EMBED_W] = w[0]

    score = (conf.nonclk_coeff * (row[acc.SHOW] - row[acc.CLICK])
             + conf.clk_coeff * row[acc.CLICK])
    if row[acc.MF_SIZE] == 0:
        if conf.mf_create_thresholds <= score:
            row[acc.MF_SIZE] = layout.embedx_dim
            # rng: with mf_initial_range=0 creation is deterministically zero
            row[layout.embedx_w:layout.embedx_w + layout.embedx_dim] = 0.0
    else:
        xw = list(row[layout.embedx_w:layout.embedx_w + layout.embedx_dim])
        row[layout.embedx_state] = update_value_work(
            xw, row[layout.embedx_state],
            grad[push.embedx_g:push.embedx_g + layout.embedx_dim],
            g_show, conf.mf_learning_rate)
        row[layout.embedx_w:layout.embedx_w + layout.embedx_dim] = xw
    return row.astype(np.float32)


@pytest.fixture
def conf():
    return SparseOptimizerConfig(mf_initial_range=0.0)


@pytest.fixture
def layout():
    return ValueLayout(D, "adagrad")


def test_adagrad_parity_vs_oracle(conf, layout):
    rng = np.random.RandomState(1)
    n = 64
    push = PushLayout(D)
    values = np.zeros((n, layout.width), dtype=np.float32)
    values[:, acc.EMBED_W] = rng.randn(n) * 0.1
    values[:, layout.embed_state] = rng.rand(n)
    values[:, acc.SHOW] = rng.randint(0, 30, n)
    values[:, acc.CLICK] = rng.randint(0, 3, n)
    # half the rows already have mf created
    values[:n // 2, acc.MF_SIZE] = D
    values[:n // 2, layout.embedx_w:layout.embedx_w + D] = rng.randn(n // 2, D) * 0.1
    values[:n // 2, layout.embedx_state] = rng.rand(n // 2)

    grads = np.zeros((n, push.width), dtype=np.float32)
    grads[:, push.SLOT] = rng.randint(1, 10, n)
    grads[:, push.SHOW] = rng.randint(0, 4, n)  # some zero-show (padding) rows
    grads[:, push.CLICK] = np.minimum(grads[:, push.SHOW],
                                      rng.randint(0, 2, n))
    grads[:, push.EMBED_G] = rng.randn(n).astype(np.float32)
    grads[:, push.embedx_g:] = rng.randn(n, D).astype(np.float32)

    got = np.asarray(apply_push(jnp.asarray(values), jnp.asarray(grads),
                                jax.random.PRNGKey(0), layout, conf))
    want = np.stack([oracle_adagrad_row(values[i], grads[i], conf, layout)
                     for i in range(n)])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_zero_show_rows_untouched(conf, layout):
    values = np.random.RandomState(0).randn(8, layout.width).astype(np.float32)
    grads = np.zeros((8, PushLayout(D).width), dtype=np.float32)
    got = np.asarray(apply_push(jnp.asarray(values), jnp.asarray(grads),
                                jax.random.PRNGKey(0), layout, conf))
    np.testing.assert_array_equal(got, values)


def test_lazy_mf_creation_range():
    conf = SparseOptimizerConfig(mf_initial_range=0.01, mf_create_thresholds=1.0)
    layout = ValueLayout(D, "adagrad")
    push = PushLayout(D)
    values = np.zeros((4, layout.width), dtype=np.float32)
    grads = np.zeros((4, push.width), dtype=np.float32)
    grads[:, push.SHOW] = 5.0
    grads[:, push.CLICK] = 2.0  # score = 0.1*3 + 2 = 2.3 >= 1.0 → create
    got = np.asarray(apply_push(jnp.asarray(values), jnp.asarray(grads),
                                jax.random.PRNGKey(3), layout, conf))
    assert (got[:, acc.MF_SIZE] == D).all()
    xw = got[:, layout.embedx_w:layout.embedx_w + D]
    assert (xw >= 0).all() and (xw < 0.01).all()
    assert np.abs(xw).sum() > 0  # actually randomized


def test_adam_step_moves_and_bounds():
    conf = SparseOptimizerConfig(optimizer="adam", mf_initial_range=0.0)
    layout = ValueLayout(D, "adam")
    push = PushLayout(D)
    values = layout.new_rows(2, np.random.RandomState(0), conf)
    values[:, acc.MF_SIZE] = D
    grads = np.zeros((2, push.width), dtype=np.float32)
    grads[:, push.SHOW] = 1.0
    grads[:, push.EMBED_G] = np.array([1.0, -1.0])
    grads[:, push.embedx_g:] = 0.5
    got = np.asarray(apply_push(jnp.asarray(values), jnp.asarray(grads),
                                jax.random.PRNGKey(0), layout, conf))
    # first adam step: m=(1-b1)g, v=(1-b2)g^2, ratio=lr*sqrt(1-b2p)/(1-b1p)
    # with b1p=b1, b2p=b2 → step ≈ lr * g/|g| ≈ ±lr
    assert got[0, acc.EMBED_W] > 0.04
    assert got[1, acc.EMBED_W] < -0.04
    es = layout.embed_state
    np.testing.assert_allclose(got[:, es + 2], 0.9 ** 2, rtol=1e-5)  # b1p *= b1
    xw = got[:, layout.embedx_w:layout.embedx_w + D]
    assert (xw > 0).all()


def test_pass_lifecycle_and_dedup():
    table = TableConfig(embedx_dim=D, pass_capacity=1 << 10,
                        optimizer=SparseOptimizerConfig(mf_initial_range=0.0,
                                                        mf_create_thresholds=1.0))
    pt = PassTable(table, seed=0)
    keys = np.array([10**12 + 7, 42, 99, 10**15], dtype=np.uint64)

    pt.begin_feed_pass()
    pt.add_keys(keys[:2])
    pt.add_keys(keys[2:])
    pt.add_keys(keys[:1])  # duplicate registration is fine
    pt.end_feed_pass()
    assert pt.pass_size == 4

    pt.begin_pass()
    # batch references key 42 twice (dedup must merge grads)
    batch_keys = np.array([42, 42, 99, 10**12 + 7], dtype=np.uint64)
    ids = pt.lookup_ids(batch_keys)
    pulled = np.asarray(pt.pull(jnp.asarray(ids)))
    assert pulled.shape == (4, 3 + D)
    np.testing.assert_array_equal(pulled[0], pulled[1])  # same key

    push = PushLayout(D)
    grads = np.zeros((4, push.width), dtype=np.float32)
    grads[:, push.SHOW] = 1.0
    grads[:, push.CLICK] = np.array([1, 0, 1, 0])
    grads[:, push.EMBED_G] = np.array([0.5, 0.5, 1.0, -1.0])
    pt.push(jnp.asarray(ids), jnp.asarray(grads))
    pt.end_pass()

    # duplicate key 42: merged g_show=2, show should be 2 after pass
    row42 = pt.store.lookup(np.array([42], dtype=np.uint64))[0]
    assert row42[acc.SHOW] == 2.0
    assert row42[acc.CLICK] == 1.0
    # unseen key never pushed keeps show 0
    row_unpushed = pt.store.lookup(np.array([10**15], dtype=np.uint64))[0]
    assert row_unpushed[acc.SHOW] == 0.0


@pytest.mark.parametrize("init_range", [0.0, 1e-3])
def test_hostdedup_push_matches_device_dedup(init_range):
    """push_sparse_hostdedup (host dedup + sorted segment-sum, no device
    sort) must produce bit-identical slabs to the jnp.unique path — incl.
    lazily CREATED embedx rows, whose randoms are content-addressed by slab
    id so the two paths' different row orders draw the same values."""
    from paddlebox_tpu.embedding.optimizers import (push_sparse_dedup,
                                                    push_sparse_hostdedup)
    table = TableConfig(embedx_dim=D, pass_capacity=1 << 8,
                        optimizer=SparseOptimizerConfig(
                            mf_initial_range=init_range,
                            mf_create_thresholds=0.0))
    pt = PassTable(table, seed=3)
    rng = np.random.RandomState(5)
    keys = np.unique(rng.randint(1, 10**9, 40).astype(np.uint64))
    pt.begin_feed_pass()
    pt.add_keys(keys)
    pt.end_feed_pass()
    pt.begin_pass()

    K = 64
    occ = rng.choice(keys, K).astype(np.uint64)
    valid = rng.rand(K) > 0.2
    ids = pt.lookup_ids(occ, valid)
    push = PushLayout(D)
    grads = rng.randn(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    grads[:, push.CLICK] = (rng.rand(K) < 0.3)
    grads[~valid] = 0.0

    prng = jax.random.PRNGKey(11)
    slab0 = pt.slab
    ref = push_sparse_dedup(slab0, jnp.asarray(ids), jnp.asarray(grads),
                            prng, pt.layout, table.optimizer)
    uids, perm, inv = pt.dedup_for_push(ids)
    got = push_sparse_hostdedup(slab0, jnp.asarray(uids), jnp.asarray(perm),
                                jnp.asarray(inv), jnp.asarray(grads), prng,
                                pt.layout, table.optimizer)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    # the train step re-derives uids ON DEVICE from (ids, perm, inv)
    # (rebuild_uids) instead of transferring them — the rebuild must hit
    # the same slab rows bit-identically
    from paddlebox_tpu.embedding.optimizers import rebuild_uids
    rebuilt = rebuild_uids(jnp.asarray(ids), jnp.asarray(perm),
                           jnp.asarray(inv), table.pass_capacity)
    got2 = push_sparse_hostdedup(slab0, rebuilt, jnp.asarray(perm),
                                 jnp.asarray(inv), jnp.asarray(grads), prng,
                                 pt.layout, table.optimizer)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got2))
    # push_write='rebuild' (gather-rebuild slab write, no scatter) must be
    # bit-identical too — pos comes from the host next to the dedup
    from paddlebox_tpu.embedding.optimizers import push_sparse_rebuild
    pos = pt.pos_for_rebuild(uids)
    assert (pos >= 0).sum() == np.unique(ids).shape[0]
    got3 = push_sparse_rebuild(slab0, jnp.asarray(uids), jnp.asarray(pos),
                               jnp.asarray(perm), jnp.asarray(inv),
                               jnp.asarray(grads), prng,
                               pt.layout, table.optimizer)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got3))
    pt.end_pass()


def test_dedup_for_push_invariants():
    table = TableConfig(embedx_dim=D, pass_capacity=128)
    pt = PassTable(table)
    pt.begin_feed_pass()
    pt.add_keys(np.arange(1, 50, dtype=np.uint64))
    pt.end_feed_pass()
    pt.begin_pass()
    rng = np.random.RandomState(0)
    occ = rng.randint(1, 50, 32).astype(np.uint64)
    valid = rng.rand(32) > 0.3
    ids = pt.lookup_ids(occ, valid)
    for native in (True, False):
        if native and not _native_available():
            continue
        uids, perm, inv = (pt.dedup_for_push(ids) if native
                           else _numpy_dedup(pt, ids))
        # all uids distinct (unique scatter contract)
        assert np.unique(uids).size == uids.size
        # inv nondecreasing over the permuted occurrence order (sorted
        # segment-sum contract)
        assert (np.diff(inv) >= 0).all()
        # perm is a permutation
        assert np.array_equal(np.sort(perm), np.arange(ids.size))
        # reconstruction: uids[inv] == ids[perm] for every occurrence
        np.testing.assert_array_equal(uids[inv], ids[perm])
        # padding ids out of range exactly beyond the unique count
        n_u = np.unique(ids).size
        assert (uids[:n_u] < table.pass_capacity).all()
        assert (uids[n_u:] >= table.pass_capacity).all()
    pt.end_pass()


def _native_available():
    from paddlebox_tpu.native.build import available
    return available()


def _numpy_dedup(pt, ids):
    """Force the numpy fallback branch of dedup_for_push."""
    import unittest.mock as mock
    with mock.patch("paddlebox_tpu.native.build.get_lib", return_value=None):
        return pt.dedup_for_push(ids)


def test_native_lookup_matches_searchsorted():
    """rt_lookup (hash probe) must agree with the numpy fallback, honor
    valid masking, and reject unregistered keys."""
    table = TableConfig(embedx_dim=D, pass_capacity=1 << 10)
    pt = PassTable(table)
    rng = np.random.RandomState(7)
    keys = np.unique(rng.randint(1, 1 << 60, 300).astype(np.uint64))
    pt.begin_feed_pass()
    pt.add_keys(keys)
    pt.end_feed_pass()
    pt.begin_pass()
    batch = rng.choice(keys, 128).astype(np.uint64)
    valid = rng.rand(128) > 0.25
    got = pt.lookup_ids(batch, valid)
    ri, pt._route_index = pt._route_index, None
    want = pt.lookup_ids(batch, valid)
    pt._route_index = ri
    np.testing.assert_array_equal(got, want)
    assert (got[~valid] == pt.padding_id).all()
    if ri is not None:
        with pytest.raises(KeyError):
            pt.lookup_ids(np.array([keys.max() + 1], dtype=np.uint64))
    pt.end_pass()


def test_unregistered_key_raises():
    table = TableConfig(embedx_dim=D, pass_capacity=64)
    pt = PassTable(table)
    pt.begin_feed_pass()
    pt.add_keys(np.array([1, 2, 3], dtype=np.uint64))
    pt.end_feed_pass()
    pt.begin_pass()
    with pytest.raises(KeyError):
        pt.lookup_ids(np.array([4], dtype=np.uint64))
    pt.end_pass()


def test_state_persists_across_passes():
    table = TableConfig(embedx_dim=D, pass_capacity=256)
    pt = PassTable(table, seed=0)
    push = PushLayout(D)
    for i in range(3):
        pt.begin_feed_pass()
        pt.add_keys(np.array([7, 8], dtype=np.uint64))
        pt.end_feed_pass()
        pt.begin_pass()
        ids = pt.lookup_ids(np.array([7, 8], dtype=np.uint64))
        grads = np.zeros((2, push.width), dtype=np.float32)
        grads[:, push.SHOW] = 1.0
        grads[:, push.EMBED_G] = 0.1
        pt.push(jnp.asarray(ids), jnp.asarray(grads))
        pt.end_pass()
    row = pt.store.lookup(np.array([7], dtype=np.uint64))[0]
    assert row[acc.SHOW] == 3.0  # accumulated across passes


def test_shrink_decay_and_delete():
    table = TableConfig(embedx_dim=D, pass_capacity=256,
                        show_click_decay_rate=0.5, delete_threshold=0.8)
    layout = ValueLayout(D, "adagrad")
    store = HostEmbeddingStore(layout, table)
    keys = np.array([1, 2], dtype=np.uint64)
    rows = store.lookup_or_create(keys)
    rows[0, acc.SHOW] = 100.0  # survives: 0.1*50 = 5 >= 0.8
    rows[1, acc.SHOW] = 1.0    # dies: 0.1*0.5 < 0.8
    store.write_back(keys, rows)
    deleted = store.shrink()
    assert deleted == 1
    assert len(store) == 1
    survivor = store.lookup(np.array([1], dtype=np.uint64))[0]
    np.testing.assert_allclose(survivor[acc.SHOW], 50.0)  # decayed


def test_spill_and_fault_in(tmp_path):
    table = TableConfig(embedx_dim=D, pass_capacity=256,
                        ssd_dir=str(tmp_path / "ssd"))
    layout = ValueLayout(D, "adagrad")
    store = HostEmbeddingStore(layout, table)
    keys = np.arange(1, 101, dtype=np.uint64)
    rows = store.lookup_or_create(keys)
    rows[:, acc.EMBED_W] = keys.astype(np.float32)
    rows[:, acc.UNSEEN_DAYS] = np.arange(100)[::-1]  # key 1 = oldest
    store.write_back(keys, rows)

    spilled = store.spill(max_resident=60)
    assert spilled == 40
    assert len(store) == 60
    # the lookup path PEEKs a spilled key: value served off the block,
    # row stays spilled (round 16 — a peek needs no journal MOVE)
    row = store.lookup(np.array([1], dtype=np.uint64))[0]
    assert row[acc.EMBED_W] == 1.0
    assert len(store) == 60
    # promotion is explicit: the BeginFeedPass/LoadSSD2Mem fault-in leg
    store.fault_in_keys(np.array([1], dtype=np.uint64))
    assert len(store) == 61
    # load everything back (LoadSSD2Mem)
    store.load_spilled()
    assert len(store) == 100


def test_save_load_roundtrip(tmp_path):
    table = TableConfig(embedx_dim=D, pass_capacity=256)
    layout = ValueLayout(D, "adagrad")
    store = HostEmbeddingStore(layout, table)
    keys = np.array([5, 6, 7], dtype=np.uint64)
    rows = store.lookup_or_create(keys)
    rows[:, acc.EMBED_W] = [1, 2, 3]
    store.write_back(keys, rows)
    p = str(tmp_path / "table.pkl")
    store.save(p)

    store2 = HostEmbeddingStore(layout, table)
    store2.load(p)
    np.testing.assert_array_equal(
        store2.lookup(keys)[:, acc.EMBED_W], [1, 2, 3])


def test_first_occurrence_idx_alignment():
    """first_idx[j] must be an occurrence position whose id == uids[j], for
    BOTH dedup backends (native rt_dedup counting sort and the numpy
    stable-argsort fallback) — the pull-row reuse contract
    (pulled_rows[first_idx] == slab[uids], _merged_new_rows)."""
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    first_occurrence_idx)
    rng = np.random.RandomState(7)
    for trial in range(4):
        K = int(rng.randint(3, 200))
        ids = rng.randint(0, 40, K).astype(np.int32)
        uids, perm, inv = dedup_ids(ids, pad_base=1000)
        first = first_occurrence_idx(perm, inv)
        n_u = int((uids < 1000).sum())
        np.testing.assert_array_equal(ids[first[:n_u]], uids[:n_u])
        # numpy fallback path must satisfy the same contract
        import paddlebox_tpu.native.build as nb
        saved = nb.get_lib
        nb.get_lib = lambda: None
        try:
            uids2, perm2, inv2 = dedup_ids(ids, pad_base=1000)
        finally:
            nb.get_lib = saved
        first2 = first_occurrence_idx(perm2, inv2)
        np.testing.assert_array_equal(ids[first2[:n_u]], uids2[:n_u])


def test_push_pull_row_reuse_matches_slab_gather():
    """push with pulled_rows/first_idx (the fused step's reuse) must be
    bit-identical to the slab-gather path, scatter and rebuild both."""
    init_range = 1e-3
    from paddlebox_tpu.embedding.optimizers import (push_sparse_hostdedup,
                                                    push_sparse_rebuild)
    from paddlebox_tpu.embedding.pass_table import (first_occurrence_idx,
                                                    pos_for_rebuild)
    table = TableConfig(embedx_dim=D, pass_capacity=1 << 8,
                        optimizer=SparseOptimizerConfig(
                            mf_initial_range=init_range,
                            mf_create_thresholds=0.0))
    pt = PassTable(table, seed=6)
    rng = np.random.RandomState(8)
    keys = np.unique(rng.randint(1, 10**9, 50).astype(np.uint64))
    pt.begin_feed_pass(); pt.add_keys(keys); pt.end_feed_pass()
    pt.begin_pass()
    K = 96
    occ = rng.choice(keys, K).astype(np.uint64)
    valid = rng.rand(K) > 0.2
    ids = pt.lookup_ids(occ, valid)
    push = PushLayout(D)
    grads = rng.randn(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    grads[~valid] = 0.0
    prng = jax.random.PRNGKey(3)
    slab0 = pt.slab
    uids, perm, inv = pt.dedup_for_push(ids)
    first = first_occurrence_idx(perm, inv)
    pulled = slab0[jnp.asarray(ids)]
    args = (jnp.asarray(uids), jnp.asarray(perm), jnp.asarray(inv),
            jnp.asarray(grads), prng, pt.layout, table.optimizer)
    ref = push_sparse_hostdedup(slab0, *args)
    got = push_sparse_hostdedup(slab0, *args, pulled_rows=pulled,
                                first_idx=jnp.asarray(first))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    pos = jnp.asarray(pos_for_rebuild(uids, table.pass_capacity))
    ref_r = push_sparse_rebuild(slab0, args[0], pos, *args[1:])
    got_r = push_sparse_rebuild(slab0, args[0], pos, *args[1:],
                                pulled_rows=pulled,
                                first_idx=jnp.asarray(first))
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(ref_r))
    np.testing.assert_array_equal(np.asarray(ref_r), np.asarray(got_r))
    pt.end_pass()
