"""File manager + pipe-command/gz inputs (BoxFileMgr role,
box_helper_py.cc:130-213; pipe-command load path, data_feed.h:2119-2134)."""

import gzip
import os
import stat

import numpy as np
import pytest

from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import BoxDataset, MultiSlotParser
from paddlebox_tpu.utils.file_mgr import (LocalFileMgr, ShellFileMgr,
                                          make_file_mgr)


def test_local_file_mgr(tmp_path):
    m = make_file_mgr("")
    assert isinstance(m, LocalFileMgr)
    d = str(tmp_path / "a")
    m.mkdir(d)
    m.touch(os.path.join(d, "x.txt"))
    with open(os.path.join(d, "x.txt"), "w") as f:
        f.write("hello")
    assert m.exists(os.path.join(d, "x.txt"))
    assert m.file_size(os.path.join(d, "x.txt")) == 5
    m.upload(os.path.join(d, "x.txt"), os.path.join(d, "up", "y.txt"))
    assert m.list_dir(os.path.join(d, "up")) == [os.path.join(d, "up", "y.txt")]
    m.rename(os.path.join(d, "up", "y.txt"), os.path.join(d, "z.txt"))
    m.download(os.path.join(d, "z.txt"), os.path.join(d, "dl.txt"))
    assert open(os.path.join(d, "dl.txt")).read() == "hello"
    m.remove(d)
    assert not m.exists(d)


def test_shell_file_mgr_with_fake_client(tmp_path):
    """Drive ShellFileMgr through a local script speaking the hadoop-fs verb
    shape (the in-process fake pattern)."""
    fake = tmp_path / "fakefs"
    fake.write_text(
        "#!/bin/sh\n"
        "verb=$1; shift\n"
        "case $verb in\n"
        "  -ls) ls -la $1 | awk -v d=$1 'NR>1 {print $1, d\"/\"$NF}';;\n"
        "  -test) shift; test -e $1;;\n"
        "  -get) cp $1 $2;;\n"
        "  -put) cp $1 $2;;\n"
        "  -mkdir) shift; mkdir -p $1;;\n"
        "  -touchz) touch $1;;\n"
        "  -mv) mv $1 $2;;\n"
        "  -rm) shift; rm -rf $1;;\n"
        "  -du) wc -c < $1;;\n"
        "esac\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    m = ShellFileMgr(str(fake))
    d = str(tmp_path / "remote")
    m.mkdir(d)
    src = tmp_path / "local.txt"
    src.write_text("abc")
    m.upload(str(src), os.path.join(d, "r.txt"))
    assert m.exists(os.path.join(d, "r.txt"))
    assert m.file_size(os.path.join(d, "r.txt")) == 3
    assert any(f.endswith("r.txt") for f in m.list_dir(d))
    m.download(os.path.join(d, "r.txt"), str(tmp_path / "back.txt"))
    assert (tmp_path / "back.txt").read_text() == "abc"
    assert not m.exists(os.path.join(d, "missing"))


@pytest.fixture
def feed_slots():
    return (SlotConfig("click", type="float", dim=1, is_used=False),
            SlotConfig("s0", type="uint64", max_len=2),
            SlotConfig("s1", type="uint64", max_len=2))


def test_gz_input(tmp_path, feed_slots):
    lines = "\n".join("1 1 1 %d 1 %d" % (i, i + 7) for i in range(20))
    p = tmp_path / "d.txt.gz"
    with gzip.open(p, "wt") as f:
        f.write(lines)
    feed = DataFeedConfig(slots=feed_slots, batch_size=4)
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert len(ds) == 20


def test_pipe_command_input(tmp_path, feed_slots):
    # raw file is csv; the pipe command rewrites it to multislot text
    p = tmp_path / "d.csv"
    p.write_text("\n".join("%d,%d" % (i, i + 7) for i in range(10)))
    feed = DataFeedConfig(
        slots=feed_slots, batch_size=4,
        pipe_command="awk -F, '{print \"1 1 1\", $1, \"1\", $2}'")
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert len(ds) == 10
    rec = ds.records[0]
    assert set(rec.uint64_slots) == {0, 1}


def test_pipe_command_failure_surfaces(tmp_path, feed_slots):
    p = tmp_path / "d.txt"
    p.write_text("1 1 1 5 1 6\n")
    feed = DataFeedConfig(slots=feed_slots, batch_size=4,
                          pipe_command="false")
    ds = BoxDataset(feed, read_threads=1, columnar=False)
    ds.set_filelist([str(p)])
    with pytest.raises(RuntimeError):
        ds.load_into_memory()
