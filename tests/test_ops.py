"""Op-level unit tests vs numpy oracles (OpTest-style, SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.ops import (cvm_transform, data_norm,
                               data_norm_summary_update, fused_seqpool_cvm,
                               pull_sparse, pull_sparse_differentiable)
from paddlebox_tpu.ops.data_norm import DataNormState
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding import accessor as acc

D = 4
LAYOUT = ValueLayout(D, "adagrad")


def test_cvm_transform_matches_cvm_op():
    pooled = jnp.asarray(np.array([[3.0, 1.0, 0.5, 0.2],
                                   [0.0, 0.0, 1.0, -1.0]], np.float32))
    y = np.asarray(cvm_transform(pooled, use_cvm=True))
    np.testing.assert_allclose(y[:, 0], np.log(pooled[:, 0] + 1), rtol=1e-6)
    np.testing.assert_allclose(
        y[:, 1], np.log(pooled[:, 1] + 1) - np.log(pooled[:, 0] + 1), rtol=1e-6)
    np.testing.assert_allclose(y[:, 2:], pooled[:, 2:])
    y2 = np.asarray(cvm_transform(pooled, use_cvm=False))
    np.testing.assert_allclose(y2, pooled[:, 2:])


def test_fused_seqpool_cvm_pools_per_segment():
    B, S, E = 2, 3, 2 + 3  # show, click, 3 emb dims
    # 4 keys: ins0/slot0 ×2, ins0/slot2, ins1/slot1; one padding
    emb = jnp.asarray(np.arange(5 * E, dtype=np.float32).reshape(5, E))
    segments = jnp.asarray(np.array([0, 0, 2, 4, 0], np.int32))
    valid = jnp.asarray(np.array([1, 1, 1, 1, 0], bool))
    out = np.asarray(fused_seqpool_cvm(emb, segments, valid, B, S,
                                       use_cvm=False))
    assert out.shape == (B, S, 3)
    np.testing.assert_allclose(out[0, 0], emb[0, 2:] + emb[1, 2:])
    np.testing.assert_allclose(out[0, 2], emb[2, 2:])
    np.testing.assert_allclose(out[1, 1], emb[3, 2:])
    np.testing.assert_allclose(out[0, 1], 0.0)  # empty slot pools to zero
    # padding key (valid=0, segment 0) must NOT pollute segment 0
    emb_bad = emb.at[4].set(999.0)
    out2 = np.asarray(fused_seqpool_cvm(emb_bad, segments, valid, B, S,
                                        use_cvm=False))
    np.testing.assert_allclose(out2[0, 0], out[0, 0])


def test_data_norm_forward_oracle():
    N, C = 8, 6
    rng = np.random.RandomState(0)
    x = rng.randn(N, C).astype(np.float32)
    st = DataNormState(
        batch_size=jnp.asarray(rng.rand(C).astype(np.float32) + 1),
        batch_sum=jnp.asarray(rng.randn(C).astype(np.float32)),
        batch_square_sum=jnp.asarray(rng.rand(C).astype(np.float32) + 1))
    y = np.asarray(data_norm(jnp.asarray(x), st))
    mean = np.asarray(st.batch_sum) / np.asarray(st.batch_size)
    scale = np.sqrt(np.asarray(st.batch_size) / np.asarray(st.batch_square_sum))
    np.testing.assert_allclose(y, (x - mean) * scale, rtol=1e-5)


def test_data_norm_slot_dim_show_skip():
    # 2 slots × slot_dim 3; instance 1's slot 0 has show=0 → zeros
    x = np.ones((2, 6), np.float32)
    x[1, 0] = 0.0
    st = DataNormState.init(6)
    y = np.asarray(data_norm(jnp.asarray(x), st, slot_dim=3))
    assert (y[1, :3] == 0).all()
    assert (y[0] != 0).any()


def test_data_norm_summary_update_accumulates():
    st = DataNormState.init(2, init_batch_size=10.0)
    x = jnp.asarray(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    st2 = data_norm_summary_update(st, x, decay=1.0)
    np.testing.assert_allclose(np.asarray(st2.batch_size), [12.0, 12.0])
    np.testing.assert_allclose(np.asarray(st2.batch_sum), [4.0, 6.0])


def test_pull_sparse_differentiable_scatter_add():
    cap = 16
    slab = jnp.asarray(np.random.RandomState(0).rand(
        cap, LAYOUT.width).astype(np.float32))
    ids = jnp.asarray(np.array([3, 3, 7], np.int32))

    def loss(slab):
        emb = pull_sparse_differentiable(slab, ids, LAYOUT)
        return (emb[:, 2] ** 2).sum() + emb[:, 3:].sum()

    g = jax.grad(loss)(slab)
    g = np.asarray(g)
    # embed_w grad: duplicate id 3 accumulates 2*w each = 2 rows of 2w
    np.testing.assert_allclose(g[3, acc.EMBED_W],
                               2 * 2 * slab[3, acc.EMBED_W], rtol=1e-5)
    np.testing.assert_allclose(g[7, acc.EMBED_W],
                               2 * slab[7, acc.EMBED_W], rtol=1e-5)
    xw0 = LAYOUT.embedx_w
    np.testing.assert_allclose(g[3, xw0:xw0 + D], 2.0)  # dup id → 2×1
    np.testing.assert_allclose(g[7, xw0:xw0 + D], 1.0)
    # untouched rows zero grad; show/click columns never receive grads
    assert g[0].sum() == 0
    assert g[3, acc.SHOW] == 0 and g[3, acc.CLICK] == 0


def test_pull_matches_differentiable_forward():
    cap = 8
    slab = jnp.asarray(np.random.RandomState(1).rand(
        cap, LAYOUT.width).astype(np.float32))
    ids = jnp.asarray(np.array([0, 5], np.int32))
    np.testing.assert_array_equal(
        np.asarray(pull_sparse(slab, ids, LAYOUT)),
        np.asarray(pull_sparse_differentiable(slab, ids, LAYOUT)))
