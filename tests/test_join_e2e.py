"""End-to-end join-phase training: pv batches with rank_offset reach the
model and train rank_param (the wiring the reference drives through
SlotPaddleBoxDataFeed's rank-offset feed + rank_attention op)."""

import numpy as np
import jax

from paddlebox_tpu.config.configs import (DataFeedConfig, SlotConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data.dataset import BoxDataset
from paddlebox_tpu.data.packer import BatchPacker
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.join_pv import JoinPvDnn
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 3
B = 16


def _feed():
    slots = tuple(SlotConfig(name=f"s{i}", type="uint64", max_len=3)
                  for i in range(NUM_SLOTS))
    return DataFeedConfig(slots=slots, batch_size=B, rank_offset=True)


def _records(n=48, seed=0):
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        slots = {si: rng.randint(1, 4000, rng.randint(1, 3)).astype(np.uint64)
                 for si in range(NUM_SLOTS)}
        recs.append(SlotRecord(
            label=int(rng.rand() < 0.3), uint64_slots=slots,
            search_id=i // 3,                # 3 ads per pv
            rank=(i % 3) + 1, cmatch=222))
    return recs


def test_packer_emits_rank_offset_from_feed_config():
    feed = _feed()
    packer = BatchPacker(feed)
    b = packer.pack(_records(B))
    assert b.rank_offset is not None
    assert b.rank_offset.shape == (B, 2 * packer.max_rank + 1)
    # ads of pv 0 (rows 0,1,2) are mutual peers including self
    assert b.rank_offset[0, 0] == 1
    assert b.rank_offset[0, 2] == 0    # rank-1 peer is row 0 itself
    assert b.rank_offset[0, 4] == 1    # rank-2 peer is row 1


def test_join_pv_trains_rank_param_e2e(tmp_path):
    feed = _feed()
    table_cfg = TableConfig(embedx_dim=D, pass_capacity=1 << 12,
                            optimizer=SparseOptimizerConfig())
    spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D)
    model = JoinPvDnn(spec, max_rank=3, att_dim=8, hidden=(16,))
    trainer = BoxTrainer(model, table_cfg, feed,
                         TrainerConfig(dense_lr=0.1), seed=0)

    files = []
    recs = _records()
    path = tmp_path / "pv_data.txt"
    # write via the dataset's record path: bypass file parsing by injecting
    # records directly (the parser path is covered by data tests)
    ds = BoxDataset(feed, read_threads=1)
    ds._records = recs
    trainer.table.begin_feed_pass()
    trainer.table.add_keys(np.concatenate([r.all_keys() for r in recs]))
    trainer.table.end_feed_pass()

    before = np.asarray(trainer.params["rank_param"]).copy()
    stats = trainer.train_pass(ds, preloaded=True)
    after = np.asarray(trainer.params["rank_param"])
    assert stats["batches"] >= 1
    assert not np.allclose(before, after), "rank_param must receive updates"
