"""Elastic recovery wiring (VERDICT r1 weak #8): heartbeat death detection
→ pass-boundary stop → restart resumes from the last completed pass with
bit-exact state."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.fleet.elastic import DeadRankError, ElasticManager
from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train.checkpoint import CheckpointManager
from paddlebox_tpu.train.recovery import RecoverableRunner
from paddlebox_tpu.train.trainer import BoxTrainer

D = 4
NUM_SLOTS = 4


@pytest.fixture(autouse=True)
def no_shuffle():
    from paddlebox_tpu.config import flags
    flags.set_flag("dataset_disable_shuffle", True)
    yield
    flags.set_flag("dataset_disable_shuffle", False)


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("recov")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=200, num_slots=NUM_SLOTS,
        vocab_per_slot=80, max_len=3, seed=17)
    feed = type(feed)(slots=feed.slots, batch_size=32)
    return files, feed


def make_trainer(feed, seed=0):
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=1 << 13,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.1,
                                        mf_learning_rate=0.1))
    return BoxTrainer(CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                             hidden=(16,)),
                      table_cfg, feed, TrainerConfig(dense_lr=0.01),
                      seed=seed)


def datasets(files, feed, n):
    out = []
    for _ in range(n):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        out.append(ds)
    return out


def ckpt_cfg(tmp_path, name):
    return CheckpointConfig(batch_model_dir=str(tmp_path / name / "batch"),
                            xbox_model_dir=str(tmp_path / name / "xbox"),
                            async_save=False)


def _store_state(trainer):
    keys, vals = trainer.table.store.state_items()
    order = np.argsort(keys)
    return keys[order], vals[order]


def test_crash_resume_matches_uninterrupted(data, tmp_path):
    files, feed = data

    # oracle: 4 uninterrupted passes under the same runner
    oracle = make_trainer(feed)
    r0 = RecoverableRunner(oracle, CheckpointManager(
        ckpt_cfg(tmp_path, "oracle"), oracle.table), day="d1")
    r0.run(datasets(files, feed, 4))

    # crashing job: dies after pass 2 (mid-sequence), restarts, resumes
    cfg = ckpt_cfg(tmp_path, "crash")
    t1 = make_trainer(feed)
    r1 = RecoverableRunner(t1, CheckpointManager(cfg, t1.table), day="d1")

    class Boom(RuntimeError):
        pass

    dss = datasets(files, feed, 4)
    orig = t1.train_pass
    calls = {"n": 0}

    def crashing_train_pass(ds, **kw):
        if calls["n"] == 2:
            raise Boom()
        calls["n"] += 1
        return orig(ds, **kw)

    t1.train_pass = crashing_train_pass
    with pytest.raises(Boom):
        r1.run(dss)

    # "restart": a FRESH process = fresh trainer + runner over the same dir
    t2 = make_trainer(feed, seed=0)
    r2 = RecoverableRunner(t2, CheckpointManager(cfg, t2.table), day="d1")
    assert r2.completed_passes() == 2
    r2.run(datasets(files, feed, 4))

    # bit-exact parity with the uninterrupted run
    k_ref, v_ref = _store_state(oracle)
    k_got, v_got = _store_state(t2)
    np.testing.assert_array_equal(k_got, k_ref)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-6, atol=1e-7)
    import jax
    for a, b in zip(jax.tree.leaves(oracle.params),
                    jax.tree.leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-6)


def test_dead_rank_stops_at_pass_boundary(data, tmp_path):
    """A peer death flips the elastic watcher; the runner raises at the
    next pass boundary; the checkpoint marker survives for resume."""
    files, feed = data
    server = KVStoreServer(host="127.0.0.1")
    cl0 = TcpStoreClient("127.0.0.1", server.port)
    cl1 = TcpStoreClient("127.0.0.1", server.port)
    # generous staleness margin: under full-suite load a LIVE peer's
    # heartbeat thread can starve past a tight window and get flagged
    # before the scripted death (flaked at 0.3s)
    e0 = ElasticManager(cl0, rank=0, world=2, heartbeat_interval=0.05,
                        stale_after=2.0)
    e1 = ElasticManager(cl1, rank=1, world=2, heartbeat_interval=0.05,
                        stale_after=2.0)
    e0.start()
    e1.start()

    trainer = make_trainer(feed)
    cfg = ckpt_cfg(tmp_path, "elastic")
    runner = RecoverableRunner(trainer, CheckpointManager(cfg, trainer.table),
                               day="d1", elastic=e0)

    dss = datasets(files, feed, 6)
    orig = trainer.train_pass
    calls = {"n": 0}

    import time

    def pass_and_kill_peer(ds, **kw):
        out = orig(ds, **kw)
        calls["n"] += 1
        if calls["n"] == 2:
            e1.stop()  # rank 1 "dies" after the 2nd pass
            deadline = time.time() + 10
            while not e0.dead_ranks and time.time() < deadline:
                time.sleep(0.05)  # let the watcher flag it
        return out

    trainer.train_pass = pass_and_kill_peer
    with pytest.raises(DeadRankError):
        runner.run(dss)
    # at least the first two passes completed and are resumable
    assert runner.completed_passes() >= 2
    assert e0.dead_ranks == [1]
    e0.stop()
    cl0.close()
    cl1.close()
    server.stop()


# tier-1 budget (round-10 headroom audit, 6.8s): crash-resume parity
# is guarded by test_crash_resume_matches_uninterrupted; this variant
# re-runs it with the shuffle stage whose determinism test_shuffle
# covers. Runs in the slow-inclusive suite and on TPU windows
@pytest.mark.slow
def test_crash_resume_parity_with_shuffle_enabled(data, tmp_path):
    """The checkpoint carries the shuffle RNG state, so resume is
    bit-identical even with per-pass local shuffle ON."""
    from paddlebox_tpu.config import flags
    flags.set_flag("dataset_disable_shuffle", False)  # override fixture
    files, feed = data

    oracle = make_trainer(feed)
    r0 = RecoverableRunner(oracle, CheckpointManager(
        ckpt_cfg(tmp_path, "sh_oracle"), oracle.table), day="d1")
    r0.run(datasets(files, feed, 4))

    cfg = ckpt_cfg(tmp_path, "sh_crash")
    t1 = make_trainer(feed)
    r1 = RecoverableRunner(t1, CheckpointManager(cfg, t1.table), day="d1")
    r1.run(datasets(files, feed, 2))  # "crash" after 2 completed passes

    t2 = make_trainer(feed, seed=0)
    r2 = RecoverableRunner(t2, CheckpointManager(cfg, t2.table), day="d1")
    r2.run(datasets(files, feed, 4))

    k_ref, v_ref = _store_state(oracle)
    k_got, v_got = _store_state(t2)
    np.testing.assert_array_equal(k_got, k_ref)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-6, atol=1e-7)


def test_sharded_crash_resume_matches_uninterrupted(data, tmp_path):
    """The same pass-boundary recovery loop over the SHARDED trainer:
    per-pass base checkpoints ride the store_view facade, a restarted
    fresh trainer resumes from the last DONE pass and converges to the
    uninterrupted run (store rows + dense params)."""
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel.mesh import device_mesh_1d

    files, feed = data

    def make_sharded(seed=0):
        table_cfg = TableConfig(
            embedx_dim=D, pass_capacity=1 << 13,
            optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                            mf_initial_range=1e-3,
                                            feature_learning_rate=0.1,
                                            mf_learning_rate=0.1))
        return ShardedBoxTrainer(
            CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(16,)),
            table_cfg, feed, TrainerConfig(dense_lr=0.01, scan_chunk=1),
            mesh=device_mesh_1d(8), seed=seed)

    def sharded_state(trainer):
        keys, vals = trainer.table.store_view().state_items()
        order = np.argsort(keys)
        return keys[order], vals[order]

    oracle = make_sharded()
    r0 = RecoverableRunner(oracle, CheckpointManager(
        ckpt_cfg(tmp_path, "sh_oracle"), oracle.table), day="d1")
    r0.run(datasets(files, feed, 4))

    cfg = ckpt_cfg(tmp_path, "sh_crash")
    t1 = make_sharded()
    r1 = RecoverableRunner(t1, CheckpointManager(cfg, t1.table), day="d1")

    class Boom(RuntimeError):
        pass

    orig = t1.train_pass
    calls = {"n": 0}

    def crashing_train_pass(ds, **kw):
        if calls["n"] == 2:
            raise Boom()
        calls["n"] += 1
        return orig(ds, **kw)

    t1.train_pass = crashing_train_pass
    with pytest.raises(Boom):
        r1.run(datasets(files, feed, 4))

    t2 = make_sharded(seed=0)
    r2 = RecoverableRunner(t2, CheckpointManager(cfg, t2.table), day="d1")
    assert r2.completed_passes() == 2
    r2.run(datasets(files, feed, 4))

    k_ref, v_ref = sharded_state(oracle)
    k_got, v_got = sharded_state(t2)
    np.testing.assert_array_equal(k_got, k_ref)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-5, atol=1e-7)
    import jax
    for a, b in zip(jax.tree.leaves(oracle.params),
                    jax.tree.leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-5,
                                   atol=1e-7)


def test_crash_resume_under_rebuild_push(data, tmp_path):
    """Crash-resume parity must hold with push_write='rebuild' (the
    tpu-side default via 'auto'): the recovered run's state matches the
    uninterrupted one exactly, as in the scatter-mode test above."""
    from paddlebox_tpu.config import flags
    flags.set_flag("push_write", "rebuild")
    try:
        test_crash_resume_matches_uninterrupted(data, tmp_path)
    finally:
        flags.set_flag("push_write", "auto")
