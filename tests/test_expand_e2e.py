"""Expand/NN-cross embedding end to end (VERDICT r2 #6): a model consuming
pull_sparse_extended trains through BoxTrainer — expand grads flow through
the push into the shared-g2sum expand adagrad rule, the expand block
learns, and SetTestMode inference works. Reference: the
pull_box_extended_sparse user path (contrib/layers/nn.py:1678 →
operators/pull_box_extended_sparse_op.cc)."""

import dataclasses

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.models import CtrDnnExpand
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train import BoxTrainer

D, E = 4, 3


def _table():
    return TableConfig(
        embedx_dim=D, pass_capacity=1 << 12, expand_embed_dim=E,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3,
                                        feature_learning_rate=0.05,
                                        mf_learning_rate=0.05))


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    out = tmp_path_factory.mktemp("expand_data")
    files, feed = write_synthetic_ctr_files(
        str(out), num_files=2, lines_per_file=300, num_slots=4,
        vocab_per_slot=90, max_len=3, seed=13)
    return files, dataclasses.replace(feed, batch_size=32)


def test_expand_model_learns_e2e(data):
    files, feed = data
    table = _table()
    model = CtrDnnExpand(ModelSpec(num_slots=4, slot_dim=3 + D),
                         expand_dim=E, hidden=(32, 16))
    tr = BoxTrainer(model, table, feed,
                    TrainerConfig(dense_lr=1e-2, scan_chunk=2))
    try:
        tr.metrics.init_metric("auc", "label", "pred", table_size=1 << 14,
                               mask_var="mask")
        losses = []
        for _ in range(10):
            ds = BoxDataset(feed, read_threads=1)
            ds.set_filelist(files)
            losses.append(tr.train_pass(ds)["loss"])
            ds.release_memory()
        assert losses[-1] < losses[0] - 0.02, losses
        msg = tr.metrics.get_metric_msg("auc")
        assert msg["auc"] > 0.6, msg

        # the expand block itself trained: nonzero vectors + advanced
        # shared-g2sum state on trained rows
        keys, vals = tr.table.store.state_items()
        lay = tr.table.layout
        exp = vals[:, lay.expand_w:lay.expand_w + E]
        assert np.abs(exp).max() > 0, "expand block never updated"
        assert (vals[:, lay.expand_state] > 0).any(), "expand g2sum still 0"

        # SetTestMode inference through the extended pull
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        preds, labels = tr.predict_batches(ds)
        assert preds.size == labels.size > 500
        assert np.isfinite(preds).all()
    finally:
        tr.close()


def test_expand_requires_table_block(data):
    files, feed = data
    table = dataclasses.replace(_table(), expand_embed_dim=0)
    model = CtrDnnExpand(ModelSpec(num_slots=4, slot_dim=3 + D),
                         expand_dim=E, hidden=(16,))
    with pytest.raises(ValueError, match="expand_embed_dim"):
        BoxTrainer(model, table, feed, TrainerConfig(dense_lr=1e-2))


def test_expand_push_changes_only_seen_rows(data):
    """One step: expand grads land on the batch's rows (dedup'd push), all
    other rows' expand blocks stay untouched."""
    files, feed = data
    table = _table()
    model = CtrDnnExpand(ModelSpec(num_slots=4, slot_dim=3 + D),
                         expand_dim=E, hidden=(16,))
    tr = BoxTrainer(model, table, feed,
                    TrainerConfig(dense_lr=1e-2, scan_chunk=1))
    try:
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        tr.train_pass(ds)
        keys, vals = tr.table.store.state_items()
        lay = tr.table.layout
        # every stored row was part of the pass; rows with show>0 trained
        seen = vals[:, acc.SHOW] > 0
        assert seen.any()
        exp_norm = np.abs(vals[:, lay.expand_w:lay.expand_w + E]).sum(1)
        assert (exp_norm[seen] > 0).mean() > 0.5
    finally:
        tr.close()


def test_expand_sharded_trainer_learns(data):
    """The expand path through the SHARDED step: base+expand blocks ride
    one a2a, expand grads return through the push a2a into the in-table
    expand adagrad on the owning shard."""
    import jax
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel.mesh import device_mesh_1d

    files, feed = data
    table = _table()
    model = CtrDnnExpand(ModelSpec(num_slots=4, slot_dim=3 + D),
                         expand_dim=E, hidden=(32, 16))
    trainer = ShardedBoxTrainer(
        model, table, feed, TrainerConfig(dense_lr=1e-2, scan_chunk=2),
        mesh=device_mesh_1d(8), seed=0)
    losses = []
    for _ in range(8):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        losses.append(trainer.train_pass(ds)["loss"])
        ds.release_memory()
    assert losses[-1] < losses[0] - 0.02, losses
    lay = trainer.table.layout
    trained = 0
    for st in trainer.table.stores:
        _, vals = st.state_items()
        if vals.size:
            trained += int((np.abs(
                vals[:, lay.expand_w:lay.expand_w + E]).sum(1) > 0).sum())
    assert trained > 50, trained


def test_expand_config_mismatches_fail_loud(data):
    """Both directions of the expand contract fail at build time with a
    config-level message, not an opaque shape error mid-trace."""
    files, feed = data
    from paddlebox_tpu.models import CtrDnn

    # table has an expand block, model does not consume it
    with pytest.raises(ValueError, match="does not consume"):
        BoxTrainer(CtrDnn(ModelSpec(num_slots=4, slot_dim=3 + D),
                          hidden=(8,)),
                   _table(), feed, TrainerConfig(dense_lr=1e-2))
    # dim mismatch
    model = CtrDnnExpand(ModelSpec(num_slots=4, slot_dim=3 + D),
                         expand_dim=E + 2, hidden=(8,))
    with pytest.raises(ValueError, match="expand_dim"):
        BoxTrainer(model, _table(), feed, TrainerConfig(dense_lr=1e-2))
